"""Request-scoped tracing & SLO plane for the serving fleet.

serving.py's aggregate telemetry (histograms, engine states) cannot
answer the questions a router or an SLO review asks: *where did THIS
request's latency go, and which phase ate the deadline it missed?*
This module keeps the per-request story:

- **Per-phase latency decomposition**: every ``ServeRequest`` carries
  measured queue-wait / prefill / decode / fetch seconds (accumulated
  by the engine's scheduler tick); at the terminal outcome the
  breakdown is recorded onto a bounded recently-terminated ring served
  at ``/requests`` (next to the live in-flight table).
- **Deadline attribution**: every ``expired`` / ``rejected_early``
  request names the phase that ate its budget (the dominant measured
  phase — under overload that is queue wait, which is exactly the
  routing signal a multi-replica front door needs).
- **SLO accounting** (``pt_slo_*``, targets from the
  ``serve_slo_ttft_ms`` / ``serve_slo_token_ms`` flags): terminal
  requests are scored met/missed and every miss burns
  ``pt_slo_burn_total{slo=,outcome=}``. The TTFT survivorship bias is
  closed here: a request terminating BEFORE its first token (expired /
  evicted / drained / error) never observes ``pt_serve_ttft_seconds``
  — so p99 TTFT would *improve* as overload worsens — and is instead
  metered as censored (``pt_serve_ttft_censored_total{outcome=}``)
  and counted AGAINST the TTFT target.
- **Per-request Chrome-trace tracks**: a request's whole life (submit,
  queue, prefill, sampled decode steps, restart replays, eviction /
  scrub events, terminal outcome) lands on ONE dynamic timeline track
  (``monitor.REQUEST_TRACK_BASE`` + slot, recycled round-robin), so
  Perfetto shows it across batch steps and across a supervised
  engine restart — the replay continues the original trace with the
  restart annotated as a span.

House invariant: with telemetry off every ``note_*`` hook is a single
cached-boolean check and allocates nothing (the tracemalloc proof in
tests/test_request_trace.py filters on this file). The module never
imports serving.py at module level — the view builders reach it
through ``sys.modules``, so a monitor-only process answers
``/requests`` with an empty view instead of pulling the serving stack
in.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor

REQUEST_RECORD_SCHEMA_VERSION = 1

# terminal outcomes that can end a request before its first token: the
# TTFT histogram never sees these (survivorship bias) so they are
# metered as censored instead. 'rejected'/'rejected_early' are refusals
# — the request never entered service, so its TTFT is not censored
# (the deadline burn row still ticks for rejected_early).
CENSORED_OUTCOMES = ("expired", "evicted", "drained", "error")

PHASES = ("queue_wait", "prefill", "decode", "fetch")

# dynamic timeline tracks are recycled round-robin across this many
# slots (a bounded label set: a server churning thousands of requests
# reuses tracks; the ring + /requests keep the full per-request story)
REQUEST_TRACK_SLOTS = 64

_M_TTFT_CENSORED = _monitor.counter(
    "pt_serve_ttft_censored_total",
    "requests that reached a terminal outcome before their first token, "
    "by outcome (expired / evicted / drained / error): "
    "pt_serve_ttft_seconds never observes them, so without this meter "
    "p99 TTFT *improves* as overload worsens (survivorship bias); the "
    "SLO plane counts every censored request against the TTFT target")
_M_SLO_TTFT = _monitor.counter(
    "pt_slo_ttft_total",
    "terminal requests measured against the serve_slo_ttft_ms target, "
    "by status (met / missed / censored — a censored request never saw "
    "a first token and counts against the target); empty while the "
    "target flag is 0")
_M_SLO_TOKEN = _monitor.counter(
    "pt_slo_token_total",
    "terminal requests measured against the serve_slo_token_ms "
    "per-token decode-latency target (mean decode+fetch seconds per "
    "emitted token), by status (met / missed); requests that emitted "
    "no token are not measured; empty while the target flag is 0")
_M_SLO_BURN = _monitor.counter(
    "pt_slo_burn_total",
    "SLO error-budget burn events by slo + outcome: slo='ttft' (missed "
    "or censored vs serve_slo_ttft_ms), slo='token' (missed vs "
    "serve_slo_token_ms), slo='deadline' (every expired / "
    "rejected_early request — its own deadline IS an SLO, so these "
    "rows tick even with the target flags unset)")

# cached hot flag values (watch_flag pattern: no dict lookup per call)
_slo_ttft_s = 0.0
_slo_token_s = 0.0

_RECENT_LOCK = threading.Lock()
_RECENT: collections.deque = collections.deque(maxlen=256)

_TRACK_LOCK = threading.Lock()
_track_seq = 0


def _sync_slo_ttft(value):
    global _slo_ttft_s
    _slo_ttft_s = float(value) / 1e3


def _sync_slo_token(value):
    global _slo_token_s
    _slo_token_s = float(value) / 1e3


def _sync_recent_cap(value):
    global _RECENT
    cap = max(1, int(value))
    with _RECENT_LOCK:
        if _RECENT.maxlen != cap:
            _RECENT = collections.deque(_RECENT, maxlen=cap)


def _ensure_track(req) -> int:
    """Lazily pin one dynamic timeline track (tid) to ``req`` — every
    span/instant of the request's life lands there, INCLUDING replays
    on a rebuilt engine (the tid lives on the handle, which survives
    the restart), so Perfetto shows one continuous request row."""
    tid = req.trace_tid
    if tid is None:
        global _track_seq
        with _TRACK_LOCK:
            slot = _track_seq % REQUEST_TRACK_SLOTS
            _track_seq += 1
        tid = _monitor.REQUEST_TRACK_BASE + slot
        req.trace_tid = tid
        _monitor.trace_register_track(tid, f"req {req.trace_id}")
    return tid


# --- lifecycle hooks (called by serving.py; trace hooks gate on
# trace_active, accounting hooks on enabled — all one cached boolean
# when telemetry is off) ---


def note_submit(req):
    """Queued (or replay-intake'd) — opens the request's track."""
    if not _monitor.trace_active():
        return
    _monitor.trace_event(
        "submit", "request", req.submit_ts,
        args={"req": req.trace_id, "engine": req.engine_id,
              "max_new_tokens": req.max_new_tokens},
        tid=_ensure_track(req))


def note_admit(req):
    """Admitted into a batch slot: closes the queue span and records
    the prefill span (``req.admit_ts`` / ``req.prefill_s`` were just
    measured by the engine)."""
    if not _monitor.trace_active():
        return
    tid = _ensure_track(req)
    if not req.replays:
        # a replay's wait is annotated by the restart span instead — a
        # second queue span over the first life would overlap it
        _monitor.trace_event("queue", "request", req.submit_ts,
                             req.admit_ts, args={"req": req.trace_id},
                             tid=tid)
    if req.prefill_s is not None:
        _monitor.trace_event("prefill", "request", req.admit_ts,
                             req.admit_ts + req.prefill_s,
                             args={"req": req.trace_id,
                                   "engine": req.engine_id}, tid=tid)


def note_decode_step(req, step, t0, t_f0, t_f1, token, pos, score):
    """One sampled decode step on the request's track: the dispatch ->
    device span plus the host-materialization (fetch) span, annotated
    with the emitted token and the greedy head's own logit."""
    tid = _ensure_track(req)
    _monitor.trace_event(
        "decode", "request", t0, t_f0,
        args={"req": req.trace_id, "step": step, "token": token,
              "pos": pos, "logit": score}, tid=tid)
    _monitor.trace_event("fetch", "request", t_f0, t_f1,
                         args={"req": req.trace_id, "step": step},
                         tid=tid)


def note_restart(req):
    """Supervised-restart replay re-entering decode (called from the
    request's replay reset at the rebuilt engine's admission): the
    restart is annotated as a span from the supervisor's replay intake
    to re-admission, ON the original request's track — one request,
    one trace."""
    if not _monitor.trace_active():
        return
    t1 = time.perf_counter()
    t0 = (req._replay_intake_ts if req._replay_intake_ts is not None
          else t1)
    _monitor.trace_event(
        "restart", "request", t0, t1,
        args={"req": req.trace_id, "replay": req.replays,
              "engine": req.engine_id}, tid=_ensure_track(req))


def note_evicted(req, cause: str, slot: int):
    """Containment evicted the request's slot (fault = slot-hinted
    decode/fetch error, nonfinite = logit probe): an instant on the
    VICTIM's track, so the eviction reads in the request's own story."""
    if not _monitor.trace_active():
        return
    _monitor.trace_event(
        "evicted", "request", time.perf_counter(),
        args={"req": req.trace_id, "cause": cause, "slot": slot},
        tid=_ensure_track(req))


def note_scrub(req, slot: int):
    """The evicted slot's device rows were scrubbed — the victim's
    containment epilogue, on its track."""
    if not _monitor.trace_active():
        return
    _monitor.trace_event(
        "scrub", "request", time.perf_counter(),
        args={"req": req.trace_id, "slot": slot},
        tid=_ensure_track(req))


def note_terminal(req):
    """Terminal-outcome accounting, called from ``ServeRequest._finish``
    (the one hook every outcome path funnels through): censored-TTFT
    metering, SLO scoring + burn, deadline attribution, the
    recently-terminated ring record, and the closing trace instant."""
    if not _monitor.enabled():
        return
    now = time.perf_counter()
    req.finish_ts = now
    outcome = req.outcome
    censored = req.ttft_s is None and outcome in CENSORED_OUTCOMES
    if censored:
        req.censored = True
        _M_TTFT_CENSORED.inc(labels={"outcome": outcome})
    ttft_status = token_status = None
    if _slo_ttft_s > 0.0:
        if req.ttft_s is not None:
            ttft_status = ("met" if req.ttft_s <= _slo_ttft_s
                           else "missed")
        elif censored:
            ttft_status = "censored"
        if ttft_status is not None:
            _M_SLO_TTFT.inc(labels={"status": ttft_status})
            if ttft_status != "met":
                _M_SLO_BURN.inc(labels={"slo": "ttft",
                                        "outcome": outcome})
    if _slo_token_s > 0.0 and req.tokens and req.decode_s > 0.0:
        per_tok = (req.decode_s + req.fetch_s) / len(req.tokens)
        token_status = "met" if per_tok <= _slo_token_s else "missed"
        _M_SLO_TOKEN.inc(labels={"status": token_status})
        if token_status == "missed":
            _M_SLO_BURN.inc(labels={"slo": "token", "outcome": outcome})
    if outcome in ("expired", "rejected_early"):
        # the request's own deadline is an SLO in itself: burn + name
        # the phase that ate the budget
        _M_SLO_BURN.inc(labels={"slo": "deadline", "outcome": outcome})
        req.deadline_attr = _attribute_deadline(req, now)
    _record(req, now, ttft_status, token_status)
    if _monitor.trace_active():
        _monitor.trace_event(
            f"outcome:{outcome}", "request", now,
            args={"req": req.trace_id, "tokens": len(req.tokens),
                  "replays": req.replays}, tid=_ensure_track(req))


def _phases_s(req, now: float) -> Dict[str, float]:
    """Measured per-phase seconds. A request still queued (or refused
    before queueing) charges everything since submit to queue wait —
    the phase it is actually stuck in."""
    qw = req.queue_wait_s
    if qw is None:
        qw = max(0.0, now - req.submit_ts)
    return {
        "queue_wait": qw,
        "prefill": req.prefill_s or 0.0,
        "decode": req.decode_s,
        "fetch": req.fetch_s,
    }


def _attribute_deadline(req, now: float) -> Dict[str, Any]:
    """Name the phase that ate an expired/rejected_early request's
    budget: the dominant measured phase (under queue overload that is
    queue wait — the signal a router sheds load on)."""
    phases = _phases_s(req, now)
    phase = max(PHASES, key=lambda k: phases[k])
    return {
        "phase": phase,
        "phase_ms": round(phases[phase] * 1e3, 3),
        "budget_ms": (None if req.deadline_ts is None else
                      round((req.deadline_ts - req.submit_ts) * 1e3, 3)),
        "phases_ms": {k: round(v * 1e3, 3) for k, v in phases.items()},
    }


def _record(req, now: float, ttft_status, token_status):
    phases = _phases_s(req, now)
    rec = {
        "v": REQUEST_RECORD_SCHEMA_VERSION,
        "trace_id": req.trace_id,
        "id": req.id,
        "engine": req.engine_id,
        "outcome": req.outcome,
        "tokens": len(req.tokens),
        "replays": req.replays,
        "capped": req.capped,
        "censored": req.censored,
        "wall_ms": round((now - req.submit_ts) * 1e3, 3),
        "ttft_ms": (None if req.ttft_s is None
                    else round(req.ttft_s * 1e3, 3)),
        "deadline_ms": (None if req.deadline_ts is None else
                        round((req.deadline_ts - req.submit_ts) * 1e3,
                              3)),
        "phases_ms": {k: round(v * 1e3, 3) for k, v in phases.items()},
        "deadline_attribution": req.deadline_attr,
        "slo": {"ttft": ttft_status, "token": token_status},
    }
    with _RECENT_LOCK:
        _RECENT.append(rec)


# --- view builders (the /requests route + fleet digest section) ---


def _inflight_row(req, state: str, slot: Optional[int],
                  now: float) -> Dict[str, Any]:
    return {
        "trace_id": req.trace_id,
        "id": req.id,
        "engine": req.engine_id,
        "state": state,
        "slot": slot,
        "tokens": len(req.tokens),
        "replays": req.replays,
        "age_ms": round((now - req.submit_ts) * 1e3, 3),
        "deadline_remaining_ms": (
            None if req.deadline_ts is None
            else round((req.deadline_ts - now) * 1e3, 3)),
        "ttft_ms": (None if req.ttft_s is None
                    else round(req.ttft_s * 1e3, 3)),
        "phases_ms": {k: round(v * 1e3, 3)
                      for k, v in _phases_s(req, now).items()},
    }


def slo_summary() -> Dict[str, Any]:
    """Targets + met/missed/censored counts + burn totals by SLO."""
    burn: Dict[str, int] = {}
    for cell in (_monitor.snapshot().get("pt_slo_burn_total", {})
                 .get("values", ())):
        slo = cell["labels"].get("slo", "?")
        burn[slo] = burn.get(slo, 0) + int(cell["value"])
    return {
        "targets_ms": {
            "ttft": _slo_ttft_s * 1e3 if _slo_ttft_s > 0.0 else None,
            "token": _slo_token_s * 1e3 if _slo_token_s > 0.0 else None,
        },
        "ttft": {s: int(_M_SLO_TTFT.value(labels={"status": s}))
                 for s in ("met", "missed", "censored")},
        "token": {s: int(_M_SLO_TOKEN.value(labels={"status": s}))
                  for s in ("met", "missed")},
        "ttft_censored": {
            o: int(_M_TTFT_CENSORED.value(labels={"outcome": o}))
            for o in CENSORED_OUTCOMES},
        "burn": burn,
    }


def requests_view() -> Dict[str, Any]:
    """The ``/requests`` route payload: the live in-flight table (one
    row per queued/decoding request across every live engine) + the
    bounded recently-terminated ring + the SLO rollup."""
    inflight: List[Dict[str, Any]] = []
    srv = sys.modules.get("paddle_tpu.serving")
    if srv is not None:
        now = time.perf_counter()
        for eng in list(srv._ENGINES):
            with eng._lock:
                queued = list(eng._queue)
                slotted = [(i, s.request)
                           for i, s in enumerate(eng._slots)
                           if s.request is not None]
            for req in queued:
                if req.outcome is None:
                    inflight.append(_inflight_row(req, "queued", None,
                                                  now))
            for i, req in slotted:
                if req.outcome is None:
                    inflight.append(_inflight_row(req, "decoding", i,
                                                  now))
    with _RECENT_LOCK:
        recent = list(_RECENT)
        cap = _RECENT.maxlen
    return {
        "v": REQUEST_RECORD_SCHEMA_VERSION,
        "inflight": inflight,
        "recent": recent,  # oldest -> newest
        "recent_cap": cap,
        "slo": slo_summary(),
    }


def digest_section() -> Optional[Dict[str, Any]]:
    """Compact per-replica serving rollup for the fleet digest (the
    roofline-section pattern: optional, absent on ranks that never
    served, fleet-digest schema stays v1). ``/fleet`` renders this as
    the per-replica SLO/latency row a multi-replica router selects on."""
    engines: Dict[str, Any] = {}
    srv = sys.modules.get("paddle_tpu.serving")
    if srv is not None:
        for eng in list(srv._ENGINES):
            with eng._lock:
                qlen = len(eng._queue)
            engines[str(eng.engine_id)] = {
                "state": eng.state,
                "queue_depth": qlen,
                "slots": eng.slots,
                "slots_active": int(eng._active_mask().sum()),
                "brownout": eng.brownout,
                "token_ewma_ms": (
                    None if eng._token_ewma_s is None
                    else round(eng._token_ewma_s * 1e3, 3)),
            }
    with _RECENT_LOCK:
        n_recent = len(_RECENT)
    if srv is None or (not engines and n_recent == 0):
        return None
    ttft_h = srv._M_TTFT_SECONDS
    token_h = srv._M_TOKEN_SECONDS
    return {
        "engines": engines,
        "recent": n_recent,
        "ttft_ms": {
            label: (None if ttft_h.quantile(q) is None
                    else round(ttft_h.quantile(q) * 1e3, 3))
            for label, q in _monitor.QUANTILE_LABELS},
        "token_ms": {
            label: (None if token_h.quantile(q) is None
                    else round(token_h.quantile(q) * 1e3, 3))
            for label, q in _monitor.QUANTILE_LABELS},
        "slo": slo_summary(),
    }


def reset():
    """Test-isolation hook (rides monitor.reset): clears the
    recently-terminated ring and rewinds track recycling."""
    global _track_seq
    with _RECENT_LOCK:
        _RECENT.clear()
    with _TRACK_LOCK:
        _track_seq = 0


_flags.watch_flag("serve_slo_ttft_ms", _sync_slo_ttft)
_flags.watch_flag("serve_slo_token_ms", _sync_slo_token)
_flags.watch_flag("serve_recent_requests", _sync_recent_cap)
