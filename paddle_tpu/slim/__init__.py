"""Model compression (reference: python/paddle/fluid/contrib/slim/)."""

from paddle_tpu.slim.distill import soft_label_distill_loss  # noqa: F401
from paddle_tpu.slim.prune import (  # noqa: F401
    SensitivePruneStrategy,
    StructurePruner,
    UniformPruneStrategy,
    apply_masks,
    compute_masks,
    pruned_ratio,
)
from paddle_tpu.slim.quantization import (  # noqa: F401
    QuantizationTransformPass,
    dequantize_weights,
    quantize_weights_int8,
)
