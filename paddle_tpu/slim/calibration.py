"""Post-training activation-range int8 calibration.

Reference: contrib/int8_inference/utility.py (``Calibrator`` — samples
activation tensors over warmup batches, computes per-tensor scales by
abs-max or KL-divergence) and contrib/slim/quantization/
quantization_pass.py:541 (``QuantizationFreezePass``) / :836
(``ConvertToInt8Pass``) — the passes that bake collected ACTIVATION
scales into the inference program and snapshot weights as int8.

TPU-native redesign: the reference rewires an IrGraph into cuDNN/MKLDNN
int8 kernels; on TPU the MXU computes in bf16/fp32 and int8 matmul
kernels are not the serving win — the win is the int8 ARTIFACT (4x
smaller weights) plus faithful int8 serving numerics. So calibration
here produces (a) per-tensor activation scales collected by running
warmup batches through the Executor, (b) an inference program with
STATIC-scale quantize-dequantize ops baked at the quantizable-op
boundaries (serving numerics == int8 deployment, still XLA-fused), and
(c) an int8 weight artifact. ``load_int8_inference_model`` restores the
whole thing into a fresh scope/Predictor.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import unique_name
from paddle_tpu.framework import Operator, Program
from paddle_tpu.slim.quantization import QUANTIZABLE


def _abs_max_scale(samples: List[np.ndarray]) -> float:
    return float(max((np.max(np.abs(s)) for s in samples), default=1.0)) \
        or 1.0


def _kl_scale(samples: List[np.ndarray], bins: int = 2048,
              target_bins: int = 128) -> float:
    """The reference Calibrator's 'KL' algo (utility.py Calibrator:
    minimize KL(P||Q) between the fp32 histogram and its int8-quantized
    rendition; the standard TensorRT-style sweep). Returns the chosen
    clip threshold (the scale)."""
    amax = _abs_max_scale(samples)
    hist = np.zeros(bins, np.float64)
    for s in samples:
        h, _ = np.histogram(np.abs(s), bins=bins, range=(0, amax))
        hist += h
    return _kl_from_hist(hist, amax, bins, target_bins)


def _kl_from_hist(hist: np.ndarray, amax: float, bins: int = 2048,
                  target_bins: int = 128) -> float:
    """KL threshold sweep over a prebuilt |x| histogram on (0, amax)."""
    total = hist.sum()
    if total == 0:
        return amax
    best_div, best_i = np.inf, bins
    for i in range(target_bins, bins + 1, 16):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()          # clip outliers into last bin
        p /= p.sum()
        # quantize the i fp32 bins down to target_bins int8 levels
        factor = i / target_bins
        q = np.zeros(i, np.float64)
        for j in range(target_bins):
            lo, hi = int(j * factor), int((j + 1) * factor)
            hi = max(hi, lo + 1)
            chunk = hist[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        qs = q.sum()
        if qs == 0:
            continue
        q /= qs
        mask = p > 0
        div = float(np.sum(p[mask] * np.log(
            p[mask] / np.maximum(q[mask], 1e-12))))
        if div < best_div:
            best_div, best_i = div, i
    return amax * best_i / bins


class Calibrator:
    """Collects activation ranges for an inference program's quantizable
    op inputs/outputs over warmup batches, then emits the int8-annotated
    program (reference: int8_inference/utility.py Calibrator +
    quantization_pass.py:541 freeze semantics).

    Usage::

        calib = Calibrator(infer_prog, exe, algo="abs_max")
        for batch in warmup_batches:
            calib.sample(feed=batch)            # runs + samples
        scales = calib.compute_scales()
        int8_prog = calib.freeze()              # static-scale QDQ baked
    """

    def __init__(self, program: Program, exe, scope=None,
                 algo: str = "abs_max",
                 op_types: Optional[Iterable[str]] = None):
        if algo not in ("abs_max", "KL"):
            raise ValueError(f"algo must be 'abs_max' or 'KL', got {algo}")
        self.program = program
        self.exe = exe
        self.scope = scope
        self.algo = algo
        self.op_types = dict(QUANTIZABLE) if op_types is None else {
            t: QUANTIZABLE[t] for t in op_types}
        block = program.global_block()
        persistable = {n for n, v in block.vars.items()
                       if getattr(v, "persistable", False)}
        # one pass over the quantizable slots partitions their inputs:
        # non-persistable -> activations to calibrate (weights get their
        # scale from the tensor itself at freeze time, like the
        # reference's abs_max weight path); persistable -> the weight
        # set save_int8_inference_model may snapshot as int8 (the
        # reference ConvertToInt8Pass quantizes only weights feeding
        # quantized ops; BN statistics, biases and every other
        # parameter stay fp32)
        names: List[str] = []
        wnames: List[str] = []
        for op in block.ops:
            if op.type not in self.op_types:
                continue
            for slot in self.op_types[op.type]:
                for n in op.inputs.get(slot, []):
                    if not n:
                        continue
                    dst = wnames if n in persistable else names
                    if n not in dst:
                        dst.append(n)
        self.activation_names = names
        self.weight_names = wnames
        # Bounded-memory sampling state: retaining raw activations for
        # every warmup batch is GBs on a real conv net. abs_max keeps a
        # running per-tensor max; KL keeps one fine per-batch |x|
        # histogram (rebinned onto the global amax grid at compute
        # time — max rebinning error is one fine bin, amax/8192).
        self._amax: Dict[str, float] = {n: 0.0 for n in names}
        self._hists: Dict[str, List[Tuple[np.ndarray, float]]] = {
            n: [] for n in names}
        self._seen = False
        self._scales: Optional[Dict[str, float]] = None

    _FINE_BINS = 8192

    def sample(self, feed: Dict[str, np.ndarray]) -> None:
        """Run one warmup batch and record the activation ranges."""
        outs = self.exe.run(self.program, feed=feed,
                            fetch_list=list(self.activation_names),
                            scope=self.scope)
        self._seen = True
        for name, val in zip(self.activation_names, outs):
            a = np.abs(np.asarray(val, dtype=np.float32))
            bmax = float(a.max()) if a.size else 0.0
            self._amax[name] = max(self._amax[name], bmax)
            if self.algo == "KL":
                h, _ = np.histogram(a, bins=self._FINE_BINS,
                                    range=(0, bmax or 1.0))
                self._hists[name].append((h.astype(np.float64), bmax))

    def compute_scales(self) -> Dict[str, float]:
        if not self._seen:
            self._scales = {}
            return {}
        if self.algo == "abs_max":
            self._scales = {n: (m or 1.0) for n, m in self._amax.items()}
            return dict(self._scales)
        scales: Dict[str, float] = {}
        for name, batches in self._hists.items():
            amax = self._amax[name] or 1.0
            hist = np.zeros(2048, np.float64)
            for h, bmax in batches:
                if bmax <= 0:
                    continue
                centers = (np.arange(self._FINE_BINS) + 0.5) * (
                    bmax / self._FINE_BINS)
                idx = np.minimum(
                    (centers / amax * 2048).astype(np.int64), 2047)
                np.add.at(hist, idx, h)
            scales[name] = _kl_from_hist(hist, amax)
        self._scales = scales
        return dict(scales)

    def freeze(self) -> Program:
        """Return a NEW program with static-scale quantize-dequantize
        ops inserted on every calibrated activation edge (the
        QuantizationFreezePass analog: scales are constants baked into
        op attrs, no scale state vars)."""
        if self._scales is None:
            self.compute_scales()
        prog = self.program.clone()
        block = prog.global_block()
        done: Dict[str, str] = {}
        new_ops = []
        for op in block.ops:
            if op.type in self.op_types:
                for slot in self.op_types[op.type]:
                    names = op.inputs.get(slot, [])
                    for i, name in enumerate(names):
                        scale = (self._scales or {}).get(name)
                        if scale is None:
                            continue
                        if name not in done:
                            var = block._find_var_recursive(name)
                            q = unique_name.generate(name + ".calib")
                            block.create_var(
                                name=q, shape=var.shape, dtype="float32",
                                stop_gradient=True)
                            new_ops.append(Operator(
                                block, "quantize_dequantize_static",
                                inputs={"X": [name]},
                                outputs={"Out": [q]},
                                attrs={"scale": float(scale), "bits": 8}))
                            done[name] = q
                        op.inputs[slot][i] = done[name]
            new_ops.append(op)
        block.ops[:] = new_ops
        prog._bump_version()
        return prog


def save_int8_inference_model(dirname: str, feed_names: Sequence[str],
                              fetch_targets, exe,
                              program: Optional[Program],
                              calibrator: Calibrator, scope=None) -> None:
    """Export the int8 serving artifact: the frozen (static-QDQ)
    inference program + int8 weights + scales (reference:
    Calibrator.save_int8_model in int8_inference/utility.py). Weights
    are stored symmetric per-tensor int8 (4x smaller artifact)."""
    from paddle_tpu import io
    from paddle_tpu.executor import global_scope, scope_guard
    from paddle_tpu.slim.quantization import quantize_weights_int8

    if program is not None and program is not calibrator.program:
        raise ValueError(
            "program must be the calibrator's program (the frozen "
            "artifact is built from calibrator.freeze()); pass "
            "program=None or the same object")
    scope = scope or global_scope()
    frozen = calibrator.freeze()
    os.makedirs(dirname, exist_ok=True)
    with scope_guard(scope):
        io.save_inference_model(dirname, list(feed_names), fetch_targets,
                                exe, frozen)
    # int8-snapshot ONLY the weights of quantizable ops (reference
    # ConvertToInt8Pass: conv filters / mul weights). Everything else —
    # BN running mean/variance (tiny dynamic range: symmetric int8
    # crushes small variances to 0 and rsqrt blows up), biases, and any
    # other persistable — stays fp32 in the params file.
    wset = set(calibrator.weight_names)
    qweights = {n: qs for n, qs in quantize_weights_int8(frozen, scope)
                .items() if n in wset}
    np.savez(os.path.join(dirname, "__params_int8__.npz"),
             **{n: q for n, (q, _) in qweights.items()})
    meta = {"weight_scales": {n: s for n, (_, s) in qweights.items()},
            "activation_scales": calibrator._scales or {}}
    with open(os.path.join(dirname, "__int8_scales__.json"), "w") as f:
        json.dump(meta, f)
    # rewrite the fp32 params file without the int8-snapshotted tensors
    ppath = os.path.join(dirname, io._PARAMS_FILE)
    fp32 = np.load(ppath)
    keep = {n: fp32[n] for n in fp32.files if n not in qweights}
    fp32.close()
    np.savez(ppath, **keep)


def load_int8_inference_model(dirname: str, exe, scope=None):
    """Load an int8 artifact: fp32 params (BN stats, biases, anything
    not int8-snapshotted) from the params file, int8 weights dequantized
    via slim.quantization.dequantize_weights; returns (program,
    feed_names, fetch_vars) like io.load_inference_model."""
    from paddle_tpu import io
    from paddle_tpu.executor import global_scope
    from paddle_tpu.slim.quantization import dequantize_weights

    scope = scope or global_scope()
    with open(os.path.join(dirname, io._MODEL_FILE), "rb") as f:
        prog = Program.parse_from_string(f.read())
    with open(os.path.join(dirname, io._META_FILE)) as f:
        io_meta = json.load(f)
    with open(os.path.join(dirname, "__int8_scales__.json")) as f:
        meta = json.load(f)
    ppath = os.path.join(dirname, io._PARAMS_FILE)
    if os.path.exists(ppath):
        fp32 = np.load(ppath)
        for name in fp32.files:
            scope.set(name, fp32[name])
        fp32.close()
    qs = np.load(os.path.join(dirname, "__params_int8__.npz"))
    dequantize_weights(
        {n: (qs[n], meta["weight_scales"][n]) for n in qs.files}, scope)
    fetch_vars = [prog.global_block().var(n)
                  for n in io_meta["fetch_names"]]
    return prog, io_meta["feed_names"], fetch_vars
