"""Post-training activation-range int8 calibration.

Reference: contrib/int8_inference/utility.py (``Calibrator`` — samples
activation tensors over warmup batches, computes per-tensor scales by
abs-max or KL-divergence) and contrib/slim/quantization/
quantization_pass.py:541 (``QuantizationFreezePass``) / :836
(``ConvertToInt8Pass``) — the passes that bake collected ACTIVATION
scales into the inference program and snapshot weights as int8.

TPU-native redesign: the reference rewires an IrGraph into cuDNN/MKLDNN
int8 kernels; on TPU the MXU computes in bf16/fp32 and int8 matmul
kernels are not the serving win — the win is the int8 ARTIFACT (4x
smaller weights) plus faithful int8 serving numerics. So calibration
here produces (a) per-tensor activation scales collected by running
warmup batches through the Executor, (b) an inference program with
STATIC-scale quantize-dequantize ops baked at the quantizable-op
boundaries (serving numerics == int8 deployment, still XLA-fused), and
(c) an int8 weight artifact. ``load_int8_inference_model`` restores the
whole thing into a fresh scope/Predictor.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import unique_name
from paddle_tpu.framework import Operator, Program
from paddle_tpu.slim.quantization import QUANTIZABLE


def _abs_max_scale(samples: List[np.ndarray]) -> float:
    return float(max((np.max(np.abs(s)) for s in samples), default=1.0)) \
        or 1.0


def _kl_scale(samples: List[np.ndarray], bins: int = 2048,
              target_bins: int = 128) -> float:
    """The reference Calibrator's 'KL' algo (utility.py Calibrator:
    minimize KL(P||Q) between the fp32 histogram and its int8-quantized
    rendition; the standard TensorRT-style sweep). Returns the chosen
    clip threshold (the scale)."""
    amax = _abs_max_scale(samples)
    hist = np.zeros(bins, np.float64)
    for s in samples:
        h, _ = np.histogram(np.abs(s), bins=bins, range=(0, amax))
        hist += h
    total = hist.sum()
    if total == 0:
        return amax
    best_div, best_i = np.inf, bins
    for i in range(target_bins, bins + 1, 16):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()          # clip outliers into last bin
        p /= p.sum()
        # quantize the i fp32 bins down to target_bins int8 levels
        factor = i / target_bins
        q = np.zeros(i, np.float64)
        for j in range(target_bins):
            lo, hi = int(j * factor), int((j + 1) * factor)
            hi = max(hi, lo + 1)
            chunk = hist[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        qs = q.sum()
        if qs == 0:
            continue
        q /= qs
        mask = p > 0
        div = float(np.sum(p[mask] * np.log(
            p[mask] / np.maximum(q[mask], 1e-12))))
        if div < best_div:
            best_div, best_i = div, i
    return amax * best_i / bins


class Calibrator:
    """Collects activation ranges for an inference program's quantizable
    op inputs/outputs over warmup batches, then emits the int8-annotated
    program (reference: int8_inference/utility.py Calibrator +
    quantization_pass.py:541 freeze semantics).

    Usage::

        calib = Calibrator(infer_prog, exe, algo="abs_max")
        for batch in warmup_batches:
            calib.sample(feed=batch)            # runs + samples
        scales = calib.compute_scales()
        int8_prog = calib.freeze()              # static-scale QDQ baked
    """

    def __init__(self, program: Program, exe, scope=None,
                 algo: str = "abs_max",
                 op_types: Optional[Iterable[str]] = None):
        if algo not in ("abs_max", "KL"):
            raise ValueError(f"algo must be 'abs_max' or 'KL', got {algo}")
        self.program = program
        self.exe = exe
        self.scope = scope
        self.algo = algo
        self.op_types = dict(QUANTIZABLE) if op_types is None else {
            t: QUANTIZABLE[t] for t in op_types}
        block = program.global_block()
        persistable = {n for n, v in block.vars.items()
                       if getattr(v, "persistable", False)}
        # activation tensors at quantizable boundaries: non-persistable
        # inputs of the quantizable slots (weights get their scale from
        # the tensor itself at freeze time, like the reference's
        # abs_max weight path)
        names: List[str] = []
        for op in block.ops:
            if op.type not in self.op_types:
                continue
            for slot in self.op_types[op.type]:
                for n in op.inputs.get(slot, []):
                    if n and n not in persistable and n not in names:
                        names.append(n)
        self.activation_names = names
        self._samples: Dict[str, List[np.ndarray]] = {n: [] for n in names}
        self._scales: Optional[Dict[str, float]] = None

    def sample(self, feed: Dict[str, np.ndarray]) -> None:
        """Run one warmup batch and record the activation tensors."""
        outs = self.exe.run(self.program, feed=feed,
                            fetch_list=list(self.activation_names),
                            scope=self.scope)
        for name, val in zip(self.activation_names, outs):
            self._samples[name].append(np.asarray(val))

    def compute_scales(self) -> Dict[str, float]:
        fn = _abs_max_scale if self.algo == "abs_max" else _kl_scale
        self._scales = {n: fn(s) for n, s in self._samples.items() if s}
        return dict(self._scales)

    def freeze(self) -> Program:
        """Return a NEW program with static-scale quantize-dequantize
        ops inserted on every calibrated activation edge (the
        QuantizationFreezePass analog: scales are constants baked into
        op attrs, no scale state vars)."""
        if self._scales is None:
            self.compute_scales()
        prog = self.program.clone()
        block = prog.global_block()
        done: Dict[str, str] = {}
        new_ops = []
        for op in block.ops:
            if op.type in self.op_types:
                for slot in self.op_types[op.type]:
                    names = op.inputs.get(slot, [])
                    for i, name in enumerate(names):
                        scale = (self._scales or {}).get(name)
                        if scale is None:
                            continue
                        if name not in done:
                            var = block._find_var_recursive(name)
                            q = unique_name.generate(name + ".calib")
                            block.create_var(
                                name=q, shape=var.shape, dtype="float32",
                                stop_gradient=True)
                            new_ops.append(Operator(
                                block, "quantize_dequantize_static",
                                inputs={"X": [name]},
                                outputs={"Out": [q]},
                                attrs={"scale": float(scale), "bits": 8}))
                            done[name] = q
                        op.inputs[slot][i] = done[name]
            new_ops.append(op)
        block.ops[:] = new_ops
        prog._bump_version()
        return prog


def save_int8_inference_model(dirname: str, feed_names: Sequence[str],
                              fetch_targets, exe,
                              program: Optional[Program],
                              calibrator: Calibrator, scope=None) -> None:
    """Export the int8 serving artifact: the frozen (static-QDQ)
    inference program + int8 weights + scales (reference:
    Calibrator.save_int8_model in int8_inference/utility.py). Weights
    are stored symmetric per-tensor int8 (4x smaller artifact)."""
    from paddle_tpu import io
    from paddle_tpu.executor import global_scope, scope_guard
    from paddle_tpu.slim.quantization import quantize_weights_int8

    if program is not None and program is not calibrator.program:
        raise ValueError(
            "program must be the calibrator's program (the frozen "
            "artifact is built from calibrator.freeze()); pass "
            "program=None or the same object")
    scope = scope or global_scope()
    frozen = calibrator.freeze()
    os.makedirs(dirname, exist_ok=True)
    with scope_guard(scope):
        io.save_inference_model(dirname, list(feed_names), fetch_targets,
                                exe, frozen)
    qweights = quantize_weights_int8(frozen, scope)
    # overwrite the fp32 params with the int8 artifact
    np.savez(os.path.join(dirname, "__params_int8__.npz"),
             **{n: q for n, (q, _) in qweights.items()})
    meta = {"weight_scales": {n: s for n, (_, s) in qweights.items()},
            "activation_scales": calibrator._scales or {}}
    with open(os.path.join(dirname, "__int8_scales__.json"), "w") as f:
        json.dump(meta, f)
    os.remove(os.path.join(dirname, "__params__.npz"))


def load_int8_inference_model(dirname: str, exe, scope=None):
    """Load an int8 artifact: dequantize weights into the scope and
    return (program, feed_names, fetch_vars) like
    io.load_inference_model (the fp32 params file does not exist in an
    int8 artifact, so the weights load from __params_int8__.npz)."""
    from paddle_tpu import io
    from paddle_tpu.executor import global_scope

    scope = scope or global_scope()
    with open(os.path.join(dirname, io._MODEL_FILE), "rb") as f:
        prog = Program.parse_from_string(f.read())
    with open(os.path.join(dirname, io._META_FILE)) as f:
        io_meta = json.load(f)
    with open(os.path.join(dirname, "__int8_scales__.json")) as f:
        meta = json.load(f)
    qs = np.load(os.path.join(dirname, "__params_int8__.npz"))
    for name in qs.files:
        scale = meta["weight_scales"][name]
        scope.set(name, qs[name].astype(np.float32) * scale / 127.0)
    fetch_vars = [prog.global_block().var(n)
                  for n in io_meta["fetch_names"]]
    return prog, io_meta["feed_names"], fetch_vars
