"""Knowledge distillation (reference: contrib/slim/distillation/
distiller.py:25 — soft-label loss between teacher and student logits)."""

from __future__ import annotations

from paddle_tpu import layers


def soft_label_distill_loss(student_logits, teacher_logits,
                            temperature: float = 2.0):
    """KL(teacher || student) at temperature T, scaled by T^2 (the
    standard Hinton correction so gradients match the hard-label scale)."""
    t = float(temperature)
    teacher = layers.softmax(layers.scale(teacher_logits, scale=1.0 / t))
    log_student = layers.log_softmax(
        layers.scale(student_logits, scale=1.0 / t))
    ce = layers.scale(
        layers.reduce_sum(
            layers.elementwise_mul(
                teacher,
                layers.elementwise_sub(
                    layers.log(
                        layers.elementwise_max(
                            teacher,
                            layers.fill_constant_like(teacher, 1e-8))),
                    log_student),
            ),
            dim=-1,
        ),
        scale=t * t,
    )
    return layers.mean(ce)
