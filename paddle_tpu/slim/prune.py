"""Structured (filter) pruning (reference:
contrib/slim/prune/prune_strategy.py:531 UniformPruneStrategy, :635
SensitivePruneStrategy, and prune/pruner.py StructurePruner).

TPU-native design: pruning is a MASK over output channels, chosen by
filter L1 magnitude, applied to the live parameter arrays in the Scope
and re-applied after optimizer steps (``apply_masks``) so pruned
channels stay zero through training. The reference physically shrinks
tensors and rewrites the graph; on TPU, static shapes are the point —
masked channels cost no accuracy and XLA still benefits via weight
sparsity at serialization time (``pruned_ratio`` reports the aggregate
zeroed fraction across the masked parameters).
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

import numpy as np


class StructurePruner:
    """Magnitude pruner: rank output channels (dim 0) by filter L1 norm
    (reference: prune/pruner.py StructurePruner, criterion
    'l1_norm')."""

    def cal_pruned_idx(self, param: np.ndarray, ratio: float) -> np.ndarray:
        n_out = param.shape[0]
        n_prune = int(n_out * ratio)
        if n_prune == 0:
            return np.zeros((0,), np.int64)
        norms = np.abs(param.reshape(n_out, -1)).sum(axis=1)
        return np.argsort(norms)[:n_prune]

    def mask_for(self, param: np.ndarray, ratio: float) -> np.ndarray:
        mask = np.ones((param.shape[0],), param.dtype)
        mask[self.cal_pruned_idx(param, ratio)] = 0
        return mask


def _match_params(scope, pattern: str) -> List[str]:
    rx = re.compile(pattern)
    return [n for n in scope.var_names() if rx.fullmatch(n)]


def compute_masks(scope, ratios: Dict[str, float],
                  pruner: Optional[StructurePruner] = None
                  ) -> Dict[str, np.ndarray]:
    """Per-parameter channel masks ([n_out] 0/1) from live scope values."""
    pruner = pruner or StructurePruner()
    masks = {}
    for name, ratio in ratios.items():
        arr = np.asarray(scope.find_var(name))
        masks[name] = pruner.mask_for(arr, ratio)
    return masks


def apply_masks(scope, masks: Dict[str, np.ndarray]):
    """Zero the pruned output channels in place (call after optimizer
    steps to keep them pruned). Stays on device: scope values are live
    JAX arrays, so the multiply runs as a tiny jit instead of a
    device->host->device round-trip per parameter per batch."""
    import jax.numpy as jnp

    for name, mask in masks.items():
        arr = scope.find_var(name)
        shape = (-1,) + (1,) * (arr.ndim - 1)
        scope.set(name, arr * jnp.asarray(mask).reshape(shape))


def pruned_ratio(scope, masks: Dict[str, np.ndarray]) -> float:
    """Fraction of weights zeroed across the masked parameters."""
    total = kept = 0
    for name, mask in masks.items():
        arr = np.asarray(scope.find_var(name))
        per = arr.size // mask.size
        total += arr.size
        kept += int(mask.sum()) * per
    return 1.0 - kept / max(total, 1)


class UniformPruneStrategy:
    """Prune every matched parameter by the same ratio (reference:
    prune_strategy.py:531).

    Usage::

        strat = UniformPruneStrategy(target_ratio=0.5,
                                     pruned_params="conv.*_w.*")
        strat.on_compression_begin(scope)
        for epoch ...:
            train steps ...
            strat.on_batch_end(scope)      # re-zero pruned channels
    """

    def __init__(self, pruner: Optional[StructurePruner] = None,
                 start_epoch=0, end_epoch=0, target_ratio: float = 0.5,
                 metric_name=None, pruned_params: str = "conv.*_weights"):
        self.pruner = pruner or StructurePruner()
        self.target_ratio = target_ratio
        self.pruned_params = pruned_params
        self.masks: Dict[str, np.ndarray] = {}

    def on_compression_begin(self, scope):
        names = _match_params(scope, self.pruned_params)
        if not names:
            raise ValueError(
                f"no parameters match pattern '{self.pruned_params}'")
        self.masks = compute_masks(
            scope, {n: self.target_ratio for n in names}, self.pruner)
        apply_masks(scope, self.masks)
        return self.masks

    def on_batch_end(self, scope):
        apply_masks(scope, self.masks)


class SensitivePruneStrategy:
    """Per-parameter ratios from a sensitivity sweep (reference:
    prune_strategy.py:635): prune each parameter alone at increasing
    ratios, measure the metric drop with ``eval_fn``, then pick the
    largest per-parameter ratios whose predicted metric loss stays
    within ``max_metric_loss``."""

    def __init__(self, pruner: Optional[StructurePruner] = None,
                 delta_rate: float = 0.2, target_ratio: float = 0.5,
                 pruned_params: str = "conv.*_weights",
                 max_metric_loss: float = 0.05):
        self.pruner = pruner or StructurePruner()
        self.delta_rate = delta_rate
        self.target_ratio = target_ratio
        self.pruned_params = pruned_params
        self.max_metric_loss = max_metric_loss
        self.sensitivities: Dict[str, Dict[float, float]] = {}
        self.masks: Dict[str, np.ndarray] = {}

    def compute_sensitivities(self, scope, eval_fn: Callable[[], float]):
        """eval_fn: metric on the CURRENT scope (higher is better)."""
        names = _match_params(scope, self.pruned_params)
        base = float(eval_fn())
        ratios = [r for r in np.arange(self.delta_rate, 1.0,
                                       self.delta_rate)]
        for name in names:
            backup = np.asarray(scope.find_var(name)).copy()
            curve = {}
            for r in ratios:
                apply_masks(scope,
                            compute_masks(scope, {name: float(r)},
                                          self.pruner))
                curve[float(r)] = base - float(eval_fn())
                scope.set(name, backup.copy())
            self.sensitivities[name] = curve
        return self.sensitivities

    def prune(self, scope, eval_fn: Callable[[], float]):
        if not self.sensitivities:
            self.compute_sensitivities(scope, eval_fn)
        ratios = {}
        for name, curve in self.sensitivities.items():
            ok = [r for r, loss in sorted(curve.items())
                  if loss <= self.max_metric_loss]
            ratios[name] = min(max(ok, default=0.0), self.target_ratio)
        self.masks = compute_masks(
            scope, {n: r for n, r in ratios.items() if r > 0}, self.pruner)
        apply_masks(scope, self.masks)
        return ratios

    def on_batch_end(self, scope):
        apply_masks(scope, self.masks)
