"""Quantization: QAT program pass + post-training int8 weight export.

Reference: contrib/slim/quantization/quantization_pass.py —
``QuantizationTransformPass`` (:41) rewrites the IR graph inserting
fake-quant/dequant pairs on quantizable op inputs and weights;
``ConvertToInt8Pass`` (:836) snapshots trained weights as int8. The
TPU-native redesign operates on the Program op list directly (our graphs
are flat op lists, not C++ ir::Graph), uses dynamic abs-max scales
computed inside the fused XLA step (no moving-average scale state vars to
carry), and bakes the straight-through estimator into the kernel
expression so the mechanical vjp autodiff yields STE gradients for free.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from paddle_tpu import unique_name
from paddle_tpu.framework import Operator, Program

# op type -> input slots to fake-quantize (activations AND weights; the
# reference quantizes both for these compute-heavy ops)
QUANTIZABLE = {
    "mul": ("X", "Y"),
    "matmul": ("X", "Y"),
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
}


class QuantizationTransformPass:
    """Insert fake_quantize_dequantize on quantizable inputs
    (reference: quantization_pass.py:41 ``apply``)."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 quantizable_op_types: Optional[Iterable[str]] = None):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.op_types = (
            dict(QUANTIZABLE)
            if quantizable_op_types is None
            else {t: QUANTIZABLE[t] for t in quantizable_op_types}
        )

    def apply(self, program: Program) -> int:
        """Rewrites ``program`` in place; returns the number of fake-quant
        ops inserted. Apply BEFORE ``append_backward``/``minimize`` so the
        quantization noise participates in training gradients."""
        n_inserted = 0
        block = program.global_block()
        # name -> already-quantized replacement, so shared vars (an
        # activation feeding two matmuls) quantize once
        quantized: Dict[str, str] = {}
        new_ops = []
        for op in block.ops:
            if op.type in self.op_types:
                for slot in self.op_types[op.type]:
                    names = op.inputs.get(slot, [])
                    for i, name in enumerate(names):
                        if not name:
                            continue
                        if name not in quantized:
                            var = block._find_var_recursive(name)
                            if var is None or var.dtype is None:
                                continue
                            q_name = unique_name.generate(name + ".quant")
                            block.create_var(
                                name=q_name,
                                shape=var.shape,
                                dtype="float32",
                                stop_gradient=var.stop_gradient,
                            )
                            qop = Operator(
                                block,
                                "fake_quantize_dequantize",
                                inputs={"X": [name]},
                                outputs={"Out": [q_name]},
                                attrs={"bits": self.weight_bits},
                            )
                            new_ops.append(qop)
                            quantized[name] = q_name
                            n_inserted += 1
                        op.inputs[slot][i] = quantized[name]
            new_ops.append(op)
        block.ops[:] = new_ops
        program._bump_version()
        return n_inserted


def quantize_weights_int8(
    program: Program, scope
) -> Dict[str, Tuple[np.ndarray, float]]:
    """Post-training quantization: snapshot the program's parameters as
    symmetric per-tensor int8 + scale (reference:
    quantization_pass.py:836 ``ConvertToInt8Pass``)."""
    out: Dict[str, Tuple[np.ndarray, float]] = {}
    for p in program.all_parameters():
        v = scope.find_var(p.name)
        if v is None:
            continue
        arr = np.asarray(v)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        scale = float(np.max(np.abs(arr))) or 1.0
        q = np.clip(np.round(arr / scale * 127.0), -127, 127).astype(np.int8)
        out[p.name] = (q, scale)
    return out


def dequantize_weights(
    quantized: Dict[str, Tuple[np.ndarray, float]], scope
) -> None:
    """Load int8 weights back into a scope as dequantized float32."""
    for name, (q, scale) in quantized.items():
        scope.set(name, (q.astype(np.float32) * scale / 127.0))
