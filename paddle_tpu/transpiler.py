"""Inference transpiler: graph rewrites for serving
(reference: python/paddle/fluid/transpiler/inference_transpiler.py —
conv+BN folding; memory_optimization_transpiler.py is subsumed by XLA's
buffer assignment and intentionally has no equivalent here).

``InferenceTranspiler.transpile`` folds each ``batch_norm`` that directly
follows a bias-free ``conv2d``/``depthwise_conv2d`` into the conv weights
plus one bias add:

    w' = w * scale / sqrt(var + eps)
    b' = -mean * scale / sqrt(var + eps) + shift

One fewer normalization per block at inference; on TPU the win is smaller
than on the reference's op-by-op executor (XLA would have fused the BN
arithmetic anyway) but the folded program also drops the BN statistics
from the serving artifact.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.executor import Scope
from paddle_tpu.framework import Program

_FOLDABLE_PRODUCERS = {"conv2d": "Output", "depthwise_conv2d": "Output"}


class InferenceTranspiler:
    def transpile(self, program: Program, scope: Scope) -> int:
        """Folds conv+BN pairs in place (program ops AND scope weights).
        Use on an inference program (``clone(for_test=True)``); returns
        the number of BN ops folded."""
        from paddle_tpu.ir_pattern import BlockGraph, match_chain

        block = program.global_block()
        graph = BlockGraph(block)

        folded = 0
        # chain: conv output feeds ONLY this inference-mode BN (any
        # other consumer would observe the pre-fold activations)
        for p_idx, idx in match_chain(
                graph, tuple(_FOLDABLE_PRODUCERS), "Output",
                "batch_norm", "X",
                second_pred=lambda o: o.attrs.get("is_test", False)):
            p_op, op = block.ops[p_idx], block.ops[idx]
            x_name = op.inputs["X"][0]

            w_name = p_op.inputs["Filter"][0]
            # a filter shared by other ops cannot be folded in place
            if len(graph.consumers.get(w_name, [])) > 1:
                continue
            w = np.asarray(scope.find_var(w_name))
            scale = np.asarray(scope.find_var(op.inputs["Scale"][0]))
            shift = np.asarray(scope.find_var(op.inputs["Bias"][0]))
            mean = np.asarray(scope.find_var(op.inputs["Mean"][0]))
            var = np.asarray(scope.find_var(op.inputs["Variance"][0]))
            eps = op.attrs.get("epsilon", 1e-5)

            inv = scale / np.sqrt(var + eps)
            # conv filter [Cout, Cin/g, kh, kw]: scale per output channel
            scope.set(w_name, (w * inv.reshape(-1, 1, 1, 1)).astype(w.dtype))
            bias = ((-mean) * inv + shift).astype(w.dtype)
            bias_name = w_name + ".bnfold_bias"
            block.create_var(name=bias_name, shape=list(bias.shape),
                             dtype="float32", persistable=True)
            scope.set(bias_name, bias)

            # rewrite: conv writes BN's output; add the folded bias
            y_name = op.outputs["Y"][0]
            from paddle_tpu.framework import Operator

            add = Operator(
                block,
                "elementwise_add",
                inputs={"X": [x_name], "Y": [bias_name]},
                outputs={"Out": [y_name]},
                attrs={"axis": 1},
            )
            block.ops[idx] = add  # replaces the batch_norm in place
            # the BN statistics are dead now — drop their persistable
            # vars so save_persistables/save_inference_model skip them
            # (unless another, unfolded op still consumes them)
            for slot in ("Scale", "Bias", "Mean", "Variance"):
                for dead in op.inputs.get(slot, []):
                    if graph.consumers.get(dead, []) == [idx]:
                        block.vars.pop(dead, None)
            folded += 1

        if folded:
            program._bump_version()
        return folded
