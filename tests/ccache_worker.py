"""Subprocess worker for the cross-process warm-start tests
(tests/test_compile_cache.py): builds the same two-program pair (startup
+ train step) every invocation, runs one startup pass and one train
step with the persistent compile cache pointed at ``argv[1]``, and
prints ONE JSON line with the cache/executor accounting the parent
asserts on.

Determinism contract: the program built here must be content-identical
across processes — that is the property the disk tier keys on.
"""

import json
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import compile_cache, flags, layers, monitor  # noqa: E402


def main():
    cache_dir, report_dir = sys.argv[1], sys.argv[2]
    flags.set_flags({
        "telemetry": True,
        "compile_cache_dir": cache_dir,
        "compile_report_dir": report_dir,
    })
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        loss = layers.mean(layers.fc(x, 4))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        out = exe.run(main_prog,
                      feed={"x": np.ones((2, 8), np.float32)},
                      fetch_list=[loss])
        wout = exe.run_steps(main_prog,
                             feed_list=[{"x": np.ones((2, 8), np.float32)}],
                             steps=2, fetch_list=[loss])
    print(json.dumps({
        "stats": compile_cache.stats(),
        "exec_misses":
            monitor.counter("pt_executor_cache_misses_total").value(),
        "outcomes": [r["cache"] for r in monitor.recent_steps()],
        "loss": float(np.asarray(out[0])),
        "window_loss": float(np.asarray(wout[0])),
    }))


if __name__ == "__main__":
    main()
