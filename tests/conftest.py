"""Test env: simulated 8-device CPU mesh.

The TPU analog of the reference's multi-process-on-localhost distributed
test pattern (reference: tests/unittests/test_dist_base.py:311): sharding
semantics are validated on a virtual CPU mesh (SURVEY.md section 4
implication (c)).

Note: the hosted-TPU ("axon") jax plugin overrides the JAX_PLATFORMS env
var, so platform selection must go through jax.config *after* import but
before backend initialization.
"""

import os

import jax

if os.environ.get("PT_TEST_TPU") == "1":
    # Opt-in real-hardware mode for the TPU-gated kernel tests
    # (tests/test_flash_attention_tpu.py); everything else still passes
    # but runs slowly through the tunnel — use for targeted runs only.
    pass
else:
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax (< 0.5) spells the virtual-device count as an XLA
        # flag; conftest runs before backend init so this still applies
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    # Numeric-gradient checks need f64 reference arithmetic.
    jax.config.update("jax_enable_x64", True)
    # Tests are compile-bound on the CPU backend (hundreds of tiny jits);
    # dialing XLA optimization down trades irrelevant runtime for compile
    # time. Opt out with PT_TEST_FULL_OPT=1 (e.g. for perf-sensitive
    # debugging).
    if os.environ.get("PT_TEST_FULL_OPT") != "1":
        jax.config.update("jax_disable_most_optimizations", True)
    # Persistent compile cache: repeat suite runs skip most XLA compiles
    # (the suite is compile-bound; a warm run is several times faster).
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("PT_TEST_CACHE",
                                     "/tmp/pt_jax_cache_tests"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


# --- suite tiering (VERDICT r4 item 3) ---
#
# Two tiers: the default SMOKE tier is the cross-round regression gate
# (<8 min cold on the builder box; every subsystem keeps at least one
# cheap representative); the FULL tier adds the expensive deep-parity
# tests (multi-axis loss parity, big one-step model compiles, spec
# oracles). Run everything with `pytest --full` or PT_TEST_TIER=full.


def pytest_addoption(parser):
    parser.addoption(
        "--full", action="store_true", default=False,
        help="run the full tier (includes tests marked 'full')")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "full: expensive deep-parity test, excluded from the default "
        "smoke tier (run with --full or PT_TEST_TIER=full)")
    config.addinivalue_line(
        "markers",
        "slow: long-running end-to-end test, excluded from the tier-1 "
        "regression gate (which runs -m 'not slow')")
    config.addinivalue_line(
        "markers",
        "chaos: multi-process chaos drill (fault-plan-driven kills, "
        "re-exec recovery) — deterministic but expensive; deselected "
        "from every default tier, run with -m chaos")
    config.addinivalue_line(
        "markers",
        "serving_e2e: serving-plane end-to-end drill at full slot "
        "counts (continuous batching vs solo-decode parity); the "
        "heavyweight ones also carry 'slow' — select the family with "
        "-m serving_e2e")
    config.addinivalue_line(
        "markers",
        "multidevice_fragile: quarantined under the environment's glibc "
        "heap-corruption crash (seeded by 8-device pjit executions; "
        "reproduces at the seed tree — see ROADMAP watch item). The "
        "corruption is heap-layout-sensitive, so the abort can land "
        "either in a TP-sharded pjit execution itself or in a "
        "downstream test's ordinary allocations; tests where a full "
        "tier-1 run deterministically dies carry this marker. "
        "Deselected by default; run with PT_TEST_MULTIDEVICE=1 or an "
        "explicit -m expression")


def pytest_collection_modifyitems(config, items):
    markexpr = getattr(config.option, "markexpr", "") or ""
    # The multidevice_fragile quarantine applies to EVERY tier: the
    # crash aborts the whole process (no pytest report survives it), so
    # even --full runs skip these unless explicitly opted in.
    drop = set()
    if os.environ.get("PT_TEST_MULTIDEVICE") != "1" and \
            "multidevice_fragile" not in markexpr:
        drop.add("multidevice_fragile")
    # chaos drills spawn whole process fleets: never part of a default
    # tier (including --full); select explicitly with -m chaos
    if "chaos" not in markexpr:
        drop.add("chaos")
    if not (config.getoption("--full")
            or os.environ.get("PT_TEST_TIER") == "full"):
        # default smoke tier drops 'full' AND 'slow' (unless the
        # caller's -m expression names 'slow' explicitly, e.g. `-m slow`
        # to run only the end-to-end tests)
        drop.add("full")
        if "slow" not in markexpr:
            drop.add("slow")
    dropped = [it for it in items if drop & set(it.keywords)]
    if dropped:
        config.hook.pytest_deselected(items=dropped)
        dropped_set = set(dropped)
        items[:] = [it for it in items if it not in dropped_set]
