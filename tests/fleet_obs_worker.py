"""Worker process for the fleet-observability multi-process tests
(harness: tests/test_fleet_observability.py).

Each of N workers trains a tiny local model with telemetry on and
heartbeats after every step; the digest plane piggybacks a registry
digest into fleet KV on each heartbeat (fleet_metrics_interval_ms=0).
Rank 0 serves the monitor endpoint (prints ``OBS_PORT <port>``) and
aggregates the cluster view each step, so the harness can scrape
``/fleet`` and ``/metrics?fleet=1`` live.

The bootstrap is metrics-only: coord KV + heartbeat WITHOUT
``jax.distributed`` — the digest plane needs only the coordination
service, and multiprocess CPU collectives are out of scope for this
jax (the GSPMD training path has its own parity tests).

Drills, selected by env:
- ``PT_FLAGS_fault_plan=executor.step:delay(X)@p1.0`` on one rank: the
  seeded straggler drill (the delay lands in the dispatch phase).
- ``PT_OBS_DIE_RANK``/``PT_OBS_DIE_STEP``: that rank exits abruptly at
  that step (no farewell) — the dead-worker drill.

After its steps every surviving worker idles (heartbeat + publish)
until the harness writes a line to its stdin, so the harness controls
exactly when digests start aging; rank 0 then prints ``OBS_RESULT``
with the final view.

Run: PT_TRAINER_ID=<r> PT_TRAINERS=<n> PT_COORD_ENDPOINT=127.0.0.1:<p>
     python fleet_obs_worker.py
"""

import json
import os
import select
import sys
import time

import jax

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import fleet_monitor, flags, layers, monitor  # noqa: E402
from paddle_tpu import native  # noqa: E402
from paddle_tpu.incubate.fleet import fleet  # noqa: E402
from paddle_tpu.incubate.fleet.fleet_base import _connect_retry  # noqa: E402
from paddle_tpu.incubate.fleet.role_maker import EnvRoleMaker  # noqa: E402

DIM, CLS = 8, 4
STEPS = int(os.environ.get("PT_OBS_STEPS", "30"))
STEP_SLEEP = float(os.environ.get("PT_OBS_STEP_SLEEP", "0.02"))
DIE_RANK = int(os.environ.get("PT_OBS_DIE_RANK", "-1"))
DIE_STEP = int(os.environ.get("PT_OBS_DIE_STEP", "5"))


def build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[DIM], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        logits = layers.fc(x, CLS)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _stdin_has_line() -> bool:
    r, _, _ = select.select([sys.stdin], [], [], 0)
    return bool(r)


def main():
    # step_phases_every_n=1: the straggler drill needs per-step honest
    # walls + phases in every digest window (sampled-phases contract)
    flags.set_flags({"telemetry": True, "fleet_metrics_interval_ms": 0,
                     "step_phases_every_n": 1})
    rank = int(os.environ["PT_TRAINER_ID"])
    host, port = os.environ["PT_COORD_ENDPOINT"].rsplit(":", 1)

    fleet._role = EnvRoleMaker()
    if rank == 0:
        fleet._server = native.CoordServer(int(port))
    fleet._client = _connect_retry(host, int(port), 60_000)
    fleet._initialized = True
    fleet_monitor.attach(fleet)

    main_prog, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()

    if rank == 0:
        srv_port = monitor.serve(0)
        print(f"OBS_PORT {srv_port}", flush=True)

    fleet.barrier("obs/start")
    # seed KV before the first step: compiles can hold a rank's first
    # in-loop heartbeat back for seconds, and an aggregation pass in
    # that window would report the rank missing (or, worse, see a fast
    # peer's digest age past the staleness floor first)
    fleet.heartbeat()
    rng = np.random.RandomState(rank + 1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for step in range(STEPS):
            if rank == DIE_RANK and step == DIE_STEP:
                os._exit(0)  # abrupt death: only the heartbeat age tells
            x = rng.randn(4, DIM).astype(np.float32)
            y = rng.randint(0, CLS, (4, 1)).astype(np.int64)
            exe.run(main_prog, feed={"x": x, "label": y},
                    fetch_list=[loss])
            try:
                fleet.heartbeat()  # piggybacks the digest publish
                if rank == 0:
                    fleet_monitor.aggregate(fleet)
            except OSError:
                # rank 0 tore the coord server down (the harness reaps
                # workers in arbitrary order): wind down cleanly
                break
            time.sleep(STEP_SLEEP)
        # idle under harness control: keep heartbeating/publishing (so
        # live digests stay fresh while the harness scrapes) until a
        # line arrives on stdin
        while not _stdin_has_line():
            try:
                fleet.heartbeat()
                if rank == 0:
                    fleet_monitor.aggregate(fleet)
            except OSError:
                break  # rank 0 tore the coord server down: we're done
            time.sleep(0.05)
    if rank == 0:
        view = fleet_monitor.aggregate(fleet)
        print("OBS_RESULT " + json.dumps(
            {"view": view,
             "stragglers": fleet_monitor.straggler_records()},
            default=str), flush=True)
    fleet.stop_worker()


if __name__ == "__main__":
    main()
