"""Self-healing fleet worker for the failure-recovery test (VERDICT r4
item 6; SURVEY.md §5 failure detection/recovery).

Generation 0: 4 workers train data-parallel; rank 0 checkpoints after
every step; the designated victim (PT_KILL_RANK) dies abruptly at the
start of step PT_KILL_STEP (no farewell — just process exit, so only
its heartbeat going stale reveals the death). The survivors' per-step
``fleet.barrier_or_dead`` (liveness-guarded barrier over csrc/coord.cc
op 'L') returns the dead id instead of hanging in the next psum; they
agree on the shrunk world (surviving old ranks in order), and each
re-execs itself as generation 1 with the pre-provisioned recovery
endpoints.

Generation 1: 3 workers rendezvous fresh, restore the checkpoint, and
finish the remaining steps on 3-way shards of the SAME global batches —
so the harness can assert loss parity against an uninterrupted
single-process run of the whole schedule.

Run (harness: tests/test_fleet_recovery.py):
  PT_TRAINER_ID=r PT_TRAINERS=4 PT_COORD_ENDPOINT=127.0.0.1:p
  PT_RECOVER_PORT=p2 PT_RECOVER_JAX_PORT=p3 PT_CKPT_DIR=dir
  PT_KILL_RANK=3 PT_KILL_STEP=2 python fleet_recover_worker.py
"""

import json
import os
import sys

import jax

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        # older jax (< 0.5): virtual-device count is an XLA flag
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import io, layers  # noqa: E402
from paddle_tpu.incubate.fleet import fleet  # noqa: E402

GLOBAL_BATCH = 24
STEPS = 6
DIM, HID, CLS = 16, 32, 4


def deterministic_params():
    r = np.random.RandomState(11)
    return (
        r.normal(0, 0.1, (DIM, HID)).astype(np.float32),
        np.zeros(HID, np.float32),
        r.normal(0, 0.1, (HID, CLS)).astype(np.float32),
        np.zeros(CLS, np.float32),
    )


def global_batches():
    rng = np.random.RandomState(3)
    probe = np.random.RandomState(5).randn(DIM, CLS)
    out = []
    for _ in range(STEPS):
        x = rng.randn(GLOBAL_BATCH, DIM).astype(np.float32)
        y = np.argmax(x @ probe, 1).astype(np.int64)[:, None]
        out.append((x, y))
    return out


def build():
    w1, b1, w2, b2 = deterministic_params()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[DIM], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(
            img, HID, act="relu",
            param_attr=fluid.ParamAttr(
                name="w1",
                initializer=fluid.initializer.NumpyArrayInitializer(w1)),
            bias_attr=fluid.ParamAttr(
                name="b1",
                initializer=fluid.initializer.NumpyArrayInitializer(b1)),
        )
        logits = layers.fc(
            h, CLS,
            param_attr=fluid.ParamAttr(
                name="w2",
                initializer=fluid.initializer.NumpyArrayInitializer(w2)),
            bias_attr=fluid.ParamAttr(
                name="b2",
                initializer=fluid.initializer.NumpyArrayInitializer(b2)),
        )
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1))
        opt.minimize(loss)
    return main, startup, loss


def _reexec_shrunk(dead_ids, resume_step):
    """Agree on the shrunk world and re-exec as generation 1."""
    n = fleet.worker_num()
    me = fleet.worker_index()
    dead_ranks = {int(d.split("-")[1]) for d in dead_ids}
    survivors = [r for r in range(n) if r not in dead_ranks]
    new_rank = survivors.index(me)
    host = os.environ["PT_COORD_ENDPOINT"].rsplit(":", 1)[0]
    env = dict(os.environ)
    env.update({
        "PT_TRAINER_ID": str(new_rank),
        "PT_TRAINERS": str(len(survivors)),
        "PT_COORD_ENDPOINT": f"{host}:{os.environ['PT_RECOVER_PORT']}",
        "PT_JAX_COORD_ENDPOINT":
            f"{host}:{os.environ['PT_RECOVER_JAX_PORT']}",
        "PT_GEN": "1",
        "PT_RESUME_STEP": str(resume_step),
        "PT_DEAD_SEEN": ",".join(sorted(dead_ids)),
    })
    fleet.stop_worker()
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)],
              env)


def main():
    gen = int(os.environ.get("PT_GEN", "0"))
    kill_rank = int(os.environ.get("PT_KILL_RANK", "-1"))
    kill_step = int(os.environ.get("PT_KILL_STEP", "2"))
    ckpt = os.environ["PT_CKPT_DIR"]

    fleet.init()
    rank, n = fleet.worker_index(), fleet.worker_num()

    main_prog, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    start_step = 0
    if gen == 1:
        start_step = int(os.environ["PT_RESUME_STEP"])
        io.load_persistables(exe, ckpt, main_prog)
    compiled = fleet.compiled_program(main_prog)

    shard = GLOBAL_BATCH // n
    losses = []
    batches = global_batches()
    for i in range(start_step, STEPS):
        if gen == 0 and rank == kill_rank and i == kill_step:
            os._exit(1)  # abrupt death: no farewell, heartbeat goes stale
        dead = fleet.barrier_or_dead(f"step{i}-g{gen}", max_age_ms=1500)
        if dead:
            _reexec_shrunk(dead, resume_step=i)
        x, y = batches[i]
        xs = x[rank * shard:(rank + 1) * shard]
        ys = y[rank * shard:(rank + 1) * shard]
        out = exe.run(compiled, feed={"img": xs, "label": ys},
                      fetch_list=[loss])
        losses.append(float(out[0]))
        fleet.heartbeat()
        if rank == 0:
            io.save_persistables(exe, ckpt, main_prog)
            with open(os.path.join(ckpt, "meta.json"), "w") as f:
                json.dump({"next_step": i + 1}, f)

    print("FLEET_RESULT " + json.dumps({
        "rank": rank, "gen": gen, "world": n, "start_step": start_step,
        "dead_seen": os.environ.get("PT_DEAD_SEEN", "").split(",")
        if os.environ.get("PT_DEAD_SEEN") else [],
        "losses": losses}), flush=True)
    fleet.barrier(f"done-g{gen}")
    fleet.stop_worker()


if __name__ == "__main__":
    main()
