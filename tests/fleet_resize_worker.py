"""Elastic-resize fleet worker for the shrink AND grow chaos drills
(ISSUE 7 8->4 shrink; ISSUE 14 4->8 scale-OUT; SURVEY.md §5 failure
detection/recovery + ROADMAP item 3 elastic resize).

SHRINK (ISSUE 7): generation 0: 8 workers train; EVERY worker
participates in the per-step coordinated checkpoint save (the
multi-host commit barrier: non-zero ranks write their manifest fragment
+ shard file, ack over the fleet KV, rank 0 publishes only after all
acks). The victims die at the start of a chosen step, driven by a
SEEDED fault plan (`elastic.step:raise@N` via PT_FLAGS_fault_plan, so
the chaos run replays exactly); only their heartbeats going stale
reveals the deaths. Survivors' ``fleet.barrier_or_dead`` returns the
dead ids; each derives the SAME shrunk world via ``fleet.plan_resize``
and re-execs itself through ``fleet.reexec_resized`` (generation 1,
pre-provisioned recovery endpoints).

GROW (ISSUE 14): generation 0: 4 workers train. Newcomer processes
(PT_JOIN_ID set) announce themselves against the RUNNING world through
``fleet.join_world`` — the generation-keyed join protocol over fleet
KV — and wait for the leader's published plan. At PT_GROW_AT_STEP the
incumbents settle the announced joiner set (``fleet.settle_joins``,
same stability-window agreement settle_dead uses), derive the grown
world (``plan_resize(joins=...)``, survivors keep relative order,
joiners take the ranks after them), rank 0 publishes the plan +
recovery endpoints for the joiners, and EVERYONE re-execs to
generation 1. The 8-worker generation restores the newest valid
4-writer checkpoint — optimizer slot state re-keyed through
``checkpoint.reshard_optimizer_state`` — and, with
PT_FLAGS_compile_cache_dir set, warm-starts every executable from the
persistent compile cache (zero fresh compiles on rejoin: the
generation-0 incumbents populated the disk tier, and the owning-shard
topology key is world-size independent for local executables).

Generation 1 (both drills): workers rendezvous fresh, restore the
newest VALID checkpoint via ``checkpoint.load_latest`` and finish the
remaining steps, so the harness can assert loss parity against an
uninterrupted single-process run.

Compute is REPLICATED (every worker runs the full global batch on its
local device): this environment's jax/CPU build cannot execute
multiprocess XLA computations (the same pre-existing wall behind the
test_fleet/test_fleet_recovery parity failures), and the drills'
subject is the host-side recovery plane — seeded kill, stale-heartbeat
detection, join announcement/settling, resize agreement, re-exec,
commit barrier, cross-world restore, compile-cache warm start.
Bit-exact SHARDED save-on-A/restore-on-B — parameters AND optimizer
slot state — is proven in-process by the mesh matrices in
tests/test_checkpoint.py.

Run (harness: tests/test_elastic_resize.py):
  PT_TRAINER_ID=r PT_TRAINERS=8 PT_COORD_ENDPOINT=127.0.0.1:p
  PT_RECOVER_PORT=p2 PT_RECOVER_JAX_PORT=p3 PT_CKPT_DIR=dir
  PT_FLAGS_fault_plan='elastic.step:raise@3'  # shrink victims only
  PT_GROW_AT_STEP=2 PT_EXPECT_JOINERS=4       # grow incumbents only
  PT_JOIN_ID=j PT_JOIN_TARGET=127.0.0.1:p     # grow joiners only
  python fleet_resize_worker.py
"""

import json
import os

import jax

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 1)
    except AttributeError:
        # older jax (< 0.5): virtual-device count is an XLA flag
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=1")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import compile_cache, faults, layers  # noqa: E402
from paddle_tpu.executor import global_scope  # noqa: E402
from paddle_tpu.incubate.fleet import fleet  # noqa: E402
from paddle_tpu.parallel import checkpoint as ckpt  # noqa: E402

GLOBAL_BATCH = 24
STEPS = 6
DIM, HID, CLS = 16, 32, 4

# the victims' seeded fault plan raises here (PT_FLAGS_fault_plan armed
# the site at import); survivors' plans are empty
_F_STEP = faults.site("elastic.step")


def deterministic_params():
    r = np.random.RandomState(11)
    return (
        r.normal(0, 0.1, (DIM, HID)).astype(np.float32),
        np.zeros(HID, np.float32),
        r.normal(0, 0.1, (HID, CLS)).astype(np.float32),
        np.zeros(CLS, np.float32),
    )


def global_batches():
    rng = np.random.RandomState(3)
    probe = np.random.RandomState(5).randn(DIM, CLS)
    out = []
    for _ in range(STEPS):
        x = rng.randn(GLOBAL_BATCH, DIM).astype(np.float32)
        y = np.argmax(x @ probe, 1).astype(np.int64)[:, None]
        out.append((x, y))
    return out


def build():
    w1, b1, w2, b2 = deterministic_params()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[DIM], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(
            img, HID, act="relu",
            param_attr=fluid.ParamAttr(
                name="w1",
                initializer=fluid.initializer.NumpyArrayInitializer(w1)),
            bias_attr=fluid.ParamAttr(
                name="b1",
                initializer=fluid.initializer.NumpyArrayInitializer(b1)),
        )
        logits = layers.fc(
            h, CLS,
            param_attr=fluid.ParamAttr(
                name="w2",
                initializer=fluid.initializer.NumpyArrayInitializer(w2)),
            bias_attr=fluid.ParamAttr(
                name="b2",
                initializer=fluid.initializer.NumpyArrayInitializer(b2)),
        )
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        # Momentum, not SGD: velocity slot state makes the resumed-loss
        # parity assert prove optimizer-state survival across the resize
        opt = fluid.optimizer.Momentum(0.1, momentum=0.9)
        opt.minimize(loss)
    return main, startup, loss, opt


def main():
    gen = fleet.generation()
    ckpt_dir = os.environ["PT_CKPT_DIR"]

    join_id = os.environ.get("PT_JOIN_ID")
    if join_id is not None and gen == 0:
        # NEWCOMER: announce against the running generation-0 world and
        # wait for the leader's plan; then re-exec as a full member of
        # generation 1 (complete EnvRoleMaker env from the plan)
        spec = fleet.join_world(os.environ["PT_JOIN_TARGET"],
                                join_id=int(join_id), timeout_ms=120_000)
        print("JOIN_RESULT " + json.dumps({
            "join_id": int(join_id), "rank": spec["rank"],
            "world": spec["world"],
            "join_latency_s": spec["join_latency_s"]}), flush=True)
        fleet.reexec_resized(spec,
                             coord_endpoint=spec["coord_endpoint"],
                             jax_endpoint=spec.get("jax_endpoint"))

    fleet.init()
    rank, n = fleet.worker_index(), fleet.worker_num()

    main_prog, startup, loss, opt = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    slots = opt.slot_descriptor()

    start_step = 0
    if gen == 1:
        # cross-world restore: serials were committed by the OTHER-SIZED
        # world (one manifest fragment + shard file per old rank);
        # load_latest reassembles them regardless of who saved, and
        # optimizer slot state is re-keyed onto THIS build's slot names
        # (identity here — the drift matrix is tests/test_checkpoint.py)
        loaded = ckpt.load_latest(ckpt_dir)
        assert loaded is not None, "no valid checkpoint after resize"
        start_step = loaded[0]
        values = ckpt.reshard_optimizer_state(
            loaded[1], ckpt.manifest_slots(ckpt_dir, start_step), slots)
        scope = global_scope()
        for k, v in values.items():
            scope.set(k, v)

    host = os.environ["PT_COORD_ENDPOINT"].rsplit(":", 1)[0]
    grow_at = os.environ.get("PT_GROW_AT_STEP")
    losses = []
    batches = global_batches()
    for i in range(start_step, STEPS):
        try:
            _F_STEP.hit()  # victims' seeded plan kills them HERE
        except faults.InjectedFault:
            os._exit(1)  # abrupt death: heartbeat goes stale, no farewell
        if gen == 0 and grow_at is not None and i == int(grow_at):
            # INCUMBENT at the grow step: settle the announced joiner
            # set, derive the grown world, leader publishes the plan
            # (and holds the coord server up until every joiner acked),
            # everyone re-execs to generation 1
            joins = fleet.settle_joins(
                max_age_ms=1500,
                min_count=int(os.environ.get("PT_EXPECT_JOINERS", "1")))
            spec = fleet.plan_resize((), joins=joins)
            coord_ep = f"{host}:{os.environ['PT_RECOVER_PORT']}"
            jax_ep = f"{host}:{os.environ['PT_RECOVER_JAX_PORT']}"
            if fleet.is_first_worker():
                fleet.publish_join_plan(spec, coord_endpoint=coord_ep,
                                        jax_endpoint=jax_ep)
            from paddle_tpu.incubate.fleet.fleet_base import (
                resize_direction,
            )
            print("RESIZE_PLAN " + json.dumps({
                "rank": rank, "direction": resize_direction(spec),
                "world": spec["world"], "joins": joins}), flush=True)
            fleet.reexec_resized(spec, coord_endpoint=coord_ep,
                                 jax_endpoint=jax_ep)
        dead = fleet.barrier_or_dead(f"step{i}-g{gen}", max_age_ms=1500)
        if dead:
            # simultaneous deaths go stale at different poll instants:
            # settle + agree on ONE dead set before planning the world
            dead = fleet.settle_dead(dead, max_age_ms=1500)
            spec = fleet.plan_resize(dead)
            fleet.reexec_resized(
                spec,
                coord_endpoint=f"{host}:{os.environ['PT_RECOVER_PORT']}",
                jax_endpoint=f"{host}:{os.environ['PT_RECOVER_JAX_PORT']}",
                extra_env={"PT_DEAD_SEEN": ",".join(
                    sorted(str(d) for d in dead))},
            )
        x, y = batches[i]
        out = exe.run(main_prog, feed={"img": x, "label": y},
                      fetch_list=[loss])
        losses.append(float(out[0]))
        fleet.heartbeat()
        # EVERY rank joins the coordinated save (commit barrier): rank 0
        # publishes only after all acks, so a committed serial always
        # holds every writer's fragments. The manifest records the slot
        # descriptors so a differently-built restore can re-key them.
        ckpt.save_scope(ckpt_dir, step=i + 1, slots=slots)

    result = {
        "rank": rank, "gen": gen, "world": n, "start_step": start_step,
        "dead_seen": os.environ.get("PT_DEAD_SEEN", "").split(",")
        if os.environ.get("PT_DEAD_SEEN") else [],
        "losses": losses}
    if compile_cache.active():
        # the grow drill's warm-start accounting: generation 1 must
        # resolve every executable from the disk tier (zero fresh
        # compiles on rejoin)
        st = compile_cache.stats()
        result["ccache"] = {"hits": st["hits"], "misses": st["misses"],
                            "errors": st["errors"]}
    print("FLEET_RESULT " + json.dumps(result), flush=True)
    fleet.barrier(f"done-g{gen}")
    fleet.stop_worker()


if __name__ == "__main__":
    main()
