"""Subprocess worker for the warm fleet spin-up drill
(tests/test_fleet_serving.py): one fresh "fleet host" process that

1. starts a single-replica ServingFleet with the persistent compile
   cache armed (``compile_cache_dir`` flag) and serves two requests,
2. scales OUT by one replica (the autoscaler's spin-up path) and
   serves two more through the router,

and prints ONE JSON line with the compile-cache accounting and the
token streams. The in-process claim: the scaled-up replica shares the
fleet's geometry, so its prefill + decode executables resolve from the
cache the first replica just populated — the spin-up itself adds ZERO
disk-tier misses even on a cold cache. Run the worker twice against
the same cache dir and the second (warm) process must resolve EVERY
executable from disk — misses == 0 — with byte-identical tokens: the
cross-host warm-start contract fleet autoscaling rides.

Determinism contract (same as tests/serving_worker.py): every program
built here must be content-identical across processes.
"""

import json
import os
import sys

# A serving fleet host is a single-device process. Scrub the parent
# test session's virtual-8-device XLA flag (tests/conftest.py) BEFORE
# backend init: the multi-device CPU path is the environment's known
# glibc-heap-corruption territory (ROADMAP watch item) and has no
# business in this worker.
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import (  # noqa: E402
    compile_cache,
    fleet_serving,
    flags,
)
from paddle_tpu.models import transformer as T  # noqa: E402


def main():
    cache_dir = sys.argv[1]
    flags.set_flags({"telemetry": True, "compile_cache_dir": cache_dir})

    cfg = T.TransformerConfig(
        src_vocab_size=37, trg_vocab_size=41, max_length=64, d_model=16,
        d_inner=32, n_head=2, n_layer=1, dropout=0.0,
        label_smooth_eps=0.0)
    scope = fluid.Scope()
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)

    fleet = fleet_serving.ServingFleet(
        cfg, scope, replicas=1, slots=2, src_len=8, max_len=10,
        poll_s=0.005)
    r1 = fleet.submit([5, 6, 7])
    r2 = fleet.submit([9, 4])
    cold_tokens = [r1.result(timeout=120), r2.result(timeout=120)]

    # the autoscaler's spin-up path: the new replica must resolve its
    # prefill + decode executables from the cache the first replica
    # populated — zero NEW disk-tier misses
    misses0 = compile_cache.stats()["misses"]
    fleet._spawn_replica()
    r3 = fleet.submit([5, 6, 7])
    r4 = fleet.submit([9, 4])
    scaled_tokens = [r3.result(timeout=120), r4.result(timeout=120)]
    spinup_misses = compile_cache.stats()["misses"] - misses0
    replica_count = fleet.stats()["replica_count"]
    fleet.close()

    print(json.dumps({
        "stats": compile_cache.stats(),
        "spinup_misses": spinup_misses,
        "replica_count": replica_count,
        "tokens": [[int(t) for t in s] for s in cold_tokens],
        "scaled_tokens": [[int(t) for t in s] for s in scaled_tokens],
    }))


if __name__ == "__main__":
    main()
