"""Worker process for the fleet 2-process localhost test
(the analog of the reference's dist_mnist.py trainer script spawned by
tests/unittests/test_dist_base.py:311). Prints per-step losses as one JSON
line on stdout; the harness asserts parity against a single-process run.

Run: PT_TRAINER_ID=<r> PT_TRAINERS=2 PT_COORD_ENDPOINT=127.0.0.1:<p> \
     python fleet_worker.py
"""

import json
import os

import jax

if __name__ == "__main__":
    # Only when run as a worker process — the test harness also imports
    # this module (for build()/global_batches()) inside a pytest process
    # whose jax backend is already configured.
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        # older jax (< 0.5): virtual-device count is an XLA flag
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import layers  # noqa: E402
from paddle_tpu.incubate.fleet import fleet  # noqa: E402

GLOBAL_BATCH = 32
STEPS = 3
DIM, HID, CLS = 16, 32, 4


def deterministic_params():
    r = np.random.RandomState(11)
    return (
        r.normal(0, 0.1, (DIM, HID)).astype(np.float32),
        np.zeros(HID, np.float32),
        r.normal(0, 0.1, (HID, CLS)).astype(np.float32),
        np.zeros(CLS, np.float32),
    )


def global_batches():
    rng = np.random.RandomState(3)
    probe = np.random.RandomState(5).randn(DIM, CLS)
    out = []
    for _ in range(STEPS):
        x = rng.randn(GLOBAL_BATCH, DIM).astype(np.float32)
        y = np.argmax(x @ probe, 1).astype(np.int64)[:, None]
        out.append((x, y))
    return out


def build():
    w1, b1, w2, b2 = deterministic_params()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[DIM], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(
            img, HID, act="relu",
            param_attr=fluid.ParamAttr(
                name="w1",
                initializer=fluid.initializer.NumpyArrayInitializer(w1)),
            bias_attr=fluid.ParamAttr(
                name="b1",
                initializer=fluid.initializer.NumpyArrayInitializer(b1)),
        )
        logits = layers.fc(
            h, CLS,
            param_attr=fluid.ParamAttr(
                name="w2",
                initializer=fluid.initializer.NumpyArrayInitializer(w2)),
            bias_attr=fluid.ParamAttr(
                name="b2",
                initializer=fluid.initializer.NumpyArrayInitializer(b2)),
        )
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1))
        opt.minimize(loss)
    return main, startup, loss


def main():
    fleet.init()
    rank, n = fleet.worker_index(), fleet.worker_num()
    assert jax.device_count() == 2 * n, jax.devices()

    main_prog, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    compiled = fleet.compiled_program(main_prog)

    shard = GLOBAL_BATCH // n
    losses = []
    for x, y in global_batches():
        xs = x[rank * shard : (rank + 1) * shard]
        ys = y[rank * shard : (rank + 1) * shard]
        out = exe.run(compiled, feed={"img": xs, "label": ys},
                      fetch_list=[loss])
        losses.append(float(out[0]))
        fleet.heartbeat()

    assert fleet.dead_workers(max_age_ms=60_000) == []
    fleet.barrier("done")
    print("FLEET_RESULT " + json.dumps({"rank": rank, "losses": losses}),
          flush=True)
    fleet.stop_worker()


if __name__ == "__main__":
    main()
