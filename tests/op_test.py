"""OpTest harness: run one op and check outputs + numeric gradients.

Re-creation of the reference's per-op test harness
(reference: python/paddle/fluid/tests/unittests/op_test.py:45-82
``get_numeric_gradient`` / ``check_output`` / ``check_grad``): builds a
single-op program, compares the kernel against a numpy reference, and
validates the auto-derived grad kernel against central finite differences.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


class OpHarness:
    def __init__(
        self,
        op_type: str,
        inputs: Dict[str, np.ndarray],
        attrs: Optional[dict] = None,
        out_slots: Sequence[str] = ("Out",),
        multi_input_slots: Sequence[str] = (),
    ):
        self.op_type = op_type
        self.inputs = {
            k: (
                [np.asarray(x) for x in v]
                if k in multi_input_slots
                else [np.asarray(v)]
            )
            for k, v in inputs.items()
        }
        self.attrs = attrs or {}
        self.out_slots = list(out_slots)

    def _build(self, with_grad: bool, grad_wrt: Sequence[str]):
        main, startup = fluid.Program(), fluid.Program()
        feed = {}
        with fluid.program_guard(main, startup):
            in_vars = {}
            for slot, arrs in self.inputs.items():
                vs = []
                for i, a in enumerate(arrs):
                    name = f"{slot.lower()}_{i}"
                    v = main.global_block().create_var(
                        name=name,
                        shape=a.shape,
                        dtype=a.dtype.name,
                        stop_gradient=not np.issubdtype(a.dtype, np.floating),
                    )
                    feed[name] = a
                    vs.append(v)
                in_vars[slot] = vs
            out_vars = {
                slot: main.global_block().create_var(
                    name=f"out_{slot.lower()}", dtype="float32"
                )
                for slot in self.out_slots
            }
            main.global_block().append_op(
                self.op_type,
                inputs={k: v for k, v in in_vars.items()},
                outputs={k: [v] for k, v in out_vars.items()},
                attrs=dict(self.attrs),
            )
            fetch = [out_vars[s] for s in self.out_slots]
            grad_fetch = []
            if with_grad:
                # Scalar objective: sum of fixed pseudo-random projections of
                # each float output (catches grads a plain mean would miss).
                proj = []
                rng = np.random.RandomState(1234)
                outs0 = self.forward()
                for s, o0 in zip(self.out_slots, outs0):
                    if not np.issubdtype(o0.dtype, np.floating):
                        continue
                    w = rng.uniform(0.1, 1.0, o0.shape).astype(o0.dtype)
                    wv = layers.assign(w)
                    proj.append(
                        layers.reduce_sum(
                            layers.elementwise_mul(out_vars[s], wv)
                        )
                    )
                self._proj_weights = rng
                loss = proj[0] if len(proj) == 1 else layers.sums(proj)
                loss = layers.reshape(loss, [1])
                fluid.append_backward(loss, parameter_list=[])
                for name in grad_wrt:
                    g = name + "@GRAD"
                    grad_fetch.append(g)
        return main, startup, feed, fetch, grad_fetch

    def forward(self) -> List[np.ndarray]:
        main, startup, feed, fetch, _ = self._build(False, [])
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch)

    def check_output(self, expected: Dict[str, np.ndarray], atol=1e-5, rtol=1e-4):
        outs = self.forward()
        for slot, exp in expected.items():
            got = outs[self.out_slots.index(slot)]
            np.testing.assert_allclose(
                got, exp, atol=atol, rtol=rtol,
                err_msg=f"{self.op_type} output {slot} mismatch",
            )

    def _objective(self, feed) -> float:
        """Scalar objective used for numeric gradients (same projections)."""
        outs = self._fwd_exe.run(self._fwd_main, feed=feed, fetch_list=self._fwd_fetch)
        rng = np.random.RandomState(1234)
        total = 0.0
        for o in outs:
            o = np.asarray(o)
            if not np.issubdtype(o.dtype, np.floating):
                continue
            w = rng.uniform(0.1, 1.0, o.shape).astype(o.dtype)
            total += float(np.sum(o.astype(np.float64) * w))
        return total

    def check_grad(
        self,
        wrt: Sequence[str],  # feed names like "x_0"
        delta: float = 1e-3,
        atol: float = 1e-4,
        rtol: float = 2e-3,
    ):
        main, startup, feed, fetch, grad_fetch = self._build(True, wrt)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=list(fetch) + grad_fetch)
        analytic = res[len(fetch):]

        # forward-only program for numeric diff
        self._fwd_main, fs, _, self._fwd_fetch, _ = self._build(False, [])
        self._fwd_exe = fluid.Executor(fluid.CPUPlace())
        self._fwd_exe.run(fs)

        for name, a_grad in zip(wrt, analytic):
            x = feed[name].astype(np.float64)
            num = np.zeros_like(x)
            flat = x.reshape(-1)
            nflat = num.reshape(-1)
            for i in range(flat.size):
                orig = flat[i]
                flat[i] = orig + delta
                f_plus = self._objective({**feed, name: x.astype(feed[name].dtype)})
                flat[i] = orig - delta
                f_minus = self._objective({**feed, name: x.astype(feed[name].dtype)})
                flat[i] = orig
                nflat[i] = (f_plus - f_minus) / (2 * delta)
            np.testing.assert_allclose(
                a_grad.astype(np.float64).reshape(-1),
                nflat,
                atol=atol,
                rtol=rtol,
                err_msg=f"{self.op_type} grad wrt {name} mismatch",
            )
