"""Subprocess worker for the warm-replica serving tests
(tests/test_serving.py): one fresh "serving replica" process that

1. serves a saved inference model through the Predictor surface
   (``Config.enable_compile_cache`` routes it through the persistent
   compile cache; ``close()`` releases its compiled entries), then
2. spins a tiny-transformer ServingEngine and decodes two requests
   through the prefill + single-token-decode program pair,

and prints ONE JSON line with the compile-cache/executor accounting the
parent asserts on. Run twice against the same cache dir, the second
(warm) replica must resolve every executable from disk — zero fresh XLA
compiles — and emit byte-identical tokens.

Determinism contract (same as tests/ccache_worker.py): every program
built here must be content-identical across processes.
"""

import json
import os
import sys

# A serving replica is a single-device process. Scrub the parent test
# session's virtual-8-device XLA flag (tests/conftest.py) BEFORE backend
# init: the multi-device CPU path is the environment's known
# glibc-heap-corruption territory (ROADMAP watch item) and has no
# business in this worker.
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import (  # noqa: E402
    compile_cache,
    flags,
    inference,
    monitor,
    serving,
)
from paddle_tpu.models import transformer as T  # noqa: E402


def main():
    cache_dir, model_dir = sys.argv[1], sys.argv[2]
    flags.set_flags({"telemetry": True})

    # --- the Predictor surface of the replica ---
    pred = inference.create_predictor(
        inference.Config(model_dir).disable_tpu()
        .enable_compile_cache(cache_dir).set_batch_buckets([4]))
    x = np.linspace(-1.0, 1.0, 4 * 16, dtype=np.float32).reshape(4, 16)
    (probs,) = pred.run([x])
    pred_entries = len(pred._exe._cache)
    pred.close()
    closed_entries = len(pred._exe._cache)

    # --- the continuous-batching engine of the replica ---
    cfg = T.TransformerConfig(
        src_vocab_size=37, trg_vocab_size=41, max_length=64, d_model=16,
        d_inner=32, n_head=2, n_layer=1, dropout=0.0,
        label_smooth_eps=0.0)
    scope = fluid.Scope()
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8, max_len=8)
    r1 = eng.submit([5, 6, 7])
    r2 = eng.submit([9, 4])
    eng.run_until_idle()
    eng.close()

    print(json.dumps({
        "stats": compile_cache.stats(),
        "exec_misses":
            monitor.counter("pt_executor_cache_misses_total").value(),
        "outcomes": [r["cache"] for r in monitor.recent_steps()],
        "pred_entries": pred_entries,
        "closed_entries": closed_entries,
        "probs_sum": float(np.asarray(probs).sum()),
        "tokens": [[int(t) for t in r1.tokens],
                   [int(t) for t in r2.tokens]],
    }))


if __name__ == "__main__":
    main()
