"""bf16 AMP correctness: activation stream runs in bf16, master weights
stay f32, and the loss trajectory tracks the f32 run.

Covers the trace-time cast policy in core/lowering.py (AMP_OP_TYPES /
AMP_FLOW_OP_TYPES) that otherwise only executes on the TPU bench host.
"""

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.core import lowering
from paddle_tpu.models import transformer as T


CFG = T.TransformerConfig(
    src_vocab_size=64, trg_vocab_size=64, d_model=32, d_inner=64,
    n_head=4, n_layer=2, max_length=32, dropout=0.0,
)


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = T.build(CFG, is_test=False)
        fluid.optimizer.Adam(1e-3).minimize(model["loss"])
    return main, startup, model


def _run(amp, n_steps=4):
    main, startup, model = _build()
    main._amp = amp
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    losses = []
    for i in range(n_steps):
        feed = T.make_batch(CFG, batch=8, src_len=16, trg_len=16, seed=i)
        out = exe.run(main, feed=feed, fetch_list=[model["loss"]],
                      scope=scope)
        losses.append(float(out[0]))
    return losses, scope, main, model


import pytest


@pytest.fixture(scope="module")
def amp_run():
    # one bf16 compile+run shared by the trajectory and master-weight
    # tests (each _run costs a full transformer compile on the CPU
    # backend)
    return _run(amp=True)


def test_amp_loss_tracks_f32(amp_run):
    f32, _, _, _ = _run(amp=False)
    bf16, _, _, _ = amp_run
    assert all(np.isfinite(bf16)), bf16
    # same trajectory within bf16 noise
    np.testing.assert_allclose(f32, bf16, rtol=0.05, atol=0.05)
    assert bf16[-1] < bf16[0]  # still learning


def test_amp_master_weights_stay_f32(amp_run):
    _, scope, main, _ = amp_run
    for p in main.all_parameters():
        v = scope.find_var(p.name)
        assert v is not None
        assert jnp.asarray(v).dtype == jnp.float32, p.name


def test_amp_stream_is_bf16():
    """The lowered computation must actually contain bf16 matmuls — guards
    against a flow op silently promoting the stream back to f32."""
    main, startup, model = _build()
    main._amp = True
    feed = T.make_batch(CFG, batch=8, src_len=16, trg_len=16, seed=0)
    feed_names = sorted(feed.keys())
    lowered = lowering.lower_block(main, 0, feed_names, [model["loss"].name])
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    state = {n: np.asarray(scope.find_var(n)) for n in lowered.state_in_names}
    jaxpr = jax.make_jaxpr(lowered.fn)(state, feed, jax.random.PRNGKey(0))
    text = str(jaxpr)
    # bf16 dot_generals present (the activation stream), f32 params in state
    assert "bf16" in text
    n_bf16_dots = text.count("preferred_element_type=bfloat16")
    n_dots = text.count("dot_general")
    assert n_dots > 0
    # the bulk of matmuls consume/produce bf16: look for bf16 dot operands
    assert text.count(":bf16") > 50, "bf16 stream missing from lowered jaxpr"
