"""bf16 AMP correctness: activation stream runs in bf16, master weights
stay f32, and the loss trajectory tracks the f32 run.

Covers the trace-time cast policy in core/lowering.py (AMP_OP_TYPES /
AMP_FLOW_OP_TYPES) that otherwise only executes on the TPU bench host.
"""

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.core import lowering
from paddle_tpu.models import transformer as T


CFG = T.TransformerConfig(
    src_vocab_size=64, trg_vocab_size=64, d_model=32, d_inner=64,
    n_head=4, n_layer=2, max_length=32, dropout=0.0,
)


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = T.build(CFG, is_test=False)
        fluid.optimizer.Adam(1e-3).minimize(model["loss"])
    return main, startup, model


def _run(amp, n_steps=4):
    main, startup, model = _build()
    main._amp = amp
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    losses = []
    for i in range(n_steps):
        feed = T.make_batch(CFG, batch=8, src_len=16, trg_len=16, seed=i)
        out = exe.run(main, feed=feed, fetch_list=[model["loss"]],
                      scope=scope)
        losses.append(float(out[0]))
    return losses, scope, main, model


import pytest


@pytest.fixture(scope="module")
def amp_run():
    # one bf16 compile+run shared by the trajectory and master-weight
    # tests (each _run costs a full transformer compile on the CPU
    # backend)
    return _run(amp=True)


def test_amp_loss_tracks_f32(amp_run):
    f32, _, _, _ = _run(amp=False)
    bf16, _, _, _ = amp_run
    assert all(np.isfinite(bf16)), bf16
    # same trajectory within bf16 noise
    np.testing.assert_allclose(f32, bf16, rtol=0.05, atol=0.05)
    assert bf16[-1] < bf16[0]  # still learning


def test_amp_master_weights_stay_f32(amp_run):
    _, scope, main, _ = amp_run
    for p in main.all_parameters():
        v = scope.find_var(p.name)
        assert v is not None
        assert jnp.asarray(v).dtype == jnp.float32, p.name


def test_amp_stream_is_bf16():
    """The lowered computation must actually contain bf16 matmuls — guards
    against a flow op silently promoting the stream back to f32."""
    main, startup, model = _build()
    main._amp = True
    feed = T.make_batch(CFG, batch=8, src_len=16, trg_len=16, seed=0)
    feed_names = sorted(feed.keys())
    lowered = lowering.lower_block(main, 0, feed_names, [model["loss"].name])
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    state = {n: np.asarray(scope.find_var(n)) for n in lowered.state_in_names}
    jaxpr = jax.make_jaxpr(lowered.fn)(state, feed, jax.random.PRNGKey(0))
    text = str(jaxpr)
    # bf16 dot_generals present (the activation stream), f32 params in state
    assert "bf16" in text
    n_bf16_dots = text.count("preferred_element_type=bfloat16")
    n_dots = text.count("dot_general")
    assert n_dots > 0
    # the bulk of matmuls consume/produce bf16: look for bf16 dot operands
    assert text.count(":bf16") > 50, "bf16 stream missing from lowered jaxpr"


# --------------------------------------------------------------------------
# dynamic loss scaling: the first direct tests of the grow/shrink/skip
# state machine (amp.decorate(use_dynamic_loss_scaling=True) compiles it
# in-graph; these drive it through the Executor step by step)
# --------------------------------------------------------------------------

from paddle_tpu import amp, flags, layers, monitor


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    flags.set_flags({"telemetry": False, "numerics": False})
    yield
    monitor.reset()
    flags.set_flags({"telemetry": False, "numerics": False})


def _scaler_setup(init_scale, incr_every_n=1000, decr_every_n=1,
                  incr_ratio=2.0, decr_ratio=0.5):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        loss = layers.mean(layers.fc(x, 2, bias_attr=False))
        opt = amp.decorate(
            fluid.optimizer.SGD(0.1), init_loss_scaling=init_scale,
            use_dynamic_loss_scaling=True,
            incr_every_n_steps=incr_every_n,
            decr_every_n_nan_or_inf=decr_every_n,
            incr_ratio=incr_ratio, decr_ratio=decr_ratio)
        opt.minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return main, loss, opt, scope, exe


def _scale(scope, opt):
    return float(np.asarray(scope.find_var(opt.loss_scaling_name))[0])


_OK_FEED = {"x": np.ones((2, 4), np.float32)}
# scaled by >=1e30 the fc gradients overflow f32
_HUGE_FEED = {"x": np.full((2, 4), 1e10, np.float32)}


def test_loss_scale_grows_after_n_good_steps():
    main, loss, opt, scope, exe = _scaler_setup(
        init_scale=4.0, incr_every_n=2)
    with fluid.scope_guard(scope):
        scales = []
        for _ in range(5):
            exe.run(main, feed=_OK_FEED, fetch_list=[loss])
            scales.append(_scale(scope, opt))
    # grows 2x on every 2nd clean step, counter resets after each growth
    assert scales == [4.0, 8.0, 8.0, 16.0, 16.0]


def test_overflow_skips_update_and_shrinks_scale():
    main, loss, opt, scope, exe = _scaler_setup(init_scale=1e30)
    pname = main.all_parameters()[0].name
    with fluid.scope_guard(scope):
        before = np.asarray(scope.find_var(pname)).copy()
        out = exe.run(main, feed=_HUGE_FEED, fetch_list=[loss])
        after = np.asarray(scope.find_var(pname))
        # the skip contract: parameters bit-unchanged on overflow, the
        # (unscaled) loss fetch itself stays finite
        np.testing.assert_array_equal(before, after)
        assert np.isfinite(out[0]).all()
        assert _scale(scope, opt) == pytest.approx(5e29)
        # recovery: the next finite step updates normally
        exe.run(main, feed=_OK_FEED, fetch_list=[loss])
        assert not np.array_equal(after,
                                  np.asarray(scope.find_var(pname)))


def test_overflow_resets_growth_counter():
    main, loss, opt, scope, exe = _scaler_setup(
        init_scale=1e30, incr_every_n=2)
    with fluid.scope_guard(scope):
        exe.run(main, feed=_OK_FEED, fetch_list=[loss])   # good: 1
        exe.run(main, feed=_HUGE_FEED, fetch_list=[loss])  # overflow
        s_after_bad = _scale(scope, opt)
        assert s_after_bad == pytest.approx(5e29)
        exe.run(main, feed=_OK_FEED, fetch_list=[loss])   # good: 1 again
        assert _scale(scope, opt) == pytest.approx(s_after_bad)
        exe.run(main, feed=_OK_FEED, fetch_list=[loss])   # good: 2 -> grow
        assert _scale(scope, opt) == pytest.approx(s_after_bad * 2)


def test_decr_every_n_requires_consecutive_overflows():
    main, loss, opt, scope, exe = _scaler_setup(
        init_scale=1e30, decr_every_n=2)
    with fluid.scope_guard(scope):
        exe.run(main, feed=_HUGE_FEED, fetch_list=[loss])  # bad: 1
        assert _scale(scope, opt) == pytest.approx(1e30)  # not yet
        exe.run(main, feed=_HUGE_FEED, fetch_list=[loss])  # bad: 2
        assert _scale(scope, opt) == pytest.approx(5e29)


def test_overflow_skip_counter_and_scale_gauge_exported():
    flags.set_flags({"telemetry": True, "numerics": True})
    main, loss, opt, scope, exe = _scaler_setup(init_scale=1e30)
    with fluid.scope_guard(scope):
        exe.run(main, feed=_HUGE_FEED, fetch_list=[loss])
        exe.run(main, feed=_OK_FEED, fetch_list=[loss])
    assert monitor.counter("pt_amp_overflow_skips_total").value() == 1
    assert monitor.gauge("pt_amp_loss_scale").value() == pytest.approx(
        5e29)
    # the step log carries the aux values too
    rec = monitor.recent_steps()[-1]
    assert rec["numerics"]["aux"]["amp_loss_scale"] == pytest.approx(5e29)
    assert rec["numerics"]["aux"]["amp_found_inf"] == 0.0


def test_skip_counter_exact_under_sampled_decode():
    """The skip count rides a cumulative in-graph var decoded as deltas,
    so overflows on UNSAMPLED steps still reach the counter."""
    flags.set_flags({"telemetry": True, "numerics": True,
                     "numerics_every_n_steps": 4})
    main, loss, opt, scope, exe = _scaler_setup(init_scale=1e30)
    with fluid.scope_guard(scope):
        # steps 1..3 (none lands on the every-4 sampling grid): two
        # overflows happen entirely between decodes
        exe.run(main, feed=_HUGE_FEED, fetch_list=[loss])
        exe.run(main, feed=_HUGE_FEED, fetch_list=[loss])
        exe.run(main, feed=_OK_FEED, fetch_list=[loss])
        assert monitor.counter(
            "pt_amp_overflow_skips_total").value() == 0  # not decoded yet
        exe.run(main, feed=_OK_FEED, fetch_list=[loss])  # step 4: decode
    assert monitor.counter("pt_amp_overflow_skips_total").value() == 2
    flags.set_flags({"numerics_every_n_steps": 1})


def test_scale_growth_guarded_against_f32_overflow():
    """A scale whose next doubling would overflow f32 must stay put —
    an inf scale would flag every later step as overflow and silently
    freeze training forever."""
    main, loss, opt, scope, exe = _scaler_setup(
        init_scale=1e38, incr_every_n=1)
    pname = main.all_parameters()[0].name
    # small activations keep the scaled loss/grads finite even at the
    # clamp, so only the growth guard is exercised
    tiny = {"x": np.full((2, 4), 1e-3, np.float32)}
    with fluid.scope_guard(scope):
        for _ in range(4):  # 2e38 is representable; 4e38 is not
            exe.run(main, feed=tiny, fetch_list=[loss])
        assert _scale(scope, opt) == pytest.approx(2e38, rel=1e-6)
        assert np.isfinite(_scale(scope, opt))
        # training still updates parameters at the clamped scale
        before = np.asarray(scope.find_var(pname)).copy()
        exe.run(main, feed=tiny, fetch_list=[loss])
        assert not np.array_equal(before,
                                  np.asarray(scope.find_var(pname)))


def test_dynamic_decorate_rejects_split_apply_gradients():
    opt = amp.decorate(fluid.optimizer.SGD(0.1),
                       use_dynamic_loss_scaling=True)
    with pytest.raises(RuntimeError, match="minimize"):
        opt.apply_gradients([])


def test_static_decorate_still_marks_amp_only():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        loss = layers.mean(layers.fc(x, 2))
        amp.decorate(fluid.optimizer.SGD(0.1)).minimize(loss)
    assert main._amp
    # no scaling machinery was built
    assert not hasattr(main, "_amp_scale_vars")
    assert not any(op.type == "isfinite"
                   for op in main.global_block().ops)
