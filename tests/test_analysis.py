"""Static program verifier tests (ISSUE 6): one positive + one clean
negative per check family, the static_lint flag plane through
Executor.run, the seeded cross-rank collective-order case, and the
zero-alloc contract for the off path (PR 2-5 contract style)."""

import tracemalloc

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import analysis, debugger, flags, layers, monitor, passes
from paddle_tpu.parallel.mesh import create_mesh
from paddle_tpu.parallel.strategy import (
    DistributedStrategy,
    ShardingRule,
    transformer_rules,
)


@pytest.fixture(autouse=True)
def _lint_default():
    flags.set_flags({"static_lint": "warn", "telemetry": False})
    yield
    flags.set_flags({"static_lint": "warn", "telemetry": False})


def _clean_model():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        logits = layers.fc(layers.fc(x, 16, act="relu"), 4)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _mesh():
    return create_mesh({"data": 2, "model": 4}, set_as_default=False)


# --------------------------------------------------------------------------
# dataflow
# --------------------------------------------------------------------------

def test_dataflow_clean_training_program_has_no_findings():
    main, _, loss = _clean_model()
    assert analysis.lint(main, feeds=["x", "label"],
                         fetches=[loss.name]) == []


def test_dataflow_uninitialized_read_flagged():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        layers.data("a", shape=[4], dtype="float32")
        prog.global_block().append_op(
            "relu", inputs={"X": ["ghost"]}, outputs={"Out": ["o"]})
    f = analysis.lint(prog, feeds=["a"])
    assert [x.check for x in f] == ["dataflow.uninitialized_read"]
    assert f[0].severity == "error" and f[0].var == "ghost"
    assert f[0].hint


def test_dataflow_read_before_write_flagged():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        b = prog.global_block()
        # consumer appended BEFORE its producer
        b.append_op("relu", inputs={"X": ["late"]}, outputs={"Out": ["o"]})
        b.append_op("scale", inputs={"X": [x.name]},
                    outputs={"Out": ["late"]}, attrs={"scale": 2.0})
    f = analysis.lint(prog, feeds=["x"])
    assert [x.check for x in f] == ["dataflow.read_before_write"]


def test_dataflow_dead_op_and_unreachable_fetch():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        kept = layers.scale(x, scale=2.0)
        layers.scale(x, scale=3.0)  # dead: never reaches the fetch
    f = analysis.lint(prog, feeds=["x"], fetches=[kept.name],
                      min_severity="info")
    checks = [x.check for x in f]
    assert "dataflow.dead_op" in checks
    # info severity: advisory (other run() calls may fetch it)
    assert all(x.severity == "info" for x in f
               if x.check == "dataflow.dead_op")
    f2 = analysis.lint(prog, feeds=["x"], fetches=["nowhere"])
    assert any(x.check == "dataflow.unreachable_fetch"
               and x.severity == "error" for x in f2)


def test_dataflow_write_never_read_persistable_is_info():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        b = prog.global_block()
        b.create_var(name="stat", shape=[4], dtype="float32",
                     persistable=True)
        b.append_op("scale", inputs={"X": [x.name]},
                    outputs={"Out": ["stat"]}, attrs={"scale": 1.0})
    f = analysis.lint(prog, feeds=["x"], min_severity="info")
    assert [x.check for x in f] == ["dataflow.write_never_read"]
    assert analysis.lint(prog, feeds=["x"]) == []  # default: warning+


# --------------------------------------------------------------------------
# shapes / dtypes
# --------------------------------------------------------------------------

def test_shapes_clean_program_negative():
    main, _, _ = _clean_model()
    assert not [f for f in analysis.lint(main, min_severity="debug")
                if f.check.startswith("shapes.")
                and f.severity != "debug"]


def test_shapes_declared_mismatch_flagged():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.fc(x, 16)
    prog.global_block()._find_var_recursive(h.name).shape = (-1, 99)
    f = analysis.lint(prog)
    assert any(x.check == "shapes.shape_mismatch" for x in f)
    msg = next(x for x in f if x.check == "shapes.shape_mismatch")
    assert "[-1, 99]" in msg.message and "[-1, 16]" in msg.message


def test_shapes_dtype_mismatch_and_implicit_downcast():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.scale(x, scale=2.0)
    prog.global_block()._find_var_recursive(h.name).dtype = "float16"
    checks = {x.check for x in analysis.lint(prog)}
    assert "shapes.dtype_mismatch" in checks
    assert "shapes.implicit_downcast" in checks
    # under an AMP-marked program the downcast audit stands down
    prog._amp = True
    prog._bump_version()
    checks_amp = {x.check for x in analysis.lint(prog)}
    assert "shapes.implicit_downcast" not in checks_amp


def test_shapes_coverage_gap_is_debug_finding():
    """Satellite: ops with no registered shape function are one
    debug-level finding instead of a silent fallthrough."""
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        prog.global_block().append_op(
            "totally_unregistered_op", inputs={"X": [x.name]},
            outputs={"Out": ["o"]})
    f = analysis.lint(prog, min_severity="debug")
    gaps = [x for x in f if x.check == "shapes.no_inference"]
    assert len(gaps) == 1 and gaps[0].severity == "debug"
    assert "no_kernel" in gaps[0].message
    # default severity filter keeps them out of warn/error reporting
    assert all(x.check != "shapes.no_inference"
               for x in analysis.lint(prog))
    # and the build-time ledger recorded the same gap
    from paddle_tpu import framework
    assert ("totally_unregistered_op", "no_kernel") in \
        framework.shape_infer_gaps()


# --------------------------------------------------------------------------
# donation / aliasing
# --------------------------------------------------------------------------

def test_donation_clean_optimizer_program_negative():
    main, _, loss = _clean_model()
    assert not [f for f in analysis.lint(
        main, feeds=["x", "label"], fetches=[loss.name],
        min_severity="debug") if f.check.startswith("donation.")]


def _donation_prog():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        b = prog.global_block()
        b.create_parameter("w", [4], "float32")
        b.append_op("elementwise_mul", inputs={"X": [x.name], "Y": ["w"]},
                    outputs={"Out": ["y"]})
        b.append_op("scale", inputs={"X": ["w"]}, outputs={"Out": ["w"]},
                    attrs={"scale": 0.9})  # the update (donation point)
        b.append_op("elementwise_add", inputs={"X": ["y"], "Y": ["w"]},
                    outputs={"Out": ["z"]})  # post-update re-read
    return prog


def test_donation_read_after_donate_flagged():
    f = analysis.lint(_donation_prog(), feeds=["x"], fetches=["z"])
    hits = [x for x in f if x.check == "donation.read_after_donate"]
    assert len(hits) == 1 and hits[0].var == "w"
    assert hits[0].severity == "warning"


def test_donation_multi_writer_flagged():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        b = prog.global_block()
        b.create_parameter("w", [4], "float32")
        b.append_op("elementwise_mul", inputs={"X": [x.name], "Y": ["w"]},
                    outputs={"Out": ["y"]})
        for s in (0.9, 0.8):  # two writers alias the donated buffer
            b.append_op("scale", inputs={"X": ["w"]},
                        outputs={"Out": ["w"]}, attrs={"scale": s})
    f = analysis.lint(prog, feeds=["x"], fetches=["y"])
    assert any(x.check == "donation.multi_writer" and x.var == "w"
               for x in f)


def test_donation_feed_aliasing_state_flagged():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        b = prog.global_block()
        b.create_parameter("w", [4], "float32")
        b.append_op("scale", inputs={"X": ["w"]}, outputs={"Out": ["o"]},
                    attrs={"scale": 1.0})
    f = analysis.lint(prog, feeds=["w"], fetches=["o"])
    assert any(x.check == "donation.feed_aliases_state" for x in f)


# --------------------------------------------------------------------------
# sharding / mesh consistency
# --------------------------------------------------------------------------

def test_sharding_clean_tp_program_negative():
    mesh = _mesh()
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data("x", shape=[16], dtype="float32")
        h = layers.fc(x, 32, param_attr=fluid.ParamAttr(name="l1_colp.w"),
                      bias_attr=fluid.ParamAttr(name="l1_colp.b"),
                      act="relu")
        y = layers.fc(h, 16, param_attr=fluid.ParamAttr(name="l2_rowp.w"),
                      bias_attr=fluid.ParamAttr(name="l2_rowp.b"))
        loss = layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    st = DistributedStrategy(mesh, rules=transformer_rules())
    assert not [f for f in analysis.lint(main, feeds=["x"],
                                         fetches=[loss.name], strategy=st)
                if f.check.startswith("sharding.")]


def test_sharding_direct_conflict_flagged_with_cost():
    mesh = _mesh()
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        b = prog.global_block()
        b.create_parameter("wa", [8, 8], "float32")
        b.create_parameter("wb", [8, 8], "float32")
        layers.elementwise_add(b.var("wa"), b.var("wb"))
    st = DistributedStrategy(mesh, rules=[
        ShardingRule(r"^wa$", P("model", None)),
        ShardingRule(r"^wb$", P("data", None)),
    ])
    f = [x for x in analysis.lint(prog, strategy=st)
         if x.check == "sharding.unresolvable_mix"]
    assert len(f) == 1
    assert f[0].cost_bytes and f[0].cost_bytes > 0
    assert "model" in f[0].message and "data" in f[0].message


def test_sharding_joint_axis_claim_flagged():
    """No single dim conflicts, but one mesh axis is claimed by two
    different dims of the union — resolvable only through a reshard."""
    mesh = _mesh()
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        b = prog.global_block()
        b.create_parameter("wa", [8, 8], "float32")
        b.create_parameter("wb", [8, 8], "float32")
        layers.elementwise_add(b.var("wa"), b.var("wb"))
    st = DistributedStrategy(mesh, rules=[
        ShardingRule(r"^wa$", P(None, "model")),
        ShardingRule(r"^wb$", P("model", None)),
    ])
    f = [x for x in analysis.lint(prog, strategy=st)
         if x.check == "sharding.unresolvable_mix"]
    assert len(f) == 1 and "axis 'model'" in f[0].message


def test_sharding_skipped_entirely_without_strategy():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        b = prog.global_block()
        b.create_parameter("wa", [8, 8], "float32")
        b.create_parameter("wb", [8, 8], "float32")
        layers.elementwise_add(b.var("wa"), b.var("wb"))
    assert not [x for x in analysis.lint(prog, min_severity="debug")
                if x.check.startswith("sharding.")]


# --------------------------------------------------------------------------
# collective order
# --------------------------------------------------------------------------

def _rank_prog(order):
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        q = layers.data("q", shape=[2, 16, 8], dtype="float32")
        b = prog.global_block()
        for t in order:
            if t == "switch_moe":
                b.append_op("switch_moe", inputs={"X": [q.name]},
                            outputs={"Out": [f"o_{t}"]})
            else:
                b.append_op(t, inputs={"Q": [q.name], "K": [q.name],
                                       "V": [q.name]},
                            outputs={"Out": [f"o_{t}"]})
    return prog


def _cp_strategy():
    mesh = create_mesh({"sp": 4, "expert": 2}, set_as_default=False)
    return DistributedStrategy(mesh, data_axis=None, context_axis="sp",
                               expert_axis="expert")


def test_collective_order_seeded_cross_rank_mismatch():
    """Seeded divergence: rank 1 emits the same two collectives in the
    opposite order — the classic static deadlock."""
    st = _cp_strategy()
    a = ["scaled_dot_product_attention", "switch_moe"]
    progs = [_rank_prog(a), _rank_prog(list(reversed(a)))]
    f = analysis.check_collective_order(progs, strategy=st)
    assert len(f) == 1 and f[0].check == "collectives.order_divergence"
    assert f[0].severity == "error"
    assert "rank 0" in f[0].message and "rank 1" in f[0].message
    # count divergence is its own finding
    f2 = analysis.check_collective_order(
        [_rank_prog(a), _rank_prog(a[:1])], strategy=st)
    assert f2[0].check == "collectives.count_divergence"


def test_collective_order_identical_ranks_negative():
    st = _cp_strategy()
    a = ["scaled_dot_product_attention", "switch_moe"]
    assert analysis.check_collective_order(
        [_rank_prog(a), _rank_prog(a), _rank_prog(a)], strategy=st) == []


def test_collective_signature_extracts_participants():
    st = _cp_strategy()
    sig = analysis.collective_signature(
        _rank_prog(["scaled_dot_product_attention", "switch_moe"]), st)
    assert [e["kind"] for e in sig] == ["ring_attention", "all_to_all"]
    assert sig[0]["participants"] == 4  # sp axis size
    assert sig[0]["schedule"] == "ppermute-ring"
    assert sig[1]["participants"] == 2  # expert axis size


def test_collective_under_cond_flagged_single_program():
    st = _cp_strategy()
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[2, 16, 8], dtype="float32")
        b = prog.global_block()
        sub = prog._create_block()
        prog._rollback()
        sub.append_op("switch_moe", inputs={"X": [x.name]},
                      outputs={"Out": ["moe_o"]})
        b.append_op("cond", inputs={"Cond": [x.name]},
                    outputs={"Out": ["c_o"]},
                    attrs={"true_block": sub, "false_block": sub,
                           "true_out_names": ["moe_o"],
                           "false_out_names": ["moe_o"]})
    f = [x for x in analysis.lint(prog, strategy=st)
         if x.check == "collectives.control_flow"]
    assert len(f) == 1 and "switch_moe" in f[0].message
    # without a strategy the sdpa/moe ops are dense kernels: no findings
    assert not [x for x in analysis.lint(prog, min_severity="debug")
                if x.check.startswith("collectives.")]


# --------------------------------------------------------------------------
# flag plane through Executor.run + pass form + annotations
# --------------------------------------------------------------------------

def test_static_lint_error_raises_through_executor_run():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        prog.global_block().append_op(
            "relu", inputs={"X": ["ghost"]}, outputs={"Out": ["o"]})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    flags.set_flags({"static_lint": "error"})
    with fluid.scope_guard(scope):
        with pytest.raises(analysis.LintError) as ei:
            exe.run(prog, feed={"x": np.ones((1, 4), np.float32)},
                    fetch_list=["o"])
    assert any(f.check == "dataflow.uninitialized_read"
               for f in ei.value.findings)
    # warn mode: same program logs but reaches the (failing) compile
    flags.set_flags({"static_lint": "warn"})
    with fluid.scope_guard(scope):
        with pytest.raises(Exception) as ei2:
            exe.run(prog, feed={"x": np.ones((1, 4), np.float32)},
                    fetch_list=["o"])
    assert not isinstance(ei2.value, analysis.LintError)


def test_static_lint_error_raises_again_on_retry():
    """The pre-compile fingerprint cache must not swallow the error
    gate: a retried run of the same broken program re-lints and
    re-raises instead of proceeding to the compiler."""
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        prog.global_block().append_op(
            "relu", inputs={"X": ["ghost"]}, outputs={"Out": ["o"]})
    exe = fluid.Executor(fluid.CPUPlace())
    flags.set_flags({"static_lint": "error"})
    for _ in range(2):  # second call must NOT hit a poisoned cache
        with pytest.raises(analysis.LintError):
            exe.run(prog, feed={}, fetch_list=["o"])


def test_collective_order_mesh_mismatch_diverges():
    """Two ranks that built different meshes diverge even when the op
    sequence matches — the mesh shape rides the signature."""
    a = ["scaled_dot_product_attention"]
    st4 = _cp_strategy()
    mesh2 = create_mesh({"sp": 2, "expert": 4}, set_as_default=False)
    st2 = DistributedStrategy(mesh2, data_axis=None, context_axis="sp",
                              expert_axis="expert")
    f = analysis.check_collective_order(
        [_rank_prog(a), _rank_prog(a)], strategy=[st4, st2])
    assert len(f) == 1 and f[0].check == "collectives.order_divergence"


def test_mode_flip_warn_to_error_relints_cached_signature():
    """Fingerprints linted under warn must re-lint after a flip to
    error: the mode change clears the pre-compile cache."""
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        prog.global_block().append_op(
            "relu", inputs={"X": ["ghost"]}, outputs={"Out": ["o"]})
    exe = fluid.Executor(fluid.CPUPlace())
    flags.set_flags({"static_lint": "warn"})
    with pytest.raises(Exception) as ei:  # lint logs, lowering raises
        exe.run(prog, feed={}, fetch_list=["o"])
    assert not isinstance(ei.value, analysis.LintError)
    flags.set_flags({"static_lint": "error"})
    with pytest.raises(analysis.LintError):
        exe.run(prog, feed={}, fetch_list=["o"])


def test_collective_order_pipe_micro_mismatch_diverges():
    """Same mesh, same op order, different pipe_micro: the GPipe
    schedules have different hop counts — a deadlock the ticks field
    must catch."""
    mesh = create_mesh({"pipe": 4, "data": 2}, set_as_default=False)

    def prog():
        p = fluid.Program()
        with fluid.program_guard(p, fluid.Program()):
            x = layers.data("x", shape=[8], dtype="float32")
            sub = p._create_block()
            p._rollback()
            p.global_block().append_op(
                "scan", inputs={"X": [x.name]}, outputs={"Y": ["y"]},
                attrs={"pipelinable": True, "sub_block": sub,
                       "x_names": ["xt"], "state_in": [],
                       "state_out": [], "y_names": ["yt"]})
        return p

    def st(micro):
        return DistributedStrategy(mesh, pipe_axis="pipe",
                                   pipe_micro=micro)

    f = analysis.check_collective_order([prog(), prog()],
                                        strategy=[st(4), st(8)])
    assert len(f) == 1 and f[0].check == "collectives.order_divergence"
    assert analysis.check_collective_order(
        [prog(), prog()], strategy=[st(4), st(4)]) == []
    with pytest.raises(ValueError):  # strategy list length mismatch
        analysis.check_collective_order([prog(), prog()],
                                        strategy=[st(4)])


def test_standalone_fetch_of_declared_input_not_flagged():
    """fetches= without feeds= must apply the same declared-input
    heuristic as the uninitialized-read check."""
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.scale(x, scale=2.0)
    assert analysis.lint(prog, fetches=["x", y.name]) == []


def test_malformed_kernel_result_is_gap_not_abort():
    """A registered op whose compute returns a non-dict must stay an
    advisory coverage gap at build AND lint time, not an abort."""
    from paddle_tpu import framework
    from paddle_tpu.core.registry import _OP_REGISTRY, register_op

    name = "lint_test_malformed_op"
    if name not in _OP_REGISTRY:
        @register_op(name, no_grad=True)
        def _malformed(ins, attrs):
            return [x * 2 for xs in ins.values() for x in xs]  # not a dict

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        prog.global_block().append_op(  # build must not raise
            name, inputs={"X": [x.name]}, outputs={"Out": ["o"]})
    assert any(t == name for t, _ in framework.shape_infer_gaps())
    f = analysis.lint(prog, feeds=["x"], min_severity="debug")
    assert any(x.check == "shapes.no_inference" and x.op_type == name
               for x in f)
    assert analysis.lint(prog, feeds=["x"]) == []


def test_strategy_fingerprint_is_content_keyed():
    """The pre-compile cache keys strategies by CONTENT, not id():
    a different strategy for the same program re-lints (id reuse after
    GC must not alias it), while an equal-content new object doesn't."""
    mesh = _mesh()
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        b = prog.global_block()
        b.create_parameter("wa", [8, 8], "float32")
        b.create_parameter("wb", [8, 8], "float32")
        layers.elementwise_add(b.var("wa"), b.var("wb"))

    def strat(axis):
        return DistributedStrategy(mesh, rules=[
            ShardingRule(r"^wa$", P(axis, None)),
            ShardingRule(r"^wb$", P("data", None))])

    flags.set_flags({"telemetry": True})  # counters need the plane on

    def runs():
        return monitor.counter("pt_lint_runs_total").value()

    r0 = runs()
    analysis.lint_at_build(prog, strategy=strat("model"), site="t-fp")
    assert runs() == r0 + 1
    analysis.lint_at_build(prog, strategy=strat("model"), site="t-fp")
    assert runs() == r0 + 1  # equal content: cached
    analysis.lint_at_build(prog, strategy=strat("data"), site="t-fp")
    assert runs() == r0 + 2  # different content: re-lints


def test_infer_gap_keeps_diagnostic_message():
    """eval_shape failures keep the kernel's actual error message in
    the lint finding (the ledger dedups on the type prefix only)."""
    from paddle_tpu import framework

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[5], dtype="float32")
        prog.global_block().append_op(  # 4 vs 5: broadcast error
            "elementwise_add", inputs={"X": [x.name], "Y": [y.name]},
            outputs={"Out": ["o"]}, attrs={"axis": -1})
    f = [g for g in analysis.lint(prog, min_severity="debug")
         if g.check == "shapes.no_inference"]
    assert f and "eval_failed:" in f[0].message
    assert any(len(m) > len("eval_failed:TypeError")
               for m in [f[0].message])  # a real diagnostic rode along
    assert any(t == "elementwise_add" and g.startswith("eval_failed:")
               for t, g in framework.shape_infer_gaps())


def test_lint_pass_registered_and_composes():
    main, _, _ = _clean_model()
    assert "lint" in passes.registered_passes()
    out = passes.apply_pass("lint", main)
    assert out is main
    rec = analysis.findings_for(main._uid)
    assert rec is not None and rec["program"] == f"program{main._uid}"


def test_lint_report_and_debugger_annotation():
    prog = _donation_prog()
    rep = analysis.lint_report(prog, feeds=["x"], fetches=["z"])
    assert rep.startswith("static lint (")
    assert "donation.read_after_donate" in rep
    listing = debugger.pprint_program(prog)
    assert "static lint (v1" in listing
    assert "donation.read_after_donate" in listing
    # opting out drops the header
    assert "static lint" not in debugger.pprint_program(
        prog, with_lint=False)


def test_findings_metered():
    flags.set_flags({"telemetry": True})
    c0 = monitor.counter("pt_lint_findings_total").value(
        labels={"check": "dataflow", "severity": "error"})
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        prog.global_block().append_op(
            "relu", inputs={"X": ["ghost"]}, outputs={"Out": ["o"]})
    analysis.lint(prog)
    c1 = monitor.counter("pt_lint_findings_total").value(
        labels={"check": "dataflow", "severity": "error"})
    assert c1 == c0 + 1
    assert monitor.counter("pt_lint_runs_total").value() > 0


def test_def_use_index_cached_per_version():
    main, _, _ = _clean_model()
    i1 = main.def_use_index()
    assert i1 is main.def_use_index()  # same version -> cached
    main.global_block().append_op(
        "scale", inputs={"X": ["x"]}, outputs={"Out": ["x2"]},
        attrs={"scale": 1.0})
    assert main.def_use_index() is not i1  # version bump invalidates


def test_executor_lint_runs_once_per_signature():
    main, startup, loss = _clean_model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 8), np.float32),
            "label": np.zeros((2, 1), np.int64)}
    runs0 = monitor.counter("pt_lint_runs_total").value()
    flags.set_flags({"telemetry": True})
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
    runs1 = monitor.counter("pt_lint_runs_total").value()
    # one lint for the startup signature + one for the train signature
    assert runs1 - runs0 <= 2


# --------------------------------------------------------------------------
# zoo cleanliness + perf budget
# --------------------------------------------------------------------------

def test_zoo_models_lint_clean_under_defaults():
    from paddle_tpu.models import mnist as mnist_model

    for build in (lambda: mnist_model.get_model(use_conv=False),
                  lambda: mnist_model.get_model(use_conv=True)):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            model = build()
            fluid.optimizer.Adam(1e-3).minimize(model["loss"])
        assert analysis.lint(main) == []
        assert analysis.lint(startup) == []


def test_bench_transformer_lints_clean_and_fast():
    """Acceptance: zero findings on the bench transformer under
    defaults, lint completes < 250 ms at steady state (def-use and
    eval-shape memos warm, the executor-path regime)."""
    import time

    from paddle_tpu.models import transformer as T

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        model = T.build(T.TransformerConfig())
        fluid.optimizer.Adam(1e-3).minimize(model["loss"])
    assert analysis.lint(main) == []  # cold: correctness
    t0 = time.perf_counter()
    assert analysis.lint(main) == []
    ms = (time.perf_counter() - t0) * 1e3
    assert ms < 250, f"steady-state lint took {ms:.0f}ms"


# --------------------------------------------------------------------------
# zero-alloc contract: static_lint=off on the executor hot path
# --------------------------------------------------------------------------

def test_static_lint_off_allocates_nothing_in_analysis():
    flags.set_flags({"static_lint": "off"})
    assert not analysis.lint_active()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # warm compile cache + lazy state
            exe.run(main, feed=feed, fetch_list=[y])
        n_runs = 30
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            exe.run(main, feed=feed, fetch_list=[y])
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    stats = snap.compare_to(base, "filename")
    grew = sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith("analysis.py")
               and s.size_diff > 0)
    assert grew < n_runs * 16, (
        f"static_lint=off Executor.run allocated {grew}B in analysis.py "
        f"over {n_runs} runs")


def test_invalid_mode_degrades_to_warn():
    flags.set_flags({"static_lint": "bogus"})
    assert analysis.lint_mode() == "warn"
