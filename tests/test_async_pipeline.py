"""Async executor pipeline (PR 10 tentpole): sampled phase attribution
(`step_phases_every_n`), the all-device feed staging skip, overlapped
fetch (`LazyFetches` + deferred-error hygiene), DeviceLoader lifecycle
(abandoned-consumer stop event, PyReader reset), trainer prefetch
equivalence, and the disabled-path zero-allocation contract."""

import threading
import time
import tracemalloc

import jax
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import faults, flags, layers, monitor
from paddle_tpu.executor import LazyFetches
from paddle_tpu.reader.pipeline import DeviceLoader, PyReader

_RESET_FLAGS = {"telemetry": False, "step_phases": True,
                "step_phases_every_n": 16, "prefetch_depth": 2,
                "check_nan_inf": False}


@pytest.fixture(autouse=True)
def _clean():
    monitor.reset()
    faults.disarm()
    flags.set_flags(dict(_RESET_FLAGS))
    yield
    monitor.reset()
    faults.disarm()
    flags.set_flags(dict(_RESET_FLAGS))


def _tiny_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        loss = layers.mean(layers.fc(x, 4))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _no_loader_threads(timeout=5.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if not any(t.name == "pt-device-loader" and t.is_alive()
                   for t in threading.enumerate()):
            return True
        time.sleep(0.01)
    return False


# --------------------------------------------------------------------------
# DeviceLoader lifecycle (satellites: abandoned consumer + PyReader)
# --------------------------------------------------------------------------

def test_device_loader_abandoned_consumer_unblocks_worker():
    """A consumer that stops iterating early must release the worker:
    before the stop event, the daemon blocked forever on q.put with up
    to `depth` device-resident batches pinned."""
    produced = []

    def reader():
        for i in range(50):
            produced.append(i)
            yield {"x": np.full((2, 2), i, np.float32)}

    loader = DeviceLoader(reader, feed_names=["x"], depth=2)
    it = iter(loader)
    _stop, _q, thread = loader._active
    first = next(it)
    assert set(first) == {"x"} and isinstance(first["x"], jax.Array)
    it.close()  # the consumer breaks after one batch
    thread.join(5.0)
    assert not thread.is_alive(), "worker still blocked after close"
    assert loader._active is None
    # bounded read-ahead: the worker never drained the 50-batch reader
    assert len(produced) <= 8, produced


def test_device_loader_break_in_for_loop_releases_worker():
    def reader():
        while True:
            yield {"x": np.zeros((2, 2), np.float32)}

    loader = DeviceLoader(reader, feed_names=["x"], depth=3)
    for i, batch in enumerate(loader):
        if i >= 1:
            break
    del batch
    loader.close()  # explicit close is idempotent with GeneratorExit
    assert _no_loader_threads()


def test_device_loader_reiteration_does_not_leak_previous_worker():
    def reader():
        while True:
            yield {"x": np.zeros((2, 2), np.float32)}

    loader = DeviceLoader(reader, feed_names=["x"], depth=2)
    it1 = iter(loader)
    _stop1, _q1, t1 = loader._active
    next(it1)
    it2 = iter(loader)  # restarts: the previous worker must exit
    t1.join(5.0)
    assert not t1.is_alive()
    next(it2)
    loader.close()
    assert _no_loader_threads()


def test_pyreader_reset_stops_active_loader():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")

    def batches():
        while True:
            yield [(np.ones(4, np.float32),)] * 2

    pr = PyReader(feed_list=[x], capacity=2)
    pr.decorate_sample_list_generator(batches)
    it1 = iter(pr)
    assert set(next(it1)) == {"x"}
    t1 = pr._loader._active[2]
    # re-iteration stops the previous iteration's worker (the old
    # silent-no-op start()/reset() leaked it)
    it2 = iter(pr)
    t1.join(5.0)
    assert not t1.is_alive()
    assert set(next(it2)) == {"x"}
    pr.reset()
    assert _no_loader_threads()
    pr.start()  # decorated: validates, does not raise
    with pytest.raises(RuntimeError, match="no reader"):
        PyReader(feed_list=[x]).start()


def test_device_loader_exhaustion_still_propagates_reader_error():
    def bad_reader():
        yield {"x": np.zeros((2, 2), np.float32)}
        raise ValueError("producer died")

    loader = DeviceLoader(bad_reader, feed_names=["x"], depth=2)
    out = []
    with pytest.raises(RuntimeError, match="producer died"):
        for b in loader:
            out.append(b)
    assert len(out) == 1
    assert _no_loader_threads()


# --------------------------------------------------------------------------
# feed-staging skip (satellite): all-jax.Array feeds, zero device_put
# --------------------------------------------------------------------------

def test_all_device_feed_skips_staging_plain_and_compiled(monkeypatch):
    flags.set_flags({"telemetry": True, "step_phases_every_n": 1})
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed_np = {"x": np.ones((2, 8), np.float32)}
    dev_feed = {k: jax.device_put(v) for k, v in feed_np.items()}
    cp = fluid.CompiledProgram(main)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=dev_feed, fetch_list=[loss])   # warm compile
        exe.run(cp, feed=dev_feed, fetch_list=[loss])
        calls = []
        real = jax.device_put

        def spy(*a, **k):
            calls.append(a)
            return real(*a, **k)

        monkeypatch.setattr(jax, "device_put", spy)
        # device-resident feeds: zero additional device_put on BOTH the
        # plain and the compiled path, even on sampled (staging) steps
        exe.run(main, feed=dev_feed, fetch_list=[loss])
        assert calls == []
        exe.run(cp, feed=dev_feed, fetch_list=[loss])
        assert calls == []
        # host numpy feeds DO stage through device_put (sampled path)
        exe.run(main, feed=feed_np, fetch_list=[loss])
        assert len(calls) == 1


# --------------------------------------------------------------------------
# sampled phase attribution
# --------------------------------------------------------------------------

def test_sampled_phase_records_follow_the_period():
    flags.set_flags({"telemetry": True, "step_phases_every_n": 3})
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(8):
            exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                    fetch_list=[loss])
    recs = monitor.recent_steps()
    assert len(recs) == 9
    for rec in recs:
        monitor.validate_step_record(rec)
        want = rec["step"] % 3 == 0
        assert rec["sampled"] is want, rec
        assert ("phases" in rec) == want
        if not want:
            assert "bound" not in rec
    # scored = sampled AND committed AND cache-hit (steps 3 and 6 here;
    # step 0 is the startup compile miss)
    scored = [r for r in recs if "bound" in r]
    assert [r["step"] for r in scored] == [3, 6]
    assert all(r["cache"] == "hit" for r in scored)
    assert monitor.boundedness()["steps"] == 2


def test_window_sampling_matches_any_step_in_window():
    flags.set_flags({"telemetry": True, "step_phases_every_n": 5})
    assert monitor.phases_sampled(0)
    assert not monitor.phases_sampled(4)
    assert monitor.phases_sampled(4, steps=2)   # window [4, 6) holds 5
    assert not monitor.phases_sampled(1, steps=4)  # [1, 5) misses 5
    flags.set_flags({"step_phases": False})
    assert not monitor.phases_sampled(0)


def test_unsampled_steps_discard_input_wait_backlog():
    """Input waits accumulated by unsampled steps must not pile into the
    next sampled step's verdict — the sampled step scores only its own
    input time (else the input share inflates by the period length)."""
    flags.set_flags({"telemetry": True, "step_phases_every_n": 3})
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)  # step 0: sampled compile (unscored)
        exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                fetch_list=[loss])  # step 1: unsampled compile
        monitor.note_input_wait(30.0)  # backlog before unsampled step 2
        exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                fetch_list=[loss])  # step 2: unsampled -> discards
        exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                fetch_list=[loss])  # step 3: sampled hit, scored
    b = monitor.boundedness()
    assert b is not None and b["steps"] == 1
    assert b["verdict"] != "input_bound", b


# --------------------------------------------------------------------------
# overlapped fetch: LazyFetches + deferred-error hygiene
# --------------------------------------------------------------------------

def test_async_fetch_returns_lazy_fetches_with_correct_values():
    flags.set_flags({"telemetry": True, "step_phases_every_n": 1})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 4], append_batch_size=False,
                        stop_gradient=True)
        s = layers.reduce_sum(x)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    sync = exe.run(main, feed={"x": np.full((4, 4), 2.0, np.float32)},
                   fetch_list=[s])
    out = exe.run(main, feed={"x": np.full((4, 4), 2.0, np.float32)},
                  fetch_list=[s], async_fetch=True)
    assert isinstance(out, LazyFetches) and not out.ready
    assert len(out) == 1
    assert float(np.asarray(out[0])) == float(np.asarray(sync[0])) == 32.0
    assert out.ready
    # materialization observed the overlap histogram exactly once, and
    # repeated access does not re-observe
    _ = out[0]
    assert monitor.histogram("pt_fetch_overlap_seconds").count() == 1


def test_run_steps_async_fetch():
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 8), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        ref = exe.run_steps(main, feed_list=[feed], steps=3,
                            fetch_list=[loss], scope=scope)
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup, scope=scope2)
        out = exe2.run_steps(main, feed_list=[feed], steps=3,
                             fetch_list=[loss], scope=scope2,
                             async_fetch=True)
    assert isinstance(out, LazyFetches)
    assert float(np.asarray(out[0])) == float(np.asarray(ref[0]))


def test_deferred_fetch_error_runs_hygiene_and_oom_forensics():
    """A device failure surfacing only at the async fetch boundary
    (drilled via the executor.fetch fault site) must run the same
    donated-buffer drop + OOM forensics as the synchronous commit
    sites, then re-raise — and leave the committed state usable."""
    flags.set_flags({"telemetry": True})
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 8), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])  # warm compile
        faults.arm("executor.fetch:raise(RESOURCE_EXHAUSTED synthetic "
                   "deferred device OOM)@1")
        out = exe.run(main, feed=feed, fetch_list=[loss],
                      async_fetch=True)
        with pytest.raises(faults.InjectedFault):
            out.wait()
        faults.disarm()
        recs = monitor.oom_records()
        assert recs and recs[-1]["phase"] == "fetch"
        assert "RESOURCE_EXHAUSTED" in recs[-1]["error"]
        # state committed before the fetch: training continues cleanly
        nxt = exe.run(main, feed=feed, fetch_list=[loss])
        assert np.isfinite(np.asarray(nxt[0])).all()


def test_prefetch_worker_oom_surfaces_with_forensics():
    """An infeed OOM (device_put in the prefetch worker, drilled via the
    pipeline.prefetch site) must surface in the consumer within one
    queue drain, carrying prefetch-phase OOM forensics."""
    flags.set_flags({"telemetry": True})

    def reader():
        for _ in range(4):
            yield {"x": np.zeros((2, 2), np.float32)}

    faults.arm("pipeline.prefetch:raise(RESOURCE_EXHAUSTED synthetic "
               "infeed OOM)@2")
    loader = DeviceLoader(reader, feed_names=["x"], depth=2)
    got = []
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED") as ei:
        for b in loader:
            got.append(b)
    assert isinstance(ei.value.__cause__, faults.InjectedFault)
    assert len(got) == 1
    recs = monitor.oom_records()
    assert recs and recs[-1]["phase"] == "prefetch"
    assert _no_loader_threads()


# --------------------------------------------------------------------------
# trainer prefetch: loss parity with the synchronous path
# --------------------------------------------------------------------------

def _trainer_pieces():
    def train_func():
        img = layers.data("img", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="ap1.w"),
                      bias_attr=fluid.ParamAttr(name="ap1.b"))
        logits = layers.fc(h, 4,
                           param_attr=fluid.ParamAttr(name="ap2.w"),
                           bias_attr=fluid.ParamAttr(name="ap2.b"))
        return [layers.mean(
            layers.softmax_with_cross_entropy(logits, label))]

    def reader():
        def gen():
            rng = np.random.RandomState(0)
            probe = np.random.RandomState(5).randn(16, 4)
            for _ in range(8):
                x = rng.randn(32, 16).astype(np.float32)
                y = np.argmax(x @ probe, 1).astype(np.int64)
                yield list(zip(x, y))

        return gen

    return train_func, reader


def test_trainer_prefetch_matches_sync_losses():
    from paddle_tpu.contrib import EndStepEvent, Trainer

    train_func, reader = _trainer_pieces()

    def run(depth):
        flags.set_flags({"prefetch_depth": depth})
        losses = []
        t = Trainer(train_func, lambda: fluid.optimizer.SGD(0.1),
                    fluid.CPUPlace())
        t.train(2, lambda e: losses.append(float(e.metrics[0]))
                if isinstance(e, EndStepEvent) else None,
                reader(), ["img", "label"])
        return losses, t.test(reader(), ["img", "label"])

    pre_losses, pre_test = run(2)
    sync_losses, sync_test = run(0)
    assert len(pre_losses) == 16
    np.testing.assert_allclose(pre_losses, sync_losses, rtol=1e-6)
    np.testing.assert_allclose(pre_test, sync_test, rtol=1e-6)
    assert _no_loader_threads()


def test_trainer_exception_releases_prefetch_worker():
    from paddle_tpu.contrib import Trainer

    train_func, reader = _trainer_pieces()
    faults.arm("reader.next:raise@3")
    t = Trainer(train_func, lambda: fluid.optimizer.SGD(0.1),
                fluid.CPUPlace())
    with pytest.raises(faults.InjectedFault):
        t.train(1, None, reader(), ["img", "label"])
    assert _no_loader_threads()


# --------------------------------------------------------------------------
# disabled path: the zero-allocation contract for the new machinery
# --------------------------------------------------------------------------

def test_async_machinery_allocates_nothing_in_monitor_when_disabled():
    """With telemetry off, the sampled-phase gate, the staging skip and
    the async-fetch path must add zero monitor.py allocations to
    Executor.run — the same contract every prior plane honors."""
    assert not monitor.enabled()
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": jax.device_put(np.ones((2, 8), np.float32))}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # warm compile cache + lazy interp state
            exe.run(main, feed=feed, fetch_list=[loss],
                    async_fetch=True).wait()
        n_runs = 30
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            exe.run(main, feed=feed, fetch_list=[loss],
                    async_fetch=True).wait()
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    stats = snap.compare_to(base, "filename")
    grew = sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith(
                   ("monitor.py", "faults.py"))
               and s.size_diff > 0)
    assert grew < n_runs * 16, (
        f"disabled async Executor.run allocated {grew}B in telemetry/"
        f"fault code over {n_runs} runs")


# --------------------------------------------------------------------------
# end-to-end (slow): 20-step MNIST with prefetch on — no input_bound
# verdict after warmup
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_mnist_prefetch_e2e_no_input_bound_after_warmup():
    from paddle_tpu.contrib import Trainer
    from paddle_tpu.models import mnist as mnist_model

    flags.set_flags({"telemetry": True, "step_phases_every_n": 4,
                     "prefetch_depth": 2})

    def train_func():
        model = mnist_model.get_model(use_conv=False)
        return [model["loss"]]

    def reader():
        def gen():
            rng = np.random.RandomState(0)
            for _ in range(20):
                x = rng.rand(64, 784).astype(np.float32)
                y = rng.randint(0, 10, (64, 1)).astype(np.int64)
                yield list(zip(x, y))

        return gen

    t = Trainer(train_func, lambda: fluid.optimizer.SGD(0.1),
                fluid.CPUPlace())
    t.train(1, None, reader(), ["pixel", "label"])
    c = monitor.counter("pt_step_bound_total")
    mix = {v: c.value(labels={"verdict": v})
           for v in monitor.BOUND_VERDICTS}
    # the prefetched pipeline must never starve the step loop: zero
    # input-bound verdicts across the scored (post-warmup) steps
    assert mix["input_bound"] == 0, mix
    assert sum(mix.values()) >= 3, mix  # the window actually scored
    assert _no_loader_threads()
