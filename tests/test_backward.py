"""append_backward / gradients structural and numeric checks."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def test_duplicate_consumer_grads_are_summed():
    """x feeds two ops -> dx must be the sum of both partials
    (reference: backward.py:135 _addup_repetitive_outputs_)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(3,), dtype="float32", stop_gradient=False
        )
        a = layers.scale(x, scale=2.0)   # da/dx = 2
        b = layers.scale(x, scale=5.0)   # db/dx = 5
        s = layers.elementwise_add(a, b)
        loss = layers.reduce_sum(s)
        grads = fluid.gradients(loss, x)
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(
        main, feed={"x": np.ones(3, np.float32)}, fetch_list=[grads[0]]
    )
    np.testing.assert_allclose(out[0], np.full(3, 7.0), rtol=1e-6)
    # a sum op must have combined the two partials
    assert any(op.type == "sum" for op in main.global_block().ops)


def test_stop_gradient_blocks_grad():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(3,), dtype="float32", stop_gradient=False
        )
        y = main.global_block().create_var(
            name="y", shape=(3,), dtype="float32", stop_gradient=True
        )
        loss = layers.reduce_sum(layers.elementwise_mul(x, y))
        fluid.append_backward(loss, parameter_list=[])
    block = main.global_block()
    assert block.has_var("x@GRAD")
    assert not block.has_var("y@GRAD")


def test_no_grad_set():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(3,), dtype="float32", stop_gradient=False
        )
        z = main.global_block().create_var(
            name="z", shape=(3,), dtype="float32", stop_gradient=False
        )
        loss = layers.reduce_sum(layers.elementwise_mul(x, z))
        fluid.append_backward(loss, parameter_list=[], no_grad_set={"z"})
    assert main.global_block().has_var("x@GRAD")
    assert not main.global_block().has_var("z@GRAD")


def test_minimize_returns_optimize_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        loss = layers.mean(layers.fc(x, 1))
        opt_ops, params_grads = fluid.optimizer.SGD(0.1).minimize(loss)
    from paddle_tpu.framework import Operator, Parameter

    assert opt_ops and all(isinstance(o, Operator) for o in opt_ops)
    assert all(o.type == "sgd" for o in opt_ops)
    assert params_grads and all(isinstance(p, Parameter) for p, _ in params_grads)


def test_grad_not_flowing_through_int_inputs():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        w = main.global_block().create_var(
            name="w", shape=(10, 4), dtype="float32", stop_gradient=False
        )
        ids = main.global_block().create_var(
            name="ids", shape=(5, 1), dtype="int64", stop_gradient=True
        )
        emb = main.global_block().create_var(name="emb", dtype="float32")
        main.global_block().append_op(
            "lookup_table",
            inputs={"W": w, "Ids": ids},
            outputs={"Out": emb},
            attrs={"padding_idx": -1},
        )
        loss = layers.reduce_sum(emb)
        fluid.append_backward(loss, parameter_list=[])
    assert main.global_block().has_var("w@GRAD")
    assert not main.global_block().has_var("ids@GRAD")
