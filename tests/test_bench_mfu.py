"""Analytic-MFU dedup (bench_common.mfu): the one shared helper must
reproduce the committed BENCH_r05.json rows' mfu_best values from their
own recorded throughputs — the proof that collapsing the three hand-
rolled copies (bench.py, bench_family.py x2, bench_resnet.py) changed
no numbers."""

import json
import os

import pytest

import bench
import bench_common
import bench_family

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R05 = json.load(open(os.path.join(REPO, "BENCH_r05.json")))["parsed"]

STEPS = 30  # the shared window protocol (bench_common.run_windows)


def _window_seconds(units_per_step, value):
    """Recover the recorded best-window seconds from a throughput row:
    value = units_per_step * STEPS / best."""
    return units_per_step * STEPS / value


def test_mfu_helper_arithmetic():
    # 1 TFLOP/step x 10 steps in 2 s = 5 TFLOP/s over the 197 TFLOP/s
    # peak
    assert bench_common.mfu(1e12, 10, 2.0) == pytest.approx(
        5e12 / bench_common.V5E_PEAK_BF16)


def test_peak_defined_once_in_roofline():
    from paddle_tpu import roofline

    assert bench_common.V5E_PEAK_BF16 is roofline.V5E_PEAK_BF16
    assert roofline.BACKEND_PEAKS["tpu"][0] == bench_common.V5E_PEAK_BF16


def test_reproduces_r05_transformer_row():
    """Headline row (bench.py's copy): tokens/sec + analytic flops ->
    the recorded mfu_best. Per-token flops are batch-independent, so
    the check holds whatever batch the OOM backoff settled on."""
    class Cfg:
        d_model, d_inner, n_layer, n_head = 512, 2048, 6, 8

    batch, seq = 64, 256
    flops = bench.analytic_flops_per_step(Cfg, batch, seq, seq)
    best = _window_seconds(batch * seq, R05["value"])
    assert bench_common.mfu(flops, STEPS, best) == pytest.approx(
        R05["mfu_best"], abs=2e-4)


def test_reproduces_r05_se_resnext_row():
    """bench_family's first copy: images/sec x per-image train flops."""
    row = R05["se_resnext50"]
    batch = 128
    train_flops = 3.0 * bench_family.se_resnext50_fwd_flops_per_image()
    best = _window_seconds(batch, row["value"])
    assert bench_common.mfu(batch * train_flops, STEPS,
                            best) == pytest.approx(row["mfu_best"],
                                                   abs=2e-4)


def test_reproduces_r05_bert_row():
    """bench_family's second copy: tokens/sec + per-step train flops."""
    from paddle_tpu.models import bert

    row = R05["bert_base"]
    batch, seq = 64, 128
    flops = bench_family.bert_train_flops_per_step(bert.base(), batch,
                                                   seq)
    best = _window_seconds(batch * seq, row["value"])
    assert bench_common.mfu(flops, STEPS, best) == pytest.approx(
        row["mfu_best"], abs=2e-4)
