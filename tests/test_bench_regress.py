"""Bench-trajectory regression gate (bench_regress.py): fixture-row
checks, tolerance semantics, the CLI exit contract, and the committed
BENCH_r*.json history gating itself."""

import json
import os

import pytest

import bench_regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _row(value_mean, metric="transformer_base_train_tokens_per_sec",
         unit="tokens/sec", **extra):
    row = {"metric": metric, "value": value_mean * 1.01,
           "value_mean": value_mean, "unit": unit}
    row.update(extra)
    return row


def _driver(parsed, n=1):
    return {"n": n, "cmd": "python bench.py", "rc": 0, "parsed": parsed}


def test_flatten_row_headline_nested_and_metrics_skipped():
    parsed = _row(
        100.0,
        resnet50={"metric": "resnet50_train_images_per_sec",
                  "value": 50.0, "unit": "images/sec"},
        warm_start={"metric": "warm_start_ratio", "value": 0.8,
                    "unit": "ratio"},
        metrics={"pt_executor_steps_total": {"metric": "not_a_row",
                                             "value": 1}},
    )
    flat = bench_regress.flatten_row(parsed)
    assert flat["transformer_base_train_tokens_per_sec"]["value"] == 100.0
    # value_mean preferred over value; nested rows without one fall back
    assert flat["resnet50_train_images_per_sec"]["value"] == 50.0
    assert flat["warm_start_ratio"]["unit"] == "ratio"
    assert "not_a_row" not in flat  # the registry snapshot never gates


def test_check_flags_twenty_percent_drop_and_passes_within_tolerance():
    history = [("r01", bench_regress.flatten_row(_row(90.0))),
               ("r02", bench_regress.flatten_row(_row(100.0)))]
    # 20% below the trailing best (100) -> regression
    (f,) = bench_regress.check(
        bench_regress.flatten_row(_row(80.0)), history)
    assert f["metric"] == "transformer_base_train_tokens_per_sec"
    assert f["best"] == 100.0 and f["best_round"] == "r02"
    assert f["ratio"] == pytest.approx(0.8)
    # 5% below: inside the 10% tolerance
    assert bench_regress.check(
        bench_regress.flatten_row(_row(95.0)), history) == []
    # improvements obviously pass
    assert bench_regress.check(
        bench_regress.flatten_row(_row(120.0)), history) == []


def test_check_per_family_tolerance_and_ungated_units():
    history = [("r01", {
        "fam_tokens_per_sec": {"value": 100.0, "unit": "tokens/sec"},
        "warm_start_seconds": {"value": 10.0, "unit": "seconds"},
    })]
    fresh = {
        "fam_tokens_per_sec": {"value": 75.0, "unit": "tokens/sec"},
        # lower-is-better rider got WORSE but its unit is not gated
        "warm_start_seconds": {"value": 50.0, "unit": "seconds"},
        # brand-new family: no history, never gates
        "decode_tokens_per_sec": {"value": 1.0, "unit": "tokens/sec"},
    }
    (f,) = bench_regress.check(fresh, history)
    assert f["metric"] == "fam_tokens_per_sec"
    # a per-family override wider than the drop silences it
    bench_regress.FAMILY_TOLERANCE["fam_tokens_per_sec"] = 0.30
    try:
        assert bench_regress.check(fresh, history) == []
    finally:
        bench_regress.FAMILY_TOLERANCE.pop("fam_tokens_per_sec")
    # the global tolerance argument works the same way
    assert bench_regress.check(fresh, history, tolerance=0.30) == []


def test_check_flags_family_missing_from_fresh_row():
    """A family whose bench subprocess crashed produces NO metric —
    the worst regression must not pass by absence. The baseline is the
    UNION of history rounds (one bad committed round without the
    family must not erode the guarantee); deliberate removals need an
    explicit RETIRED_METRICS entry."""
    history = [
        ("r01", {"old_tokens_per_sec": {"value": 5.0,
                                        "unit": "tokens/sec"},
                 "fam_tokens_per_sec": {"value": 90.0,
                                        "unit": "tokens/sec"}}),
        # r02 (the newest) itself lacks both old_* and crashy_* —
        # carried-by-ANY-round still gates them
        ("r02", {"fam_tokens_per_sec": {"value": 100.0,
                                        "unit": "tokens/sec"}}),
        ("r01b", {"crashy_images_per_sec": {"value": 40.0,
                                            "unit": "images/sec"}}),
    ]
    fresh = {"fam_tokens_per_sec": {"value": 99.0,
                                    "unit": "tokens/sec"}}
    found = {f["metric"]: f for f in bench_regress.check(fresh, history)}
    assert set(found) == {"old_tokens_per_sec", "crashy_images_per_sec"}
    f = found["crashy_images_per_sec"]
    assert f["missing"] is True and f["value"] is None
    assert f["best"] == 40.0 and f["best_round"] == "r01b"
    # a deliberate retirement is an explicit escape, not silence
    old = bench_regress.RETIRED_METRICS
    bench_regress.RETIRED_METRICS = frozenset({"old_tokens_per_sec"})
    try:
        (f2,) = bench_regress.check(fresh, history)
        assert f2["metric"] == "crashy_images_per_sec"
    finally:
        bench_regress.RETIRED_METRICS = old
    # present again -> no finding
    fresh["crashy_images_per_sec"] = {"value": 41.0,
                                      "unit": "images/sec"}
    fresh["old_tokens_per_sec"] = {"value": 6.0, "unit": "tokens/sec"}
    assert bench_regress.check(fresh, history) == []


def _write_rounds(tmp_path, values):
    paths = []
    for i, v in enumerate(values, start=1):
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps(_driver(_row(v), n=i)))
        paths.append(str(p))
    return paths


def test_main_exits_nonzero_on_synthetic_drop(tmp_path, capsys):
    _write_rounds(tmp_path, [90.0, 100.0, 79.0])  # fresh = 79 vs best 100
    rc = bench_regress.main(
        ["--history", str(tmp_path / "BENCH_r*.json")])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is False and out["row"] == "BENCH_r03.json"
    (f,) = out["regressions"]
    assert f["ratio"] == pytest.approx(0.79)


def test_main_passes_on_healthy_trajectory(tmp_path, capsys):
    _write_rounds(tmp_path, [90.0, 100.0, 97.0])
    rc = bench_regress.main(
        ["--history", str(tmp_path / "BENCH_r*.json")])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True


def test_main_row_mode_gates_fresh_row_against_all_rounds(tmp_path,
                                                          capsys):
    _write_rounds(tmp_path, [90.0, 100.0])
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(_row(70.0)))  # bare row, no wrapper
    rc = bench_regress.main(
        ["--history", str(tmp_path / "BENCH_r*.json"),
         "--row", str(fresh)])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["row"] == "fresh.json"
    assert out["rounds"] == ["BENCH_r01.json", "BENCH_r02.json"]
    # the same fresh row passes once the tolerance covers the gap
    rc = bench_regress.main(
        ["--history", str(tmp_path / "BENCH_r*.json"),
         "--row", str(fresh), "--tolerance", "0.5"])
    assert rc == 0
    capsys.readouterr()


def test_main_needs_enough_history(tmp_path, capsys):
    _write_rounds(tmp_path, [100.0])
    rc = bench_regress.main(
        ["--history", str(tmp_path / "BENCH_r*.json")])
    assert rc == 2
    capsys.readouterr()


def test_committed_history_passes_the_gate(capsys):
    """The acceptance row: the repo's own BENCH_r*.json trajectory must
    pass — r05 gated against r01..r04 regresses nothing at the default
    tolerance."""
    rc = bench_regress.main(
        ["--history", os.path.join(REPO, "BENCH_r*.json")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["regressions"]
    assert out["row"] == "BENCH_r05.json"
    assert "transformer_base_train_tokens_per_sec" in out["gated_metrics"]


def test_committed_history_flags_synthetic_twenty_percent_drop(
        tmp_path, capsys):
    """The other acceptance half: a synthetic 20% throughput drop on
    the REAL history is flagged."""
    r05 = json.load(open(os.path.join(REPO, "BENCH_r05.json")))["parsed"]
    degraded = json.loads(json.dumps(r05))  # deep copy
    for key in ("value", "value_mean"):
        degraded[key] = r05[key] * 0.8
    fresh = tmp_path / "degraded.json"
    fresh.write_text(json.dumps(degraded))
    rc = bench_regress.main(
        ["--history", os.path.join(REPO, "BENCH_r*.json"),
         "--row", str(fresh)])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert any(f["metric"] == "transformer_base_train_tokens_per_sec"
               for f in out["regressions"])


def test_degraded_serving_family_gates_with_wide_tolerance():
    """The bench_serving degraded-mode rider (tokens/s under a seeded
    serve.decode delay fault at 1% of steps) is a gated family: a 30%
    drop is flagged under its 0.20 tolerance, a 15% drop is inside it —
    resilience overhead is tracked, not guessed."""
    assert bench_regress.FAMILY_TOLERANCE[
        "serving_degraded_tokens_per_sec"] == pytest.approx(0.20)
    base = _row(
        5000.0, metric="serving_decode_tokens_per_sec",
        degraded={"metric": "serving_degraded_tokens_per_sec",
                  "value": 4000.0, "unit": "tokens/sec",
                  "token_ms_p99": 2.0})
    flat = bench_regress.flatten_row(base)
    assert flat["serving_degraded_tokens_per_sec"]["value"] == 4000.0
    history = [("r06", flat)]

    def fresh(v):
        return bench_regress.flatten_row(_row(
            5000.0, metric="serving_decode_tokens_per_sec",
            degraded={"metric": "serving_degraded_tokens_per_sec",
                      "value": v, "unit": "tokens/sec"}))

    (f,) = bench_regress.check(fresh(2800.0), history)  # -30%
    assert f["metric"] == "serving_degraded_tokens_per_sec"
    assert f["tolerance"] == pytest.approx(0.20)
    assert bench_regress.check(fresh(3400.0), history) == []  # -15%
    # a crashed degraded sweep (row absent) is itself a finding
    missing = bench_regress.flatten_row(_row(
        5000.0, metric="serving_decode_tokens_per_sec"))
    (f,) = bench_regress.check(missing, history)
    assert f["metric"] == "serving_degraded_tokens_per_sec"
    assert f.get("missing") is True


def test_serving_latency_riders_gate_lower_is_better():
    """The serving TTFT/queue-wait p95 riders (bench_serving.py's
    ``latency`` block) gate in the OPPOSITE direction: best is the
    MINIMUM across history, and a fresh value rising more than the
    allowlist tolerance above it is a regression. A plain ``ms`` unit
    outside the allowlist still never gates."""
    assert bench_regress.LATENCY_TOLERANCE[
        "serving_ttft_ms_p95"] == pytest.approx(0.50)

    def row(ttft, qwait):
        return bench_regress.flatten_row(_row(
            5000.0, metric="serving_decode_tokens_per_sec",
            latency={
                "ttft": {"metric": "serving_ttft_ms_p95",
                         "value": ttft, "unit": "ms"},
                "qwait": {"metric": "serving_queue_wait_ms_p95",
                          "value": qwait, "unit": "ms"},
            }))

    history = [("r06", row(100.0, 40.0)), ("r07", row(80.0, 50.0))]
    # best = min across history (80 / 40); +50% boundaries 120 / 60
    found = {f["metric"]: f
             for f in bench_regress.check(row(130.0, 70.0), history)}
    assert set(found) == {"serving_ttft_ms_p95",
                          "serving_queue_wait_ms_p95"}
    f = found["serving_ttft_ms_p95"]
    assert f["direction"] == "above"
    assert f["best"] == 80.0 and f["best_round"] == "r07"
    assert f["ratio"] == pytest.approx(130.0 / 80.0)
    # inside the envelope (and improvements) pass
    assert bench_regress.check(row(115.0, 55.0), history) == []
    assert bench_regress.check(row(10.0, 5.0), history) == []
    # carried-by-history latency rows missing from fresh are findings
    bare = bench_regress.flatten_row(_row(
        5000.0, metric="serving_decode_tokens_per_sec"))
    found = {f["metric"]: f for f in bench_regress.check(bare, history)}
    assert found["serving_ttft_ms_p95"]["missing"] is True
    assert found["serving_ttft_ms_p95"]["tolerance"] == pytest.approx(0.50)
    # an un-allowlisted ms rider never gates, even when it balloons
    hist2 = [("r01", {"tile_ms_p95": {"value": 1.0, "unit": "ms"}})]
    assert bench_regress.check(
        {"tile_ms_p95": {"value": 99.0, "unit": "ms"}}, hist2) == []
