"""The "book" acceptance chapters the round-1 suite didn't cover.

Reference: python/paddle/fluid/tests/book/ trains each chapter's model to
a convergence threshold and round-trips save/load_inference_model
(SURVEY.md section 4.6 — the reference's acceptance suite). fit_a_line
and recognize_digits live in test_train.py; this file adds
image_classification (cifar10), understand_sentiment (imdb),
word2vec, recommender_system, and machine_translation.
"""

import pytest
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import dataset, io, layers, reader
from paddle_tpu.data_feeder import DataFeeder


def _train_loop(main, startup, feeder, loss, batches, exe=None):
    exe = exe or fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for batch in batches:
        out = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
        losses.append(float(out[0]))
    return exe, losses


def _pad(seqs, maxlen, pad=0):
    out = np.full((len(seqs), maxlen), pad, np.int64)
    for i, s in enumerate(seqs):
        out[i, : min(len(s), maxlen)] = s[:maxlen]
    return out


@pytest.mark.full
def test_book_image_classification_cifar(tmp_path):
    """book ch3: a small conv net on cifar10 (reference:
    tests/book/test_image_classification.py)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("pixel", shape=[3 * 32 * 32], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        x = layers.reshape(img, [0, 3, 32, 32])
        x = layers.conv2d(x, 16, 3, padding=1, act="relu")
        x = layers.pool2d(x, 2, pool_stride=2)
        x = layers.conv2d(x, 32, 3, padding=1, act="relu")
        x = layers.pool2d(x, 2, pool_stride=2)
        logits = layers.fc(layers.flatten(x), 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(logits, label)
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(2e-3).minimize(loss)
    feeder = DataFeeder([img, label])
    batches = list(reader.batch(dataset.cifar.train10(), 64)())[:50]
    exe, losses = _train_loop(main, startup, feeder, loss, batches)
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.9, losses[::16]

    d = str(tmp_path / "cifar_model")
    io.save_inference_model(d, ["pixel"], [logits], exe, main)
    exe2 = fluid.Executor(fluid.CPUPlace())
    prog2, feed_names, fetch_vars = io.load_inference_model(d, exe2)
    fd = feeder.feed(batches[0])
    ref = exe.run(test_prog, feed=fd, fetch_list=[logits])[0]
    got = exe2.run(prog2, feed={"pixel": fd["pixel"]},
                   fetch_list=fetch_vars)[0]
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-5)


def test_book_understand_sentiment_imdb():
    """book ch6: embedding + sequence pooling sentiment classifier
    (reference: tests/book/test_understand_sentiment.py)."""
    vocab, maxlen = 5148, 64
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = layers.data("words", shape=[maxlen], dtype="int64")
        label = layers.data("label", shape=[1], dtype="int64")
        emb = layers.embedding(words, size=[vocab, 32])
        pooled = layers.sequence_pool(emb, "average")
        h = layers.fc(pooled, 32, act="relu", num_flatten_dims=1)
        logits = layers.fc(h, 2, num_flatten_dims=1)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(logits, label)
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(5e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def batches(rdr, n):
        out = []
        buf_w, buf_l = [], []
        for ids, lbl in rdr():
            buf_w.append(ids)
            buf_l.append(lbl)
            if len(buf_w) == 32:
                out.append({"words": _pad(buf_w, maxlen),
                            "label": np.asarray(buf_l, np.int64)[:, None]})
                buf_w, buf_l = [], []
            if len(out) >= n:
                break
        return out

    train_b = batches(dataset.imdb.train(), 60)
    losses = [
        float(exe.run(main, feed=fd, fetch_list=[loss])[0])
        for fd in train_b
    ]
    accs = [
        float(exe.run(test_prog, feed=fd, fetch_list=[acc])[0])
        for fd in batches(dataset.imdb.test(), 8)
    ]
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.9
    assert np.mean(accs) > 0.6, accs


def test_book_word2vec():
    """book ch4: N-gram next-word prediction over shared embeddings
    (reference: tests/book/test_word2vec.py)."""
    vocab, emb_dim, n = 128, 16, 4
    r = np.random.RandomState(5)
    # synthetic corpus with learnable bigram structure
    trans = r.permutation(vocab)
    corpus = [0]
    for _ in range(4000):
        nxt = trans[corpus[-1]] if r.rand() < 0.8 else r.randint(vocab)
        corpus.append(int(nxt))
    corpus = np.asarray(corpus, np.int64)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ctx = layers.data("ctx", shape=[n], dtype="int64")
        nxt = layers.data("next", shape=[1], dtype="int64")
        embs = layers.embedding(
            ctx, size=[vocab, emb_dim],
            param_attr=fluid.ParamAttr(name="shared_emb.w"))
        concat = layers.reshape(embs, [0, n * emb_dim])
        h = layers.fc(concat, 64, act="relu", num_flatten_dims=1)
        logits = layers.fc(h, vocab, num_flatten_dims=1)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, nxt))
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for step in range(120):
        i = (step * 32) % (len(corpus) - n - 33)
        windows = np.stack([corpus[i + k: i + k + n] for k in range(32)])
        nxts = corpus[i + n: i + n + 32][:, None]
        out = exe.run(main, feed={"ctx": windows, "next": nxts},
                      fetch_list=[loss])
        losses.append(float(out[0]))
    # ppl must drop well below uniform (log 128 ~= 4.85)
    assert np.mean(losses[-10:]) < 3.0, losses[::24]


def test_book_recommender_system():
    """book ch5: dot-product factorization of a user/item rating matrix
    (reference: tests/book/test_recommender_system.py)."""
    users, items, k = 64, 96, 8
    r = np.random.RandomState(7)
    u_lat = r.normal(0, 1, (users, k))
    i_lat = r.normal(0, 1, (items, k))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = layers.data("uid", shape=[1], dtype="int64")
        iid = layers.data("iid", shape=[1], dtype="int64")
        rating = layers.data("rating", shape=[1], dtype="float32")
        ue = layers.reshape(layers.embedding(uid, size=[users, 16]), [0, 16])
        ie = layers.reshape(layers.embedding(iid, size=[items, 16]), [0, 16])
        uf = layers.fc(ue, 16, num_flatten_dims=1)
        itf = layers.fc(ie, 16, num_flatten_dims=1)
        pred = layers.reduce_sum(
            layers.elementwise_mul(uf, itf), dim=1, keep_dim=True)
        loss = layers.reduce_mean(layers.square_error_cost(pred, rating))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for step in range(150):
        us = r.randint(0, users, (64, 1)).astype(np.int64)
        its = r.randint(0, items, (64, 1)).astype(np.int64)
        ratings = np.sum(u_lat[us[:, 0]] * i_lat[its[:, 0]],
                         axis=1, keepdims=True).astype(np.float32)
        out = exe.run(main, feed={"uid": us, "iid": its, "rating": ratings},
                      fetch_list=[loss])
        losses.append(float(out[0]))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.5, losses[::30]


@pytest.mark.full
def test_book_machine_translation(tmp_path):
    """book ch8: seq2seq NMT trains and greedy-decodes (reference:
    tests/book/test_machine_translation.py). Uses the zoo's LSTM
    seq2seq-with-attention on the wmt16 synthetic reader."""
    from paddle_tpu.models import seq2seq

    cfg = seq2seq.Seq2SeqConfig(src_vocab_size=200, trg_vocab_size=200,
                                hidden_dim=64, embed_dim=32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = seq2seq.build(cfg)
        fluid.optimizer.Adam(5e-3).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for step in range(90):
        fd = seq2seq.make_batch(cfg, 16, 12, 12, seed=step % 6)
        out = exe.run(main, feed=fd, fetch_list=[model["loss"]])
        losses.append(float(out[0]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7, losses[::15]


def test_book_stacked_dynamic_lstm_sentiment():
    """The reference benchmark's stacked_dynamic_lstm model family
    (reference: benchmark/fluid/models/stacked_dynamic_lstm.py) trains on
    the imdb-style synthetic signal."""
    from paddle_tpu.models import stacked_lstm

    cfg = stacked_lstm.StackedLSTMConfig(
        vocab_size=512, embed_dim=32, hidden_dim=32, stacked_num=2,
        max_len=48)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = stacked_lstm.build(cfg)
        fluid.optimizer.Adam(5e-3).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses, accs = [], []
    for step in range(60):
        fd = stacked_lstm.make_batch(cfg, 32, seed=step % 8)
        out = exe.run(main, feed=fd,
                      fetch_list=[model["loss"], model["acc"]])
        losses.append(float(out[0]))
        accs.append(float(out[1]))
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) * 0.8, losses[::12]
    assert np.mean(accs[-8:]) > 0.75, accs[::12]


@pytest.mark.full
def test_book_recommender_system_movielens():
    """book ch5 on the movielens loader (reference:
    tests/book/test_recommender_system.py): the full feature network —
    user id/gender/age/job embeddings + movie id/category/title
    embeddings -> fused fc towers -> dot product rating."""
    from paddle_tpu.dataset import movielens

    CAT_PAD, TITLE_PAD = 6, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = layers.data("uid", shape=[1], dtype="int64")
        gender = layers.data("gender", shape=[1], dtype="int64")
        age = layers.data("age", shape=[1], dtype="int64")
        job = layers.data("job", shape=[1], dtype="int64")
        mid = layers.data("mid", shape=[1], dtype="int64")
        cats = layers.data("cats", shape=[CAT_PAD], dtype="int64")
        cmask = layers.data("cmask", shape=[CAT_PAD], dtype="float32")
        title = layers.data("title", shape=[TITLE_PAD], dtype="int64")
        tmask = layers.data("tmask", shape=[TITLE_PAD], dtype="float32")
        rating = layers.data("rating", shape=[1], dtype="float32")

        def emb(x, size, dim=16):
            return layers.embedding(x, size=[size, dim])

        usr = layers.concat([
            layers.reshape(emb(uid, movielens.max_user_id() + 1), [0, 16]),
            layers.reshape(emb(gender, 2), [0, 16]),
            layers.reshape(emb(age, len(movielens.age_table)), [0, 16]),
            layers.reshape(emb(job, movielens.max_job_id() + 1), [0, 16]),
        ], axis=1)
        usr_feat = layers.fc(usr, 32, act="tanh")

        cat_e = emb(cats, len(movielens.movie_categories()))  # [N, C, 16]
        cat_pool = layers.reduce_sum(
            layers.elementwise_mul(cat_e, layers.unsqueeze(cmask, [2])),
            dim=1)
        tit_e = emb(title, len(movielens.get_movie_title_dict()))
        tit_pool = layers.reduce_sum(
            layers.elementwise_mul(tit_e, layers.unsqueeze(tmask, [2])),
            dim=1)
        mov = layers.concat([
            layers.reshape(emb(mid, movielens.max_movie_id() + 1), [0, 16]),
            cat_pool, tit_pool], axis=1)
        mov_feat = layers.fc(mov, 32, act="tanh")

        pred = layers.reduce_sum(
            layers.elementwise_mul(usr_feat, mov_feat), dim=1,
            keep_dim=True)
        loss = layers.reduce_mean(layers.square_error_cost(pred, rating))
        fluid.optimizer.Adam(5e-3).minimize(loss)

    def batches(reader, bs):
        buf = []
        for rec in reader():
            buf.append(rec)
            if len(buf) == bs:
                yield buf
                buf = []

    def feed_of(batch):
        n = len(batch)
        fd = {"uid": np.zeros((n, 1), np.int64),
              "gender": np.zeros((n, 1), np.int64),
              "age": np.zeros((n, 1), np.int64),
              "job": np.zeros((n, 1), np.int64),
              "mid": np.zeros((n, 1), np.int64),
              "cats": np.zeros((n, CAT_PAD), np.int64),
              "cmask": np.zeros((n, CAT_PAD), np.float32),
              "title": np.zeros((n, TITLE_PAD), np.int64),
              "tmask": np.zeros((n, TITLE_PAD), np.float32),
              "rating": np.zeros((n, 1), np.float32)}
        for i, (u, g, a, j, m, cs, ts, sc) in enumerate(batch):
            fd["uid"][i], fd["gender"][i], fd["age"][i] = u, g, a
            fd["job"][i], fd["mid"][i], fd["rating"][i] = j, m, sc
            cs, ts = cs[:CAT_PAD], ts[:TITLE_PAD]
            fd["cats"][i, :len(cs)] = cs
            fd["cmask"][i, :len(cs)] = 1.0 / max(len(cs), 1)
            fd["title"][i, :len(ts)] = ts
            fd["tmask"][i, :len(ts)] = 1.0 / max(len(ts), 1)
        return fd

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for epoch in range(3):
        for batch in batches(movielens.train(), 256):
            out = exe.run(main, feed=feed_of(batch), fetch_list=[loss])
            losses.append(float(out[0]))
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) * 0.55, losses[::40]


def test_book_understand_sentiment_nltk_loader():
    """book ch6 on the dataset.sentiment loader (reference:
    tests/book/test_understand_sentiment.py + dataset/sentiment.py):
    embedding + mean-pool + fc classifier learns the polarity split."""
    from paddle_tpu.dataset import sentiment

    vocab = len(sentiment.get_word_dict())
    MAXLEN = 120
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = layers.data("words", shape=[MAXLEN], dtype="int64")
        mask = layers.data("mask", shape=[MAXLEN], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        e = layers.embedding(words, size=[vocab, 16])
        pooled = layers.reduce_sum(
            layers.elementwise_mul(e, layers.unsqueeze(mask, [2])), dim=1)
        logits = layers.fc(layers.fc(pooled, 32, act="relu"), 2)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        fluid.optimizer.Adam(2e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    accs = []
    # ~39.8k-word vocab over 1600 docs: each word is seen only a few
    # times per epoch, so run 3 epochs before asking for separation
    for _ in range(3):
        buf = []
        for ids, lab in sentiment.train()():
            buf.append((ids, lab))
            if len(buf) < 64:
                continue
            w = np.zeros((64, MAXLEN), np.int64)
            mk = np.zeros((64, MAXLEN), np.float32)
            lb = np.zeros((64, 1), np.int64)
            for i, (ids_i, l_i) in enumerate(buf):
                ids_i = ids_i[:MAXLEN]
                w[i, :len(ids_i)] = ids_i
                mk[i, :len(ids_i)] = 1.0 / len(ids_i)
                lb[i] = l_i
            buf = []
            _, a = exe.run(main, feed={"words": w, "mask": mk,
                                       "label": lb},
                           fetch_list=[loss, acc])
            accs.append(float(np.asarray(a)))
    assert np.mean(accs[-5:]) > 0.75, accs[::5]


def test_conll05_and_wmt14_loader_contracts():
    """The conll05/wmt14 loaders honor the reference record contracts
    (9 parallel sequences with the verb context window; BOS/EOS framed
    token triples)."""
    from paddle_tpu.dataset import conll05, wmt14

    w_d, v_d, l_d = conll05.get_dict()
    emb = conll05.get_embedding()
    assert emb.shape == (len(w_d), 32)
    rec = next(iter(conll05.test()()))
    assert len(rec) == 9
    words = rec[0]
    for seq in rec[1:8]:
        assert len(seq) == len(words)
    assert sum(rec[7]) <= 5 and max(rec[8]) < len(l_d)
    # the B-V analog sits at the verb position
    vi = rec[8].index(1)
    assert rec[7][vi] == 1

    sd, td = wmt14.get_dict(100)
    assert sd[0] == "<s>" and sd[1] == "<e>" and sd[2] == "<unk>"
    src, trg, nxt = next(iter(wmt14.train(100)()))
    assert src[0] == wmt14.BOS and src[-1] == wmt14.EOS
    assert trg[0] == wmt14.BOS and nxt[-1] == wmt14.EOS
    assert list(trg[1:]) == list(nxt[:-1])
