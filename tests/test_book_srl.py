"""Book ch07 label_semantic_roles + DynamicRNN/IfElse layers.

Reference: python/paddle/fluid/tests/book/test_label_semantic_roles.py
(CRF-based semantic role labelling trained end to end, then a
save/load_inference_model round-trip) and layers/control_flow.py
DynamicRNN:1661 / IfElse:1525.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import io, layers

WORD_DICT, LABEL_DICT = 64, 8
SEQ, BATCH = 12, 16
EMB, HID = 24, 32


def _srl_batch(seed):
    """Synthetic SRL data with a learnable rule: the label of a word
    depends on its id bucket and whether the predicate is nearby."""
    r = np.random.RandomState(seed)
    words = r.randint(1, WORD_DICT, (BATCH, SEQ)).astype(np.int64)
    pred = r.randint(0, SEQ, (BATCH,))
    mark = np.zeros((BATCH, SEQ), np.int64)
    for i, p in enumerate(pred):
        mark[i, p] = 1
    length = r.randint(SEQ // 2, SEQ + 1, (BATCH,)).astype(np.int64)
    labels = ((words % 4) + 4 * mark) % LABEL_DICT
    for i in range(BATCH):
        labels[i, length[i]:] = 0
        words[i, length[i]:] = 0
    return {"word": words, "mark": mark, "label": labels,
            "length": length}


def _build_srl():
    word = layers.data("word", shape=[SEQ], dtype="int64")
    mark = layers.data("mark", shape=[SEQ], dtype="int64")
    label = layers.data("label", shape=[SEQ], dtype="int64")
    length = layers.data("length", shape=[1], dtype="int64")

    word_emb = layers.embedding(word, size=[WORD_DICT, EMB])
    mark_emb = layers.embedding(mark, size=[2, EMB // 2])
    feat = layers.concat([word_emb, mark_emb], axis=-1)  # [B, T, E]

    drnn = layers.DynamicRNN()
    with drnn.block():
        w = drnn.step_input(feat, length=length)
        prev = drnn.memory(shape=[HID])
        h = layers.fc([w, prev], HID, act="tanh")
        drnn.update_memory(prev, h)
        drnn.output(h)
    hidden = drnn()                                      # [B, T, HID]

    emission = layers.fc(hidden, LABEL_DICT, num_flatten_dims=2)
    crf_cost = layers.linear_chain_crf(
        emission, label, param_attr=fluid.ParamAttr(name="crfw"),
        length=length)
    avg_cost = layers.mean(crf_cost)
    decode = layers.crf_decoding(
        emission, param_attr=fluid.ParamAttr(name="crfw"), length=length)
    return word, mark, label, length, emission, avg_cost, decode


def test_book_label_semantic_roles(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        (word, mark, label, length, emission, avg_cost,
         decode) = _build_srl()
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(5e-3).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for step in range(120):
            out = exe.run(main, feed=_srl_batch(step % 8),
                          fetch_list=[avg_cost])
            losses.append(float(out[0]))
        assert np.isfinite(losses).all()
        # converges like the reference's train loop (cost drops hard)
        assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.6, \
            losses[::20]

        # save/load_inference_model round-trip on the decode path
        d = str(tmp_path / "srl_model")
        io.save_inference_model(
            d, ["word", "mark", "length"], [emission, decode], exe, main)
        fd = _srl_batch(3)
        ref_em, ref_path = exe.run(
            test_prog, feed=fd, fetch_list=[emission, decode])

    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        prog2, feed_names, fetch_vars = io.load_inference_model(d, exe2)
        got_em, got_path = exe2.run(
            prog2,
            feed={k: fd[k] for k in ("word", "mark", "length")},
            fetch_list=fetch_vars)
    np.testing.assert_allclose(ref_em, got_em, rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(ref_path, got_path)


def test_dynamic_rnn_masks_and_freezes():
    """Memories freeze and outputs zero past each sample's length."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[5, 3], dtype="float32")
        length = layers.data("length", shape=[1], dtype="int64")
        drnn = layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x, length=length)
            acc = drnn.memory(shape=[3])
            new = layers.elementwise_add(acc, xt)
            drnn.update_memory(acc, new)
            drnn.output(new)
        out = drnn()
        last = layers.sequence_pool(out, "last", length=length)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    xv = np.ones((2, 5, 3), np.float32)
    lv = np.array([[2], [4]], np.int64)
    with fluid.scope_guard(scope):
        exe.run(startup)
        o, l = exe.run(main, feed={"x": xv, "length": lv},
                       fetch_list=[out, last])
    # running sums up to the length, zeros after
    np.testing.assert_allclose(o[0, :2, 0], [1, 2])
    np.testing.assert_allclose(o[0, 2:, 0], [0, 0, 0])
    np.testing.assert_allclose(o[1, :4, 0], [1, 2, 3, 4])
    np.testing.assert_allclose(l[:, 0], [2, 4])


def test_if_else_merges_rows():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        limit = layers.data("limit", shape=[1], dtype="float32")
        row_sum = layers.reduce_sum(x, dim=1, keep_dim=True)
        cond = layers.less_than(row_sum, limit)
        ie = layers.IfElse(cond)
        with ie.true_block():
            ie.output(layers.scale(ie.input(x), 2.0))
        with ie.false_block():
            ie.output(layers.scale(ie.input(x), -1.0))
        out = ie()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    xv = np.array([[1, 1, 1, 1], [9, 9, 9, 9]], np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        o = exe.run(main, feed={"x": xv,
                                "limit": np.full((2, 1), 10.0, np.float32)},
                    fetch_list=[out])[0]
    np.testing.assert_allclose(o[0], xv[0] * 2.0)   # sum 4 < 10 -> true
    np.testing.assert_allclose(o[1], xv[1] * -1.0)  # sum 36 >= 10 -> false
