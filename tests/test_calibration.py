"""Activation-range int8 PTQ calibration (reference:
contrib/int8_inference/utility.py Calibrator +
contrib/slim/quantization/quantization_pass.py:541,836): collect
activation abs-max over warmup batches, bake static QDQ into the
inference program, export/load an int8 artifact, and check the
accuracy delta vs float serving."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.slim.calibration import (Calibrator, _kl_scale,
                                         load_int8_inference_model,
                                         save_int8_inference_model)


def _train_mnist_mlp(steps=30):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[784])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, 64, act="relu")
        logits = layers.fc(h, 10)
        infer = main.clone(for_test=True)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(2e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            x = rng.normal(0, 1, (32, 784)).astype(np.float32)
            y = np.argmax(x[:, :10], 1)[:, None].astype(np.int64)
            exe.run(main, feed={"img": x, "label": y}, fetch_list=[loss])
    return infer, logits, exe, scope, rng


def test_calibrate_freeze_export_load_accuracy(tmp_path):
    infer, logits, exe, scope, rng = _train_mnist_mlp()
    with fluid.scope_guard(scope):
        calib = Calibrator(infer, exe, scope=scope, algo="abs_max")
        # both matmuls' activation inputs are calibrated
        assert len(calib.activation_names) >= 2
        for _ in range(4):
            calib.sample({"img": rng.normal(0, 1, (32, 784)).astype(
                np.float32)})
        scales = calib.compute_scales()
        assert all(s > 0 for s in scales.values())

        frozen = calib.freeze()
        f_types = [o.type for o in frozen.global_block().ops]
        assert f_types.count("quantize_dequantize_static") == len(scales)
        # original program untouched
        assert "quantize_dequantize_static" not in [
            o.type for o in infer.global_block().ops]

        save_int8_inference_model(str(tmp_path / "int8"), ["img"],
                                  [logits], exe, infer, calib, scope=scope)

    # artifact shape: int8 params, no fp32 params file
    import os
    assert os.path.exists(tmp_path / "int8" / "__params_int8__.npz")
    assert not os.path.exists(tmp_path / "int8" / "__params__.npz")
    qs = np.load(tmp_path / "int8" / "__params_int8__.npz")
    assert all(qs[n].dtype == np.int8 for n in qs.files)

    # load into a FRESH scope and compare against float serving
    x_eval = rng.normal(0, 1, (64, 784)).astype(np.float32)
    with fluid.scope_guard(scope):
        (f_logits,) = exe.run(infer, feed={"img": x_eval},
                              fetch_list=[logits])
    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = load_int8_inference_model(
            str(tmp_path / "int8"), exe2, scope=scope2)
        assert feeds == ["img"]
        (q_logits,) = exe2.run(prog, feed={"img": x_eval},
                               fetch_list=fetches)
    f_logits, q_logits = np.asarray(f_logits), np.asarray(q_logits)
    # int8 artifact serving is quantized-but-close: top-1 agreement
    agree = (np.argmax(f_logits, 1) == np.argmax(q_logits, 1)).mean()
    assert agree >= 0.95, agree
    err = np.abs(f_logits - q_logits).max() / np.abs(f_logits).max()
    assert 0 < err < 0.15, err  # quantization error present but bounded


def test_kl_scale_clips_outliers():
    """The KL algo picks a threshold below abs-max for heavy-tailed
    data (the reference's 'KL' option) and equals-ish abs-max for
    uniform data."""
    rng = np.random.RandomState(1)
    body = rng.normal(0, 1, (10000,)).astype(np.float32)
    spiked = np.concatenate([body, [80.0]]).astype(np.float32)
    s = _kl_scale([spiked])
    assert s < 40.0, s                      # outlier clipped away
    flat = rng.uniform(-1, 1, (10000,)).astype(np.float32)
    s2 = _kl_scale([flat])
    assert s2 > 0.5, s2
