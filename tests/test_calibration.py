"""Activation-range int8 PTQ calibration (reference:
contrib/int8_inference/utility.py Calibrator +
contrib/slim/quantization/quantization_pass.py:541,836): collect
activation abs-max over warmup batches, bake static QDQ into the
inference program, export/load an int8 artifact, and check the
accuracy delta vs float serving."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.slim.calibration import (Calibrator, _kl_scale,
                                         load_int8_inference_model,
                                         save_int8_inference_model)


def _train_mnist_mlp(steps=30):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[784])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, 64, act="relu")
        logits = layers.fc(h, 10)
        infer = main.clone(for_test=True)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(2e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            x = rng.normal(0, 1, (32, 784)).astype(np.float32)
            y = np.argmax(x[:, :10], 1)[:, None].astype(np.int64)
            exe.run(main, feed={"img": x, "label": y}, fetch_list=[loss])
    return infer, logits, exe, scope, rng


def test_calibrate_freeze_export_load_accuracy(tmp_path):
    infer, logits, exe, scope, rng = _train_mnist_mlp()
    with fluid.scope_guard(scope):
        calib = Calibrator(infer, exe, scope=scope, algo="abs_max")
        # both matmuls' activation inputs are calibrated
        assert len(calib.activation_names) >= 2
        for _ in range(4):
            calib.sample({"img": rng.normal(0, 1, (32, 784)).astype(
                np.float32)})
        scales = calib.compute_scales()
        assert all(s > 0 for s in scales.values())

        frozen = calib.freeze()
        f_types = [o.type for o in frozen.global_block().ops]
        assert f_types.count("quantize_dequantize_static") == len(scales)
        # original program untouched
        assert "quantize_dequantize_static" not in [
            o.type for o in infer.global_block().ops]

        save_int8_inference_model(str(tmp_path / "int8"), ["img"],
                                  [logits], exe, infer, calib, scope=scope)

    # artifact shape: int8 snapshot holds ONLY the quantizable-op
    # weights; everything else (biases here; BN stats in conv nets)
    # stays fp32 in the params file, with no overlap
    import os
    assert os.path.exists(tmp_path / "int8" / "__params_int8__.npz")
    qs = np.load(tmp_path / "int8" / "__params_int8__.npz")
    assert all(qs[n].dtype == np.int8 for n in qs.files)
    assert set(qs.files) == set(calib.weight_names)
    fp32 = np.load(tmp_path / "int8" / "__params__.npz")
    assert not (set(fp32.files) & set(qs.files))
    assert len(fp32.files) > 0  # the fc biases survived fp32

    # load into a FRESH scope and compare against float serving
    x_eval = rng.normal(0, 1, (64, 784)).astype(np.float32)
    with fluid.scope_guard(scope):
        (f_logits,) = exe.run(infer, feed={"img": x_eval},
                              fetch_list=[logits])
    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = load_int8_inference_model(
            str(tmp_path / "int8"), exe2, scope=scope2)
        assert feeds == ["img"]
        (q_logits,) = exe2.run(prog, feed={"img": x_eval},
                               fetch_list=fetches)
    f_logits, q_logits = np.asarray(f_logits), np.asarray(q_logits)
    # int8 artifact serving is quantized-but-close: top-1 agreement
    agree = (np.argmax(f_logits, 1) == np.argmax(q_logits, 1)).mean()
    assert agree >= 0.95, agree
    err = np.abs(f_logits - q_logits).max() / np.abs(f_logits).max()
    assert 0 < err < 0.15, err  # quantization error present but bounded


def test_kl_scale_clips_outliers():
    """The KL algo picks a threshold below abs-max for heavy-tailed
    data (the reference's 'KL' option) and equals-ish abs-max for
    uniform data."""
    rng = np.random.RandomState(1)
    body = rng.normal(0, 1, (10000,)).astype(np.float32)
    spiked = np.concatenate([body, [80.0]]).astype(np.float32)
    s = _kl_scale([spiked])
    assert s < 40.0, s                      # outlier clipped away
    flat = rng.uniform(-1, 1, (10000,)).astype(np.float32)
    s2 = _kl_scale([flat])
    assert s2 > 0.5, s2


def test_conv_bn_int8_roundtrip(tmp_path):
    """BN statistics must NOT be int8-quantized: a moving_variance with
    small entries crushes to 0 under symmetric per-tensor int8 and
    rsqrt(0+eps) blows the channel up (the ConvertToInt8Pass keeps
    non-weight params fp32; so do we)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[4, 8, 8], dtype="float32")
        y = layers.conv2d(img, 8, 3, padding=1, bias_attr=False)
        y = layers.batch_norm(y, is_test=True, moving_variance_name="bn_moving_var")
        logits = layers.fc(layers.reshape(y, [-1, 8 * 8 * 8]), 10)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        # force a wide-dynamic-range variance: int8 would zero the
        # small entries
        bn_var = "bn_moving_var"
        var = np.asarray(scope.find_var(bn_var)).copy()
        var[: len(var) // 2] = 1e-4
        var[len(var) // 2:] = 5.0
        scope.set(bn_var, var)

        calib = Calibrator(main, exe, scope=scope, algo="abs_max")
        for _ in range(2):
            calib.sample({"img": rng.normal(0, 1, (8, 4, 8, 8)).astype(
                np.float32)})
        save_int8_inference_model(str(tmp_path / "i8"), ["img"],
                                  [logits], exe, main, calib, scope=scope)
        x = rng.normal(0, 1, (16, 4, 8, 8)).astype(np.float32)
        (ref,) = exe.run(main, feed={"img": x}, fetch_list=[logits])

    qs = np.load(tmp_path / "i8" / "__params_int8__.npz")
    assert bn_var not in qs.files  # BN variance stayed fp32

    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = load_int8_inference_model(
            str(tmp_path / "i8"), exe2, scope=scope2)
        np.testing.assert_allclose(np.asarray(scope2.find_var(bn_var)),
                                   var)  # bit-exact fp32 roundtrip
        (q_out,) = exe2.run(prog, feed={"img": x}, fetch_list=fetches)
    ref, q_out = np.asarray(ref), np.asarray(q_out)
    err = np.abs(ref - q_out).max() / max(np.abs(ref).max(), 1e-6)
    assert err < 0.2, err  # no rsqrt blow-up from a zeroed variance


def test_calibrator_kl_matches_exact_sweep():
    """The PRODUCTION KL path (bounded-memory per-batch fine histograms
    rebinned onto the global amax grid in compute_scales) must agree
    with the exact-from-raw-samples sweep (_kl_scale) to within one
    sweep quantum (16/2048 of amax)."""
    infer, logits, exe, scope, rng = _train_mnist_mlp(steps=5)
    with fluid.scope_guard(scope):
        calib = Calibrator(infer, exe, scope=scope, algo="KL")
        raw = {n: [] for n in calib.activation_names}
        for _ in range(3):
            feed = {"img": rng.normal(0, 1, (32, 784)).astype(np.float32)}
            calib.sample(feed)
            outs = exe.run(infer, feed=feed,
                           fetch_list=list(calib.activation_names))
            for n, v in zip(calib.activation_names, outs):
                raw[n].append(np.asarray(v))
        scales = calib.compute_scales()
    assert scales
    for n, s in scales.items():
        exact = _kl_scale(raw[n])
        amax = max(float(np.abs(v).max()) for v in raw[n])
        assert abs(s - exact) <= amax * 16 / 2048 + 1e-6, (n, s, exact)


def test_predictor_serves_int8_artifact(tmp_path):
    """The Predictor (and therefore the native C ABI built on it)
    auto-detects an int8 PTQ artifact and serves it with quantized
    numerics — the calibrate -> export -> serve loop closes through the
    same surface float artifacts use."""
    from paddle_tpu.inference import Config, create_predictor

    infer, logits, exe, scope, rng = _train_mnist_mlp(steps=10)
    with fluid.scope_guard(scope):
        calib = Calibrator(infer, exe, scope=scope, algo="abs_max")
        for _ in range(2):
            calib.sample({"img": rng.normal(0, 1, (32, 784)).astype(
                np.float32)})
        save_int8_inference_model(str(tmp_path / "i8"), ["img"],
                                  [logits], exe, infer, calib, scope=scope)
        x = rng.normal(0, 1, (16, 784)).astype(np.float32)
        (ref,) = exe.run(infer, feed={"img": x}, fetch_list=[logits])

    cfg = Config(str(tmp_path / "i8"))
    cfg.disable_tpu()
    pred = create_predictor(cfg)
    assert pred.get_input_names() == ["img"]
    (got,) = pred.run({"img": x})
    ref, got = np.asarray(ref), np.asarray(got)
    agree = (np.argmax(ref, 1) == np.argmax(got, 1)).mean()
    assert agree >= 0.9, agree
    err = np.abs(ref - got).max() / np.abs(ref).max()
    assert 0 < err < 0.15, err  # quantized-but-close, not float-equal
