"""Sharded checkpoint/resume tests (reference: distributed persistables
re-merge io.py:282,315-360; Trainer serial checkpoint dirs
contrib/trainer.py:100). Acceptance: restore resumes training bit-exact
on a TP-sharded model over the 8-device mesh; a crash at ANY point of a
save (exercised via injected faults) leaves resume on the previous
valid committed serial."""

import os

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import faults, flags, layers, monitor
from paddle_tpu.parallel import checkpoint as ckpt
from paddle_tpu.parallel.strategy import DistributedStrategy, ShardingRule


@pytest.fixture(autouse=True)
def _chaos_clean():
    faults.disarm()
    yield
    faults.disarm()
    flags.set_flags({"telemetry": False})


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="l1_colp.w"),
                      bias_attr=fluid.ParamAttr(name="l1_colp.b"))
        logits = layers.fc(h, 8,
                           param_attr=fluid.ParamAttr(name="l2_rowp.w"),
                           bias_attr=fluid.ParamAttr(name="l2_rowp.b"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Momentum(0.05, momentum=0.9).minimize(loss)
    return main, startup, loss


def _strategy():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))
    return DistributedStrategy(
        mesh, data_axis="data",
        rules=[
            ShardingRule(r"_colp\.w(_|$)", P(None, "model")),
            ShardingRule(r"_colp\.b(_|$)", P("model")),
            ShardingRule(r"_rowp\.w(_|$)", P("model", None)),
            ShardingRule(r"_rowp\.b(_|$)", P()),
        ],
    )


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    probe = np.random.RandomState(9).randn(16, 8)
    out = []
    for _ in range(n):
        x = rng.randn(32, 16).astype(np.float32)
        y = np.argmax(x @ probe, 1).astype(np.int64)[:, None]
        out.append({"x": x, "label": y})
    return out


@pytest.mark.multidevice_fragile
def test_tp_sharded_roundtrip_bit_exact_resume(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    compiled = fluid.CompiledProgram(main).with_strategy(_strategy())
    batches = _batches(8)

    with fluid.scope_guard(scope):
        exe.run(startup)
        # steps 0-3, checkpoint, steps 4-7 (uninterrupted reference run)
        ref = [float(exe.run(compiled, feed=fd, fetch_list=[loss])[0])
               for fd in batches[:4]]
        import jax

        arr = scope.find_var("l1_colp.w")
        assert isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1
        ckpt.save_scope(str(tmp_path), scope, step=4)
        ref += [float(exe.run(compiled, feed=fd, fetch_list=[loss])[0])
                for fd in batches[4:]]

    # fresh scope + executor: restore and resume
    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        restored = ckpt.restore_scope(str(tmp_path), scope2)
        assert "l1_colp.w" in restored
        resumed = [float(exe2.run(compiled, feed=fd, fetch_list=[loss])[0])
                   for fd in batches[4:]]
    np.testing.assert_array_equal(ref[4:], resumed)  # bit-exact


@pytest.mark.multidevice_fragile
def test_sharded_values_roundtrip_exactly(tmp_path):
    """The reassembled full array must equal the original global value."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    compiled = fluid.CompiledProgram(main).with_strategy(_strategy())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(compiled, feed=_batches(1)[0], fetch_list=[loss])
        before = {n: np.asarray(scope.find_var(n))
                  for n in scope.var_names()}
        ckpt.save_scope(str(tmp_path), scope, step=0)
    values = ckpt.load_checkpoint(str(tmp_path))
    assert set(values) == set(before)
    for n in before:
        np.testing.assert_array_equal(values[n], before[n], err_msg=n)


def test_async_save_and_latest_pointer(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        h = ckpt.save_scope(str(tmp_path), scope, step=3, async_save=True)
        h.wait()
        h2 = ckpt.save_scope(str(tmp_path), scope, step=7, async_save=True)
        h2.wait()
    assert ckpt.latest_step(str(tmp_path)) == 7
    v3 = ckpt.load_checkpoint(str(tmp_path), step=3)
    v7 = ckpt.load_checkpoint(str(tmp_path), step=7)
    assert set(v3) == set(v7)
    # default load follows the latest pointer
    vl = ckpt.load_checkpoint(str(tmp_path))
    for n in v7:
        np.testing.assert_array_equal(vl[n], v7[n])


def test_missing_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.load_checkpoint(str(tmp_path))


def test_incomplete_latest_falls_back_to_previous(tmp_path):
    """A torn newest checkpoint (no commit barrier across hosts) must not
    brick resume when an older complete one exists (code-review finding,
    round 2)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        ckpt.save_scope(str(tmp_path), scope, step=2)
    # corrupt the newest: drop its shard payloads
    import os

    for fn in os.listdir(str(tmp_path / "checkpoint_2")):
        if fn.startswith("shards_"):
            os.remove(str(tmp_path / "checkpoint_2" / fn))
    vals = ckpt.load_checkpoint(str(tmp_path))  # falls back to step 1
    assert vals
    with pytest.raises((IOError, KeyError)):
        ckpt.load_checkpoint(str(tmp_path), step=2)  # explicit still raises


def test_truncated_shard_file_falls_back(tmp_path):
    """A TRUNCATED (not just missing) shard file must also trigger the
    fallback (code-review finding, round 2: BadZipFile is not IOError)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        ckpt.save_scope(str(tmp_path), scope, step=2)
    import os

    for fn in os.listdir(str(tmp_path / "checkpoint_2")):
        if fn.startswith("shards_"):
            p = str(tmp_path / "checkpoint_2" / fn)
            with open(p, "r+b") as f:
                f.truncate(20)  # torn write
    vals = ckpt.load_checkpoint(str(tmp_path))
    assert vals  # fell back to checkpoint_1


# --------------------------------------------------------------------------
# crash-consistent commit protocol (ISSUE 5 tentpole)
# --------------------------------------------------------------------------

def _save_two(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        ckpt.save_scope(str(tmp_path), scope, step=2)
    return scope


def test_committed_dir_has_marker_and_no_staging_left(tmp_path):
    _save_two(tmp_path)
    assert (tmp_path / "checkpoint_2" / "COMMIT").exists()
    assert not [d for d in os.listdir(str(tmp_path)) if d.endswith(".tmp")]
    assert ckpt.validate_checkpoint(str(tmp_path), 2)
    assert ckpt.validate_checkpoint(str(tmp_path), 2,
                                    verify_checksums=False)
    assert ckpt.latest_step(str(tmp_path)) == 2


@pytest.mark.multidevice_fragile
def test_crash_mid_shard_write_falls_back_bit_identical(tmp_path):
    """Kill-mid-write via injected fault: the Nth checkpoint's shard
    write crashes -> resume restores checkpoint N-1 bit-identically and
    latest_step never returns the uncommitted dir (ISSUE 5 acceptance)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        before = {n: np.asarray(scope.find_var(n))
                  for n in scope.var_names()}
        # train one step so the in-memory state DIFFERS from checkpoint_1,
        # then crash checkpoint_2's shard write
        exe.run(fluid.CompiledProgram(main).with_strategy(_strategy()),
                feed=_batches(1)[0], fetch_list=[loss])
        faults.arm("ckpt.write_shards:raise@1")
        with pytest.raises(faults.InjectedFault):
            ckpt.save_scope(str(tmp_path), scope, step=2)
        faults.disarm()
    # the torn save left only a staging dir: not a serial, not latest
    assert (tmp_path / "checkpoint_2.tmp").exists()
    assert not (tmp_path / "checkpoint_2").exists()
    assert ckpt.available_steps(str(tmp_path)) == [1]
    assert ckpt.latest_step(str(tmp_path)) == 1
    restored = ckpt.load_checkpoint(str(tmp_path))
    assert set(restored) == set(before)
    for n in before:  # bit-identical params on restore
        np.testing.assert_array_equal(restored[n], before[n], err_msg=n)


def test_crash_before_commit_marker_falls_back(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        faults.arm("ckpt.commit:raise@1")
        with pytest.raises(faults.InjectedFault):
            ckpt.save_scope(str(tmp_path), scope, step=2)
    assert ckpt.latest_step(str(tmp_path)) == 1


def test_truncated_shard_skipped_by_latest_step(tmp_path):
    """latest_step must skip a committed-then-corrupted serial (torn by
    an injected truncate fault) and count the skip."""
    monitor.enable()
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        faults.arm("ckpt.write_shards:truncate(24)@1")
        ckpt.save_scope(str(tmp_path), scope, step=2)  # commits, but torn
        faults.disarm()
    assert (tmp_path / "checkpoint_2" / "COMMIT").exists()
    skips0 = monitor.counter("pt_ckpt_invalid_skipped_total").value()
    assert ckpt.latest_step(str(tmp_path)) == 1  # pointer said 2
    assert monitor.counter("pt_ckpt_invalid_skipped_total").value() > skips0
    vals = ckpt.load_checkpoint(str(tmp_path))
    assert vals


def test_bad_checksum_skipped_and_explicit_load_raises(tmp_path):
    """Bit-rot: a shard file that unzips fine but whose array bytes no
    longer match the manifest crc32 is skipped by latest_step; loading
    it explicitly raises the checksum error."""
    _save_two(tmp_path)
    d = tmp_path / "checkpoint_2"
    for fn in os.listdir(str(d)):
        if fn.startswith("shards_"):
            with np.load(str(d / fn)) as z:
                data = {k: np.array(z[k]) for k in z.files}
            k0 = sorted(data)[0]
            flat = data[k0].reshape(-1)
            flat[0] += 1.0  # silent corruption, still a valid npz
            np.savez(str(d / fn), **data)  # fn ends in .npz: no suffixing
            break
    assert ckpt.latest_step(str(tmp_path)) == 1
    with pytest.raises(IOError, match="checksum"):
        ckpt.load_checkpoint(str(tmp_path), step=2)
    vals = ckpt.load_checkpoint(str(tmp_path))  # falls back to 1
    assert vals


def test_stale_pointer_does_not_hide_newer_committed_serial(tmp_path):
    """Crash between the serial-dir rename and the pointer update: the
    committed serial must win over the stale pointer (code-review
    finding — pointer-first ordering replayed a whole epoch)."""
    _save_two(tmp_path)
    with open(str(tmp_path / "latest"), "w") as f:
        f.write("1")  # pointer never advanced past the crash
    assert ckpt.latest_step(str(tmp_path)) == 2
    assert ckpt.load_latest(str(tmp_path))[0] == 2


def test_stale_staging_dirs_swept_at_next_commit(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        faults.arm("ckpt.write_shards:raise@1")
        with pytest.raises(faults.InjectedFault):
            ckpt.save_scope(str(tmp_path), scope, step=2)
        faults.disarm()
        assert (tmp_path / "checkpoint_2.tmp").exists()
        ckpt.save_scope(str(tmp_path), scope, step=3)  # commit sweeps
    assert not (tmp_path / "checkpoint_2.tmp").exists()
    assert ckpt.latest_step(str(tmp_path)) == 3


def test_all_serials_invalid_raises_ioerror(tmp_path):
    _save_two(tmp_path)
    for s in (1, 2):
        for fn in os.listdir(str(tmp_path / f"checkpoint_{s}")):
            if fn.startswith("shards_"):
                with open(str(tmp_path / f"checkpoint_{s}" / fn),
                          "r+b") as f:
                    f.truncate(10)  # every serial torn
    assert ckpt.latest_step(str(tmp_path)) is None
    with pytest.raises(IOError):
        ckpt.load_checkpoint(str(tmp_path))


def test_empty_foreign_dir_skipped_not_loaded_as_empty(tmp_path):
    """A manifest-less final-named dir (pre-plane crash debris, manual
    mkdir) must be SKIPPED by load_latest, not returned as (step, {})
    that out-shadows an older real checkpoint (code-review finding,
    round 3)."""
    _save_two(tmp_path)
    os.makedirs(str(tmp_path / "checkpoint_9"))
    assert ckpt.latest_step(str(tmp_path)) == 2
    step, values = ckpt.load_latest(str(tmp_path))
    assert step == 2 and values
    with pytest.raises(IOError, match="manifest"):
        ckpt.load_checkpoint(str(tmp_path), step=9)


def test_legacy_dir_without_commit_marker_still_loads(tmp_path):
    """Upgrade path (code-review finding, round 2): checkpoints written
    BEFORE the commit protocol carry no COMMIT marker — they must stay
    loadable (the new protocol never leaves a markerless final dir, so
    a missing marker can only mean pre-plane format)."""
    scope = _save_two(tmp_path)
    for s in (1, 2):
        os.remove(str(tmp_path / f"checkpoint_{s}" / "COMMIT"))
    assert ckpt.latest_step(str(tmp_path)) == 2
    assert ckpt.validate_checkpoint(str(tmp_path), 2)
    step, values = ckpt.load_latest(str(tmp_path))
    assert step == 2
    for n in values:
        np.testing.assert_array_equal(
            values[n], np.asarray(scope.find_var(n)), err_msg=n)


def test_displaced_serial_recovered_after_resave_crash(tmp_path):
    """Crash in the re-save publish window parks the committed copy at
    checkpoint_<n>.old.tmp — discovery renames it back (code-review
    finding, round 2: rmtree-before-replace lost the only copy)."""
    _save_two(tmp_path)
    os.rename(str(tmp_path / "checkpoint_2"),
              str(tmp_path / "checkpoint_2.old.tmp"))
    assert ckpt.latest_step(str(tmp_path)) == 2  # recovered in place
    assert (tmp_path / "checkpoint_2").exists()
    assert not (tmp_path / "checkpoint_2.old.tmp").exists()
    assert ckpt.load_latest(str(tmp_path))[0] == 2


@pytest.mark.multidevice_fragile
def test_resave_same_serial_replaces_it(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        exe.run(fluid.CompiledProgram(main).with_strategy(_strategy()),
                feed=_batches(1)[0], fetch_list=[loss])
        after = {n: np.asarray(scope.find_var(n))
                 for n in scope.var_names()}
        ckpt.save_scope(str(tmp_path), scope, step=1)  # overwrite serial
    vals = ckpt.load_checkpoint(str(tmp_path), step=1)
    for n in after:
        np.testing.assert_array_equal(vals[n], after[n], err_msg=n)


# --------------------------------------------------------------------------
# async-save error surfacing (satellite: no silent loss)
# --------------------------------------------------------------------------

def test_async_save_error_surfaces_at_next_save_without_wait(tmp_path):
    monitor.enable()
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        faults.arm("ckpt.write_shards:raise@1")
        h = ckpt.save_scope(str(tmp_path), scope, step=1, async_save=True)
        h._thread.join()  # let the background failure land (no wait())
        faults.disarm()
        errs0 = monitor.counter("pt_ckpt_async_errors_total").value()
        with pytest.warns(RuntimeWarning, match="async checkpoint save"):
            ckpt.save_scope(str(tmp_path), scope, step=2)
        assert monitor.counter(
            "pt_ckpt_async_errors_total").value() == errs0 + 1
    assert ckpt.latest_step(str(tmp_path)) == 2


def test_async_wait_is_idempotent_and_raises_each_time(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        faults.arm("ckpt.write_shards:raise@1")
        h = ckpt.save_scope(str(tmp_path), scope, step=1, async_save=True)
        with pytest.raises(faults.InjectedFault):
            h.wait()
        with pytest.raises(faults.InjectedFault):
            h.wait()  # idempotent: same answer, no deadlock
        faults.disarm()
        h2 = ckpt.save_scope(str(tmp_path), scope, step=2, async_save=True)
        h2.wait()
        h2.wait()  # success path equally idempotent
    assert ckpt.latest_step(str(tmp_path)) == 2


# --------------------------------------------------------------------------
# trainer auto-resume + pruning order (satellites)
# --------------------------------------------------------------------------

def _trainer_pieces():
    from paddle_tpu.contrib import EndStepEvent

    def train_func():
        img = layers.data("img", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="ar1.w"),
                      bias_attr=fluid.ParamAttr(name="ar1.b"))
        logits = layers.fc(h, 4,
                           param_attr=fluid.ParamAttr(name="ar2.w"),
                           bias_attr=fluid.ParamAttr(name="ar2.b"))
        return [layers.mean(
            layers.softmax_with_cross_entropy(logits, label))]

    def optimizer_func():
        return fluid.optimizer.SGD(0.1)

    def reader():
        probe = np.random.RandomState(5).randn(16, 4)

        def gen():
            rng = np.random.RandomState(0)
            for _ in range(4):
                x = rng.randn(32, 16).astype(np.float32)
                y = np.argmax(x @ probe, 1).astype(np.int64)
                yield list(zip(x, y))

        return gen

    return train_func, optimizer_func, reader, EndStepEvent


def test_trainer_auto_resumes_from_last_valid_checkpoint(tmp_path):
    """Chaos regression (ISSUE 5 acceptance): a fault mid-training with
    max_resume_retries restores the newest valid checkpoint and the
    replayed epochs match the uninterrupted run."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    monitor.enable()
    train_func, optimizer_func, reader, EndStepEvent = _trainer_pieces()

    ref = []
    t_ref = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                    checkpoint_config=CheckpointConfig(
                        str(tmp_path / "ref"), epoch_interval=1))
    t_ref.train(4, lambda e: ref.append(float(e.metrics[0]))
                if isinstance(e, EndStepEvent) else None,
                reader(), ["img", "label"])

    # chaos run: the 10th batch fetch (epoch 3's 2nd batch, after
    # checkpoint_2 committed) raises; one auto-resume allowed
    chaos = []
    faults.arm("reader.next:raise@10")
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(
                    str(tmp_path / "chaos"), epoch_interval=1,
                    max_resume_retries=1))
    with pytest.warns(RuntimeWarning, match="auto-resuming"):
        t.train(4, lambda e: chaos.append(float(e.metrics[0]))
                if isinstance(e, EndStepEvent) else None,
                reader(), ["img", "label"])
    faults.disarm()
    assert monitor.counter("pt_trainer_auto_resumes_total").value() == 1
    from paddle_tpu.parallel import checkpoint as _ck
    assert _ck.latest_step(str(tmp_path / "chaos")) == 4
    # epochs 3-4 were replayed from checkpoint_2: their losses match the
    # uninterrupted reference run exactly
    assert len(chaos) > len(ref)  # epoch 3 ran once partially, then fully
    np.testing.assert_allclose(ref[8:], chaos[-8:], rtol=1e-6)


def test_trainer_resume_budget_exhausts_then_raises(tmp_path):
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    train_func, optimizer_func, reader, _ = _trainer_pieces()
    faults.arm("reader.next:raise@5,6,7")  # every epoch-2 start fails
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(
                    str(tmp_path), epoch_interval=1, max_resume_retries=1))
    with pytest.raises(faults.InjectedFault), \
            pytest.warns(RuntimeWarning, match="auto-resuming"):
        t.train(4, None, reader(), ["img", "label"])


def test_trainer_never_prunes_the_last_valid_checkpoint(tmp_path):
    """Pruning-order satellite: with max_num_checkpoints=1, a failed
    save of serial N must leave serial N-1 on disk (the old prune-first
    order could leave ZERO resumable state)."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    train_func, optimizer_func, reader, _ = _trainer_pieces()
    faults.arm("ckpt.commit:raise@2")  # epoch 2's save dies pre-commit
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(
                    str(tmp_path), epoch_interval=1,
                    max_num_checkpoints=1))
    with pytest.raises(faults.InjectedFault):
        t.train(2, None, reader(), ["img", "label"])
    faults.disarm()
    assert ckpt.latest_step(str(tmp_path)) == 1
    assert ckpt.validate_checkpoint(str(tmp_path), 1)
