"""Sharded checkpoint/resume tests (reference: distributed persistables
re-merge io.py:282,315-360; Trainer serial checkpoint dirs
contrib/trainer.py:100). Acceptance: restore resumes training bit-exact
on a TP-sharded model over the 8-device mesh."""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import checkpoint as ckpt
from paddle_tpu.parallel.strategy import DistributedStrategy, ShardingRule


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="l1_colp.w"),
                      bias_attr=fluid.ParamAttr(name="l1_colp.b"))
        logits = layers.fc(h, 8,
                           param_attr=fluid.ParamAttr(name="l2_rowp.w"),
                           bias_attr=fluid.ParamAttr(name="l2_rowp.b"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Momentum(0.05, momentum=0.9).minimize(loss)
    return main, startup, loss


def _strategy():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))
    return DistributedStrategy(
        mesh, data_axis="data",
        rules=[
            ShardingRule(r"_colp\.w(_|$)", P(None, "model")),
            ShardingRule(r"_colp\.b(_|$)", P("model")),
            ShardingRule(r"_rowp\.w(_|$)", P("model", None)),
            ShardingRule(r"_rowp\.b(_|$)", P()),
        ],
    )


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    probe = np.random.RandomState(9).randn(16, 8)
    out = []
    for _ in range(n):
        x = rng.randn(32, 16).astype(np.float32)
        y = np.argmax(x @ probe, 1).astype(np.int64)[:, None]
        out.append({"x": x, "label": y})
    return out


def test_tp_sharded_roundtrip_bit_exact_resume(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    compiled = fluid.CompiledProgram(main).with_strategy(_strategy())
    batches = _batches(8)

    with fluid.scope_guard(scope):
        exe.run(startup)
        # steps 0-3, checkpoint, steps 4-7 (uninterrupted reference run)
        ref = [float(exe.run(compiled, feed=fd, fetch_list=[loss])[0])
               for fd in batches[:4]]
        import jax

        arr = scope.find_var("l1_colp.w")
        assert isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1
        ckpt.save_scope(str(tmp_path), scope, step=4)
        ref += [float(exe.run(compiled, feed=fd, fetch_list=[loss])[0])
                for fd in batches[4:]]

    # fresh scope + executor: restore and resume
    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        restored = ckpt.restore_scope(str(tmp_path), scope2)
        assert "l1_colp.w" in restored
        resumed = [float(exe2.run(compiled, feed=fd, fetch_list=[loss])[0])
                   for fd in batches[4:]]
    np.testing.assert_array_equal(ref[4:], resumed)  # bit-exact


def test_sharded_values_roundtrip_exactly(tmp_path):
    """The reassembled full array must equal the original global value."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    compiled = fluid.CompiledProgram(main).with_strategy(_strategy())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(compiled, feed=_batches(1)[0], fetch_list=[loss])
        before = {n: np.asarray(scope.find_var(n))
                  for n in scope.var_names()}
        ckpt.save_scope(str(tmp_path), scope, step=0)
    values = ckpt.load_checkpoint(str(tmp_path))
    assert set(values) == set(before)
    for n in before:
        np.testing.assert_array_equal(values[n], before[n], err_msg=n)


def test_async_save_and_latest_pointer(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        h = ckpt.save_scope(str(tmp_path), scope, step=3, async_save=True)
        h.wait()
        h2 = ckpt.save_scope(str(tmp_path), scope, step=7, async_save=True)
        h2.wait()
    assert ckpt.latest_step(str(tmp_path)) == 7
    v3 = ckpt.load_checkpoint(str(tmp_path), step=3)
    v7 = ckpt.load_checkpoint(str(tmp_path), step=7)
    assert set(v3) == set(v7)
    # default load follows the latest pointer
    vl = ckpt.load_checkpoint(str(tmp_path))
    for n in v7:
        np.testing.assert_array_equal(vl[n], v7[n])


def test_missing_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.load_checkpoint(str(tmp_path))


def test_incomplete_latest_falls_back_to_previous(tmp_path):
    """A torn newest checkpoint (no commit barrier across hosts) must not
    brick resume when an older complete one exists (code-review finding,
    round 2)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        ckpt.save_scope(str(tmp_path), scope, step=2)
    # corrupt the newest: drop its shard payloads
    import os

    for fn in os.listdir(str(tmp_path / "checkpoint_2")):
        if fn.startswith("shards_"):
            os.remove(str(tmp_path / "checkpoint_2" / fn))
    vals = ckpt.load_checkpoint(str(tmp_path))  # falls back to step 1
    assert vals
    with pytest.raises((IOError, KeyError)):
        ckpt.load_checkpoint(str(tmp_path), step=2)  # explicit still raises


def test_truncated_shard_file_falls_back(tmp_path):
    """A TRUNCATED (not just missing) shard file must also trigger the
    fallback (code-review finding, round 2: BadZipFile is not IOError)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        ckpt.save_scope(str(tmp_path), scope, step=2)
    import os

    for fn in os.listdir(str(tmp_path / "checkpoint_2")):
        if fn.startswith("shards_"):
            p = str(tmp_path / "checkpoint_2" / fn)
            with open(p, "r+b") as f:
                f.truncate(20)  # torn write
    vals = ckpt.load_checkpoint(str(tmp_path))
    assert vals  # fell back to checkpoint_1
