"""Sharded checkpoint/resume tests (reference: distributed persistables
re-merge io.py:282,315-360; Trainer serial checkpoint dirs
contrib/trainer.py:100). Acceptance: restore resumes training bit-exact
on a TP-sharded model over the 8-device mesh; a crash at ANY point of a
save (exercised via injected faults) leaves resume on the previous
valid committed serial."""

import json
import os
import threading
import time
import zlib

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import faults, flags, layers, monitor
from paddle_tpu.parallel import checkpoint as ckpt
from paddle_tpu.parallel import mesh as pmesh
from paddle_tpu.parallel.strategy import DistributedStrategy, ShardingRule


@pytest.fixture(autouse=True)
def _chaos_clean():
    faults.disarm()
    yield
    faults.disarm()
    flags.set_flags({"telemetry": False})


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="l1_colp.w"),
                      bias_attr=fluid.ParamAttr(name="l1_colp.b"))
        logits = layers.fc(h, 8,
                           param_attr=fluid.ParamAttr(name="l2_rowp.w"),
                           bias_attr=fluid.ParamAttr(name="l2_rowp.b"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Momentum(0.05, momentum=0.9).minimize(loss)
    return main, startup, loss


def _strategy():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))
    return DistributedStrategy(
        mesh, data_axis="data",
        rules=[
            ShardingRule(r"_colp\.w(_|$)", P(None, "model")),
            ShardingRule(r"_colp\.b(_|$)", P("model")),
            ShardingRule(r"_rowp\.w(_|$)", P("model", None)),
            ShardingRule(r"_rowp\.b(_|$)", P()),
        ],
    )


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    probe = np.random.RandomState(9).randn(16, 8)
    out = []
    for _ in range(n):
        x = rng.randn(32, 16).astype(np.float32)
        y = np.argmax(x @ probe, 1).astype(np.int64)[:, None]
        out.append({"x": x, "label": y})
    return out


@pytest.mark.multidevice_fragile
def test_tp_sharded_roundtrip_bit_exact_resume(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    compiled = fluid.CompiledProgram(main).with_strategy(_strategy())
    batches = _batches(8)

    with fluid.scope_guard(scope):
        exe.run(startup)
        # steps 0-3, checkpoint, steps 4-7 (uninterrupted reference run)
        ref = [float(exe.run(compiled, feed=fd, fetch_list=[loss])[0])
               for fd in batches[:4]]
        import jax

        arr = scope.find_var("l1_colp.w")
        assert isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1
        ckpt.save_scope(str(tmp_path), scope, step=4)
        ref += [float(exe.run(compiled, feed=fd, fetch_list=[loss])[0])
                for fd in batches[4:]]

    # fresh scope + executor: restore and resume
    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        restored = ckpt.restore_scope(str(tmp_path), scope2)
        assert "l1_colp.w" in restored
        resumed = [float(exe2.run(compiled, feed=fd, fetch_list=[loss])[0])
                   for fd in batches[4:]]
    np.testing.assert_array_equal(ref[4:], resumed)  # bit-exact


@pytest.mark.multidevice_fragile
def test_sharded_values_roundtrip_exactly(tmp_path):
    """The reassembled full array must equal the original global value."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    compiled = fluid.CompiledProgram(main).with_strategy(_strategy())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(compiled, feed=_batches(1)[0], fetch_list=[loss])
        before = {n: np.asarray(scope.find_var(n))
                  for n in scope.var_names()}
        ckpt.save_scope(str(tmp_path), scope, step=0)
    values = ckpt.load_checkpoint(str(tmp_path))
    assert set(values) == set(before)
    for n in before:
        np.testing.assert_array_equal(values[n], before[n], err_msg=n)


def test_async_save_and_latest_pointer(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        h = ckpt.save_scope(str(tmp_path), scope, step=3, async_save=True)
        h.wait()
        h2 = ckpt.save_scope(str(tmp_path), scope, step=7, async_save=True)
        h2.wait()
    assert ckpt.latest_step(str(tmp_path)) == 7
    v3 = ckpt.load_checkpoint(str(tmp_path), step=3)
    v7 = ckpt.load_checkpoint(str(tmp_path), step=7)
    assert set(v3) == set(v7)
    # default load follows the latest pointer
    vl = ckpt.load_checkpoint(str(tmp_path))
    for n in v7:
        np.testing.assert_array_equal(vl[n], v7[n])


def test_missing_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.load_checkpoint(str(tmp_path))


def test_incomplete_latest_falls_back_to_previous(tmp_path):
    """A torn newest checkpoint (no commit barrier across hosts) must not
    brick resume when an older complete one exists (code-review finding,
    round 2)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        ckpt.save_scope(str(tmp_path), scope, step=2)
    # corrupt the newest: drop its shard payloads
    import os

    for fn in os.listdir(str(tmp_path / "checkpoint_2")):
        if fn.startswith("shards_"):
            os.remove(str(tmp_path / "checkpoint_2" / fn))
    vals = ckpt.load_checkpoint(str(tmp_path))  # falls back to step 1
    assert vals
    with pytest.raises((IOError, KeyError)):
        ckpt.load_checkpoint(str(tmp_path), step=2)  # explicit still raises


def test_truncated_shard_file_falls_back(tmp_path):
    """A TRUNCATED (not just missing) shard file must also trigger the
    fallback (code-review finding, round 2: BadZipFile is not IOError)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        ckpt.save_scope(str(tmp_path), scope, step=2)
    import os

    for fn in os.listdir(str(tmp_path / "checkpoint_2")):
        if fn.startswith("shards_"):
            p = str(tmp_path / "checkpoint_2" / fn)
            with open(p, "r+b") as f:
                f.truncate(20)  # torn write
    vals = ckpt.load_checkpoint(str(tmp_path))
    assert vals  # fell back to checkpoint_1


# --------------------------------------------------------------------------
# crash-consistent commit protocol (ISSUE 5 tentpole)
# --------------------------------------------------------------------------

def _save_two(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        ckpt.save_scope(str(tmp_path), scope, step=2)
    return scope


def test_committed_dir_has_marker_and_no_staging_left(tmp_path):
    _save_two(tmp_path)
    assert (tmp_path / "checkpoint_2" / "COMMIT").exists()
    assert not [d for d in os.listdir(str(tmp_path)) if d.endswith(".tmp")]
    assert ckpt.validate_checkpoint(str(tmp_path), 2)
    assert ckpt.validate_checkpoint(str(tmp_path), 2,
                                    verify_checksums=False)
    assert ckpt.latest_step(str(tmp_path)) == 2


@pytest.mark.multidevice_fragile
def test_crash_mid_shard_write_falls_back_bit_identical(tmp_path):
    """Kill-mid-write via injected fault: the Nth checkpoint's shard
    write crashes -> resume restores checkpoint N-1 bit-identically and
    latest_step never returns the uncommitted dir (ISSUE 5 acceptance)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        before = {n: np.asarray(scope.find_var(n))
                  for n in scope.var_names()}
        # train one step so the in-memory state DIFFERS from checkpoint_1,
        # then crash checkpoint_2's shard write
        exe.run(fluid.CompiledProgram(main).with_strategy(_strategy()),
                feed=_batches(1)[0], fetch_list=[loss])
        faults.arm("ckpt.write_shards:raise@1")
        with pytest.raises(faults.InjectedFault):
            ckpt.save_scope(str(tmp_path), scope, step=2)
        faults.disarm()
    # the torn save left only a staging dir: not a serial, not latest
    assert (tmp_path / "checkpoint_2.tmp").exists()
    assert not (tmp_path / "checkpoint_2").exists()
    assert ckpt.available_steps(str(tmp_path)) == [1]
    assert ckpt.latest_step(str(tmp_path)) == 1
    restored = ckpt.load_checkpoint(str(tmp_path))
    assert set(restored) == set(before)
    for n in before:  # bit-identical params on restore
        np.testing.assert_array_equal(restored[n], before[n], err_msg=n)


def test_crash_before_commit_marker_falls_back(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        faults.arm("ckpt.commit:raise@1")
        with pytest.raises(faults.InjectedFault):
            ckpt.save_scope(str(tmp_path), scope, step=2)
    assert ckpt.latest_step(str(tmp_path)) == 1


def test_truncated_shard_skipped_by_latest_step(tmp_path):
    """latest_step must skip a committed-then-corrupted serial (torn by
    an injected truncate fault) and count the skip."""
    monitor.enable()
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        faults.arm("ckpt.write_shards:truncate(24)@1")
        ckpt.save_scope(str(tmp_path), scope, step=2)  # commits, but torn
        faults.disarm()
    assert (tmp_path / "checkpoint_2" / "COMMIT").exists()
    skips0 = monitor.counter("pt_ckpt_invalid_skipped_total").value()
    assert ckpt.latest_step(str(tmp_path)) == 1  # pointer said 2
    assert monitor.counter("pt_ckpt_invalid_skipped_total").value() > skips0
    vals = ckpt.load_checkpoint(str(tmp_path))
    assert vals


def test_bad_checksum_skipped_and_explicit_load_raises(tmp_path):
    """Bit-rot: a shard file that unzips fine but whose array bytes no
    longer match the manifest crc32 is skipped by latest_step; loading
    it explicitly raises the checksum error."""
    _save_two(tmp_path)
    d = tmp_path / "checkpoint_2"
    for fn in os.listdir(str(d)):
        if fn.startswith("shards_"):
            with np.load(str(d / fn)) as z:
                data = {k: np.array(z[k]) for k in z.files}
            k0 = sorted(data)[0]
            flat = data[k0].reshape(-1)
            flat[0] += 1.0  # silent corruption, still a valid npz
            np.savez(str(d / fn), **data)  # fn ends in .npz: no suffixing
            break
    assert ckpt.latest_step(str(tmp_path)) == 1
    with pytest.raises(IOError, match="checksum"):
        ckpt.load_checkpoint(str(tmp_path), step=2)
    vals = ckpt.load_checkpoint(str(tmp_path))  # falls back to 1
    assert vals


def test_stale_pointer_does_not_hide_newer_committed_serial(tmp_path):
    """Crash between the serial-dir rename and the pointer update: the
    committed serial must win over the stale pointer (code-review
    finding — pointer-first ordering replayed a whole epoch)."""
    _save_two(tmp_path)
    with open(str(tmp_path / "latest"), "w") as f:
        f.write("1")  # pointer never advanced past the crash
    assert ckpt.latest_step(str(tmp_path)) == 2
    assert ckpt.load_latest(str(tmp_path))[0] == 2


def test_stale_staging_dirs_swept_at_next_commit(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        faults.arm("ckpt.write_shards:raise@1")
        with pytest.raises(faults.InjectedFault):
            ckpt.save_scope(str(tmp_path), scope, step=2)
        faults.disarm()
        assert (tmp_path / "checkpoint_2.tmp").exists()
        ckpt.save_scope(str(tmp_path), scope, step=3)  # commit sweeps
    assert not (tmp_path / "checkpoint_2.tmp").exists()
    assert ckpt.latest_step(str(tmp_path)) == 3


def test_all_serials_invalid_raises_ioerror(tmp_path):
    _save_two(tmp_path)
    for s in (1, 2):
        for fn in os.listdir(str(tmp_path / f"checkpoint_{s}")):
            if fn.startswith("shards_"):
                with open(str(tmp_path / f"checkpoint_{s}" / fn),
                          "r+b") as f:
                    f.truncate(10)  # every serial torn
    assert ckpt.latest_step(str(tmp_path)) is None
    with pytest.raises(IOError):
        ckpt.load_checkpoint(str(tmp_path))


def test_empty_foreign_dir_skipped_not_loaded_as_empty(tmp_path):
    """A manifest-less final-named dir (pre-plane crash debris, manual
    mkdir) must be SKIPPED by load_latest, not returned as (step, {})
    that out-shadows an older real checkpoint (code-review finding,
    round 3)."""
    _save_two(tmp_path)
    os.makedirs(str(tmp_path / "checkpoint_9"))
    assert ckpt.latest_step(str(tmp_path)) == 2
    step, values = ckpt.load_latest(str(tmp_path))
    assert step == 2 and values
    with pytest.raises(IOError, match="manifest"):
        ckpt.load_checkpoint(str(tmp_path), step=9)


def test_legacy_dir_without_commit_marker_still_loads(tmp_path):
    """Upgrade path (code-review finding, round 2): checkpoints written
    BEFORE the commit protocol carry no COMMIT marker — they must stay
    loadable (the new protocol never leaves a markerless final dir, so
    a missing marker can only mean pre-plane format)."""
    scope = _save_two(tmp_path)
    for s in (1, 2):
        os.remove(str(tmp_path / f"checkpoint_{s}" / "COMMIT"))
    assert ckpt.latest_step(str(tmp_path)) == 2
    assert ckpt.validate_checkpoint(str(tmp_path), 2)
    step, values = ckpt.load_latest(str(tmp_path))
    assert step == 2
    for n in values:
        np.testing.assert_array_equal(
            values[n], np.asarray(scope.find_var(n)), err_msg=n)


def test_displaced_serial_recovered_after_resave_crash(tmp_path):
    """Crash in the re-save publish window parks the committed copy at
    checkpoint_<n>.old.tmp — discovery renames it back (code-review
    finding, round 2: rmtree-before-replace lost the only copy)."""
    _save_two(tmp_path)
    os.rename(str(tmp_path / "checkpoint_2"),
              str(tmp_path / "checkpoint_2.old.tmp"))
    assert ckpt.latest_step(str(tmp_path)) == 2  # recovered in place
    assert (tmp_path / "checkpoint_2").exists()
    assert not (tmp_path / "checkpoint_2.old.tmp").exists()
    assert ckpt.load_latest(str(tmp_path))[0] == 2


@pytest.mark.multidevice_fragile
def test_resave_same_serial_replaces_it(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        exe.run(fluid.CompiledProgram(main).with_strategy(_strategy()),
                feed=_batches(1)[0], fetch_list=[loss])
        after = {n: np.asarray(scope.find_var(n))
                 for n in scope.var_names()}
        ckpt.save_scope(str(tmp_path), scope, step=1)  # overwrite serial
    vals = ckpt.load_checkpoint(str(tmp_path), step=1)
    for n in after:
        np.testing.assert_array_equal(vals[n], after[n], err_msg=n)


# --------------------------------------------------------------------------
# async-save error surfacing (satellite: no silent loss)
# --------------------------------------------------------------------------

def test_async_save_error_surfaces_at_next_save_without_wait(tmp_path):
    monitor.enable()
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        faults.arm("ckpt.write_shards:raise@1")
        h = ckpt.save_scope(str(tmp_path), scope, step=1, async_save=True)
        h._thread.join()  # let the background failure land (no wait())
        faults.disarm()
        errs0 = monitor.counter("pt_ckpt_async_errors_total").value()
        with pytest.warns(RuntimeWarning, match="async checkpoint save"):
            ckpt.save_scope(str(tmp_path), scope, step=2)
        assert monitor.counter(
            "pt_ckpt_async_errors_total").value() == errs0 + 1
    assert ckpt.latest_step(str(tmp_path)) == 2


def test_async_wait_is_idempotent_and_raises_each_time(tmp_path):
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        faults.arm("ckpt.write_shards:raise@1")
        h = ckpt.save_scope(str(tmp_path), scope, step=1, async_save=True)
        with pytest.raises(faults.InjectedFault):
            h.wait()
        with pytest.raises(faults.InjectedFault):
            h.wait()  # idempotent: same answer, no deadlock
        faults.disarm()
        h2 = ckpt.save_scope(str(tmp_path), scope, step=2, async_save=True)
        h2.wait()
        h2.wait()  # success path equally idempotent
    assert ckpt.latest_step(str(tmp_path)) == 2


# --------------------------------------------------------------------------
# trainer auto-resume + pruning order (satellites)
# --------------------------------------------------------------------------

def _trainer_pieces():
    from paddle_tpu.contrib import EndStepEvent

    def train_func():
        img = layers.data("img", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="ar1.w"),
                      bias_attr=fluid.ParamAttr(name="ar1.b"))
        logits = layers.fc(h, 4,
                           param_attr=fluid.ParamAttr(name="ar2.w"),
                           bias_attr=fluid.ParamAttr(name="ar2.b"))
        return [layers.mean(
            layers.softmax_with_cross_entropy(logits, label))]

    def optimizer_func():
        return fluid.optimizer.SGD(0.1)

    def reader():
        probe = np.random.RandomState(5).randn(16, 4)

        def gen():
            rng = np.random.RandomState(0)
            for _ in range(4):
                x = rng.randn(32, 16).astype(np.float32)
                y = np.argmax(x @ probe, 1).astype(np.int64)
                yield list(zip(x, y))

        return gen

    return train_func, optimizer_func, reader, EndStepEvent


def test_trainer_auto_resumes_from_last_valid_checkpoint(tmp_path):
    """Chaos regression (ISSUE 5 acceptance): a fault mid-training with
    max_resume_retries restores the newest valid checkpoint and the
    replayed epochs match the uninterrupted run."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    monitor.enable()
    train_func, optimizer_func, reader, EndStepEvent = _trainer_pieces()

    ref = []
    t_ref = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                    checkpoint_config=CheckpointConfig(
                        str(tmp_path / "ref"), epoch_interval=1))
    t_ref.train(4, lambda e: ref.append(float(e.metrics[0]))
                if isinstance(e, EndStepEvent) else None,
                reader(), ["img", "label"])

    # chaos run: the 10th batch fetch (epoch 3's 2nd batch, after
    # checkpoint_2 committed) raises; one auto-resume allowed
    chaos = []
    faults.arm("reader.next:raise@10")
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(
                    str(tmp_path / "chaos"), epoch_interval=1,
                    max_resume_retries=1))
    with pytest.warns(RuntimeWarning, match="auto-resuming"):
        t.train(4, lambda e: chaos.append(float(e.metrics[0]))
                if isinstance(e, EndStepEvent) else None,
                reader(), ["img", "label"])
    faults.disarm()
    assert monitor.counter("pt_trainer_auto_resumes_total").value(
        labels={"resized": "false"}) == 1
    from paddle_tpu.parallel import checkpoint as _ck
    assert _ck.latest_step(str(tmp_path / "chaos")) == 4
    # epochs 3-4 were replayed from checkpoint_2: their losses match the
    # uninterrupted reference run exactly
    assert len(chaos) > len(ref)  # epoch 3 ran once partially, then fully
    np.testing.assert_allclose(ref[8:], chaos[-8:], rtol=1e-6)


def test_trainer_resume_budget_exhausts_then_raises(tmp_path):
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    train_func, optimizer_func, reader, _ = _trainer_pieces()
    faults.arm("reader.next:raise@5,6,7")  # every epoch-2 start fails
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(
                    str(tmp_path), epoch_interval=1, max_resume_retries=1))
    with pytest.raises(faults.InjectedFault), \
            pytest.warns(RuntimeWarning, match="auto-resuming"):
        t.train(4, None, reader(), ["img", "label"])


# --------------------------------------------------------------------------
# topology-independent checkpoints: manifest v2 + mesh matrix (ISSUE 7)
# --------------------------------------------------------------------------

def _grid_mesh(shape, axes, ndev=None):
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices())
    if ndev is not None:
        devs = devs[:ndev]
    return Mesh(devs.reshape(shape), axes)


def _sharded(w, mesh, spec):
    import jax
    from jax.sharding import NamedSharding

    return jax.device_put(w, NamedSharding(mesh, spec))


def test_manifest_v2_records_global_shape_dtype_sharding(tmp_path):
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    arr = _sharded(w, _grid_mesh((2, 4), ("data", "model")),
                   P(None, "model"))
    ckpt.save_checkpoint(str(tmp_path), {"w": arr, "h": np.arange(5)},
                         step=1)
    with open(str(tmp_path / "checkpoint_1" / "manifest.json.0")) as f:
        man = json.load(f)
    assert man["w"]["shape"] == [8, 8] and man["w"]["dtype"] == "float32"
    assert man["w"]["sharding"] == {"mesh": {"data": 2, "model": 4},
                                    "spec": [None, ["model"]]}
    assert man["h"]["shape"] == [5] and man["h"]["sharding"] is None
    with open(str(tmp_path / "checkpoint_1" / "COMMIT")) as f:
        assert json.load(f)["format"] == 2
    # descriptor round-trips into a live NamedSharding on this host
    sh = pmesh.sharding_from_descriptor(man["w"]["sharding"])
    np.testing.assert_array_equal(
        np.asarray(_sharded(w, sh.mesh, sh.spec)), w)


def test_mesh_matrix_restore_bit_exact(tmp_path):
    """Saved on a 2x4 mesh; restored bit-exact onto 1x8, onto a 4-device
    mesh, and onto plain host memory (the ISSUE 7 acceptance matrix) —
    the manifest carries the layout, the restore ignores it."""
    import jax
    from jax.sharding import NamedSharding

    w = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    b = np.random.RandomState(1).randn(16).astype(np.float32)
    mesh_a = _grid_mesh((2, 4), ("data", "model"))
    state = {"w": _sharded(w, mesh_a, P(None, "model")),
             "b": _sharded(b, mesh_a, P("model"))}
    ckpt.save_checkpoint(str(tmp_path), state, step=1)

    targets = [
        (_grid_mesh((8,), ("model",)), {"w": P("model"), "b": P()}),
        (_grid_mesh((4,), ("model",), ndev=4),
         {"w": P(None, "model"), "b": P("model")}),
    ]
    for mesh_b, specs in targets:
        shardings = {n: NamedSharding(mesh_b, s) for n, s in specs.items()}
        vals = ckpt.load_checkpoint(str(tmp_path), shardings=shardings)
        for n, want in (("w", w), ("b", b)):
            assert isinstance(vals[n], jax.Array)
            assert vals[n].sharding.mesh.shape == mesh_b.shape
            np.testing.assert_array_equal(np.asarray(vals[n]), want,
                                          err_msg=n)
    # host restore: no shardings -> plain numpy, still bit-exact
    vals = ckpt.load_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(vals["w"], w)
    np.testing.assert_array_equal(vals["b"], b)


@pytest.mark.multidevice_fragile
def test_save_on_2x4_resume_on_1x8_training_parity(tmp_path):
    """Train on a 2x4 TP strategy, checkpoint, restore onto a 1-D
    8-way mesh with a different rule set, and resume: restored params
    are bit-exact and the resumed losses match the uninterrupted 2x4
    run (reduction order may differ across meshes -> allclose)."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    compiled_a = fluid.CompiledProgram(main).with_strategy(_strategy())
    batches = _batches(8)
    with fluid.scope_guard(scope):
        exe.run(startup)
        ref = [float(exe.run(compiled_a, feed=fd, fetch_list=[loss])[0])
               for fd in batches[:4]]
        saved = {n: np.asarray(scope.find_var(n))
                 for n in scope.var_names()}
        ckpt.save_scope(str(tmp_path), scope, step=4)
        ref += [float(exe.run(compiled_a, feed=fd, fetch_list=[loss])[0])
                for fd in batches[4:]]

    strategy_b = DistributedStrategy(
        _grid_mesh((8,), ("model",)), data_axis=None,
        rules=[ShardingRule(r"_colp\.w(_|$)", P(None, "model")),
               ShardingRule(r"_colp\.b(_|$)", P("model")),
               ShardingRule(r"_rowp\.w(_|$)", P("model", None)),
               ShardingRule(r"_rowp\.b(_|$)", P())])
    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    compiled_b = fluid.CompiledProgram(main).with_strategy(strategy_b)
    with fluid.scope_guard(scope2):
        ckpt.restore_scope(str(tmp_path), scope2, strategy=strategy_b)
        for n, want in saved.items():  # bit-exact restore, resharded
            np.testing.assert_array_equal(
                np.asarray(scope2.find_var(n)), want, err_msg=n)
        resumed = [float(exe2.run(compiled_b, feed=fd,
                                  fetch_list=[loss])[0])
                   for fd in batches[4:]]
    np.testing.assert_allclose(resumed, ref[4:], rtol=1e-5, atol=1e-6)


def _handcraft_replicated(tmp_path, w):
    """A committed checkpoint in the multi-host layout the single-process
    CPU harness cannot produce natively: TWO processes' shard files each
    holding a full-range replica copy of 'w' (e.g. a TP-replicated value
    saved by both data rows)."""
    cd = tmp_path / "checkpoint_1"
    os.makedirs(str(cd))
    np.savez(str(cd / "shards_0.npz"), **{"w::0::0": w})
    np.savez(str(cd / "shards_1.npz"), **{"w::1::0": w})
    crc = zlib.crc32(np.ascontiguousarray(w).tobytes())
    full = [[0, int(d)] for d in w.shape]
    man = {"w": {"shape": list(w.shape), "dtype": str(w.dtype),
                 "sharded": True,
                 "shards": {"w::0::0": full, "w::1::0": full},
                 "checksums": {"w::0::0": crc, "w::1::0": crc},
                 "sharding": None}}
    with open(str(cd / "manifest.json.0"), "w") as f:
        json.dump(man, f)
    with open(str(cd / "COMMIT"), "w") as f:
        json.dump({"step": 1, "format": 2}, f)


def test_partial_shard_subset_restores_when_replica_coverage_complete(
        tmp_path):
    monitor.enable()
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    _handcraft_replicated(tmp_path, w)
    os.remove(str(tmp_path / "checkpoint_1" / "shards_1.npz"))
    p0 = monitor.counter("pt_ckpt_partial_restores_total").value()
    vals = ckpt.load_checkpoint(str(tmp_path), step=1)
    np.testing.assert_array_equal(vals["w"], w)
    assert monitor.counter(
        "pt_ckpt_partial_restores_total").value() == p0 + 1
    # validation agrees: the file subset still covers every element
    assert ckpt.validate_checkpoint(str(tmp_path), 1)
    assert ckpt.latest_step(str(tmp_path)) == 1


def test_missing_shards_raise_structured_ioerror(tmp_path):
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    _handcraft_replicated(tmp_path, w)
    for fn in ("shards_0.npz", "shards_1.npz"):
        os.remove(str(tmp_path / "checkpoint_1" / fn))
    with pytest.raises(IOError) as ei:
        ckpt.load_checkpoint(str(tmp_path), step=1)
    msg = str(ei.value)
    # names the variable, the absent shard files, and the coverage verdict
    assert "'w'" in msg and "shards_0.npz" in msg and "shards_1.npz" in msg
    assert "replica coverage does NOT permit reassembly" in msg
    assert not ckpt.validate_checkpoint(str(tmp_path), 1)


def test_legacy_v1_manifest_without_sharding_fields_still_loads(tmp_path):
    """v1 checkpoints (no per-entry sharding descriptor, format-1 COMMIT)
    must keep loading — upgrade path."""
    scope = _save_two(tmp_path)
    for s in (1, 2):
        mp = str(tmp_path / f"checkpoint_{s}" / "manifest.json.0")
        with open(mp) as f:
            man = json.load(f)
        for entry in man.values():
            entry.pop("sharding", None)
        with open(mp, "w") as f:
            json.dump(man, f)
        with open(str(tmp_path / f"checkpoint_{s}" / "COMMIT"), "w") as f:
            json.dump({"step": s, "format": 1}, f)
    assert ckpt.latest_step(str(tmp_path)) == 2
    step, values = ckpt.load_latest(str(tmp_path))
    assert step == 2
    for n in values:
        np.testing.assert_array_equal(
            values[n], np.asarray(scope.find_var(n)), err_msg=n)


def test_ckpt_read_fault_tears_restore_path(tmp_path):
    """The new ckpt.read site lets chaos plans fail the RESTORE:
    a raise on the newest serial's first read makes discovery fall back
    to the previous valid serial, metered as an injected fault."""
    monitor.enable()
    _save_two(tmp_path)
    inj0 = monitor.counter("pt_fault_injected_total").value(
        labels={"site": "ckpt.read"})
    faults.arm("ckpt.read:raise@1")
    step, values = ckpt.load_latest(str(tmp_path))
    faults.disarm()
    assert step == 1 and values  # newest torn by the plan -> fell back
    assert monitor.counter("pt_fault_injected_total").value(
        labels={"site": "ckpt.read"}) == inj0 + 1
    assert {"site": "ckpt.read", "hit": 1, "action": "raise"} \
        in faults.records()


# --------------------------------------------------------------------------
# multi-host commit barrier (ISSUE 7 tentpole: the race the v1 docstring
# admitted). A coordinator + process_index simulate the world in-process.
# --------------------------------------------------------------------------

class _MemCoordinator:
    """In-memory stand-in for FleetCommitCoordinator: same protocol,
    shared dict + events instead of the coord KV server. ack_write goes
    through the fleet.kv_put fault site exactly like the real one (via
    fleet.put), so chaos plans can kill a writer mid-barrier."""

    def __init__(self, shared, rank, world, timeout_s=5.0,
                 ack_gate=None):
        self.shared, self.rank, self.world = shared, rank, world
        self.timeout_s = timeout_s
        self._ack_gate = ack_gate

    def ack_write(self, seq, step):
        if self._ack_gate is not None:
            assert self._ack_gate.wait(self.timeout_s)
        faults.site("fleet.kv_put").hit()
        self.shared[("ack", seq, step, self.rank)] = True

    def wait_writers(self, seq, step):
        deadline = time.monotonic() + self.timeout_s
        while not all(self.shared.get(("ack", seq, step, r))
                      for r in range(1, self.world)):
            if time.monotonic() > deadline:
                raise TimeoutError("writer acks missing")
            time.sleep(0.005)

    def publish(self, seq, step):
        self.shared[("pub", seq, step)] = True

    def wait_published(self, seq, step):
        deadline = time.monotonic() + self.timeout_s
        while not self.shared.get(("pub", seq, step)):
            if time.monotonic() > deadline:
                raise TimeoutError("publish missing")
            time.sleep(0.005)


def _barrier_world(tmp_path, world, shared, state, step, gates=None,
                   timeout_s=5.0):
    """Run `world` writers of one coordinated save on threads; returns
    {rank: exception-or-None}. Coordinated saves share one seq: pin it
    so per-thread _next_coord_seq draws can't diverge."""
    seq = ckpt._next_coord_seq()
    results = {}

    def _writer(r):
        coord = _MemCoordinator(shared, r, world, timeout_s=timeout_s,
                                ack_gate=(gates or {}).get(r))
        try:
            ckpt.save_checkpoint(
                str(tmp_path), state if r == 0 else {}, step=step,
                coordinator=coord, process_index=r)
            results[r] = None
        except BaseException as e:  # noqa: BLE001 — harvested by caller
            results[r] = e

    orig = ckpt._next_coord_seq
    ckpt._next_coord_seq = lambda: seq
    try:
        ts = [threading.Thread(target=_writer, args=(r,))
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        ckpt._next_coord_seq = orig
    return results


def test_commit_waits_for_every_writer_ack_before_marker(tmp_path):
    """The COMMIT marker / rename must not happen until EVERY writer
    acked — the late-writer race the single-host protocol had."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        state = {n: scope.find_var(n) for n in scope.var_names()}
    shared = {}
    gate = threading.Event()  # writer 1's ack held back
    done = {}

    def _run():
        done.update(_barrier_world(tmp_path, 2, shared, state, step=1,
                                   gates={1: gate}))

    t = threading.Thread(target=_run)
    t.start()
    time.sleep(0.3)  # writers 0+1 wrote files; ack still gated
    assert not (tmp_path / "checkpoint_1").exists()
    assert not (tmp_path / "checkpoint_1.tmp" / "COMMIT").exists()
    gate.set()
    t.join(10)
    assert done == {0: None, 1: None}
    assert (tmp_path / "checkpoint_1" / "COMMIT").exists()
    assert ckpt.validate_checkpoint(str(tmp_path), 1)
    # both writers' fragments landed inside the committed dir
    names = os.listdir(str(tmp_path / "checkpoint_1"))
    assert {"manifest.json.0", "manifest.json.1",
            "shards_0.npz", "shards_1.npz"} <= set(names)


def test_writer_killed_mid_commit_barrier_falls_back(tmp_path):
    """Seeded fault-plan replay (ISSUE 7 acceptance): the plan kills
    writer 1 at its ack -> process 0's barrier times out, the save
    fails STAGED (no COMMIT, no rename), resume falls back to the
    previous serial — and a replay injects the identical sequence."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        state = {n: scope.find_var(n) for n in scope.var_names()}
        ckpt.save_scope(str(tmp_path), scope, step=1)  # prior serial

    replays = []
    for _ in range(2):
        faults.arm("fleet.kv_put:raise@1", seed=7)
        shared = {}
        res = _barrier_world(tmp_path, 2, shared, state, step=2,
                             timeout_s=0.6)
        replays.append(list(faults.records()))
        faults.disarm()
        assert isinstance(res[1], faults.InjectedFault)  # the kill
        assert isinstance(res[0], TimeoutError)  # barrier starved
        assert not (tmp_path / "checkpoint_2").exists()
        assert (tmp_path / "checkpoint_2.tmp").exists()  # staged only
        assert ckpt.latest_step(str(tmp_path)) == 1
        assert ckpt.load_latest(str(tmp_path))[0] == 1
    assert replays[0] == replays[1] == [
        {"site": "fleet.kv_put", "hit": 1, "action": "raise"}]


class _FakeFleet:
    """Enough of the Fleet KV surface for FleetCommitCoordinator: a
    shared dict + condition, per-rank views."""

    def __init__(self, store, cond, rank, world):
        self._store, self._cond = store, cond
        self._rank, self._world = rank, world
        self._initialized = True

    def worker_index(self):
        return self._rank

    def worker_num(self):
        return self._world

    def put(self, key, value):
        with self._cond:
            self._store[key] = value
            self._cond.notify_all()

    def get(self, key, timeout_ms=None):
        deadline = time.monotonic() + (timeout_ms or 1000) / 1000.0
        with self._cond:
            while key not in self._store:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(key)
                self._cond.wait(left)
            return self._store[key]


def test_fleet_commit_coordinator_protocol_over_kv(tmp_path):
    """The production FleetCommitCoordinator drives the same barrier
    over its KV alphabet (ack/<seq>:<step>/<rank> then pub)."""
    store, cond = {}, threading.Condition()
    res = {}

    def _writer(r):
        coord = ckpt.FleetCommitCoordinator(
            fleet=_FakeFleet(store, cond, r, 3), timeout_ms=5000)
        try:
            ckpt.save_checkpoint(str(tmp_path),
                                 {"a": np.arange(4.0)} if r == 0 else {},
                                 step=9, coordinator=coord,
                                 process_index=r)
            res[r] = None
        except BaseException as e:  # noqa: BLE001
            res[r] = e

    seq = ckpt._next_coord_seq()
    orig = ckpt._next_coord_seq
    ckpt._next_coord_seq = lambda: seq
    try:
        ts = [threading.Thread(target=_writer, args=(r,)) for r in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
    finally:
        ckpt._next_coord_seq = orig
    assert res == {0: None, 1: None, 2: None}
    assert ckpt.latest_step(str(tmp_path)) == 9
    assert f"ckpt/ack/{seq}:9/1" in store and f"ckpt/pub/{seq}:9" in store


# --------------------------------------------------------------------------
# async-save overlap (ISSUE 7 tentpole: snapshot in caller, commit
# off-thread, training continues meanwhile)
# --------------------------------------------------------------------------

def test_async_save_overlaps_commit_with_training_steps(tmp_path):
    """With the commit delayed by a chaos plan, training steps complete
    WHILE the commit is still in flight, and the async wall time beats
    the synchronous sum ``t_steps + delay`` — the sync commit would
    block the caller for the full delay before any step could run.
    (Telemetry stays OFF: per-step phase syncs would tax the measured
    window.)"""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    batches = _batches(4)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=batches[0], fetch_list=[loss])  # warm compile

        # size the window to ~1s of warm-step wall time, then calibrate
        # its cost with a second pass (min of the two: the serial
        # baseline must not be inflated by a transient stall, which
        # would fake an overlap win)
        window = []
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 1.0:
            fd = batches[1 + len(window) % 3]
            exe.run(main, feed=fd, fetch_list=[loss])
            window.append(fd)
        t_first = time.perf_counter() - t0
        t0 = time.perf_counter()
        for fd in window:
            exe.run(main, feed=fd, fetch_list=[loss])
        t_steps = min(t_first, time.perf_counter() - t0)

        # async: a commit delayed by a full window overlaps the steps.
        # The measurement itself is retried: a scheduler stall can make
        # one async window run arbitrarily slower than the calibrated
        # baseline, but a genuinely SERIALIZED commit can never pass the
        # bound (it would need the window to run 25% FASTER than the
        # calibrated minimum), so retrying cannot mask a regression.
        delay = t_steps
        for attempt in range(3):
            faults.arm(f"ckpt.commit:delay({delay:.3f})@1")
            t0 = time.perf_counter()
            h = ckpt.save_scope(str(tmp_path / f"async{attempt}"), scope,
                                step=1, async_save=True)
            step_done = False
            for i, fd in enumerate(window):
                exe.run(main, feed=fd, fetch_list=[loss])
                if i == 0:
                    step_done = not h.done()  # a step landed mid-commit?
            h.wait()
            t_async = time.perf_counter() - t0
            faults.disarm()
            # measurably below the synchronous sum (>= t_steps + delay =
            # 2*t_steps: a sync save blocks the caller for the full
            # delay before any step runs): the overlap must reclaim at
            # least a quarter of it. Expected t_async ~ 1.05*t_steps.
            if step_done and t_async < 1.75 * t_steps:
                break
        assert step_done  # a step completed while commit in flight
        assert t_async < 1.75 * t_steps, (t_async, t_steps)
        assert ckpt.validate_checkpoint(str(tmp_path / f"async{attempt}"), 1)


def test_snapshot_phase_metered_separately_from_commit(tmp_path):
    monitor.enable()
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        snaps0 = monitor.histogram("pt_ckpt_snapshot_seconds").count()
        commits0 = monitor.histogram("pt_ckpt_commit_seconds").count()
        h = ckpt.save_scope(str(tmp_path), scope, step=1, async_save=True)
        # the snapshot is metered BEFORE the background thread commits:
        # the caller-side device->host copy is what donation-safety needs
        assert monitor.histogram(
            "pt_ckpt_snapshot_seconds").count() == snaps0 + 1
        h.wait()
    assert monitor.histogram(
        "pt_ckpt_commit_seconds").count() == commits0 + 1
    assert ckpt.validate_checkpoint(str(tmp_path), 1)


def test_crash_during_overlapped_commit_leaves_valid_or_absent(tmp_path):
    """ISSUE 7 acceptance: a crash while the OVERLAPPED commit is in
    flight leaves only valid-or-absent serials (validate_checkpoint
    proof) — training that continued meanwhile is unaffected."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    batches = _batches(3)
    with fluid.scope_guard(scope):
        exe.run(startup)
        ckpt.save_scope(str(tmp_path), scope, step=1)
        faults.arm("ckpt.commit:raise@1")
        h = ckpt.save_scope(str(tmp_path), scope, step=2, async_save=True)
        out = [float(exe.run(main, feed=fd, fetch_list=[loss])[0])
               for fd in batches]  # training rides over the dying commit
        assert len(out) == 3
        with pytest.raises(faults.InjectedFault):
            h.wait()
        faults.disarm()
    assert not ckpt.validate_checkpoint(str(tmp_path), 2)
    assert not (tmp_path / "checkpoint_2").exists()  # absent, not torn
    assert ckpt.latest_step(str(tmp_path)) == 1
    assert ckpt.validate_checkpoint(str(tmp_path), 1)


def test_trainer_async_save_config_end_to_end(tmp_path):
    """CheckpointConfig(async_save=True): same trajectory as sync saves,
    every serial valid, pruning still bounded."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    train_func, optimizer_func, reader, EndStepEvent = _trainer_pieces()
    losses = {}
    for mode, async_save in (("sync", False), ("async", True)):
        out = []
        t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                    checkpoint_config=CheckpointConfig(
                        str(tmp_path / mode), epoch_interval=1,
                        max_num_checkpoints=2, async_save=async_save))
        t.train(3, lambda e: out.append(float(e.metrics[0]))
                if isinstance(e, EndStepEvent) else None,
                reader(), ["img", "label"])
        losses[mode] = out
    np.testing.assert_array_equal(losses["sync"], losses["async"])
    d = str(tmp_path / "async")
    assert ckpt.latest_step(d) == 3
    assert sorted(ckpt.available_steps(d)) == [2, 3]  # pruned to 2
    for s in (2, 3):
        assert ckpt.validate_checkpoint(d, s)


# --------------------------------------------------------------------------
# resized resume (ISSUE 7 satellite: shard boundaries move with the world)
# --------------------------------------------------------------------------

def test_trainer_resized_resume_rederives_rng_cursor(tmp_path, monkeypatch):
    from paddle_tpu.contrib import CheckpointConfig, Trainer
    import paddle_tpu.contrib.trainer as trainer_mod

    monitor.enable()
    train_func, optimizer_func, reader, _ = _trainer_pieces()
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(str(tmp_path)))
    t.train(2, None, reader(), ["img", "label"])
    cursor = t.exe._step
    assert cursor > 0

    # the restoring process comes up in a 2-worker world: the cursor is
    # re-derived (global data position preserved) and the resume counts
    # into the resized="true" cell
    monkeypatch.setattr(trainer_mod, "_current_world", lambda: 2)
    r0 = monitor.counter("pt_trainer_auto_resumes_total").value(
        labels={"resized": "true"})
    with pytest.warns(RuntimeWarning, match="re-derived"):
        t2 = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                     checkpoint_config=CheckpointConfig(str(tmp_path)))
    assert t2._start_epoch == 2  # epoch position is world-independent
    assert t2.exe._step == cursor // 2
    assert monitor.counter("pt_trainer_auto_resumes_total").value(
        labels={"resized": "true"}) == r0 + 1


@pytest.mark.multidevice_fragile
def test_trainer_resume_settles_pending_save_with_one_retry(tmp_path):
    """One fault, one retry: a training failure that arrives while an
    overlapped save is ALSO failing in the background must not burn two
    resume retries — the pending handle is settled (warned) before the
    restore, never re-raised by the replay's next save."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    train_func, optimizer_func, reader, _ = _trainer_pieces()
    # epoch 2's background commit dies; epoch 3's first batch fetch
    # (hit 9: 4 batches per epoch) raises while that save is pending
    faults.arm("ckpt.commit:raise@2;reader.next:raise@9")
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(
                    str(tmp_path), epoch_interval=1, max_resume_retries=1,
                    async_save=True))
    with pytest.warns(RuntimeWarning) as rec:
        t.train(3, None, reader(), ["img", "label"])
    faults.disarm()
    msgs = [str(w.message) for w in rec]
    assert any("failed during auto-resume" in m for m in msgs)
    assert any("auto-resuming" in m for m in msgs)
    assert ckpt.latest_step(str(tmp_path)) == 3  # replay finished


def test_trainer_exhausted_retries_still_settles_pending_save(tmp_path):
    """With the resume budget spent (or zero), the raise path must still
    land the in-flight overlapped save: caller-side recovery scans the
    checkpoint dir next, and must not race the background commit — nor
    lose its failure to an atexit warning."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    train_func, optimizer_func, reader, _ = _trainer_pieces()
    # epoch 2's background commit dies; epoch 3's first batch (hit 9)
    # raises with NO retries left
    faults.arm("ckpt.commit:raise@2;reader.next:raise@9")
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(
                    str(tmp_path), epoch_interval=1, async_save=True))
    with pytest.warns(RuntimeWarning, match="failed during auto-resume"):
        with pytest.raises(faults.InjectedFault):
            t.train(3, None, reader(), ["img", "label"])
    faults.disarm()
    assert t._pending_save is None  # settled, not orphaned
    assert ckpt.latest_step(str(tmp_path)) == 1  # serial 2 never committed


def test_trainer_resized_auto_resume_counts_once(tmp_path, monkeypatch):
    """An in-train auto-resume that restores a checkpoint saved by a
    DIFFERENT world size lands exactly one count, in the resized="true"
    cell — not one in each cell."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer
    import paddle_tpu.contrib.trainer as trainer_mod

    monitor.enable()
    train_func, optimizer_func, reader, _ = _trainer_pieces()
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(str(tmp_path)))
    t.train(2, None, reader(), ["img", "label"])

    monkeypatch.setattr(trainer_mod, "_current_world", lambda: 2)
    c = monitor.counter("pt_trainer_auto_resumes_total")
    t0 = c.value(labels={"resized": "true"})
    f0 = c.value(labels={"resized": "false"})
    faults.arm("reader.next:raise@1")  # epoch 3's first batch dies
    with pytest.warns(RuntimeWarning, match="auto-resuming"):
        t2 = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                     checkpoint_config=CheckpointConfig(
                         str(tmp_path), max_resume_retries=1))
        t2.train(3, None, reader(), ["img", "label"])
    faults.disarm()
    # +2 resized: the init-time restore AND the in-train resume (both
    # restored a 1-world checkpoint onto the 2-world run); the false
    # cell must NOT tick for the same events
    assert c.value(labels={"resized": "true"}) == t0 + 2
    assert c.value(labels={"resized": "false"}) == f0


def test_trainer_never_prunes_the_last_valid_checkpoint(tmp_path):
    """Pruning-order satellite: with max_num_checkpoints=1, a failed
    save of serial N must leave serial N-1 on disk (the old prune-first
    order could leave ZERO resumable state)."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    train_func, optimizer_func, reader, _ = _trainer_pieces()
    faults.arm("ckpt.commit:raise@2")  # epoch 2's save dies pre-commit
    t = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                checkpoint_config=CheckpointConfig(
                    str(tmp_path), epoch_interval=1,
                    max_num_checkpoints=1))
    with pytest.raises(faults.InjectedFault):
        t.train(2, None, reader(), ["img", "label"])
    faults.disarm()
    assert ckpt.latest_step(str(tmp_path)) == 1
    assert ckpt.validate_checkpoint(str(tmp_path), 1)


# --------------------------------------------------------------------------
# optimizer slot-state resharding (ISSUE 14): manifest slot descriptors,
# re-keying onto a differently-built program's slot names, mesh matrix
# --------------------------------------------------------------------------

def _slot_state(mesh):
    """Param + Adam-style slot state sharded on ``mesh`` (2x4 TP shape),
    with the manifest slot descriptors an Optimizer would record."""
    import jax
    from jax.sharding import NamedSharding

    r = np.random.RandomState(3)
    w = r.randn(8, 16).astype(np.float32)
    m1 = r.randn(8, 16).astype(np.float32)
    m2 = np.abs(r.randn(8, 16)).astype(np.float32)
    b1p = np.asarray([0.81], np.float32)
    state = {
        "w": _sharded(w, mesh, P(None, "model")),
        "w_moment1_0": _sharded(m1, mesh, P(None, "model")),
        "w_moment2_0": _sharded(m2, mesh, P(None, "model")),
        "w_beta1_pow_0": jax.device_put(
            b1p, NamedSharding(mesh, P())),
    }
    slots = {
        "w_moment1_0": {"param": "w", "slot": "moment1"},
        "w_moment2_0": {"param": "w", "slot": "moment2"},
        "w_beta1_pow_0": {"param": "w", "slot": "beta1_pow"},
    }
    return state, slots, {"w": w, "m1": m1, "m2": m2, "b1p": b1p}


def test_manifest_records_slot_descriptors(tmp_path):
    """save_checkpoint(slots=) lands a ``slot`` field on each covered
    manifest entry; manifest_slots reads the merged descriptor map back
    without touching any array data."""
    mesh_a = _grid_mesh((2, 4), ("data", "model"))
    state, slots, _ = _slot_state(mesh_a)
    ckpt.save_checkpoint(str(tmp_path), state, step=1, slots=slots)
    assert ckpt.manifest_slots(str(tmp_path), 1) == slots
    with open(str(tmp_path / "checkpoint_1" / "manifest.json.0")) as f:
        man = json.load(f)
    assert man["w_moment1_0"]["slot"] == {"param": "w", "slot": "moment1"}
    assert "slot" not in man["w"]  # parameters carry no slot field
    # v2 validation is indifferent to the new optional field
    assert ckpt.validate_checkpoint(str(tmp_path), 1)


def test_optimizer_slot_state_mesh_matrix_bit_exact(tmp_path):
    """THE ISSUE 14 slot matrix (mirrors the parameter mesh matrix):
    slot state saved under a 2x4 TP layout restores bit-exact onto a
    1x8 mesh and onto a 4-device layout, re-KEYED onto the restoring
    program's (drifted) slot names and re-PLACED onto its shardings."""
    import jax
    from jax.sharding import NamedSharding

    mesh_a = _grid_mesh((2, 4), ("data", "model"))
    state, slots, raw = _slot_state(mesh_a)
    ckpt.save_checkpoint(str(tmp_path), state, step=1, slots=slots)
    saved_slots = ckpt.manifest_slots(str(tmp_path), 1)

    # the restoring build's unique_name counters drifted: _0 -> _3
    target_slots = {
        "w_moment1_3": {"param": "w", "slot": "moment1"},
        "w_moment2_3": {"param": "w", "slot": "moment2"},
        "w_beta1_pow_3": {"param": "w", "slot": "beta1_pow"},
    }
    targets = [
        (_grid_mesh((8,), ("model",)), P("model", None)),
        (_grid_mesh((4,), ("model",), ndev=4), P(None, "model")),
    ]
    for mesh_b, spec in targets:
        vals = ckpt.load_checkpoint(str(tmp_path), step=1)
        shardings = {n: NamedSharding(mesh_b, spec if "pow" not in n
                                      else P())
                     for n in target_slots}
        out = ckpt.reshard_optimizer_state(
            vals, saved_slots, target_slots, shardings=shardings)
        # re-keyed: the saved names are gone, the restoring names carry
        # the values bit-exact, placed on the restoring mesh
        for old in saved_slots:
            assert old not in out
        for new, want in (("w_moment1_3", raw["m1"]),
                          ("w_moment2_3", raw["m2"]),
                          ("w_beta1_pow_3", raw["b1p"])):
            assert isinstance(out[new], jax.Array)
            assert out[new].sharding.mesh.shape == mesh_b.shape
            np.testing.assert_array_equal(np.asarray(out[new]), want,
                                          err_msg=new)
        # the parameter itself passes through untouched
        np.testing.assert_array_equal(np.asarray(out["w"]), raw["w"])


def test_reshard_optimizer_state_strategy_placement_and_drops(tmp_path):
    """strategy= resolves each target slot's sharding through
    sharding_for (the restore_scope convention); slots whose (param,
    kind) has no target in the restoring program are DROPPED — the
    per-stage pipeline case, where a stage restores only its own
    params' state — and the re-key events are metered."""
    monitor.enable()
    mesh_a = _grid_mesh((2, 4), ("data", "model"))
    state, slots, raw = _slot_state(mesh_a)
    state["other_moment1_0"] = np.ones(3, np.float32)
    slots["other_moment1_0"] = {"param": "other", "slot": "moment1"}
    ckpt.save_checkpoint(str(tmp_path), state, step=1, slots=slots)

    strategy_b = DistributedStrategy(
        _grid_mesh((8,), ("model",)), data_axis=None,
        rules=[ShardingRule(r"^w(_|$)", P(None, "model"))])
    target_slots = {
        "w_moment1_7": {"param": "w", "slot": "moment1"},
        "w_moment2_7": {"param": "w", "slot": "moment2"},
        "w_beta1_pow_7": {"param": "w", "slot": "beta1_pow"},
        # no saved (param, kind) match: stays absent, never invented
        "w_extra_7": {"param": "w", "slot": "extra"},
    }
    rk0 = monitor.counter("pt_ckpt_slot_rekeys_total").value()
    vals = ckpt.load_checkpoint(str(tmp_path), step=1)
    out = ckpt.reshard_optimizer_state(
        vals, ckpt.manifest_slots(str(tmp_path), 1), target_slots,
        strategy=strategy_b)
    # 'other' has no target in this program: its slot state is dropped
    assert "other_moment1_0" not in out and "w_extra_7" not in out
    assert monitor.counter("pt_ckpt_slot_rekeys_total").value() == rk0 + 3
    np.testing.assert_array_equal(np.asarray(out["w_moment1_7"]),
                                  raw["m1"])
    # strategy placement: scalar state replicated, matrix state sharded
    assert len(out["w_moment1_7"].sharding.device_set) == 8
    sd = pmesh.sharding_descriptor(out["w_beta1_pow_7"].sharding)
    assert sd["spec"] == []  # P(): replicated scalar state
    # identity re-key (same names) is a no-op passthrough, not a count
    out2 = ckpt.reshard_optimizer_state(
        dict(vals), ckpt.manifest_slots(str(tmp_path), 1),
        {n: dict(d) for n, d in slots.items()})
    assert monitor.counter("pt_ckpt_slot_rekeys_total").value() == rk0 + 3
    np.testing.assert_array_equal(np.asarray(out2["w_moment1_0"]),
                                  raw["m1"])


def test_trainer_resume_rekeys_drifted_slot_names(tmp_path):
    """A resized/rebuilt trainer resume must not silently zero the
    moments: the SAME build code in an already-warm process drifts the
    unique_name slot counters (ar1.w_velocity_0 -> _1), exactly like a
    per-stage pipeline program differing across worlds. The manifest's
    slot descriptors let _maybe_resume re-key the saved velocity onto
    the new build's names — bit-exact, old names dropped."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    train_func, _, reader, _ = _trainer_pieces()

    def optimizer_func():
        return fluid.optimizer.Momentum(0.1, momentum=0.9)

    t1 = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                 checkpoint_config=CheckpointConfig(
                     str(tmp_path), epoch_interval=1))
    t1.train(2, None, reader(), ["img", "label"])
    old_names = sorted(n for n in t1._optimizer.slot_descriptor()
                       if "velocity" in n)
    assert old_names and all(n.endswith("_0") for n in old_names)
    saved = {n: np.asarray(t1.scope.find_var(n)) for n in old_names}
    assert any(np.abs(v).max() > 0 for v in saved.values())

    t2 = Trainer(train_func, optimizer_func, fluid.CPUPlace(),
                 checkpoint_config=CheckpointConfig(
                     str(tmp_path), epoch_interval=1))
    new_names = sorted(n for n in t2._optimizer.slot_descriptor()
                       if "velocity" in n)
    assert new_names != old_names  # the drift is real
    for old, new in zip(old_names, new_names):
        assert t2.scope.find_var(old) is None  # stale key dropped
        np.testing.assert_array_equal(
            np.asarray(t2.scope.find_var(new)), saved[old], err_msg=new)
