"""Gradient clipping accounting (paddle_tpu/clip.py): global-norm clip
math against ground truth (triggered vs not), the reported pre/post
norms, the numerics-plane clip instruments, param_list scoping, and the
by-value / by-norm variants — previously untested and metric-less."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import clip as clip_mod
from paddle_tpu import flags, layers, monitor, numerics


@pytest.fixture(autouse=True)
def _clean():
    monitor.reset()
    clip_mod.set_gradient_clip.__globals__["_clip_attr"] = None
    clip_mod.set_gradient_clip.__globals__["_clip_param_names"] = None
    flags.set_flags({"telemetry": False, "numerics": False,
                     "numerics_vars": ""})
    yield
    monitor.reset()
    clip_mod.set_gradient_clip.__globals__["_clip_attr"] = None
    clip_mod.set_gradient_clip.__globals__["_clip_param_names"] = None
    flags.set_flags({"telemetry": False, "numerics": False,
                     "numerics_vars": ""})


def _build_and_run(clip_norm, x_val, lr=1.0):
    """One param w [4] with loss = sum(w * x): grad_w == x exactly, so
    the global norm is ||x|| — ground truth without model noise.
    Returns (w_before, w_after, grad, clip_attr)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        w = layers.create_parameter([4], "float32", name="clip_w")
        loss = layers.reduce_sum(layers.elementwise_mul(x, w))
        attr = clip_mod.GradientClipByGlobalNorm(clip_norm)
        clip_mod.set_gradient_clip(attr)
        fluid.optimizer.SGD(lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        before = np.asarray(scope.find_var("clip_w")).copy()
        exe.run(main, feed={"x": x_val[None, :].astype(np.float32)},
                fetch_list=[loss])
        after = np.asarray(scope.find_var("clip_w"))
    return before, after, x_val, attr


def test_global_norm_clip_triggered_scales_to_clip_norm():
    flags.set_flags({"telemetry": True, "numerics": True})
    grad = np.array([3.0, 4.0, 0.0, 0.0])  # ||g|| = 5
    before, after, _g, attr = _build_and_run(clip_norm=2.5, x_val=grad)
    # scale = 2.5 / max(5, 2.5) = 0.5 -> update = g * 0.5
    np.testing.assert_allclose(before - after, grad * 0.5, rtol=1e-5)
    # the in-graph norm/scale vars are registered + exported
    assert attr.global_norm_name is not None
    assert monitor.gauge("pt_grad_global_norm").value() == pytest.approx(
        5.0, rel=1e-5)
    assert monitor.gauge("pt_grad_clip_ratio").value() == pytest.approx(
        0.5, rel=1e-5)
    assert monitor.counter("pt_grad_clips_total").value() == 1
    # post-clip norm = pre * scale = the clip bound
    post = monitor.gauge("pt_grad_global_norm").value() * \
        monitor.gauge("pt_grad_clip_ratio").value()
    assert post == pytest.approx(2.5, rel=1e-5)


def test_global_norm_clip_not_triggered_reports_ratio_one():
    flags.set_flags({"telemetry": True, "numerics": True})
    grad = np.array([3.0, 4.0, 0.0, 0.0])  # ||g|| = 5 < 100
    before, after, _g, _attr = _build_and_run(clip_norm=100.0, x_val=grad)
    np.testing.assert_allclose(before - after, grad, rtol=1e-5)
    assert monitor.gauge("pt_grad_global_norm").value() == pytest.approx(
        5.0, rel=1e-5)
    assert monitor.gauge("pt_grad_clip_ratio").value() == pytest.approx(
        1.0, rel=1e-5)
    assert monitor.counter("pt_grad_clips_total").value() == 0


def test_global_norm_clip_math_without_telemetry():
    """The clip itself never depends on the observability plane."""
    grad = np.array([6.0, 8.0, 0.0, 0.0])  # ||g|| = 10
    before, after, _g, _attr = _build_and_run(clip_norm=5.0, x_val=grad)
    np.testing.assert_allclose(before - after, grad * 0.5, rtol=1e-5)
    assert monitor.counter("pt_grad_clips_total").value() == 0  # tele off


def test_set_gradient_clip_param_list_scopes_clipping():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        wa = layers.create_parameter([4], "float32", name="scoped_a")
        wb = layers.create_parameter([4], "float32", name="scoped_b")
        loss = layers.reduce_sum(
            layers.elementwise_add(layers.elementwise_mul(x, wa),
                                   layers.elementwise_mul(x, wb)))
        clip_mod.set_gradient_clip(
            clip_mod.GradientClipByGlobalNorm(2.5), param_list=["scoped_a"])
        assert clip_mod.clip_applies_to("scoped_a")
        assert not clip_mod.clip_applies_to("scoped_b")
        fluid.optimizer.SGD(1.0).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    grad = np.array([3.0, 4.0, 0.0, 0.0], np.float32)  # per-param ||g||=5
    with fluid.scope_guard(scope):
        exe.run(startup)
        a0 = np.asarray(scope.find_var("scoped_a")).copy()
        b0 = np.asarray(scope.find_var("scoped_b")).copy()
        exe.run(main, feed={"x": grad[None, :]}, fetch_list=[loss])
        a1 = np.asarray(scope.find_var("scoped_a"))
        b1 = np.asarray(scope.find_var("scoped_b"))
    # only scoped_a is clipped (its own norm 5 -> scale 0.5)
    np.testing.assert_allclose(a0 - a1, grad * 0.5, rtol=1e-5)
    np.testing.assert_allclose(b0 - b1, grad, rtol=1e-5)


def test_clip_by_value_and_by_norm_variants():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        w = layers.create_parameter([4], "float32", name="val_w")
        loss = layers.reduce_sum(layers.elementwise_mul(x, w))
        clip_mod.set_gradient_clip(clip_mod.GradientClipByValue(1.0))
        fluid.optimizer.SGD(1.0).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    grad = np.array([3.0, -4.0, 0.5, 0.0], np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = np.asarray(scope.find_var("val_w")).copy()
        exe.run(main, feed={"x": grad[None, :]}, fetch_list=[loss])
        w1 = np.asarray(scope.find_var("val_w"))
    np.testing.assert_allclose(
        w0 - w1, np.clip(grad, -1.0, 1.0), rtol=1e-5)


def test_clip_norm_vars_ride_the_numerics_bundle():
    """With the full pass applied, the clip's norm/scale ride the SAME
    single bundle as the tensor stats (no extra transfers)."""
    flags.set_flags({"telemetry": True, "numerics": True})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        w = layers.create_parameter([4], "float32", name="bundle_w")
        loss = layers.reduce_sum(layers.elementwise_mul(x, w))
        clip_mod.set_gradient_clip(clip_mod.GradientClipByGlobalNorm(2.5))
        fluid.optimizer.SGD(1.0).minimize(loss)
    plan = numerics.instrument(main)
    kinds = [k for k, _v in plan.aux]
    assert "grad_global_norm" in kinds and "grad_clip_scale" in kinds
    assert plan.bundle_size == (
        len(plan.entries) * len(numerics.STAT_FIELDS) + len(plan.aux))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    grad = np.array([3.0, 4.0, 0.0, 0.0], np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": grad[None, :]}, fetch_list=[loss])
    aux = numerics.latest_stats()[main._uid]["aux"]
    assert aux["grad_global_norm"] == pytest.approx(5.0, rel=1e-5)
    assert aux["grad_clip_scale"] == pytest.approx(0.5, rel=1e-5)
