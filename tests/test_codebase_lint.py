"""Self-lint gate (ISSUE 6 satellite): run ruff (pyflakes + bugbear
rules, configured in pyproject.toml) over the codebase as a tier-1 test
so real-defect regressions — undefined names, unused imports/vars,
mutable default args — fail CI. Skips when ruff is not installed (the
container does not ship it); the config still drives editor/CI runs.

A dependency-free fallback check (AST walk for unused module-level
imports, the highest-volume pyflakes class) runs either way, so the
self-lint invariant survives environments without ruff."""

import ast
import os
import shutil
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SKIP_DIRS = {"__pycache__", "proto", ".git", ".claude", "csrc"}
# files whose unused imports are intentional re-export surfaces —
# mirrors pyproject's [tool.ruff.lint.per-file-ignores]
REEXPORT_FILES = {"__init__.py", "lowering.py"}


def _py_files():
    for dirpath, dirs, files in os.walk(ROOT):
        dirs[:] = [d for d in dirs if d not in SKIP_DIRS]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


@pytest.mark.skipif(shutil.which("ruff") is None,
                    reason="ruff not installed in this environment")
def test_ruff_pyflakes_bugbear_clean():
    out = subprocess.run(
        ["ruff", "check", "--no-cache", "."],
        cwd=ROOT, capture_output=True, text=True)
    assert out.returncode == 0, (
        f"ruff found issues:\n{out.stdout}\n{out.stderr}")


def _unused_imports(path):
    src = open(path).read()
    tree = ast.parse(src)
    noqa = {i + 1 for i, line in enumerate(src.splitlines())
            if "noqa" in line}
    imported = {}
    for node in tree.body:  # module level only (function-local imports
        # are often for side effects / lazy cycles)
        if isinstance(node, ast.Import):
            for a in node.names:
                imported[(a.asname or a.name).split(".")[0]] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name in ("*", "annotations"):
                    continue
                imported[a.asname or a.name] = node.lineno
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)  # __all__ strings / doc references
    return [(ln, name) for name, ln in imported.items()
            if name not in used and ln not in noqa]


def test_no_unused_module_level_imports():
    problems = []
    for path in _py_files():
        if os.path.basename(path) in REEXPORT_FILES:
            continue
        try:
            for ln, name in _unused_imports(path):
                problems.append(
                    f"{os.path.relpath(path, ROOT)}:{ln}: "
                    f"unused import '{name}'")
        except SyntaxError as e:
            problems.append(f"{path}: syntax error: {e}")
    assert not problems, "\n".join(problems)


def test_all_sources_compile():
    """Syntax gate: every source file byte-compiles (catches stray
    merge markers / py-version slips before any import-time cost)."""
    for path in _py_files():
        with open(path, "rb") as f:
            compile(f.read(), path, "exec")
    assert True


def test_ruff_config_present():
    """The ruff config (pyflakes F + bugbear B) must stay in
    pyproject.toml so editor/CI runs agree with this gate."""
    cfg = open(os.path.join(ROOT, "pyproject.toml")).read()
    assert "[tool.ruff.lint]" in cfg
    assert '"F"' in cfg and '"B"' in cfg
