"""Persistent (level-2) compile cache: canonical fingerprints shared
across the executor / lint / compile-report subsystems, disk
round-trips, cross-process warm start with zero fresh compiles,
corruption degrading to a metered miss (never a crash), and the
disabled-path zero-allocation contract."""

import glob
import json
import os
import pickle
import subprocess
import sys
import tracemalloc
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis, compile_cache, faults, flags, layers, monitor

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    monitor.reset()
    flags.set_flags({"telemetry": True,
                     "compile_cache_dir": str(tmp_path / "ccache")})
    yield
    monitor.reset()
    faults.disarm()
    flags.set_flags({"telemetry": False, "compile_cache_dir": "",
                     "executor_cache_capacity": 0})


def _build(stateless=False):
    from paddle_tpu import unique_name

    # name counters restart per build (the fresh-process condition the
    # disk tier keys on): identical build code -> identical content
    main, startup = fluid.Program(), fluid.Program()
    with unique_name.guard(), fluid.program_guard(main, startup):
        if stateless:
            x = layers.data("x", shape=[4, 8], append_batch_size=False,
                            stop_gradient=True)
            out = layers.reduce_sum(x)
        else:
            x = layers.data("x", shape=[8], dtype="float32")
            out = layers.mean(layers.fc(x, 4))
            fluid.optimizer.SGD(0.1).minimize(out)
    return main, startup, out


def _feed(batch=4):
    return {"x": np.arange(batch * 8, dtype=np.float32).reshape(batch, 8)}


def _hits():
    return monitor.counter("pt_compile_cache_hits_total").value()


def _errors(stage):
    return monitor.counter("pt_compile_cache_errors_total").value(
        labels={"stage": stage})


# --------------------------------------------------------------------------
# canonical fingerprint (the satellite: ONE helper for executor key,
# lint-once cache, compile-report cache_key, disk tier)
# --------------------------------------------------------------------------

def test_program_fingerprint_is_content_keyed_across_builds():
    """Two identically-built programs (different uids — the
    cross-process stand-in) fingerprint identically; any content change
    diverges."""
    m1, _, _ = _build(stateless=True)
    m2, _, _ = _build(stateless=True)
    assert m1._uid != m2._uid
    assert m1.content_digest() == m2.content_digest()
    fp = compile_cache.program_fingerprint
    assert fp(m1, feed_sig=("x",), fetch_names=("o",)) == \
        fp(m2, feed_sig=("x",), fetch_names=("o",))
    # feed/fetch signature rides the fingerprint
    assert fp(m1, feed_sig=("x",), fetch_names=("o",)) != \
        fp(m1, feed_sig=("x",), fetch_names=("other",))
    # content mutation diverges (and the per-version digest cache sees it)
    with fluid.program_guard(m2, fluid.Program()):
        layers.scale(m2.global_block().var("x"), scale=2.0)
    assert m1.content_digest() != m2.content_digest()


def test_noncanonical_content_degrades_to_local_fingerprint(monkeypatch):
    """A program whose content cannot be canonicalized still keys
    in-process caches (local- prefix) but never resolves from disk."""
    main, startup, out = _build(stateless=True)
    monkeypatch.setattr(fluid.framework.Program, "content_digest",
                        lambda self: (_ for _ in ()).throw(TypeError("x")))
    fp = compile_cache.program_fingerprint(main)
    assert fp.startswith("local-")
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(), fetch_list=[out])
    # nothing was written: local fingerprints are not portable
    assert glob.glob(flags.get_flag("compile_cache_dir") + "/pcc-*") == []


def test_lint_once_cache_is_content_keyed_via_canonical_fingerprint():
    """The static verifier's lint-once cache now keys on the same
    canonical fingerprint: two identically-built programs share ONE
    lint run (previously uid-keyed — every rebuild re-linted)."""
    m1, _, _ = _build(stateless=True)
    m2, _, _ = _build(stateless=True)

    def runs():
        return monitor.counter("pt_lint_runs_total").value()

    r0 = runs()
    analysis.lint_before_compile(m1, ["x"], ["o"], site="t-ccfp")
    assert runs() == r0 + 1
    analysis.lint_before_compile(m2, ["x"], ["o"], site="t-ccfp")
    assert runs() == r0 + 1  # same content: cached
    analysis.lint_before_compile(m2, ["x"], [], site="t-ccfp")
    assert runs() == r0 + 2  # different fetch signature: re-lints


def test_compile_report_cache_key_is_canonical(tmp_path):
    """Identical programs run through different executors produce
    compile reports with the SAME cache_key digest — the canonical
    fingerprint, not a process-local identity tuple."""
    d = tmp_path / "reports"
    flags.set_flags({"compile_report_dir": str(d),
                     "compile_cache_dir": ""})
    try:
        keys = []
        for _ in range(2):
            main, startup, out = _build(stateless=True)
            scope = fluid.Scope()
            exe = fluid.Executor(fluid.CPUPlace())
            with fluid.scope_guard(scope):
                exe.run(startup)
                exe.run(main, feed=_feed(), fetch_list=[out])
                exe.run_steps(main, feed_list=[_feed()], steps=2,
                              fetch_list=[out])
        reports = [json.load(open(f)) for f in glob.glob(str(d) + "/*.json")]
        # 2 iterations x (startup step + main step + window) = 6 reports;
        # each pair of identically-built programs must share ONE key, so
        # the step reports collapse to 2 distinct keys (startup, main)
        # and the window reports to 1
        step_keys = [r["cache_key"] for r in reports if r["kind"] == "step"]
        window_keys = [r["cache_key"] for r in reports
                       if r["kind"] == "window"]
        assert len(step_keys) == 4 and len(set(step_keys)) == 2, step_keys
        assert len(window_keys) == 2 and len(set(window_keys)) == 1
    finally:
        flags.set_flags({"compile_report_dir": ""})


# --------------------------------------------------------------------------
# disk round-trips (same machine, fresh level-1 caches)
# --------------------------------------------------------------------------

def test_fresh_executor_resolves_from_disk_bit_exact():
    main, startup, out = _build(stateless=True)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        cold = exe.run(main, feed=_feed(), fetch_list=[out])
    assert _hits() == 0
    assert glob.glob(flags.get_flag("compile_cache_dir") + "/pcc-*.bin")
    exe2 = fluid.Executor(fluid.CPUPlace())  # fresh level-1 cache
    with fluid.scope_guard(scope):
        warm = exe2.run(main, feed=_feed(), fetch_list=[out])
    assert _hits() == 1
    assert monitor.recent_steps()[-1]["cache"] == "disk"
    assert float(np.asarray(cold[0])) == float(np.asarray(warm[0]))
    load_ms = monitor.recent_steps()[-1]["compile_ms"]
    assert load_ms is not None and load_ms > 0
    assert monitor.histogram("pt_compile_cache_load_seconds").count() == 1


def test_run_steps_window_resolves_from_disk_and_is_steps_keyed():
    """A run_steps window round-trips through disk; a different
    ``steps`` count is a DIFFERENT entry end to end (the executable
    bakes the static step count)."""
    main, startup, out = _build(stateless=True)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        cold = exe.run_steps(main, feed_list=[_feed()], steps=3,
                             fetch_list=[out])
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        warm = exe2.run_steps(main, feed_list=[_feed()], steps=3,
                              fetch_list=[out])
        assert monitor.recent_steps()[-1]["cache"] == "disk"
        assert float(np.asarray(cold[0])) == float(np.asarray(warm[0]))
        h = _hits()
        # same signature, different steps: fresh compile, not a stale
        # disk wrapper silently running 3 baked steps
        exe2.run_steps(main, feed_list=[_feed()], steps=2,
                       fetch_list=[out])
        assert _hits() == h
        assert monitor.recent_steps()[-1]["cache"] == "miss"


def test_trained_state_continues_identically_after_disk_resolve():
    """A disk-resolved train step continues a parameter trajectory
    exactly where a fresh-compiled one would: same scope, fresh
    executor, losses keep decreasing from the committed state."""
    main, startup, out = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        l1 = float(np.asarray(exe.run(main, feed=_feed(),
                                      fetch_list=[out])[0]))
        l2 = float(np.asarray(exe.run(main, feed=_feed(),
                                      fetch_list=[out])[0]))
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        l3 = float(np.asarray(exe2.run(main, feed=_feed(),
                                       fetch_list=[out])[0]))
    assert monitor.recent_steps()[-1]["cache"] == "disk"
    assert l2 < l1 and l3 < l2  # SGD keeps descending through the swap


def test_disk_hit_emits_no_fresh_compile_report(tmp_path):
    d = tmp_path / "reports"
    flags.set_flags({"compile_report_dir": str(d)})
    try:
        main, startup, out = _build(stateless=True)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed=_feed(), fetch_list=[out])
        n_cold = len(glob.glob(str(d) + "/*.json"))
        assert n_cold >= 1
        exe2 = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe2.run(main, feed=_feed(), fetch_list=[out])
        assert monitor.recent_steps()[-1]["cache"] == "disk"
        assert len(glob.glob(str(d) + "/*.json")) == n_cold
    finally:
        flags.set_flags({"compile_report_dir": ""})


# --------------------------------------------------------------------------
# degrade paths: corruption, tampering, torn stores — metered, never fatal
# --------------------------------------------------------------------------

def test_truncated_entry_degrades_to_metered_miss_via_fault_site():
    """The corruption regression, driven through the faults.py site
    machinery: a ccache.load truncate plan tears the published file
    right before the read — the run must recompile (and republish),
    metering one load error, raising nothing."""
    main, startup, out = _build(stateless=True)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        cold = exe.run(main, feed=_feed(), fetch_list=[out])
    assert _errors("load") == 0
    faults.arm("ccache.load:truncate(8)@1")
    try:
        exe2 = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                warm = exe2.run(main, feed=_feed(), fetch_list=[out])
        assert any("recompiling" in str(x.message) for x in w)
    finally:
        faults.disarm()
    assert _errors("load") == 1
    assert monitor.recent_steps()[-1]["cache"] == "miss"
    assert float(np.asarray(cold[0])) == float(np.asarray(warm[0]))
    assert monitor.counter(
        "pt_fault_injected_total").value(labels={"site": "ccache.load"}) == 1
    # the recompile republished an intact entry: next fresh executor hits
    exe3 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe3.run(main, feed=_feed(), fetch_list=[out])
    assert monitor.recent_steps()[-1]["cache"] == "disk"


def test_env_tampered_entry_is_silent_miss_not_error():
    """A header mismatch (another jax/topology/format wrote this name)
    is an expected miss — counted as such, no error, no warning."""
    main, startup, out = _build(stateless=True)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(), fetch_list=[out])
    paths = glob.glob(flags.get_flag("compile_cache_dir") + "/pcc-*.bin")
    assert paths
    for path in paths:  # tamper every entry: the warm run must miss
        payload = pickle.load(open(path, "rb"))
        payload["env"] = ("other-jax",)
        pickle.dump(payload, open(path, "wb"))
    misses0 = monitor.counter("pt_compile_cache_misses_total").value()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe2.run(main, feed=_feed(), fetch_list=[out])
    assert monitor.recent_steps()[-1]["cache"] == "miss"
    assert monitor.counter(
        "pt_compile_cache_misses_total").value() > misses0
    assert _errors("load") == 0


def test_torn_store_leaves_no_published_entry():
    """A crash (raise) at the staged write never publishes a torn file:
    the .tmp straggler is cleaned, the run proceeds on the in-memory
    entry, and the error is metered."""
    main, startup, out = _build(stateless=True)
    scope = fluid.Scope()
    faults.arm("ccache.store:raise@1")
    try:
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            with warnings.catch_warnings(record=True):
                warnings.simplefilter("always")
                exe.run(startup)  # first store attempt crashes
                exe.run(main, feed=_feed(), fetch_list=[out])
    finally:
        faults.disarm()
    d = flags.get_flag("compile_cache_dir")
    assert _errors("store") == 1
    assert glob.glob(d + "/*.tmp.*") == []  # no straggler
    # the second entry (not faulted) still published and resolves
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe2.run(main, feed=_feed(), fetch_list=[out])
    assert monitor.recent_steps()[-1]["cache"] == "disk"


def test_aot_build_traces_under_the_strategy_spmd_context():
    """The AOT compile for the disk tier must trace inside
    spmd_ctx_scope(strategy), exactly like the eager jit's first call:
    collective ops (DGC exchange, MoE all_to_all) read the context at
    TRACE time, and without it they silently lower their non-collective
    fallback — which would then be executed AND persisted."""
    import types

    from paddle_tpu.core import interp

    strategy = types.SimpleNamespace(
        mesh=None, context_axis=None, table_axis="tp", data_axis="dp",
        slice_axis=None, expert_axis=None, pipe_axis=None, pipe_micro=None)
    seen = {}

    class FakeJit:
        def lower(self, *args):
            seen["ctx"] = interp.spmd_ctx()
            raise RuntimeError("stop after recording the trace context")

    spec = compile_cache.Spec(
        path="/nonexistent", digest="d", lower_args=({}, {}, None),
        static_steps=None, program=None, feed_names=(), fetch_names=(),
        strategy=strategy)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        assert compile_cache.aot_build(spec, FakeJit()) is None
    assert seen["ctx"] is not None and seen["ctx"].table_axis == "tp"
    # and the executor's spec carries the CompiledProgram's strategy
    assert interp.spmd_ctx() is None  # scope exited


def test_local_fingerprints_build_no_spec(monkeypatch):
    """Non-canonical (local-) fingerprints never resolve from disk.
    NOTE the before/after flip (ISSUE 14): this test used to also pin
    the blanket multi-host decline (``process_count() > 1`` -> no
    spec, a silent fresh compile); multi-host entries are now keyed by
    the OWNING shard's topology instead — see
    tests/test_elastic_grow.py for the after-contract."""
    main, startup, out = _build(stateless=True)
    monkeypatch.setattr(fluid.framework.Program, "content_digest",
                        lambda self: (_ for _ in ()).throw(TypeError("x")))
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(), fetch_list=[out])
    assert glob.glob(flags.get_flag("compile_cache_dir") + "/pcc-*") == []


# --------------------------------------------------------------------------
# cross-process warm start (THE acceptance flow)
# --------------------------------------------------------------------------

def test_cross_process_warm_start_zero_fresh_compiles(tmp_path):
    """A subprocess compiles and populates the disk cache; a second
    fresh subprocess resolves EVERY entry from disk — zero fresh XLA
    compiles (all outcomes 'disk', miss counter 0) and no new compile
    report."""
    cache_d, report_d = str(tmp_path / "cc"), str(tmp_path / "cr")
    env = {**os.environ, "PYTHONPATH": os.path.dirname(HERE)}

    def launch():
        out = subprocess.run(
            [sys.executable, os.path.join(HERE, "ccache_worker.py"),
             cache_d, report_d],
            capture_output=True, text=True, timeout=600, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = launch()
    assert cold["stats"]["hits"] == 0
    assert cold["stats"]["misses"] >= 3  # startup + step + window
    assert cold["stats"]["errors"] == {"spec": 0, "load": 0, "store": 0}
    n_reports = len(glob.glob(report_d + "/*.json"))
    assert n_reports >= 1

    warm = launch()
    assert warm["stats"]["misses"] == 0, warm
    assert warm["stats"]["hits"] == cold["stats"]["misses"]
    assert set(warm["outcomes"]) == {"disk"}, warm["outcomes"]
    assert warm["exec_misses"] == cold["exec_misses"]  # L1 always misses
    # no fresh compile -> no new compile report
    assert len(glob.glob(report_d + "/*.json")) == n_reports
    assert np.isfinite(warm["loss"]) and np.isfinite(warm["window_loss"])


def test_clearing_flag_releases_the_xla_fallback_tier():
    """Unsetting compile_cache_dir must also release jax's persistent
    compilation cache IF we pointed it at <dir>/xla — otherwise every
    later XLA compile keeps writing into the disabled (possibly deleted
    temp) directory. A user-configured dir is never touched."""
    import jax

    engaged = compile_cache.stats()["xla_fallback"]
    if engaged is None:  # another suite configured jax's cache first
        pytest.skip("xla fallback tier not engaged in this process")
    assert jax.config.jax_compilation_cache_dir == engaged
    flags.set_flags({"compile_cache_dir": ""})
    assert jax.config.jax_compilation_cache_dir is None
    assert compile_cache.stats()["xla_fallback"] is None


# --------------------------------------------------------------------------
# disabled path: the one-boolean-check / zero-allocation contract
# --------------------------------------------------------------------------

def test_disabled_path_allocates_nothing_in_compile_cache():
    flags.set_flags({"compile_cache_dir": "", "telemetry": False})
    assert not compile_cache.active()
    main, startup, out = _build(stateless=True)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # warm L1 + the fingerprint memo
            exe.run(main, feed=_feed(), fetch_list=[out])
        n_runs = 30
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            exe.run(main, feed=_feed(), fetch_list=[out])
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    stats = snap.compare_to(base, "filename")
    grew = sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith("compile_cache.py")
               and s.size_diff > 0)
    assert grew < n_runs * 16, (
        f"disabled Executor.run allocated {grew}B in compile_cache.py "
        f"over {n_runs} runs")


# --------------------------------------------------------------------------
# disk GC (ISSUE 9 satellite): size-capped LRU-by-mtime sweep
# --------------------------------------------------------------------------

def _fake_entry(d, name, nbytes, mtime):
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, name)
    with open(path, "wb") as f:
        f.write(b"\0" * nbytes)
    os.utime(path, (mtime, mtime))
    return path


def _evictions():
    return monitor.counter("pt_compile_cache_evictions_total").value()


def test_gc_sweeps_oldest_entries_to_fit_the_cap():
    import time as _time

    d = flags.get_flag("compile_cache_dir")
    now = _time.time()
    old = _fake_entry(d, "pcc-old.bin", 600, now - 300)
    mid = _fake_entry(d, "pcc-mid.bin", 600, now - 200)
    new = _fake_entry(d, "pcc-new.bin", 600, now - 100)
    # a foreign file and a FRESH .tmp straggler are never GC victims
    other = _fake_entry(d, "notes.txt", 600, now - 900)
    staged = _fake_entry(d, "pcc-x.bin.tmp.123", 600, now - 10)
    assert compile_cache.gc(max_bytes=1300) == 1
    assert not os.path.exists(old)
    assert os.path.exists(mid) and os.path.exists(new)
    assert os.path.exists(other) and os.path.exists(staged)
    assert _evictions() == 1
    # an HOUR-old .tmp straggler is a crash leftover: reaped
    crashed = _fake_entry(d, "pcc-y.bin.tmp.9", 10, now - 7200)
    compile_cache.gc(max_bytes=1300)
    assert not os.path.exists(crashed)
    # the newest entry survives even a cap smaller than itself
    compile_cache.gc(max_bytes=100)
    assert os.path.exists(new)
    assert not os.path.exists(mid)
    assert _evictions() == 2


def test_gc_concurrent_removal_counts_freed_space(monkeypatch):
    """Two processes sharing the dir both sweep: an entry a concurrent
    GC already reclaimed (os.remove -> FileNotFoundError) is not OUR
    eviction, but its space IS freed — without the subtraction this
    process would keep looping and over-evict still-hot entries that
    actually fit the budget."""
    import time as _time

    d = flags.get_flag("compile_cache_dir")
    now = _time.time()
    old = _fake_entry(d, "pcc-old.bin", 600, now - 300)
    mid = _fake_entry(d, "pcc-mid.bin", 600, now - 200)
    new = _fake_entry(d, "pcc-new.bin", 600, now - 100)
    real_remove = os.remove

    def _raced(path):
        # the concurrent sweeper wins the race for the oldest entry
        if path == old:
            real_remove(path)
            raise FileNotFoundError(path)
        real_remove(path)

    monkeypatch.setattr(os, "remove", _raced)
    # cap fits two entries: only `old` must go, and it went to the
    # OTHER process — zero evictions of ours, survivors untouched
    assert compile_cache.gc(max_bytes=1300) == 0
    assert os.path.exists(mid) and os.path.exists(new)
    assert _evictions() == 0


def test_gc_disabled_without_cap_and_loads_refresh_mtime():
    """cap 0 = unbounded (no sweep); a disk HIT refreshes the entry's
    mtime so eviction order is least-recently-USED, not least-recently-
    written."""
    import time as _time

    d = flags.get_flag("compile_cache_dir")
    _fake_entry(d, "pcc-a.bin", 4096, _time.time() - 500)
    assert compile_cache.gc() == 0  # flag default: unbounded
    assert os.path.exists(os.path.join(d, "pcc-a.bin"))

    # real entry, stored then re-resolved by a fresh executor: the hit
    # must bump its mtime past the fake older entry's
    main, startup, out = _build(stateless=True)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(), fetch_list=[out])
    entries = [p for p in glob.glob(d + "/pcc-*.bin")
               if "pcc-a.bin" not in p]  # startup + main entries
    assert entries
    past = _time.time() - 400
    for p in entries:
        os.utime(p, (past, past))
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe2.run(main, feed=_feed(), fetch_list=[out])
    assert monitor.recent_steps()[-1]["cache"] == "disk"
    # exactly the re-resolved entry (main's) got its mtime refreshed
    refreshed = [p for p in entries if os.stat(p).st_mtime > past + 1]
    assert len(refreshed) == 1


def test_store_sweeps_via_the_flag_cap():
    """A store with compile_cache_max_bytes set runs the sweep
    inline: pre-seeded cold entries beyond the cap are evicted by the
    publish itself, and the metric accounts for them."""
    import time as _time

    d = flags.get_flag("compile_cache_dir")
    for i in range(3):
        _fake_entry(d, f"pcc-cold{i}.bin", 50_000,
                    _time.time() - 1000 - i)
    flags.set_flags({"compile_cache_max_bytes": 120_000})
    try:
        main, startup, out = _build(stateless=True)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed=_feed(), fetch_list=[out])
    finally:
        flags.set_flags({"compile_cache_max_bytes": 0})
    # the published entries fit only after evicting cold ones
    total = sum(os.path.getsize(p) for p in glob.glob(d + "/pcc-*.bin"))
    assert total <= 120_000
    assert _evictions() >= 1
    # the just-published (newest) entries survived
    assert glob.glob(d + "/pcc-*.bin")
