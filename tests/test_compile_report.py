"""Compile & memory observability (PR 2 tentpole, piece 1): per-program
compile reports (schema, file emission, gauges, estimate fallback),
the estimate_memory pre-flight + budget warning, and the
debugger.pprint_program annotation. CPU-only jax; non-slow — the graded
smoke for the compile-report plane (also referenced from
.claude/skills/verify/SKILL.md)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import debugger, flags, layers, monitor
from paddle_tpu.core import lowering


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    defaults = {"telemetry": False, "step_log_path": "",
                "metrics_dump_path": "", "compile_report_dir": "",
                "device_memory_budget_bytes": 0}
    flags.set_flags(defaults)
    yield
    monitor.reset()
    flags.set_flags(defaults)


def _small_train_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        logits = layers.fc(x, 10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feed(rng, batch=8):
    return {"x": rng.rand(batch, 16).astype(np.float32),
            "label": rng.randint(0, 10, (batch, 1)).astype(np.int64)}


# --------------------------------------------------------------------------
# the acceptance smoke: one compile -> one schema-valid report on disk
# --------------------------------------------------------------------------

def test_compile_emits_schema_valid_report(tmp_path):
    flags.set_flags({"telemetry": True,
                     "compile_report_dir": str(tmp_path)})
    main, startup, loss = _small_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(rng), fetch_list=[loss])
        exe.run(main, feed=_feed(rng), fetch_list=[loss])  # cache hit

    # one report per fresh compile: startup + main = 2 files, no third
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 2, files
    main_rep = None
    for f in files:
        rep = json.loads((tmp_path / f).read_text())
        monitor.validate_compile_report(rep)  # schema version + types
        assert rep["v"] == monitor.COMPILE_REPORT_SCHEMA_VERSION
        assert rep["backend"] == "cpu"
        # flops/peak present, or explicitly null with the estimate marker
        if rep["source"] == "xla":
            assert rep["flops"] is not None or rep["peak_bytes"] is not None
        else:
            assert rep["source"] == "estimate"
            assert rep["flops"] is None and rep["peak_bytes"] is None
        assert rep["n_ops"] == sum(rep["op_histogram"].values())
        if rep["program_uid"] == main._uid:
            main_rep = rep
    assert main_rep is not None
    # the training program lowers fc + softmax_xent + mean + sgd (+grads)
    assert main_rep["n_ops"] > 4
    assert main_rep["kind"] == "step"
    assert main_rep["strategy"] is None

    # in-memory mirror (the /compile endpoint's source) + gauges
    reports = monitor.compile_reports()
    assert f"program{main._uid}" in reports
    if main_rep["source"] == "xla":
        assert monitor.gauge("pt_compile_flops").value(
            labels={"program": f"program{main._uid}"}) == main_rep["flops"]
        assert monitor.gauge("pt_compile_peak_bytes").value(
            labels={"program": f"program{main._uid}"}
        ) == main_rep["peak_bytes"]
    assert monitor.counter("pt_compile_reports_total").value() == 2


def test_cpu_backend_reports_real_xla_numbers(tmp_path):
    """On CPU-only jax 0.4.37 cost_analysis/memory_analysis both work —
    this pins the happy path so a silent regression to 'estimate' (an
    API drift swallowed by the guards) fails loudly on the platform the
    suite actually runs."""
    flags.set_flags({"telemetry": True,
                     "compile_report_dir": str(tmp_path)})
    main, startup, loss = _small_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(np.random.RandomState(0)),
                fetch_list=[loss])
    rep = monitor.compile_reports()[f"program{main._uid}"]
    assert rep["source"] == "xla"
    assert rep["flops"] > 0
    assert rep["bytes_accessed"] > 0
    assert rep["peak_bytes"] > 0
    assert rep["argument_bytes"] > 0
    assert rep["analysis_ms"] > 0


def test_run_steps_window_emits_window_report(tmp_path):
    flags.set_flags({"telemetry": True,
                     "compile_report_dir": str(tmp_path)})
    main, startup, loss = _small_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_steps(main, feed_list=[_feed(rng), _feed(rng)], steps=4,
                      fetch_list=[loss])
    kinds = {r["kind"] for r in monitor.compile_reports().values()}
    assert "window" in kinds
    win = [r for r in monitor.compile_reports().values()
           if r["kind"] == "window"][0]
    monitor.validate_compile_report(win)


def test_estimate_fallback_marks_source(monkeypatch, tmp_path):
    """When the AOT analysis path is unavailable (older jax, exotic
    backend), the report must still emit — cost fields null, source
    'estimate', op histogram intact."""
    flags.set_flags({"telemetry": True,
                     "compile_report_dir": str(tmp_path)})
    main, startup, loss = _small_train_program()

    class _NoLower:
        def __getattr__(self, name):
            raise AttributeError(name)

    feed = _feed(np.random.RandomState(0))
    lowered = lowering.lower_block(
        main, 0, sorted(feed), [loss.name])
    rep = lowering.build_compile_report(
        _NoLower(), lowered, (), program=main, compile_ms=1.0,
        cache_key=("k",))
    monitor.validate_compile_report(rep)
    assert rep["source"] == "estimate"
    assert rep["flops"] is None and rep["peak_bytes"] is None
    assert rep["analysis_ms"] is None
    assert rep["op_histogram"] and rep["n_ops"] > 0


def test_no_reports_without_dir_or_server():
    """compile_reports_active gates the extra AOT compile: telemetry on
    alone (no dir, no live endpoint) must not generate reports."""
    flags.set_flags({"telemetry": True})
    assert not monitor.compile_reports_active()
    main, startup, loss = _small_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(np.random.RandomState(0)),
                fetch_list=[loss])
    assert monitor.compile_reports() == {}


def test_validate_compile_report_rejects_bad():
    good = {f: None for f in monitor.COMPILE_REPORT_FIELDS}
    good.update({"v": monitor.COMPILE_REPORT_SCHEMA_VERSION, "ts": 0.0,
                 "program": "program1", "program_uid": 1, "cache_key": "k",
                 "kind": "step", "backend": "cpu", "source": "estimate",
                 "n_ops": 0, "op_histogram": {}})
    monitor.validate_compile_report(good)
    with pytest.raises(ValueError, match="missing field"):
        monitor.validate_compile_report(
            {k: v for k, v in good.items() if k != "flops"})
    with pytest.raises(ValueError, match="unknown fields"):
        monitor.validate_compile_report(dict(good, bogus=1))
    with pytest.raises(ValueError, match="schema"):
        monitor.validate_compile_report(dict(good, v=999))
    with pytest.raises(ValueError, match="source"):
        monitor.validate_compile_report(dict(good, source="psychic"))


# --------------------------------------------------------------------------
# pre-flight memory estimate + budget warning
# --------------------------------------------------------------------------

def test_estimate_memory_accounts_params_feeds_activations():
    main, startup, loss = _small_train_program()
    est = monitor.estimate_memory(
        main, {"x": (8, 16), "label": (8, 1)})
    # fc weight [16, 10] f32 + bias [10] f32 (+ SGD has no slots)
    assert est["param_bytes"] >= (16 * 10 + 10) * 4
    assert est["feed_bytes"] == 8 * 16 * 4 + 8 * 1 * 8
    assert est["activation_bytes"] > 0
    assert est["total_bytes"] == (est["param_bytes"] + est["feed_bytes"]
                                  + est["activation_bytes"])
    assert est["fits"] is None  # no budget configured
    # explicit budget: verdict flips around the total
    over = monitor.estimate_memory(
        main, {"x": (8, 16)}, budget_bytes=est["total_bytes"] * 2)
    assert over["fits"] is True
    under = monitor.estimate_memory(main, {"x": (8, 16)}, budget_bytes=1)
    assert under["fits"] is False


def test_budget_preflight_warns_before_compile():
    flags.set_flags({"telemetry": True,
                     "device_memory_budget_bytes": 1})  # everything OOMs
    main, startup, loss = _small_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        with pytest.warns(RuntimeWarning, match="memory estimate"):
            exe.run(startup)
        with pytest.warns(RuntimeWarning, match="likely to OOM"):
            exe.run(main, feed=_feed(np.random.RandomState(0)),
                    fetch_list=[loss])


# --------------------------------------------------------------------------
# debugger annotation
# --------------------------------------------------------------------------

def test_pprint_program_carries_compile_annotation(tmp_path):
    flags.set_flags({"telemetry": True,
                     "compile_report_dir": str(tmp_path)})
    main, startup, loss = _small_train_program()
    # before any compile: listing renders without the annotation
    assert "compile report" not in debugger.pprint_program(main)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(np.random.RandomState(0)),
                fetch_list=[loss])
    text = debugger.pprint_program(main)
    assert "compile report" in text
    assert "flops=" in text and "peak=" in text
    # opt-out restores the plain listing
    assert "compile report" not in debugger.pprint_program(
        main, with_compile_report=False)
