"""Control-flow ops + layers: while / cond / scan / StaticRNN / while_loop.

Mirrors the reference's control-flow coverage
(reference: tests/unittests/test_while_op.py, test_cond.py,
test_recurrent_op.py) on the XLA lowering: the sub-block is traced into
lax.while_loop / lax.cond / lax.scan instead of being interpreted
per-iteration.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.layers import control_flow


def _run(main, feed, fetch_list, startup=None):
    exe = fluid.Executor(fluid.CPUPlace())
    if startup is not None:
        exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch_list)


def test_while_counts_to_ten():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant(shape=[1], dtype="int32", value=0)
        limit = layers.fill_constant(shape=[1], dtype="int32", value=10)
        total = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with w.block():
            layers.assign(total + 2.0, output=total)
            layers.increment(i, value=1.0, in_place=True)
            layers.less_than(i, limit, cond=cond)
    (out, iv) = _run(main, {}, [total, i])
    np.testing.assert_allclose(out, [20.0], rtol=1e-6)
    assert int(iv[0]) == 10


def test_while_body_must_update_cond():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant(shape=[1], dtype="int32", value=0)
        limit = layers.fill_constant(shape=[1], dtype="int32", value=10)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with pytest.raises(ValueError, match="condition"):
            with w.block():
                layers.increment(i, value=1.0, in_place=True)


def test_functional_while_loop():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant(shape=[1], dtype="int32", value=0)
        x = layers.fill_constant(shape=[4], dtype="float32", value=1.0)

        def cond_fn(i, x):
            n = layers.fill_constant(shape=[1], dtype="int32", value=5)
            return layers.less_than(i, n)

        def body_fn(i, x):
            return [i + 1, x * 2.0]

        i, x = layers.while_loop(cond_fn, body_fn, [i, x])
    (xv,) = _run(main, {}, [x])
    np.testing.assert_allclose(xv, np.full(4, 32.0), rtol=1e-6)


def test_cond_selects_branch_and_differentiates():
    """lax.cond branch selection + gradient through the taken branch."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(3,), dtype="float32", stop_gradient=False
        )
        flag = main.global_block().create_var(
            name="flag", shape=(1,), dtype="bool"
        )
        out = layers.cond(
            flag,
            lambda: layers.scale(x, scale=3.0),
            lambda: layers.scale(x, scale=7.0),
        )
        loss = layers.reduce_sum(out)
        grads = fluid.gradients(loss, x)
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    o_t, g_t = _run(
        main, {"x": xv, "flag": np.array([True])}, [out, grads[0]]
    )
    np.testing.assert_allclose(o_t, xv * 3.0, rtol=1e-6)
    np.testing.assert_allclose(g_t, np.full(3, 3.0), rtol=1e-6)
    o_f, g_f = _run(
        main, {"x": xv, "flag": np.array([False])}, [out, grads[0]]
    )
    np.testing.assert_allclose(o_f, xv * 7.0, rtol=1e-6)
    np.testing.assert_allclose(g_f, np.full(3, 7.0), rtol=1e-6)


def test_cond_branch_arity_mismatch_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        flag = main.global_block().create_var(
            name="flag", shape=(1,), dtype="bool"
        )
        x = layers.fill_constant(shape=[2], dtype="float32", value=1.0)
        with pytest.raises(ValueError, match="arit"):
            layers.cond(
                flag,
                lambda: [x, x],
                lambda: x,
            )


def test_static_rnn_matches_numpy_recurrence():
    """h_t = tanh(x_t + h_{t-1}) — forward parity with a numpy loop."""
    b, t, d = 2, 5, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(b, t, d), dtype="float32", stop_gradient=False
        )
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(shape=(b, d), init_value=0.0)
            h = layers.tanh(x_t + h_prev)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
    xv = np.random.RandomState(0).randn(b, t, d).astype(np.float32)
    (ov,) = _run(main, {"x": xv}, [out])
    h = np.zeros((b, d), np.float32)
    expect = np.zeros((b, t, d), np.float32)
    for i in range(t):
        h = np.tanh(xv[:, i] + h)
        expect[:, i] = h
    np.testing.assert_allclose(ov, expect, rtol=1e-5, atol=1e-6)


def test_static_rnn_gradients_flow_to_captured_params():
    """Backprop through scan reaches weights read from the enclosing scope
    (the reference needs RecurrentGradOp's saved per-step scopes for this,
    reference: operators/recurrent_op.cc:250; here XLA transposes the scan).
    """
    b, t, d = 2, 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(b, t, d), dtype="float32", stop_gradient=False
        )
        w = layers.create_parameter([d, d], "float32", name="rnn_w")
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(shape=(b, d), init_value=0.0)
            h = layers.tanh(layers.matmul(x_t + h_prev, w))
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
        loss = layers.reduce_sum(out)
        fluid.append_backward(loss)
    assert main.global_block().has_var("rnn_w@GRAD")

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(1).randn(b, t, d).astype(np.float32)
    gw, lv = exe.run(
        main, feed={"x": xv}, fetch_list=["rnn_w@GRAD", loss]
    )
    assert np.abs(gw).sum() > 0  # gradient actually reaches the weight

    # Numeric check of d(loss)/dW via central differences on one entry.
    from paddle_tpu.executor import global_scope

    wv = np.asarray(global_scope().find_var("rnn_w"))
    eps = 1e-3

    def loss_at(wmod):
        p = fluid.Program()
        with fluid.program_guard(p, fluid.Program()):
            x2 = p.global_block().create_var(
                name="x", shape=(b, t, d), dtype="float32"
            )
            w2 = p.global_block().create_var(
                name="w2", shape=(d, d), dtype="float32"
            )
            rnn = layers.StaticRNN()
            with rnn.step():
                x_t = rnn.step_input(x2)
                h_prev = rnn.memory(shape=(b, d), init_value=0.0)
                h = layers.tanh(layers.matmul(x_t + h_prev, w2))
                rnn.update_memory(h_prev, h)
                rnn.step_output(h)
            l2 = layers.reduce_sum(rnn())
        e2 = fluid.Executor(fluid.CPUPlace())
        (lv2,) = e2.run(p, feed={"x": xv, "w2": wmod}, fetch_list=[l2])
        return float(lv2)

    wp = wv.copy()
    wp[0, 0] += eps
    wm = wv.copy()
    wm[0, 0] -= eps
    numeric = (loss_at(wp) - loss_at(wm)) / (2 * eps)
    np.testing.assert_allclose(gw[0, 0], numeric, rtol=2e-2, atol=1e-3)


def test_scan_trains_with_optimizer():
    """An RNN regression trained via scan: loss must decrease."""
    b, t, d = 4, 6, 8
    rs = np.random.RandomState(2)
    xv = rs.randn(b, t, d).astype(np.float32)
    yv = np.sum(xv, axis=(1, 2), keepdims=False).reshape(b, 1) * 0.1

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(b, t, d), dtype="float32", stop_gradient=True
        )
        y = main.global_block().create_var(
            name="y", shape=(b, 1), dtype="float32", stop_gradient=True
        )
        w = layers.create_parameter([d, d], "float32", name="srnn_w")
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(shape=(b, d), init_value=0.0)
            h = layers.tanh(layers.matmul(x_t, w) + h_prev)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
        last = layers.reduce_mean(out, dim=1)  # [b, d]
        pred = layers.fc(last, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, losses


def test_dynamic_array_write_read():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.fill_constant(shape=[3], dtype="float32", value=5.0)
        arr = layers.array_fill(4, x, value=0.0)
        idx = layers.fill_constant(shape=[1], dtype="int32", value=2)
        arr2 = layers.array_write_step(arr, idx, x)
    (av,) = _run(main, {}, [arr2])
    expect = np.zeros((4, 3), np.float32)
    expect[2] = 5.0
    np.testing.assert_allclose(av, expect)


def test_switch_lr_warmup():
    """Switch used the reference way: piecewise value by global step."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        step = layers.data("step", shape=[1], dtype="float32")
        lr = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        b1 = layers.fill_constant(shape=[1], dtype="float32", value=10.0)
        with layers.Switch() as sw:
            with sw.case(layers.less_than(step, b1)):
                layers.assign(
                    layers.fill_constant(
                        shape=[1], dtype="float32", value=0.1
                    ),
                    output=lr,
                )
            with sw.default():
                layers.assign(
                    layers.fill_constant(
                        shape=[1], dtype="float32", value=0.01
                    ),
                    output=lr,
                )
    (v,) = _run(main, {"step": np.array([3.0], np.float32)}, [lr])
    np.testing.assert_allclose(v, [0.1])
    (v,) = _run(main, {"step": np.array([30.0], np.float32)}, [lr])
    np.testing.assert_allclose(v, [0.01])


def test_while_backprop_raises_loudly():
    """Gradient demand on an unbounded `while` output must be a loud
    error pointing at max_trip_count / scan, not a silently-dropped
    gradient (VERDICT r4 weak #6)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        acc = layers.fc(x, 4, bias_attr=False)
        i = layers.fill_constant([1], "int64", 0)
        lim = layers.fill_constant([1], "int64", 3)
        cond = layers.less_than(i, lim)
        with control_flow.While(cond).block():
            acc2 = layers.scale(acc, scale=2.0)
            layers.assign(acc2, output=acc)
            layers.increment(i)
            layers.less_than(i, lim, cond=cond)
        loss = layers.mean(acc)
        with pytest.raises(RuntimeError, match="max_trip_count"):
            fluid.optimizer.SGD(0.1).minimize(loss)


def test_bounded_while_trains_through_loop():
    """While(cond, max_trip_count=N) is differentiable: gradients flow
    to weights read inside the loop, and the computed value matches the
    unbounded While exactly (including a data-dependent trip count
    shorter than the bound)."""
    def build(bounded):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            acc = layers.fc(x, 4, bias_attr=False,
                            param_attr=fluid.ParamAttr(
                                name="w",
                                initializer=fluid.initializer
                                .ConstantInitializer(0.5)))
            i = layers.fill_constant([1], "int64", 0)
            lim = layers.fill_constant([1], "int64", 3)
            cond = layers.less_than(i, lim)
            w = control_flow.While(
                cond, max_trip_count=5 if bounded else None)
            with w.block():
                acc2 = layers.scale(acc, scale=2.0)
                layers.assign(acc2, output=acc)
                layers.increment(i)
                layers.less_than(i, lim, cond=cond)
            loss = layers.mean(acc)
            if bounded:
                fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    fd = {"x": np.full((2, 4), 1.0, np.float32)}

    main_u, startup_u, loss_u = build(bounded=False)
    exe = fluid.Executor(fluid.CPUPlace())
    scope_u = fluid.Scope()
    with fluid.scope_guard(scope_u):
        exe.run(startup_u)
        (ref,) = exe.run(main_u, feed=fd, fetch_list=[loss_u])

    main_b, startup_b, loss_b = build(bounded=True)
    scope_b = fluid.Scope()
    with fluid.scope_guard(scope_b):
        exe.run(startup_b)
        (got,) = exe.run(main_b, feed=fd, fetch_list=[loss_b],
                         )
        # value parity: 3 live iterations out of the 5-step bound
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6)
        # gradient flowed: w was updated by the SGD step
        w_after = np.asarray(scope_b.find_var("w"))
        assert not np.allclose(w_after, 0.5), "no gradient reached w"
        # and training moves the loss
        (got2,) = exe.run(main_b, feed=fd, fetch_list=[loss_b])
        assert float(got2) != float(got)
