"""CRF / CTC / edit-distance op tests against brute-force references
(reference harness pattern: tests/unittests/test_linear_chain_crf_op.py,
test_warpctc_op.py compare to python reimplementations)."""

import itertools

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from tests.op_test import OpHarness

RS = np.random.RandomState


def _crf_brute(em, trans, lengths):
    """Exact log Z and gold scorer by path enumeration."""
    start, end, pair = trans[0], trans[1], trans[2:]
    b, t, c = em.shape

    def path_score(row, tags):
        n = lengths[row]
        s = start[tags[0]] + end[tags[n - 1]]
        for i in range(n):
            s += em[row, i, tags[i]]
        for i in range(n - 1):
            s += pair[tags[i], tags[i + 1]]
        return s

    logz = np.zeros(b)
    for row in range(b):
        scores = [
            path_score(row, tags)
            for tags in itertools.product(range(c), repeat=lengths[row])
        ]
        m = np.max(scores)
        logz[row] = m + np.log(np.sum(np.exp(np.asarray(scores) - m)))
    return path_score, logz


def test_linear_chain_crf_matches_enumeration():
    b, t, c = 3, 4, 3
    em = RS(0).randn(b, t, c).astype(np.float64)
    trans = RS(1).randn(c + 2, c).astype(np.float64) * 0.5
    label = RS(2).randint(0, c, (b, t)).astype(np.int64)
    lengths = np.array([4, 3, 2], np.int64)

    path_score, logz = _crf_brute(em, trans, lengths)
    expected = np.array([
        logz[row] - path_score(row, list(label[row])) for row in range(b)
    ])[:, None]

    h = OpHarness(
        "linear_chain_crf",
        {"Emission": em, "Transition": trans, "Label": label,
         "Length": lengths},
        out_slots=("LogLikelihood",),
    )
    h.check_output({"LogLikelihood": expected}, atol=1e-6)
    h.check_grad(["emission_0", "transition_0"])


def test_crf_decoding_matches_enumeration():
    b, t, c = 3, 4, 3
    em = RS(3).randn(b, t, c)
    trans = RS(4).randn(c + 2, c) * 0.5
    lengths = np.array([4, 3, 2], np.int64)
    path_score, _ = _crf_brute(em, trans, lengths)

    expected = np.zeros((b, t), np.int64)
    for row in range(b):
        best = max(
            itertools.product(range(c), repeat=lengths[row]),
            key=lambda tags: path_score(row, tags),
        )
        expected[row, : lengths[row]] = best

    h = OpHarness(
        "crf_decoding",
        {"Emission": em, "Transition": trans, "Length": lengths},
        out_slots=("ViterbiPath",),
    )
    h.check_output({"ViterbiPath": expected})


def _ctc_brute(logp, label, blank):
    """Sum of p(path) over all alignments collapsing to `label`."""
    t, c = logp.shape

    def collapse(path):
        out = []
        prev = None
        for p in path:
            if p != prev and p != blank:
                out.append(p)
            prev = p
        return tuple(out)

    total = 0.0
    for path in itertools.product(range(c), repeat=t):
        if collapse(path) == tuple(label):
            total += np.exp(sum(logp[i, p] for i, p in enumerate(path)))
    return -np.log(total)


def test_warpctc_matches_enumeration():
    b, t, c, l = 2, 4, 3, 2
    logits = RS(5).randn(b, t, c).astype(np.float64)
    label = np.array([[1, 2], [2, 2]], np.int64)
    logp = logits - np.log(
        np.exp(logits).sum(-1, keepdims=True)
    )
    expected = np.array([
        _ctc_brute(logp[i], label[i], blank=0) for i in range(b)
    ])[:, None]

    h = OpHarness(
        "warpctc",
        {"Logits": logits, "Label": label},
        attrs={"blank": 0},
        out_slots=("Loss",),
    )
    h.check_output({"Loss": expected}, atol=1e-6)
    h.check_grad(["logits_0"], delta=1e-4)


def test_warpctc_variable_lengths():
    b, t, c = 2, 5, 4
    logits = RS(6).randn(b, t, c).astype(np.float64)
    label = np.array([[1, 3, 0], [2, 0, 0]], np.int64)
    logit_len = np.array([5, 3], np.int64)
    label_len = np.array([2, 1], np.int64)
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    expected = np.array([
        _ctc_brute(logp[0][:5], [1, 3], blank=0),
        _ctc_brute(logp[1][:3], [2], blank=0),
    ])[:, None]
    h = OpHarness(
        "warpctc",
        {"Logits": logits, "Label": label, "LogitsLength": logit_len,
         "LabelLength": label_len},
        attrs={"blank": 0},
        out_slots=("Loss",),
    )
    h.check_output({"Loss": expected}, atol=1e-6)


def test_edit_distance():
    import difflib  # noqa: F401  (just to note: we use a manual DP ref)

    def lev(a, b):
        dp = np.zeros((len(a) + 1, len(b) + 1))
        dp[:, 0] = np.arange(len(a) + 1)
        dp[0, :] = np.arange(len(b) + 1)
        for i in range(1, len(a) + 1):
            for j in range(1, len(b) + 1):
                dp[i, j] = min(
                    dp[i - 1, j] + 1, dp[i, j - 1] + 1,
                    dp[i - 1, j - 1] + (a[i - 1] != b[j - 1]),
                )
        return dp[len(a), len(b)]

    hyp = np.array([[1, 2, 3, 4], [5, 5, 0, 0]], np.int64)
    ref = np.array([[1, 3, 4], [5, 6, 7]], np.int64)
    hlen = np.array([4, 2], np.int64)
    rlen = np.array([3, 3], np.int64)
    expected = np.array([
        lev([1, 2, 3, 4], [1, 3, 4]), lev([5, 5], [5, 6, 7])
    ])[:, None]
    h = OpHarness(
        "edit_distance",
        {"Hyps": hyp, "Refs": ref, "HypsLength": hlen, "RefsLength": rlen},
        out_slots=("Out",),
    )
    h.check_output({"Out": expected})


def test_crf_layer_trains():
    """linear_chain_crf through the layers API end to end: NLL decreases
    and crf_decoding recovers structure."""
    b, t, c = 8, 6, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feats = layers.data("feats", shape=[t, 8], dtype="float32")
        label = layers.data("label", shape=[t], dtype="int64")
        length = layers.data("length", shape=[], dtype="int64")
        em = layers.fc(feats, c, num_flatten_dims=2,
                       param_attr=fluid.ParamAttr(name="crf_em.w"))
        ll = layers.linear_chain_crf(
            em, label, length=length,
            param_attr=fluid.ParamAttr(name="crfw"))
        loss = layers.mean(ll)
        decoded = layers.crf_decoding(
            em, length=length, param_attr=fluid.ParamAttr(name="crfw"))
        fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = RS(0)
    feats_np = rng.randn(b, t, 8).astype(np.float32)
    lab_np = rng.randint(0, c, (b, t)).astype(np.int64)
    len_np = np.full((b,), t, np.int64)
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(30):
            l, d = exe.run(
                main,
                feed={"feats": feats_np, "label": lab_np, "length": len_np},
                fetch_list=[loss, decoded],
            )
            losses.append(float(l))
    assert losses[-1] < losses[0]
    assert d.shape == (b, t)


def test_warpctc_empty_label_row():
    """LabelLength == 0 (all-blank target) must not double-count the
    single alpha cell (code-review finding, round 2)."""
    t, c = 3, 3
    logits = RS(7).randn(1, t, c).astype(np.float64)
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    expected = np.array([[-logp[0, :, 0].sum()]])  # all-blank path only
    h = OpHarness(
        "warpctc",
        {"Logits": logits, "Label": np.zeros((1, 2), np.int64),
         "LabelLength": np.array([0], np.int64)},
        attrs={"blank": 0},
        out_slots=("Loss",),
    )
    h.check_output({"Loss": expected}, atol=1e-6)


def test_crf_decoding_label_gives_correctness_mask():
    """Reference semantics: with a label input, the layer returns per
    position 1/0 agreement flags, not tag ids."""
    b, t, c = 2, 3, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        em = layers.data("em", shape=[t, c], dtype="float32")
        lab = layers.data("lab", shape=[t], dtype="int64")
        path = layers.crf_decoding(
            em, param_attr=fluid.ParamAttr(name="crfw2"))
        mask = layers.crf_decoding(
            em, param_attr=fluid.ParamAttr(name="crfw2"), label=lab)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    em_np = RS(8).randn(b, t, c).astype(np.float32)
    lab_np = RS(9).randint(0, c, (b, t)).astype(np.int64)
    p, m = exe.run(main, feed={"em": em_np, "lab": lab_np},
                   fetch_list=[path, mask])
    np.testing.assert_array_equal(m, (p == lab_np).astype(np.int64))
