"""Dataset API + install_check tests (reference: fluid/dataset.py,
fluid/install_check.py)."""

import numpy as np
import pytest

from paddle_tpu import install_check
from paddle_tpu.dataset_api import (
    DatasetFactory,
    InMemoryDataset,
    QueueDataset,
)


def _write_files(tmp_path, n_files=3, rows=8):
    paths = []
    k = 0
    for i in range(n_files):
        p = tmp_path / f"part-{i}.txt"
        with open(p, "w") as f:
            for _ in range(rows):
                f.write(f"{k} {k % 5}\n")
                k += 1
        paths.append(str(p))
    return paths, k


def _parse(line):
    a, b = line.split()
    return np.array([float(a)], np.float32), np.array([int(b)], np.int64)


def test_factory_and_queue_dataset(tmp_path):
    paths, total = _write_files(tmp_path)
    ds = DatasetFactory().create_dataset("QueueDataset")
    assert isinstance(ds, QueueDataset)
    ds.set_filelist(paths)
    ds.set_batch_size(4)
    ds.set_thread(2)
    ds.set_use_var(["x", "y"])
    ds.set_parse_fn(_parse)
    seen = []
    for batch in ds.batch_reader()():
        assert set(batch) == {"x", "y"}
        assert batch["x"].dtype == np.float32
        seen.extend(batch["x"][:, 0].tolist())
    assert sorted(int(v) for v in seen) == list(range(total))


def test_in_memory_dataset_shuffles(tmp_path):
    paths, total = _write_files(tmp_path)
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    assert isinstance(ds, InMemoryDataset)
    ds.set_filelist(paths)
    ds.set_batch_size(total)
    ds.set_use_var(["x", "y"])
    ds.set_parse_fn(_parse)
    ds.load_into_memory()
    before = next(iter(ds.batch_reader()()))["x"][:, 0]
    ds.set_shuffle_seed(7)
    ds.local_shuffle()
    after = next(iter(ds.batch_reader()()))["x"][:, 0]
    assert sorted(before) == sorted(after)
    assert not np.array_equal(before, after)
    # global shuffle without a fleet degrades to local shuffle
    ds.global_shuffle()
    again = next(iter(ds.batch_reader()()))["x"][:, 0]
    assert sorted(again) == sorted(before)
    ds.release_memory()


def test_dataset_errors(tmp_path):
    ds = InMemoryDataset()
    with pytest.raises(RuntimeError, match="set_parse_fn"):
        list(ds.batch_reader()())
    with pytest.raises(RuntimeError, match="load_into_memory"):
        ds.local_shuffle()
    with pytest.raises(ValueError, match="unknown dataset"):
        DatasetFactory().create_dataset("nope")


def test_install_check_runs():
    assert install_check.run_check(verbose=False) is True


def test_train_from_dataset(tmp_path):
    """Executor.train_from_dataset drives the Dataset through the
    compiled step (reference: executor.py:846)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    paths, total = _write_files(tmp_path, n_files=2, rows=16)
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist(paths)
    ds.set_batch_size(8)
    ds.set_use_var(["x", "y"])
    ds.set_parse_fn(_parse)
    ds.load_into_memory()
    ds.local_shuffle()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], dtype="float32")
        y = layers.data("y", shape=[1], dtype="int64")
        logits = layers.fc(x, 5)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        wname = next(n for n in scope.var_names() if ".w_0" in n)
        w0 = np.asarray(scope.find_var(wname)).copy()
        steps = exe.train_from_dataset(main, ds, fetch_list=[loss],
                                       debug=True, print_period=2)
        w1 = np.asarray(scope.find_var(wname))
    assert steps == total // 8
    assert not np.allclose(w0, w1)     # the optimizer actually stepped
