"""Beam-search decoding tests (reference: operators/beam_search_op.cc and
the while-loop NMT infer program in tests/book/test_machine_translation.py).

Checks the whole decode graph (encoder once + XLA while loop over
decoder + beam_search_step op) for:
- greedy parity: beam_size=1 equals a step-by-step argmax decode driven
  through the *training* program's logits,
- score consistency: the returned beam score equals the teacher-forced
  sum of log-probs of the returned sequence,
- beam ordering and EOS semantics.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import transformer

BOS, EOS = 0, 1


def tiny_cfg():
    return transformer.TransformerConfig(
        src_vocab_size=37,
        trg_vocab_size=41,
        max_length=64,
        d_model=16,
        d_inner=32,
        n_head=2,
        n_layer=1,
        dropout=0.0,
        label_smooth_eps=0.0,
    )


@pytest.fixture(scope="module")
def trained():
    """Startup-initialized tiny transformer + its programs and scope."""
    cfg = tiny_cfg()
    scope = fluid.Scope()
    train_main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(train_main, startup):
        model = transformer.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return cfg, scope, exe, train_main, model


def _decode(trained, beam_size, src, src_pad, max_len=8):
    cfg, scope, exe, _, _ = trained
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        dec = transformer.build_decode(
            cfg, beam_size=beam_size, max_len=max_len,
            src_len=src.shape[1], bos_id=BOS, end_id=EOS,
        )
    with fluid.scope_guard(scope):
        # startup would re-init shared params; only run it for vars the
        # training startup did not create (none here), so skip it.
        ids, scores = exe.run(
            prog,
            feed={"src_ids": src, "src_pad_mask": src_pad},
            fetch_list=[dec["ids"], dec["scores"]],
        )
    return ids, scores


def _teacher_logp(trained, src, src_pad, seq):
    """Sum of log-probs of `seq` (one row, starts with BOS) under the
    training program's logits, stopping at (and including) first EOS."""
    cfg, scope, exe, train_main, model = trained
    t = len(seq)
    trg = np.asarray(seq, np.int64)[None, :]
    feed = {
        "src_ids": src,
        "trg_ids": trg,
        "lbl_ids": np.zeros((1, t), np.int64),
        "src_pad_mask": src_pad,
        "trg_pad_mask": np.ones((1, t), np.float32),
    }
    with fluid.scope_guard(scope):
        (logits,) = exe.run(train_main, feed=feed,
                            fetch_list=[model["logits"]])
    logp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)
                                  ).sum(-1, keepdims=True)) - logits.max(
        -1, keepdims=True)
    total = 0.0
    for pos in range(t - 1):
        tok = seq[pos + 1]
        total += logp[0, pos, tok]
        if tok == EOS:
            break
    return total


def _src_batch(b=2, s=5, seed=0):
    r = np.random.RandomState(seed)
    src = r.randint(2, 37, (b, s)).astype(np.int64)
    src_pad = np.ones((b, s), np.float32)
    return src, src_pad


def test_greedy_parity_beam1(trained):
    cfg, scope, exe, train_main, model = trained
    src, src_pad = _src_batch(b=2)
    max_len = 6
    ids, scores = _decode(trained, 1, src, src_pad, max_len=max_len)
    assert ids.shape == (2, 1, max_len) and scores.shape == (2, 1)

    # manual greedy through the training program
    for row in range(2):
        seq = [BOS]
        for t in range(1, max_len):
            trg = np.asarray(seq, np.int64)[None, :]
            feed = {
                "src_ids": src[row : row + 1],
                "trg_ids": trg,
                "lbl_ids": np.zeros((1, t), np.int64),
                "src_pad_mask": src_pad[row : row + 1],
                "trg_pad_mask": np.ones((1, t), np.float32),
            }
            with fluid.scope_guard(scope):
                (logits,) = exe.run(train_main, feed=feed,
                                    fetch_list=[model["logits"]])
            nxt = int(np.argmax(logits[0, t - 1]))
            seq.append(nxt)
            if nxt == EOS:
                break
        got = list(ids[row, 0][: len(seq)])
        assert got == seq, f"row {row}: greedy mismatch {got} vs {seq}"


def test_beam_scores_consistent_and_sorted(trained):
    src, src_pad = _src_batch(b=2, seed=1)
    ids, scores = _decode(trained, 4, src, src_pad, max_len=6)
    assert ids.shape == (2, 4, 6) and scores.shape == (2, 4)
    # sorted descending
    assert (np.diff(scores, axis=1) <= 1e-5).all()
    # every hypothesis starts with BOS
    assert (ids[:, :, 0] == BOS).all()
    # teacher-forced log-prob of each returned hypothesis == its score
    for row in range(2):
        for beam in range(4):
            want = scores[row, beam]
            got = _teacher_logp(
                trained, src[row : row + 1], src_pad[row : row + 1],
                list(ids[row, beam]),
            )
            np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_beam_beats_or_matches_greedy(trained):
    src, src_pad = _src_batch(b=3, seed=2)
    _, s1 = _decode(trained, 1, src, src_pad, max_len=6)
    _, s4 = _decode(trained, 4, src, src_pad, max_len=6)
    assert (s4[:, 0] >= s1[:, 0] - 1e-5).all()


def test_eos_padding_after_finish(trained):
    """Once a hypothesis emits EOS its tail must stay EOS and its score
    frozen relative to longer continuations."""
    src, src_pad = _src_batch(b=4, seed=3)
    ids, _ = _decode(trained, 2, src, src_pad, max_len=8)
    for row in range(ids.shape[0]):
        for beam in range(ids.shape[1]):
            seq = list(ids[row, beam])
            if EOS in seq[1:]:
                first = seq[1:].index(EOS) + 1
                assert all(x == EOS for x in seq[first:]), seq


def test_translate_helper(trained):
    cfg, scope, exe, _, _ = trained
    src, src_pad = _src_batch(b=2, seed=5)
    ids, scores = transformer.translate(
        exe, scope, src, src_pad, cfg, beam_size=3, max_len=5)
    assert ids.shape == (2, 3, 5) and scores.shape == (2, 3)
