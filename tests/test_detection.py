"""Detection op family + SSD model (reference op set:
paddle/fluid/operators/detection/; layer set: layers/detection.py:33-57).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.registry import get_op_def
from tests.op_test import OpHarness


def _r(shape, seed, scale=1.0):
    return (np.random.RandomState(seed).rand(*shape) * scale).astype(
        np.float32)


def _boxes(n, m, seed, size=1.0):
    r = np.random.RandomState(seed)
    xy = r.uniform(0, size * 0.7, (n, m, 2))
    wh = r.uniform(size * 0.05, size * 0.3, (n, m, 2))
    return np.concatenate([xy, xy + wh], -1).astype(np.float32)


def test_target_assign():
    x = _r((2, 3, 4), 0)
    match = np.array([[0, -1, 2, 1], [2, 2, -1, -1]], np.int32)
    h = OpHarness("target_assign", {"X": x, "MatchIndices": match},
                  {"mismatch_value": 0.5},
                  out_slots=("Out", "OutWeight"))
    ref = np.full((2, 4, 4), 0.5, np.float32)
    w = np.zeros((2, 4, 1), np.float32)
    for i in range(2):
        for j in range(4):
            if match[i, j] >= 0:
                ref[i, j] = x[i, match[i, j]]
                w[i, j] = 1.0
    h.check_output({"Out": ref, "OutWeight": w})


def test_target_assign_negative_indices():
    x = _r((1, 2, 3), 1)
    match = np.array([[0, -1, -1]], np.int32)
    neg = np.array([[1, -1]], np.int32)
    outs = get_op_def("target_assign").compute(
        {"X": [x], "MatchIndices": [match], "NegIndices": [neg]},
        {"mismatch_value": 0.0})
    w = np.asarray(outs["OutWeight"][0])
    assert w[0, 0, 0] == 1.0 and w[0, 1, 0] == 1.0 and w[0, 2, 0] == 0.0


def test_mine_hard_examples():
    loss = np.array([[0.1, 0.9, 0.5, 0.7, 0.2]], np.float32)
    match = np.array([[0, -1, -1, -1, -1]], np.int32)
    outs = get_op_def("mine_hard_examples").compute(
        {"ClsLoss": [loss], "MatchIndices": [match]},
        {"neg_pos_ratio": 2.0})
    neg = np.asarray(outs["NegIndices"][0])
    # 1 positive -> 2 negatives, hardest first: indices 1 (0.9), 3 (0.7)
    assert set(neg[0][neg[0] >= 0].tolist()) == {1, 3}


def test_ssd_loss_positive_and_grad():
    n, p, g, c = 2, 16, 3, 5
    prior = _boxes(1, p, 3)[0]
    gt = _boxes(n, g, 4)
    gt[:, -1] = 0.0  # padding row
    label = np.random.RandomState(5).randint(1, c, (n, g)).astype(np.int64)
    loc = _r((n, p, 4), 6)
    conf = _r((n, p, c), 7)
    h = OpHarness("ssd_loss",
                  {"Location": loc, "Confidence": conf, "GtBox": gt,
                   "GtLabel": label, "PriorBox": prior},
                  {"neg_pos_ratio": 3.0},
                  out_slots=("Loss",))
    out = h.forward()[0]
    assert out.shape == (n, 1) and np.all(out > 0) and np.isfinite(out).all()

    # analytic grads exist, are finite, and flow to both heads
    import jax
    import jax.numpy as jnp

    def f(loc_, conf_):
        outs = get_op_def("ssd_loss").compute(
            {"Location": [loc_], "Confidence": [conf_], "GtBox": [gt],
             "GtLabel": [label], "PriorBox": [prior]},
            {"neg_pos_ratio": 3.0})
        return jnp.sum(outs["Loss"][0])

    gl, gc = jax.grad(f, argnums=(0, 1))(jnp.asarray(loc), jnp.asarray(conf))
    assert np.isfinite(np.asarray(gl)).all() and np.any(np.asarray(gl) != 0)
    assert np.isfinite(np.asarray(gc)).all() and np.any(np.asarray(gc) != 0)


def test_yolov3_loss_matches_structure():
    n, hgrid, c = 1, 4, 3
    anchors = [10, 13, 16, 30, 33, 23]
    mask = [0, 1, 2]
    x = _r((n, len(mask) * (5 + c), hgrid, hgrid), 8) - 0.5
    gt = np.zeros((n, 2, 4), np.float32)
    gt[0, 0] = [0.5, 0.5, 0.3, 0.4]   # one valid center-format box
    lbl = np.array([[1, 0]], np.int64)
    outs = get_op_def("yolov3_loss").compute(
        {"X": [x], "GTBox": [gt], "GTLabel": [lbl]},
        {"anchors": anchors, "anchor_mask": mask, "class_num": c,
         "ignore_thresh": 0.7, "downsample_ratio": 32})
    loss = np.asarray(outs["Loss"][0])
    obj = np.asarray(outs["ObjectnessMask"][0])
    gmm = np.asarray(outs["GTMatchMask"][0])
    assert loss.shape == (n,) and np.isfinite(loss).all() and loss[0] > 0
    assert gmm[0, 0] >= 0 and gmm[0, 1] == -1      # padding row unmatched
    assert np.any(obj > 0)                          # a positive cell

    # analytic grad flows to X and is finite
    import jax
    import jax.numpy as jnp

    def f(x_):
        o = get_op_def("yolov3_loss").compute(
            {"X": [x_], "GTBox": [gt], "GTLabel": [lbl]},
            {"anchors": anchors, "anchor_mask": mask, "class_num": c,
             "ignore_thresh": 0.7, "downsample_ratio": 32})
        return jnp.sum(o["Loss"][0])

    gx = np.asarray(jax.grad(f)(jnp.asarray(x)))
    assert np.isfinite(gx).all() and np.any(gx != 0)


def test_yolov3_loss_padding_gt_does_not_clobber_cell00():
    # Regression: a padding gt row (w=h=0) scatters to (anchor 0, cell
    # 0,0); it must not overwrite a REAL positive living in that exact
    # slot with a stale pre-scatter value.
    n, hgrid, c = 1, 4, 3
    anchors = [10, 13, 16, 30, 33, 23]
    mask = [0, 1, 2]
    x = np.zeros((n, len(mask) * (5 + c), hgrid, hgrid), np.float32)
    gt = np.zeros((n, 2, 4), np.float32)
    # small box centered in cell (0,0): best anchor is anchor 0 = mask[0]
    gt[0, 0] = [0.06, 0.06, 10.0 / 128.0, 13.0 / 128.0]
    lbl = np.array([[1, 0]], np.int64)
    outs = get_op_def("yolov3_loss").compute(
        {"X": [x], "GTBox": [gt], "GTLabel": [lbl]},
        {"anchors": anchors, "anchor_mask": mask, "class_num": c,
         "ignore_thresh": 0.7, "downsample_ratio": 32})
    obj = np.asarray(outs["ObjectnessMask"][0])
    assert obj[0, 0, 0, 0] == 1.0   # real positive survives padding row


def test_mine_hard_examples_sample_size_gating():
    # sample_size only applies to hard_example mining; max_negative keeps
    # the neg_pos_ratio cap (reference mine_hard_examples_op.cc).
    loss = np.array([[0.1, 0.9, 0.5, 0.7, 0.2]], np.float32)
    match = np.array([[0, -1, -1, -1, -1]], np.int32)
    outs = get_op_def("mine_hard_examples").compute(
        {"ClsLoss": [loss], "MatchIndices": [match]},
        {"neg_pos_ratio": 2.0, "sample_size": 4,
         "mining_type": "max_negative"})
    neg = np.asarray(outs["NegIndices"][0])
    assert (neg[0] >= 0).sum() == 2  # ratio cap, not sample_size
    outs = get_op_def("mine_hard_examples").compute(
        {"ClsLoss": [loss], "MatchIndices": [match]},
        {"neg_pos_ratio": 2.0, "sample_size": 3,
         "mining_type": "hard_example"})
    neg = np.asarray(outs["NegIndices"][0])
    assert (neg[0] >= 0).sum() == 3  # sample_size governs


def test_rpn_target_assign_dense():
    anchors = _boxes(1, 32, 9, size=50.0)[0]
    gt = _boxes(2, 4, 10, size=50.0)
    gt[:, -1] = 0.0
    im_info = np.tile(np.array([[60.0, 60.0, 1.0]], np.float32), (2, 1))
    outs = get_op_def("rpn_target_assign").compute(
        {"Anchor": [anchors], "GtBoxes": [gt], "ImInfo": [im_info]},
        {"rpn_batch_size_per_im": 16, "rpn_straddle_thresh": -1.0,
         "use_random": False})
    label = np.asarray(outs["ScoreLabel"][0])
    sw = np.asarray(outs["ScoreWeight"][0])
    bw = np.asarray(outs["BboxWeight"][0])
    assert label.shape == (2, 32)
    assert np.all((sw == 0) | (sw == 1))
    assert np.sum(sw, 1).max() <= 16
    # every gt has at least one positive anchor
    assert np.all(np.sum(label == 1, axis=1) >= 1)
    assert np.all(bw[label != 1] == 0)


def test_generate_proposals_shapes():
    n, a, hh, ww = 2, 3, 4, 4
    scores = _r((n, a, hh, ww), 11)
    deltas = _r((n, 4 * a, hh, ww), 12, 0.1) - 0.05
    im_info = np.tile(np.array([[64.0, 64.0, 1.0]], np.float32), (n, 1))
    anchors = _boxes(1, hh * ww * a, 13, size=60.0)[0].reshape(hh, ww, a, 4)
    var = np.full((hh, ww, a, 4), 1.0, np.float32)
    outs = get_op_def("generate_proposals").compute(
        {"Scores": [scores], "BboxDeltas": [deltas], "ImInfo": [im_info],
         "Anchors": [anchors], "Variances": [var]},
        {"pre_nms_topN": 24, "post_nms_topN": 8, "nms_thresh": 0.7,
         "min_size": 2.0})
    rois = np.asarray(outs["RpnRois"][0])
    num = np.asarray(outs["RpnRoisNum"][0])
    assert rois.shape == (n, 8, 4)
    assert np.all(num >= 1) and np.all(num <= 8)
    for i in range(n):
        live = rois[i, :num[i]]
        assert np.all(live[:, 2] >= live[:, 0])
        assert np.all(rois[i, num[i]:] == 0)


def test_generate_proposal_labels_sampling():
    rois = _boxes(2, 20, 14, size=50.0)
    gt = _boxes(2, 3, 15, size=50.0)
    gt_cls = np.random.RandomState(16).randint(1, 5, (2, 3)).astype(np.int32)
    im_info = np.tile(np.array([[60.0, 60.0, 1.0]], np.float32), (2, 1))
    outs = get_op_def("generate_proposal_labels").compute(
        {"RpnRois": [rois], "GtClasses": [gt_cls], "GtBoxes": [gt],
         "ImInfo": [im_info]},
        {"batch_size_per_im": 8, "fg_fraction": 0.5, "fg_thresh": 0.5,
         "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0, "class_nums": 5,
         "use_random": False})
    labels = np.asarray(outs["LabelsInt32"][0])
    tgt = np.asarray(outs["BboxTargets"][0])
    win = np.asarray(outs["BboxInsideWeights"][0])
    assert labels.shape == (2, 8) and tgt.shape == (2, 8, 20)
    # fg rows get exactly one class's 4 columns of weight
    fg = labels > 0
    assert np.all(win[fg].sum(-1) == 4.0)
    assert np.all(win[~fg] == 0.0)


def test_distribute_and_collect_fpn():
    rois = np.zeros((1, 6, 4), np.float32)
    sizes = [16, 32, 90, 200, 300, 0]   # last row padding
    for j, s in enumerate(sizes):
        rois[0, j] = [10, 10, 10 + s, 10 + s]
    outs = get_op_def("distribute_fpn_proposals").compute(
        {"FpnRois": [rois]},
        {"min_level": 2, "max_level": 5, "refer_level": 4,
         "refer_scale": 224})
    multi = [np.asarray(x) for x in outs["MultiFpnRois"]]
    nums = [np.asarray(x) for x in outs["MultiLevelRoIsNum"]]
    restore = np.asarray(outs["RestoreInd"][0])
    assert sum(int(x[0]) for x in nums) == 5
    # small rois land on the lowest level
    assert nums[0][0] >= 1 and multi[0][0, 0, 2] <= 50
    assert restore[0, -1] == -1       # padding row
    concat = np.concatenate(multi, 1)[0]
    for j in range(5):
        np.testing.assert_allclose(concat[restore[0, j]], rois[0, j])

    scores = [np.linspace(0.9, 0.1, multi[i].shape[1],
                          dtype=np.float32)[None] for i in range(4)]
    out2 = get_op_def("collect_fpn_proposals").compute(
        {"MultiLevelRois": multi, "MultiLevelScores": scores},
        {"post_nms_topN": 4})
    fpn = np.asarray(out2["FpnRois"][0])
    num = np.asarray(out2["RoisNum"][0])
    assert fpn.shape == (1, 4, 4) and num[0] == 4


def test_box_decoder_and_assign():
    p, c = 6, 3
    prior = _boxes(1, p, 17, size=50.0)[0]
    pvar = np.full((4,), 0.1, np.float32)
    target = _r((p, 4 * c), 18, 0.2) - 0.1
    score = _r((p, c), 19)
    outs = get_op_def("box_decoder_and_assign").compute(
        {"PriorBox": [prior], "PriorBoxVar": [pvar], "TargetBox": [target],
         "BoxScore": [score]}, {"box_clip": 4.135})
    dec = np.asarray(outs["DecodeBox"][0])
    assign = np.asarray(outs["OutputAssignBox"][0])
    assert dec.shape == (p, 4 * c) and assign.shape == (p, 4)
    best = score.argmax(1)
    for i in range(p):
        np.testing.assert_allclose(assign[i],
                                   dec[i, best[i] * 4:(best[i] + 1) * 4],
                                   rtol=1e-5)


def test_detection_map_perfect_and_miss():
    # one class, one gt, one perfect detection -> mAP 1
    det = np.array([[[0, 0.9, 10, 10, 20, 20]]], np.float32)
    gt = np.array([[[0, 10, 10, 20, 20]]], np.float32)
    outs = get_op_def("detection_map").compute(
        {"DetectRes": [det], "Label": [gt]}, {"class_num": 1})
    assert np.asarray(outs["MAP"][0]) == pytest.approx(1.0, abs=1e-5)
    # detection misses -> mAP 0
    det2 = np.array([[[0, 0.9, 40, 40, 50, 50]]], np.float32)
    outs2 = get_op_def("detection_map").compute(
        {"DetectRes": [det2], "Label": [gt]}, {"class_num": 1})
    assert np.asarray(outs2["MAP"][0]) == pytest.approx(0.0, abs=1e-5)


def test_detection_layers_build():
    """The layer API builds a program end to end (shapes/attrs wiring)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feat = layers.data("feat", shape=[8, 8, 8], dtype="float32")
        img = layers.data("img", shape=[3, 64, 64], dtype="float32")
        boxes, var = layers.prior_box(feat, img, min_sizes=[16.0],
                                      aspect_ratios=[1.0, 2.0], flip=True)
        anchors, avar = layers.anchor_generator(feat,
                                                anchor_sizes=[32.0, 64.0],
                                                aspect_ratios=[1.0],
                                                stride=[8.0, 8.0])
        assert boxes.shape[-1] == 4 and anchors.shape[-1] == 4


@pytest.mark.full
def test_ssd_model_trains():
    from paddle_tpu.models import ssd

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = ssd.get_model(batch_size=8, num_classes=5, gt_capacity=4)
        fluid.optimizer.Adam(2e-3).minimize(model["loss"])
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for s in range(25):
            feed = ssd.synthetic_batch(8, num_classes=5, gt_capacity=4,
                                       seed=s % 5)
            out = exe.run(main, feed=feed, fetch_list=[model["loss"]])
            losses.append(float(out[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.2, losses


def test_ssd_detection_output_shape():
    from paddle_tpu.models import ssd

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = ssd.get_model(batch_size=2, num_classes=5, gt_capacity=4)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = ssd.synthetic_batch(2, num_classes=5, gt_capacity=4)
        det = exe.run(main, feed=feed, fetch_list=[model["detection"]])[0]
    assert det.shape[0] == 2 and det.shape[2] == 6


def test_generate_mask_labels_dense():
    """Square polygon filling the left half of the roi -> left half of
    the MxM target is 1 (reference: generate_mask_labels_op.cc with the
    dense-padded polygon encoding)."""
    n, g, q, v, r, m, c = 1, 2, 2, 6, 4, 8, 3
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    gt_classes = np.array([[1, 0]], np.int32)
    is_crowd = np.zeros((n, g), np.int32)
    segms = np.zeros((n, g, q, v, 2), np.float32)
    # gt 0: one square part covering x in [0, 16], y in [0, 32]
    segms[0, 0, 0, :4] = [[0, 0], [16, 0], [16, 32], [0, 32]]
    plens = np.zeros((n, g, q), np.int32)
    plens[0, 0, 0] = 4
    rois = np.zeros((n, r, 4), np.float32)
    rois[0, 0] = [0, 0, 32, 32]     # fg: left half covered by the poly
    rois[0, 1] = [0, 0, 8, 8]       # fg: fully inside the poly
    labels = np.zeros((n, r), np.int32)
    labels[0, 0] = 1
    labels[0, 1] = 2
    outs = get_op_def("generate_mask_labels").compute(
        {"ImInfo": [im_info], "GtClasses": [gt_classes],
         "IsCrowd": [is_crowd], "GtSegms": [segms], "PolyLens": [plens],
         "Rois": [rois], "LabelsInt32": [labels]},
        {"num_classes": c, "resolution": m})
    mask_rois = np.asarray(outs["MaskRois"][0])
    has_mask = np.asarray(outs["RoiHasMaskInt32"][0])
    masks = np.asarray(outs["MaskInt32"][0])
    count = np.asarray(outs["MaskNum"][0])
    assert count[0] == 2
    assert set(has_mask[0][:2].tolist()) == {0, 1}
    np.testing.assert_allclose(mask_rois[0, 0], rois[0, 0])
    # roi 0 (class 1): left half of the grid inside the polygon
    m0 = masks[0, 0].reshape(c, m, m)[1]
    assert (m0[:, : m // 2] == 1).all()
    assert (m0[:, m // 2:] == 0).all()
    # other class blocks are ignore (-1)
    assert (masks[0, 0].reshape(c, m, m)[2] == -1).all()
    # roi 1 (class 2): fully inside -> all ones in class-2 block
    m1 = masks[0, 1].reshape(c, m, m)[2]
    assert (m1 == 1).all()
    # padding rows
    assert (has_mask[0][2:] == -1).all()
    assert (masks[0, 2:] == -1).all()


@pytest.mark.full
def test_generate_mask_labels_no_fg():
    n, g, q, v, r, m, c = 1, 1, 1, 6, 3, 4, 2
    im_info = np.array([[32.0, 32.0, 1.0]], np.float32)
    gt_classes = np.ones((n, g), np.int32)
    segms = np.zeros((n, g, q, v, 2), np.float32)
    segms[0, 0, 0, :4] = [[0, 0], [8, 0], [8, 8], [0, 8]]
    plens = np.full((n, g, q), 4, np.int32)
    rois = np.tile(np.array([[0, 0, 8, 8]], np.float32), (n, r, 1))
    labels = np.zeros((n, r), np.int32)    # all background
    outs = get_op_def("generate_mask_labels").compute(
        {"ImInfo": [im_info], "GtClasses": [gt_classes],
         "IsCrowd": [np.zeros((n, g), np.int32)], "GtSegms": [segms],
         "PolyLens": [plens], "Rois": [rois], "LabelsInt32": [labels]},
        {"num_classes": c, "resolution": m})
    count = np.asarray(outs["MaskNum"][0])
    masks = np.asarray(outs["MaskInt32"][0])
    has = np.asarray(outs["RoiHasMaskInt32"][0])
    assert count[0] == 1          # one bg roi stand-in
    assert has[0, 0] == 0 and (has[0, 1:] == -1).all()
    assert (masks[0, 0] == -1).all()   # all-ignore mask


def test_detection_map_metric_class_accumulates():
    """metrics.DetectionMAP (reference: metrics.py:687): per-batch mAP
    plus fixed-size binned cross-batch accumulation, reset via
    has_state."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        det = layers.data("det", shape=[1, 2, 6], append_batch_size=False,
                          stop_gradient=True)
        gtl = layers.data("gtl", shape=[1, 1, 1], append_batch_size=False,
                          stop_gradient=True)
        gtb = layers.data("gtb", shape=[1, 1, 4], append_batch_size=False,
                          stop_gradient=True)
        m = fluid.metrics.DetectionMAP(det, gtl, gtb, class_num=1)
        cur, accum = m.get_map_var()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    gt_l = np.zeros((1, 1, 1), np.float32)
    gt_b = np.array([[[10, 10, 20, 20]]], np.float32)
    hit = np.array([[[0, 0.9, 10, 10, 20, 20],
                     [-1, 0, 0, 0, 0, 0]]], np.float32)
    miss = np.array([[[0, 0.8, 40, 40, 50, 50],
                      [-1, 0, 0, 0, 0, 0]]], np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        fd = {"det": hit, "gtl": gt_l, "gtb": gt_b}
        c1, a1 = exe.run(main, feed=fd, fetch_list=[cur, accum])
        assert float(np.asarray(c1)) == pytest.approx(1.0, abs=1e-3)
        assert float(np.asarray(a1)) == pytest.approx(1.0, abs=1e-3)
        # second batch misses: cur drops to 0, accumulated is the
        # 2-batch PR curve (1 TP at 0.9, 1 FP at 0.8, 2 positives):
        # integral AP = 0.5
        fd2 = {"det": miss, "gtl": gt_l, "gtb": gt_b}
        c2, a2 = exe.run(main, feed=fd2, fetch_list=[cur, accum])
        assert float(np.asarray(c2)) == pytest.approx(0.0, abs=1e-3)
        assert float(np.asarray(a2)) == pytest.approx(0.5, abs=1e-2)
        # reset: the next batch starts a fresh accumulation
        m.reset(exe)
        c3, a3 = exe.run(main, feed=fd, fetch_list=[cur, accum])
        assert float(np.asarray(a3)) == pytest.approx(1.0, abs=1e-3)
