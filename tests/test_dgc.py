"""Deep Gradient Compression (reference: optimizer.py:696
DGCMomentumOptimizer, operators/dgc_op.h, sparse_all_reduce_op_handle.h).

Covers the three layers of the design: the pure dgc_step kernel, the
multi-worker shard_map exchange with genuinely LOCAL per-worker
gradients (the honest sparse-allreduce analog), and the program-level
DGCMomentumOptimizer (dense-parity before rampup, sparse after)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import dgc


def test_dgc_step_mechanics():
    n = 64
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.normal(0, 1, (n,)), jnp.float32)
    u = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)

    # before rampup_begin_step: dense passthrough, accumulators untouched
    dec, u1, v1 = dgc.dgc_step(g, u, v, jnp.float32(0.0), momentum=0.9,
                               sparsity=[0.9], rampup_begin_step=5,
                               rampup_step=1)
    np.testing.assert_array_equal(np.asarray(dec), np.asarray(g))
    assert np.all(np.asarray(u1) == 0) and np.all(np.asarray(v1) == 0)

    # past rampup: k = numel*(1-0.999) -> 1 entry sent (top |v| = top |g|
    # on the first active step), residuals keep the rest
    dec, u1, v1 = dgc.dgc_step(g, u, v, jnp.float32(10.0), momentum=0.9,
                               sparsity=[0.999], rampup_begin_step=5,
                               rampup_step=1)
    dec = np.asarray(dec)
    sent = np.nonzero(dec)[0]
    assert len(sent) == 1
    assert sent[0] == int(np.argmax(np.abs(np.asarray(g))))
    # sent position zeroed in the accumulators, others accumulate
    assert np.asarray(u1)[sent[0]] == 0 and np.asarray(v1)[sent[0]] == 0
    assert np.count_nonzero(np.asarray(v1)) == n - 1

    # conservation over time: repeated steps with zero new gradient
    # eventually drain the residual into the decoded stream
    total = dec.copy()
    uu, vv = u1, v1
    for s in range(11, 600):
        d, uu, vv = dgc.dgc_step(jnp.zeros_like(g), uu, vv,
                                 jnp.float32(s), momentum=0.0,
                                 sparsity=[0.999], rampup_begin_step=5,
                                 rampup_step=1)
        total += np.asarray(d)
    np.testing.assert_allclose(total, np.asarray(g), rtol=1e-5, atol=1e-6)

    # local gradient clipping (reference dgc_clip_by_norm_op.h): active
    # only past rampup_begin_step, scales to the target norm
    big = jnp.full((4,), 10.0, jnp.float32)
    before = dgc.clip_by_norm_rampup(big, jnp.float32(0.0), clip_norm=1.0,
                                     rampup_begin_step=5)
    np.testing.assert_array_equal(np.asarray(before), np.asarray(big))
    after = dgc.clip_by_norm_rampup(big, jnp.float32(9.0), clip_norm=1.0,
                                    rampup_begin_step=5)
    assert np.linalg.norm(np.asarray(after)) == pytest.approx(1.0, rel=1e-5)


def test_dgc_exchange_sums_local_topk():
    """8 workers with different local grads: the decoded gradient equals
    the scatter-add of every worker's own top-k selection."""
    n, W = 32, 8
    mesh = Mesh(np.asarray(jax.devices()[:W]), ("dp",))
    rng = np.random.RandomState(1)
    g_all = jnp.asarray(rng.normal(0, 1, (W, n)), jnp.float32)
    u0 = jnp.zeros((W, n), jnp.float32)
    v0 = jnp.zeros((W, n), jnp.float32)

    def worker(g, u, v):
        dec, u2, v2 = dgc.dgc_step(
            g[0], u[0], v[0], jnp.float32(0.0), momentum=0.9,
            sparsity=[0.9], rampup_begin_step=0, rampup_step=1,
            axis="dp", combine="sum")
        return dec[None], u2[None], v2[None]

    dec, u1, v1 = jax.jit(jax.shard_map(
        worker, mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"))))(g_all, u0, v0)
    dec = np.asarray(dec)
    # every worker holds the same decoded sum
    for w in range(1, W):
        np.testing.assert_array_equal(dec[w], dec[0])
    # numpy oracle: sum of each worker's top-k of v (= g here, u=v=0,
    # momentum correction gives v = m*0 + g on step one... u = g, v = u)
    k = max(1, int(n * (1 - 0.9)))
    expect = np.zeros(n, np.float32)
    gn = np.asarray(g_all)
    for w in range(W):
        idx = np.argsort(-np.abs(gn[w]))[:k]
        expect[idx] += gn[w][idx]
        # sent positions cleared locally
        assert np.all(np.asarray(v1)[w][idx] == 0)
    np.testing.assert_allclose(dec[0], expect, rtol=1e-5, atol=1e-6)


def test_dgc_convergence_parity_vs_dense_momentum():
    """Manual-DP linear regression on an 8-worker mesh: DGC at terminal
    sparsity 0.999 with the paper's rampup reaches the same loss
    neighborhood as dense momentum (VERDICT r4 item 5 bar)."""
    W, n_feat, bs = 8, 50, 8
    mesh = Mesh(np.asarray(jax.devices()[:W]), ("dp",))
    rng = np.random.RandomState(2)
    w_true = rng.normal(0, 1, (n_feat, 1)).astype(np.float32)
    X = rng.normal(0, 1, (W * bs, n_feat)).astype(np.float32)
    Y = X @ w_true

    sparsity = [0.75, 0.9375, 0.984375, 0.996, 0.999]
    # lr respects the staleness envelope: a coordinate is exchanged
    # every ~numel/(k*W) steps, and the sent value is the accumulated
    # sum since last send, so the impulse amplitude scales with that
    # delay — deterministic quadratics need lr * lambda * delay/(1-m)
    # inside the stability region (measured: 0.02 diverges, 0.001
    # converges to l0/100; the paper leans on SGD noise + warmup for
    # the same reason)
    mu, lr, steps = 0.9, 0.001, 600

    def local_grad(w, xb, yb):
        # per-worker grad on the LOCAL shard (scaled as 1/global_batch
        # so the cross-worker sum is the global-mean gradient)
        pred = xb @ w
        return xb.T @ (pred - yb) * (2.0 / (W * bs))

    def dgc_train():
        def step_fn(carry, s):
            w, vel, u, v = carry

            def worker(xb, yb, w, vel, u, v, s):
                # xb/yb are the LOCAL [bs, .] shards of the global batch
                g = local_grad(w, xb, yb)
                dec, u2, v2 = dgc.dgc_step(
                    g, u, v, s.astype(jnp.float32), momentum=mu,
                    sparsity=sparsity, rampup_begin_step=10,
                    rampup_step=100, axis="dp", combine="sum")
                # paper eq. 4-5 (momentum correction): the momentum
                # EMA lives in u, so the weight step is plain SGD on
                # the decoded sparse gradient; before rampup dec == g,
                # so vel carries the dense-phase momentum and freezes
                # (dgc phase: vel2 = mu*vel keeps decaying it)
                dense_phase = s < 10
                vel2 = mu * vel + jnp.where(dense_phase, dec, 0.0)
                step_v = jnp.where(dense_phase, vel2, dec)
                return w - lr * step_v, vel2, u2, v2

            return jax.shard_map(
                worker, mesh=mesh,
                in_specs=(P("dp"), P("dp"), P(), P(), P(), P(), P()),
                out_specs=(P(), P(), P(), P()), check_vma=False,
            )(Xs, Ys, w, vel, u, v, s), None

        z = jnp.zeros((n_feat, 1), jnp.float32)
        Xs_l, Ys_l = jnp.asarray(X), jnp.asarray(Y)
        (wf, _, _, _), _ = jax.lax.scan(
            step_fn, (z, z, z, z), jnp.arange(steps))
        return wf

    Xs, Ys = jnp.asarray(X), jnp.asarray(Y)

    def dense_train():
        def step_fn(carry, _):
            w, vel = carry
            g = Xs.T @ (Xs @ w - Ys) * (2.0 / (W * bs))
            vel = mu * vel + g
            return (w - lr * vel, vel), None

        z = jnp.zeros((n_feat, 1), jnp.float32)
        (wf, _), _ = jax.lax.scan(step_fn, (z, z), None, length=steps)
        return wf

    w_dgc = np.asarray(jax.jit(dgc_train)())
    w_dense = np.asarray(jax.jit(dense_train)())
    loss = lambda w: float(np.mean((X @ w - Y) ** 2))
    l0 = loss(np.zeros((n_feat, 1), np.float32))
    l_dgc, l_dense = loss(w_dgc), loss(w_dense)
    assert l_dense < l0 * 1e-2
    # parity bar: DGC lands in the same convergence regime
    assert l_dgc < l0 * 5e-2, (l_dgc, l_dense, l0)


def test_dgc_optimizer_dense_parity_before_rampup():
    """Program path: before rampup_begin_step the DGC optimizer IS
    momentum (the reference kernel's early return) — bit-identical
    trajectories; with rampup at 0 it diverges but still trains."""

    def build(opt_ctor):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 5
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[8, 128], append_batch_size=False,
                            stop_gradient=True)
            y = layers.data("y", shape=[8, 1], append_batch_size=False,
                            stop_gradient=True)
            # 128x128 = 16384: exactly at the reference eligibility gate
            h = layers.fc(x, 128, act="relu",
                          param_attr=fluid.ParamAttr(name="w1"))
            pred = layers.fc(h, 1, param_attr=fluid.ParamAttr(name="w2"))
            loss = layers.mean(layers.square(pred - y))
            opt_ctor().minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(3)
    feeds = []
    for _ in range(6):
        x = rng.normal(0, 1, (8, 128)).astype(np.float32)
        # learnable target so the trains-check has signal
        y = x[:, :8].mean(1, keepdims=True).astype(np.float32)
        feeds.append({"x": x, "y": y})
    feeds = feeds * 2  # two epochs

    def run(opt_ctor):
        main, startup, loss = build(opt_ctor)
        types = [o.type for o in main.global_block().ops]
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        out = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for fd in feeds:
                (l,) = exe.run(main, feed=fd, fetch_list=[loss])
                out.append(float(np.asarray(l)))
            w1 = np.asarray(scope.find_var("w1"))
        return out, w1, types

    mom = lambda: fluid.optimizer.MomentumOptimizer(0.05, 0.9)
    dgc_late = lambda: fluid.optimizer.DGCMomentumOptimizer(
        0.05, 0.9, rampup_begin_step=1000)
    # moderate sparsity + gentler lr for the 6-step trains-check: the
    # op mechanics (top-k, exchange, residual masking) are ratio-
    # independent, and extreme-sparsity convergence over hundreds of
    # steps is covered by the manual-DP parity test above
    dgc_now = lambda: fluid.optimizer.DGCMomentumOptimizer(
        0.01, 0.9, rampup_begin_step=0, rampup_step=1,
        sparsity=[0.5])

    l_mom, w_mom, t_mom = run(mom)
    l_late, w_late, t_late = run(dgc_late)
    l_now, w_now, t_now = run(dgc_now)

    # the eligible 128x128 param got the dgc op; the small ones didn't
    assert "dgc_momentum" in t_late and t_late.count("dgc_momentum") == 1
    assert "momentum" in t_late  # w2 and biases stay dense
    # pre-rampup == dense momentum, bit for bit
    np.testing.assert_array_equal(l_mom, l_late)
    np.testing.assert_array_equal(w_mom, w_late)
    # active DGC diverges from dense but still trains
    assert not np.allclose(w_mom, w_now)
    assert l_now[-1] < l_now[0]


def test_dgc_steady_state_gather_width():
    """Past rampup the exchange runs at the TERMINAL width (~n/1000+1),
    not the schedule max (~n/4 with the paper's warmup): the warmup
    schedule and a terminal-only schedule must produce identical decoded
    grads/accumulators once the schedule has saturated."""
    rng = np.random.RandomState(3)
    n = 4000
    g = jnp.asarray(rng.normal(0, 1, (n,)), jnp.float32)
    u = jnp.asarray(rng.normal(0, 0.1, (n,)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 0.1, (n,)), jnp.float32)
    step = jnp.float32(50.0)  # >= rampup_step=10 -> saturated at 0.999
    warm = dgc.dgc_step(g, u, v, step, momentum=0.9,
                        sparsity=[0.75, 0.9375, 0.984375, 0.996, 0.999],
                        rampup_begin_step=0, rampup_step=10)
    term = dgc.dgc_step(g, u, v, step, momentum=0.9, sparsity=[0.999],
                        rampup_begin_step=0, rampup_step=10)
    for a, b in zip(warm, term):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
