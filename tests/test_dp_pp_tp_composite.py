"""dp x pp x tp composed in ONE program on a 3-axis mesh, with loss
parity vs the single-device run (VERDICT r4 item 4: tensor parallelism
INSIDE a pipeline stage — the composition every real large-model config
uses; SURVEY.md §2.3 final row).

Mechanism under test: gpipe's shard_map is manual over {pipe, data} and
leaves 'model' as an AUTO axis, so GSPMD partitions each stage body over
the stacked weights' model-dim shardings (pipeline_tp_rules) and inserts
the row-parallel all-reduces inside the per-tick computation."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.models import transformer as T
from paddle_tpu.parallel.strategy import pipeline_tp_rules


def _build(n_layer):
    cfg = T.TransformerConfig(
        src_vocab_size=200, trg_vocab_size=200, d_model=32, d_inner=64,
        n_head=2, n_layer=n_layer, max_length=20, dropout=0.0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = T.build_scan(cfg)
        fluid.optimizer.SGD(0.05).minimize(model["loss"])
    return cfg, main, startup, model


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_dp2_pp2_tp2_single_program_parity():
    n_layer = 2
    losses = {}
    for mode in ("single", "dp_pp_tp"):
        cfg, main, startup, model = _build(n_layer)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if mode == "single":
                prog = main
            else:
                mesh = parallel.create_mesh(
                    {"data": 2, "pipe": 2, "model": 2},
                    devices=jax.devices()[:8])
                strategy = parallel.DistributedStrategy(
                    mesh, data_axis="data",
                    rules=pipeline_tp_rules("pipe", "model"),
                    pipe_axis="pipe", pipe_micro=2)
                prog = fluid.CompiledProgram(main).with_strategy(strategy)
            cur = []
            for s in range(2):
                fd = T.make_batch(cfg, batch=8, src_len=16, trg_len=16,
                                  seed=s)
                out = exe.run(prog, feed=fd, fetch_list=[model["loss"]])
                cur.append(float(out[0]))
            losses[mode] = cur
    np.testing.assert_allclose(losses["single"], losses["dp_pp_tp"],
                               rtol=2e-4, atol=2e-4)
