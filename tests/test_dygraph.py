"""Dygraph (eager) engine tests.

The eager analog of the reference's imperative tests
(reference: tests/unittests/test_imperative*.py): taped autograd checked
against the static graph, layer classes, optimizer parity, and the
state-dict round trip.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph, layers
from paddle_tpu.dygraph import VarBase, nn, to_variable


def test_trace_and_backward_matches_manual():
    with dygraph.guard():
        x = VarBase(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        w = VarBase(np.array([[0.5, -1.0], [2.0, 0.25]], np.float32))
        y = x @ w
        z = y * y
        tr = dygraph.get_tracer()
        loss_outs = tr.trace_op("mean", {"X": [z]}, {})
        loss = loss_outs["Out"][0]
        loss.backward()

        import jax
        import jax.numpy as jnp

        def ref(xv, wv):
            return jnp.mean((xv @ wv) ** 2)

        gx, gw = jax.grad(ref, argnums=(0, 1))(
            jnp.asarray(x.numpy()), jnp.asarray(w.numpy())
        )
        np.testing.assert_allclose(x.gradient(), np.asarray(gx), rtol=1e-5)
        np.testing.assert_allclose(w.gradient(), np.asarray(gw), rtol=1e-5)


def test_stop_gradient_blocks_tape():
    with dygraph.guard():
        x = VarBase(np.ones((2, 2), np.float32), stop_gradient=True)
        w = VarBase(np.ones((2, 2), np.float32))
        y = (x @ w) * 3.0
        tr = dygraph.get_tracer()
        loss = tr.trace_op("mean", {"X": [y]}, {})["Out"][0]
        loss.backward()
        assert x.gradient() is None
        assert w.gradient() is not None


def test_no_grad_context():
    with dygraph.guard():
        w = VarBase(np.ones((2, 2), np.float32))
        with dygraph.no_grad():
            y = w * 2.0
        assert y.stop_gradient


def _mlp_params(seed=7):
    rng = np.random.RandomState(seed)
    w1 = rng.normal(0, 0.1, (784, 64)).astype(np.float32)
    b1 = np.zeros(64, np.float32)
    w2 = rng.normal(0, 0.1, (64, 10)).astype(np.float32)
    b2 = np.zeros(10, np.float32)
    return w1, b1, w2, b2


def _batches(n=4, bs=16, seed=3):
    rng = np.random.RandomState(seed)
    return [
        (
            rng.normal(0, 1, (bs, 784)).astype(np.float32),
            rng.randint(0, 10, (bs, 1)).astype(np.int64),
        )
        for _ in range(n)
    ]


def _static_losses(batches, params, lr=0.1, opt="sgd"):
    w1, b1, w2, b2 = params
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[784], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(
            img,
            64,
            act="relu",
            param_attr=fluid.ParamAttr(
                name="w1", initializer=fluid.initializer.NumpyArrayInitializer(w1)
            ),
            bias_attr=fluid.ParamAttr(
                name="b1", initializer=fluid.initializer.NumpyArrayInitializer(b1)
            ),
        )
        logits = layers.fc(
            h,
            10,
            param_attr=fluid.ParamAttr(
                name="w2", initializer=fluid.initializer.NumpyArrayInitializer(w2)
            ),
            bias_attr=fluid.ParamAttr(
                name="b2", initializer=fluid.initializer.NumpyArrayInitializer(b2)
            ),
        )
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        if opt == "sgd":
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
        else:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = []
    for x, y in batches:
        (l,) = exe.run(main, feed={"img": x, "label": y}, fetch_list=[loss])
        out.append(float(l))
    return out


class _EagerMLP(dygraph.Layer):
    def __init__(self, params):
        super().__init__("mlp")
        w1, b1, w2, b2 = params
        self.fc1 = nn.FC(
            "fc1",
            64,
            act="relu",
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NumpyArrayInitializer(w1)
            ),
            bias_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NumpyArrayInitializer(b1)
            ),
        )
        self.fc2 = nn.FC(
            "fc2",
            10,
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NumpyArrayInitializer(w2)
            ),
            bias_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NumpyArrayInitializer(b2)
            ),
        )

    def forward(self, x):
        return self.fc2(self.fc1(x))


def _eager_losses(batches, params, lr=0.1, opt="sgd"):
    tr = dygraph.get_tracer()
    with dygraph.guard():
        model = _EagerMLP(params)
        if opt == "sgd":
            optimizer = fluid.optimizer.SGD(learning_rate=lr)
        else:
            optimizer = fluid.optimizer.Adam(learning_rate=lr)
        out = []
        for x, y in batches:
            logits = model(to_variable(x))
            label = to_variable(y)
            ce = tr.trace_op(
                "softmax_with_cross_entropy",
                {"Logits": [logits], "Label": [label]},
                {},
            )["Loss"][0]
            loss = tr.trace_op("mean", {"X": [ce]}, {})["Out"][0]
            loss.backward()
            optimizer.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            out.append(float(loss.numpy()))
    return out


@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_eager_matches_static_mlp(opt):
    """VERDICT item 5 acceptance: eager training matches static-graph
    losses step for step with identical inits and data."""
    params = _mlp_params()
    batches = _batches()
    lr = 0.1 if opt == "sgd" else 1e-3
    static = _static_losses(batches, params, lr=lr, opt=opt)
    eager = _eager_losses(batches, params, lr=lr, opt=opt)
    np.testing.assert_allclose(static, eager, rtol=1e-4, atol=1e-5)


def test_conv_bn_pool_layers_run_and_train():
    with dygraph.guard():
        conv = nn.Conv2D("conv", num_filters=4, filter_size=3, padding=1)
        bn = nn.BatchNorm("bn", num_channels=4)
        pool = nn.Pool2D("pool", pool_size=2, pool_stride=2)
        x = to_variable(np.random.randn(2, 3, 8, 8).astype(np.float32))
        y = pool(bn(conv(x)))
        assert y.shape == (2, 4, 4, 4)
        tr = dygraph.get_tracer()
        loss = tr.trace_op("mean", {"X": [y]}, {})["Out"][0]
        loss.backward()
        g = conv._filter.gradient()
        assert g is not None and np.isfinite(g).all()
        # BatchNorm running stats moved away from init
        assert not np.allclose(bn._mean.numpy(), 0.0)

        bn.eval()
        y2 = bn(conv(x))
        assert y2.shape == (2, 4, 8, 8)


def test_embedding_layernorm_gru_unit():
    with dygraph.guard():
        emb = nn.Embedding("emb", size=[20, 8])
        ln = nn.LayerNorm("ln", 8, begin_norm_axis=2)
        ids = to_variable(np.random.randint(0, 20, (2, 5)).astype(np.int64))
        e = ln(emb(ids))
        assert e.shape == (2, 5, 8)

        gru = nn.GRUUnit("gru", size=3 * 8)
        xproj = to_variable(np.random.randn(2, 24).astype(np.float32))
        h0 = to_variable(np.zeros((2, 8), np.float32))
        h, _, gate = gru(xproj, h0)
        assert h.shape == (2, 8) and gate.shape == (2, 24)


def test_state_dict_roundtrip(tmp_path):
    with dygraph.guard():
        model = _EagerMLP(_mlp_params())
        x = to_variable(np.random.randn(2, 784).astype(np.float32))
        ref = model(x).numpy()
        sd = model.state_dict()
        assert len(sd) == 4

        dygraph.save_dygraph(sd, str(tmp_path / "m"))
        loaded = dygraph.load_dygraph(str(tmp_path / "m"))

        model2 = _EagerMLP(_mlp_params(seed=99))  # different init
        model2(x)  # build lazy FC params
        assert not np.allclose(model2(x).numpy(), ref)
        with pytest.raises(KeyError):
            model2.set_dict({})  # strict: missing params raise
        # names differ between instances; remap by position
        remap = dict(zip([n for n, _ in model2.named_parameters()], loaded.values()))
        model2.set_dict(remap)
        np.testing.assert_allclose(model2(x).numpy(), ref, rtol=1e-6)


def test_dropout_train_eval_modes():
    with dygraph.guard():
        drop = nn.Dropout("drop", p=0.5)
        x = to_variable(np.ones((100, 100), np.float32))
        y_train = drop(x).numpy()
        assert (y_train == 0).mean() > 0.3  # training: some zeros
        drop.eval()
        y_eval = drop(x).numpy()
        assert np.isclose(y_eval.mean(), 0.5, atol=0.01)  # downgrade_in_infer


def test_linear_explicit_dims():
    with dygraph.guard():
        lin = nn.Linear(8, 4, act="relu")
        x = to_variable(np.random.randn(3, 8).astype(np.float32))
        y = lin(x)
        assert y.shape == (3, 4)
        assert lin.weight.shape == (8, 4)


def test_minimize_without_backward_raises():
    with dygraph.guard():
        model = _EagerMLP(_mlp_params())
        x = to_variable(np.random.randn(2, 784).astype(np.float32))
        loss = dygraph.get_tracer().trace_op("mean", {"X": [model(x)]}, {})["Out"][0]
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        with pytest.raises(RuntimeError, match="backward"):
            opt.minimize(loss, parameter_list=model.parameters())


def test_adam_state_survives_param_set_change():
    """Freezing a parameter mid-training must not reset the surviving
    parameters' moments (code-review finding, round 2)."""
    with dygraph.guard():
        tr = dygraph.get_tracer()
        a = VarBase(np.ones((3,), np.float32), name="pa")
        b = VarBase(np.ones((3,), np.float32), name="pb")
        opt = fluid.optimizer.Adam(learning_rate=0.1)
        for _ in range(3):
            s = tr.trace_op("elementwise_add", {"X": [a], "Y": [b]}, {})["Out"][0]
            loss = tr.trace_op("mean", {"X": [s]}, {})["Out"][0]
            loss.backward()
            opt.minimize(loss, parameter_list=[a, b])
            a.clear_gradient(); b.clear_gradient()
        m1 = {k: np.asarray(v) for k, v in opt._dy_state.items() if "moment1" in k}
        assert m1 and all(np.abs(v).max() > 0 for v in m1.values())

        b.stop_gradient = True  # freeze -> param set changes -> rebuild
        s = tr.trace_op("elementwise_add", {"X": [a], "Y": [b]}, {})["Out"][0]
        loss = tr.trace_op("mean", {"X": [s]}, {})["Out"][0]
        loss.backward()
        opt.minimize(loss, parameter_list=[a, b])
        m1_after = {
            k: np.asarray(v) for k, v in opt._dy_state.items() if "moment1" in k
        }
        # the surviving param's moment1 continued from its old value, not 0
        (mkey,) = [k for k in m1_after if k.startswith("pa")]
        old = [v for k, v in m1.items() if k.startswith("pa")][0]
        got = m1_after[mkey]
        assert not np.allclose(got, 0.1 * (1 - 0.9) * np.ones(3) / 3, atol=1e-8) \
            or np.abs(old).max() > 0


def test_static_group_norm_layer():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6, 4, 4], dtype="float32")
        y = layers.group_norm(x, groups=3)
        loss = layers.mean(y)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.randn(2, 6, 4, 4).astype(np.float32)
    out = exe.run(main, feed={"x": xv}, fetch_list=[y, loss])
    assert out[0].shape == (2, 6, 4, 4)
    assert np.isfinite(out[1]).all()


def test_batchnorm_stats_roundtrip_and_no_affine(tmp_path):
    """Running mean/variance must survive state_dict round-trips
    (code-review finding, round 2), and param_attr=False must work."""
    with dygraph.guard():
        bn = nn.BatchNorm("bn", num_channels=3)
        x = to_variable(np.random.randn(4, 3, 5, 5).astype(np.float32) + 2.0)
        bn(x)
        sd = bn.state_dict()
        stats = [k for k in sd if k.endswith(".mean") or k.endswith(".variance")]
        assert len(stats) == 2
        assert not np.allclose(sd[[k for k in stats if k.endswith(".mean")][0]], 0)

        bn2 = nn.BatchNorm("bn2", num_channels=3)
        remap = dict(zip([n for n, _ in bn2.named_parameters()], sd.values()))
        bn2.set_dict(remap)
        bn.eval(); bn2.eval()
        np.testing.assert_allclose(bn2(x).numpy(), bn(x).numpy(), rtol=1e-6)

        bn3 = nn.BatchNorm("bn3", num_channels=3, param_attr=False,
                           bias_attr=False)
        y = bn3(x)
        assert y.shape == (4, 3, 5, 5)


def test_gru_unit_without_bias():
    with dygraph.guard():
        gru = nn.GRUUnit("gru", size=3 * 8, bias_attr=False)
        xproj = to_variable(np.random.randn(2, 24).astype(np.float32))
        h0 = to_variable(np.zeros((2, 8), np.float32))
        h, _, _ = gru(xproj, h0)
        assert h.shape == (2, 8)


@pytest.mark.full
def test_dygraph_round4_layer_classes():
    """The 8 reference dygraph classes added round 4 (Conv3D,
    Conv3DTranspose, NCE, BilinearTensorProduct, SequenceConv, RowConv,
    SpectralNorm, TreeConv) run forward with finite outputs."""
    import numpy as np
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph import nn as dnn

    r = np.random.RandomState(0)
    with dygraph.guard():
        x3d = dygraph.to_variable(
            r.randn(2, 3, 4, 5, 5).astype(np.float32))
        y = dnn.Conv3D("c3", 6, 3, padding=1)(x3d)
        assert y.shape == (2, 6, 4, 5, 5)
        yt = dnn.Conv3DTranspose("c3t", 6, 3, padding=1)(x3d)
        assert yt.shape[1] == 6
        feats = dygraph.to_variable(r.randn(4, 8).astype(np.float32))
        lbl = dygraph.to_variable(r.randint(0, 10, (4, 1)).astype(np.int64))
        cost = dnn.NCE("nce", 10, num_neg_samples=3)(feats, lbl)
        assert cost.shape == (4, 1)
        yb = dnn.BilinearTensorProduct("blt", 5)(feats, feats)
        assert yb.shape == (4, 5)
        seq = dygraph.to_variable(r.randn(2, 6, 8).astype(np.float32))
        ys = dnn.SequenceConv("sc", 12, 3)(seq)
        assert ys.shape == (2, 6, 12)
        yr = dnn.RowConv("rc", 2)(seq)
        assert yr.shape == (2, 6, 8)
        w = dygraph.to_variable(r.randn(6, 8).astype(np.float32))
        wn = dnn.SpectralNorm("sn", power_iters=2)(w)
        assert wn.shape == (6, 8)
        nodes = dygraph.to_variable(r.randn(2, 6, 4).astype(np.float32))
        edges = dygraph.to_variable(np.tile(
            np.array([[1, 2], [1, 3], [0, 0]], np.int32), (2, 1, 1)))
        yt2 = dnn.TreeConv("tc", output_size=5, num_filters=2)(nodes, edges)
        assert yt2.shape == (2, 6, 5, 2)
        for v in (y, yt, cost, yb, ys, yr, wn, yt2):
            assert np.isfinite(np.asarray(v.numpy(),
                                          np.float64)).all()
