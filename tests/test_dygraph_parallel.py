"""Eager DataParallel over the virtual 8-device mesh.

Reference: dygraph/parallel.py:84 DataParallel (loss scaling + NCCL grad
all-reduce). Here parameters replicate, inputs batch-shard, and XLA
reduces the parameter cotangents across shards during the taped backward
— the wrapper's job is placement, so the acceptance test is per-step loss
parity against the unwrapped single-device eager run.
"""

import pytest
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph import DataParallel, nn, to_variable


class _MLP(dygraph.Layer):
    def __init__(self, params):
        super().__init__("dp_mlp")
        w1, b1, w2, b2 = params
        self.fc1 = nn.FC(
            "fc1", 32, act="relu",
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NumpyArrayInitializer(w1)),
            bias_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NumpyArrayInitializer(b1)),
        )
        self.fc2 = nn.FC(
            "fc2", 10,
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NumpyArrayInitializer(w2)),
            bias_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NumpyArrayInitializer(b2)),
        )

    def forward(self, x):
        return self.fc2(self.fc1(x))


def _params(seed=11):
    r = np.random.RandomState(seed)
    return (r.normal(0, 0.1, (64, 32)).astype(np.float32),
            np.zeros(32, np.float32),
            r.normal(0, 0.1, (32, 10)).astype(np.float32),
            np.zeros(10, np.float32))


def _batches(n=6, bs=32, seed=4):
    r = np.random.RandomState(seed)
    return [(r.normal(0, 1, (bs, 64)).astype(np.float32),
             r.randint(0, 10, (bs, 1)).astype(np.int64)) for _ in range(n)]


def _train(batches, wrap):
    tr = dygraph.get_tracer()
    with dygraph.guard():
        model = _MLP(_params())
        if wrap:
            model = DataParallel(model)
        optimizer = fluid.optimizer.SGD(learning_rate=0.2)
        out = []
        for x, y in batches:
            logits = model(to_variable(x))
            label = to_variable(y)
            ce = tr.trace_op(
                "softmax_with_cross_entropy",
                {"Logits": [logits], "Label": [label]}, {},
            )["Loss"][0]
            loss = tr.trace_op("mean", {"X": [ce]}, {})["Out"][0]
            if wrap:
                loss = model.scale_loss(loss)
            loss.backward()
            if wrap:
                model.apply_collective_grads()
            optimizer.minimize(loss, parameter_list=model.parameters())
            (model._layers if wrap else model).clear_gradients()
            out.append(float(loss.numpy()))
    return out


@pytest.mark.full
def test_dataparallel_matches_single_device():
    batches = _batches()
    single = _train(batches, wrap=False)
    parallel = _train(batches, wrap=True)
    np.testing.assert_allclose(single, parallel, rtol=1e-5, atol=1e-6)
    assert parallel[-1] < parallel[0]


def test_dataparallel_inputs_are_sharded():
    import jax

    with dygraph.guard():
        model = DataParallel(_MLP(_params()))
        x = model.shard_input(np.ones((32, 64), np.float32))
        sh = x._value.sharding
        assert sh.spec == jax.sharding.PartitionSpec("data")
        model(to_variable(np.ones((32, 64), np.float32)))  # build lazy params
        p = model.parameters()[0]
        assert p._value.sharding.spec == jax.sharding.PartitionSpec()
        assert model._env.nranks == 8
