"""Elastic scale-OUT (ISSUE 14): the grow half of fleet elasticity.

Before this PR the contract was shrink-only, pinned by the first two
tests below in their original form (run against the pre-change tree):

- ``plan_resize`` had no ``joins`` parameter at all — a grow spec was
  inexpressible (``TypeError: unexpected keyword argument 'joins'``)
  and a world could only ever get smaller;
- ``compile_cache.executor_spec`` DECLINED every multi-host process
  (``jax.process_count() > 1 -> None``): a joining host always paid the
  ~60x cold compile, with no disk entry even attempted.

Both asserts are now FLIPPED to the after-contract (the tentpole): a
grow spec admits joining workers with deterministic rank assignment,
and multi-host processes build disk specs keyed by the owning shard's
process index/count (local executables share entries across worlds —
what lets a gen-N+1 newcomer warm-start from gen-N's cache).

The full 4->8 grow drill (seeded, multi-process, warm-start + loss
parity) is the ``chaos``-marked test at the bottom of
tests/test_elastic_resize.py.
"""

import glob
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import compile_cache, faults, flags, monitor
from paddle_tpu.incubate.fleet.fleet_base import Fleet


@pytest.fixture(autouse=True)
def _clean():
    faults.disarm()
    yield
    faults.disarm()


# --------------------------------------------------------------------------
# before/after contract: plan_resize admits a grow spec
# --------------------------------------------------------------------------

def test_plan_resize_admits_grow_spec():
    """BEFORE: ``plan_resize(..., joins=...)`` raised TypeError (the
    parameter did not exist; the planner could only shrink). AFTER: a
    grow spec assigns joiners the ranks past the survivors, survivors
    keep relative order, and every participant derives the identical
    world from the same (dead, joins) agreement."""
    f = Fleet()
    spec = f.plan_resize((), joins=[0, 1, 2, 3], rank=2, world=4)
    assert spec["survivors"] == [0, 1, 2, 3]
    assert spec["world"] == 8 and spec["rank"] == 2
    assert spec["joiners"] == [[0, 4], [1, 5], [2, 6], [3, 7]]
    # a joiner derives ITS rank from the same agreement
    jspec = f.plan_resize((), joins=[0, 1, 2, 3], join_id=2, world=4)
    assert jspec["rank"] == 6 and jspec["world"] == 8
    assert jspec["survivors"] == spec["survivors"]
    assert jspec["joiners"] == spec["joiners"]


def test_plan_resize_grow_and_shrink_compose():
    """Replacement flow: dead workers leave AND fresh capacity joins in
    one resize — survivors first (relative order kept), joiners after."""
    f = Fleet()
    spec = f.plan_resize(["worker-1"], joins=[7], rank=2, world=4)
    assert spec["survivors"] == [0, 2, 3]
    assert spec["dead"] == [1]
    assert spec["world"] == 4 and spec["rank"] == 1
    assert spec["joiners"] == [[7, 3]]
    jspec = f.plan_resize(["worker-1"], joins=[7], join_id=7, world=4)
    assert jspec["rank"] == 3


def test_plan_resize_rejects_joiner_id_not_in_joins():
    f = Fleet()
    with pytest.raises(ValueError, match="join"):
        f.plan_resize((), joins=[0, 1], join_id=5, world=4)


def test_multihost_executor_spec_now_builds_with_owning_shard_key(
        monkeypatch, tmp_path):
    """BEFORE: ``jax.process_count() > 1`` made executor_spec return
    None unconditionally (pinned by the old
    test_multihost_and_local_fingerprints_build_no_spec) — the decline
    surfaced as a plain fresh compile with no disk entry. AFTER: a
    multi-host process whose executable only spans LOCAL devices (the
    replicated-compute fleet shape) builds a spec whose topology token
    is world-size independent, so entries stored by a 4-process
    generation warm-start an 8-process one."""
    import jax as _jax

    flags.set_flags({"compile_cache_dir": str(tmp_path / "cc")})
    try:
        main, startup = fluid.Program(), fluid.Program()
        from paddle_tpu import layers

        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4, 8], append_batch_size=False,
                            stop_gradient=True)
            out = layers.reduce_sum(x)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        feed = {"x": np.ones((4, 8), np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)

            monkeypatch.setattr(_jax, "process_count", lambda: 4)
            spec4 = compile_cache.executor_spec(
                main, feed_vals=feed, fetch_names=(out.name,), scope=scope,
                base_key=exe._base_key_for(main),
                fingerprint=compile_cache.program_fingerprint(
                    main, feed_sig=(("x", (4, 8), "float32"),),
                    fetch_names=(out.name,)))
            assert spec4 is not None, \
                "multi-host executor_spec declined (pre-ISSUE-14 contract)"
            monkeypatch.setattr(_jax, "process_count", lambda: 8)
            spec8 = compile_cache.executor_spec(
                main, feed_vals=feed, fetch_names=(out.name,), scope=scope,
                base_key=exe._base_key_for(main),
                fingerprint=compile_cache.program_fingerprint(
                    main, feed_sig=(("x", (4, 8), "float32"),),
                    fetch_names=(out.name,)))
            # local executable: the digest must NOT bake the world size —
            # this equality is exactly the 4->8 warm-start property
            assert spec8 is not None and spec8.digest == spec4.digest
            # and the real run against the spec'd cache dir round-trips
            monkeypatch.setattr(_jax, "process_count", lambda: 1)
            exe.run(main, feed=feed, fetch_list=[out])
        assert glob.glob(str(tmp_path / "cc") + "/pcc-*.bin")
    finally:
        flags.set_flags({"compile_cache_dir": ""})


def test_spmd_executor_spec_keys_on_process_index_and_count(monkeypatch):
    """A genuinely multi-host SPMD executable (state spanning
    non-addressable devices) keys on the owning shard's (process index,
    process count): rank 3's entry can never resolve as rank 5's."""
    t_local = compile_cache.topology_token()
    assert t_local[0] == "local"
    import jax as _jax

    monkeypatch.setattr(_jax, "process_count", lambda: 8)
    monkeypatch.setattr(_jax, "process_index", lambda: 3)

    # duck-typed probe: topology_token treats any non-local device in
    # the referenced set as SPMD ownership
    class _Dev:
        pass

    foreign = _Dev()
    t_spmd = compile_cache.topology_token(extra_devices={foreign})
    assert t_spmd[:3] == ("spmd", 3, 8)
    monkeypatch.setattr(_jax, "process_index", lambda: 5)
    assert compile_cache.topology_token(
        extra_devices={foreign})[:3] == ("spmd", 5, 8)


# --------------------------------------------------------------------------
# settle_joins / join_world over a stub KV (the in-process protocol half)
# --------------------------------------------------------------------------

class _StubRole:
    def __init__(self, rank, world):
        self._r, self._n = rank, world

    def worker_index(self):
        return self._r

    def worker_num(self):
        return self._n


class _StubClient:
    """In-memory coord KV stand-in (tests/test_elastic_resize.py's, plus
    delete): shared dict + lock, blocking get with timeout."""

    def __init__(self, store, lock, dead=()):
        self._store, self._lock, self._dead = store, lock, list(dead)

    def put(self, key, value):
        with self._lock:
            self._store[key] = bytes(value)

    def get(self, key, timeout_ms=-1, max_len=0):
        deadline = time.monotonic() + max(0, timeout_ms) / 1000.0
        while True:
            with self._lock:
                if key in self._store:
                    return self._store[key]
            if time.monotonic() >= deadline:
                raise TimeoutError(key)
            time.sleep(0.002)

    def heartbeat(self, worker_id):
        pass

    def dead_peers(self, max_age_ms):
        return list(self._dead)

    def delete(self, key):
        with self._lock:
            self._store.pop(key, None)

    def close(self):
        pass


def _stub_fleet(rank, world, store, lock):
    f = Fleet()
    f._role = _StubRole(rank, world)
    f._client = _StubClient(store, lock)
    f._initialized = True
    return f


def test_settle_joins_converges_on_announced_set():
    """Two survivors observe announcements landing at different times;
    settle_joins holds the stability window open until the set stops
    growing, the leader publishes, the peer adopts + acks — the same
    agreement discipline settle_dead uses for deaths."""
    store, lock = {}, threading.Lock()
    f0 = _stub_fleet(0, 2, store, lock)
    f1 = _stub_fleet(1, 2, store, lock)
    # joiner 0 announced already; joiner 1 lands mid-window
    store["fleet/join/g0/0"] = b"1"

    def _late_announce():
        time.sleep(0.03)
        with lock:
            store["fleet/join/g0/1"] = b"1"

    out = {}

    def _run(rank, fobj):
        out[rank] = fobj.settle_joins(max_age_ms=120, poll_ms=10,
                                      timeout_ms=5000, min_count=1)

    ts = [threading.Thread(target=_late_announce),
          threading.Thread(target=_run, args=(0, f0)),
          threading.Thread(target=_run, args=(1, f1))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert out == {0: [0, 1], 1: [0, 1]}
    assert store["fleet/resize/joins/g0"] == b"0,1"
    assert store["fleet/resize/jsack/g0/1"] == b"1"


def test_pending_joins_probes_contiguous_slots():
    store, lock = {}, threading.Lock()
    f = _stub_fleet(0, 2, store, lock)
    assert f.pending_joins() == []
    store["fleet/join/g0/0"] = b"1"
    store["fleet/join/g0/1"] = b"1"
    assert f.pending_joins() == [0, 1]
    # known ids are reported without re-probing (settle_joins'
    # accumulated set keeps each poll tick under the 64-slot scan)
    assert f.pending_joins(known=[0]) == [0, 1]


def test_settle_joins_composed_with_dead_uses_surviving_leader():
    """The composed shrink+grow resize: settle_joins(dead=) derives
    the leader and the ack set from the SURVIVORS. With rank 0 dead,
    rank 1 leads (publishes, collects rank 2's ack) — a dead rank is
    never waited on, so replacement-in-one-resize completes instead of
    timing out against acks nobody will write."""
    store, lock = {}, threading.Lock()
    store["fleet/join/g0/3"] = b"1"
    dead = ["worker-0"]
    f1 = _stub_fleet(1, 3, store, lock)
    f2 = _stub_fleet(2, 3, store, lock)
    out = {}

    def _run(rank, fobj):
        out[rank] = fobj.settle_joins(max_age_ms=60, poll_ms=10,
                                      timeout_ms=5000, min_count=1,
                                      dead=dead)

    ts = [threading.Thread(target=_run, args=(1, f1)),
          threading.Thread(target=_run, args=(2, f2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert out == {1: [3], 2: [3]}
    assert store["fleet/resize/joins/g0"] == b"3"
    assert store["fleet/resize/jsack/g0/2"] == b"1"
    assert "fleet/resize/jsack/g0/0" not in store  # dead: never awaited
    # and the composed plan seats the joiner after the survivors
    spec = f1.plan_resize(dead, joins=out[1], rank=1, world=3)
    assert spec == {"survivors": [1, 2], "rank": 0, "world": 3,
                    "dead": [0], "joiners": [[3, 2]]}


def test_join_world_announce_plan_ack_roundtrip():
    """The newcomer half: announce under the generation key, wait for
    the leader's published plan, ack, return the spec (with the
    recovery endpoints and the newcomer's assigned rank)."""
    store, lock = {}, threading.Lock()
    monitor.enable()
    # the running world published its generation at init (join_world
    # blocks on this key, bounded, before announcing)
    store["fleet/generation"] = b"0"
    leader = _stub_fleet(0, 4, store, lock)
    plan = leader.plan_resize((), joins=[0], rank=0, world=4)
    joins_before = monitor.histogram("pt_fleet_join_seconds").count()

    newcomer = Fleet()
    newcomer._role = _StubRole(0, 1)

    def _leader_side():
        # wait for the announce, then publish the plan like the drill's
        # leader does (publish_join_plan waits for the joiner acks)
        c = _StubClient(store, lock)
        c.get("fleet/join/g0/0", timeout_ms=5000)
        leader.publish_join_plan(
            plan, coord_endpoint="127.0.0.1:9999",
            jax_endpoint="127.0.0.1:9998", timeout_ms=5000)

    t = threading.Thread(target=_leader_side)
    t.start()
    spec = newcomer.join_world(
        "stub", join_id=0, timeout_ms=5000,
        _client=_StubClient(store, lock))
    t.join(10)
    assert spec["rank"] == 4 and spec["world"] == 5
    assert spec["coord_endpoint"] == "127.0.0.1:9999"
    assert spec["jax_endpoint"] == "127.0.0.1:9998"
    assert spec["gen"] == 1
    assert store["fleet/resize/jack/g0/0"] == b"1"
    assert monitor.histogram(
        "pt_fleet_join_seconds").count() == joins_before + 1


def test_fleet_join_fault_site_tears_the_admission():
    """Chaos plans tear admissions at the fleet.join site: the announce
    raises, nothing is published, the injection is metered."""
    monitor.enable()
    store, lock = {}, threading.Lock()
    store["fleet/generation"] = b"0"
    newcomer = Fleet()
    newcomer._role = _StubRole(0, 1)
    inj0 = monitor.counter("pt_fault_injected_total").value(
        labels={"site": "fleet.join"})
    faults.arm("fleet.join:raise@1")
    with pytest.raises(faults.InjectedFault):
        newcomer.join_world("stub", join_id=0, timeout_ms=100,
                            _client=_StubClient(store, lock))
    faults.disarm()
    assert monitor.counter("pt_fault_injected_total").value(
        labels={"site": "fleet.join"}) == inj0 + 1
    assert "fleet/join/g0/0" not in store


def test_join_world_rejects_out_of_range_slot():
    """An announce outside the probed slot range would be a silent
    deterministic hang (pending_joins never sees it) — reject it
    loudly instead."""
    f = Fleet()
    for bad in (-1, 64, 1000):
        with pytest.raises(ValueError, match="join_id"):
            f.join_world("stub", join_id=bad, timeout_ms=50,
                         _client=_StubClient({}, threading.Lock()))


def test_pending_joins_surfaces_connection_failure():
    """A broken coord connection must not read as 'no joiners
    announced' — settle_joins would agree on an EMPTY set and bump the
    generation while the announced joiners hang. TimeoutError (slot
    absent) is the expected answer; other OSErrors propagate."""

    class _Broken:
        def get(self, key, timeout_ms=0, max_len=0):
            raise ConnectionResetError("coord connection died")

    f = Fleet()
    f._role = _StubRole(0, 2)
    f._client = _Broken()
    f._initialized = True
    with pytest.raises(ConnectionResetError):
        f.pending_joins()


# --------------------------------------------------------------------------
# reexec env completeness for a grown world (the satellite bugfix)
# --------------------------------------------------------------------------

def test_reexec_resized_grow_env_is_complete_for_newcomers(monkeypatch):
    """The shrink-only env assembly leaked generation-N endpoints into
    generation N+1: a newcomer that announced against the OLD world
    inherited a stale PT_JAX_COORD_ENDPOINT (the dead generation's PJRT
    coordinator) whenever the caller passed none, and its PT_GEN
    derived from its own (zero) generation instead of the plan's. The
    grow spec's env must be complete and self-consistent: rank/world
    from the spec, endpoints from the plan, stale inherited vars
    scrubbed."""
    import paddle_tpu.incubate.fleet.fleet_base as fb

    calls = {}
    monkeypatch.setattr(
        fb._os, "execve",
        lambda exe, args, env: calls.update(exe=exe, args=args, env=env))
    monkeypatch.setattr(fb._sys, "argv", ["/work/train.py"])
    # the newcomer's inherited env points at the OLD world
    monkeypatch.setenv("PT_JAX_COORD_ENDPOINT", "10.0.0.1:555")
    monkeypatch.setenv("PT_TRAINER_ID", "0")
    monkeypatch.setenv("PT_TRAINERS", "1")

    f = Fleet()
    spec = f.plan_resize((), joins=[0, 1, 2, 3], join_id=1, world=4)
    spec["gen"] = 1
    f.reexec_resized(spec, coord_endpoint="127.0.0.1:7777")
    env = calls["env"]
    assert env["PT_TRAINER_ID"] == "5" and env["PT_TRAINERS"] == "8"
    assert env["PT_COORD_ENDPOINT"] == "127.0.0.1:7777"
    assert env["PT_GEN"] == "1"  # the plan's generation, not ours+1
    # the stale jax coordinator must NOT survive into the new world
    assert "PT_JAX_COORD_ENDPOINT" not in env
    # explicit endpoint still lands
    f2 = Fleet()
    f2.reexec_resized(dict(spec), coord_endpoint="127.0.0.1:7777",
                      jax_endpoint="127.0.0.1:7778")
    assert calls["env"]["PT_JAX_COORD_ENDPOINT"] == "127.0.0.1:7778"


def test_reexec_resized_meters_direction():
    """pt_fleet_resizes_total now carries the direction label; the
    verdict derives from the SPEC through the one resize_direction
    helper (grow = the resize admits joiners, per the metric's doc —
    a composed replacement that loses as many ranks as it admits is
    still an admission event), so survivors and joiners meter
    identically."""
    from paddle_tpu.incubate.fleet.fleet_base import resize_direction

    f0 = Fleet()
    assert resize_direction(
        f0.plan_resize(["worker-1"], joins=[7], rank=0, world=4)) == \
        "grow"  # replacement-in-one-resize admits a joiner
    assert resize_direction(
        f0.plan_resize(["worker-1"], rank=0, world=4)) == "shrink"
    import paddle_tpu.incubate.fleet.fleet_base as fb

    monitor.enable()

    class _NoExec:
        @staticmethod
        def execve(exe, args, env):
            pass

    orig = fb._os.execve
    fb._os.execve = _NoExec.execve
    try:
        f = Fleet()
        g0 = monitor.counter("pt_fleet_resizes_total").value(
            labels={"direction": "grow"})
        s0 = monitor.counter("pt_fleet_resizes_total").value(
            labels={"direction": "shrink"})
        f.reexec_resized(f.plan_resize((), joins=[0], rank=0, world=2),
                         coord_endpoint="127.0.0.1:1")
        f.reexec_resized(f.plan_resize([1], rank=0, world=2),
                         coord_endpoint="127.0.0.1:1")
        assert monitor.counter("pt_fleet_resizes_total").value(
            labels={"direction": "grow"}) == g0 + 1
        assert monitor.counter("pt_fleet_resizes_total").value(
            labels={"direction": "shrink"}) == s0 + 1
    finally:
        fb._os.execve = orig


# --------------------------------------------------------------------------
# /fleet: joining ranks transition missing -> alive (in-process)
# --------------------------------------------------------------------------

def test_fleet_view_joining_ranks_transition_missing_to_alive():
    """The grown world's cluster view before the newcomers' first
    digest publish names them ``missing``; after they publish they are
    alive rows — the /fleet transition the drill watches."""
    from paddle_tpu import fleet_monitor

    flags.set_flags({"telemetry": True, "fleet_metrics_interval_ms": 0})
    try:
        store, lock = {}, threading.Lock()

        class _F:
            _client = _StubClient(store, lock)
            _role = None

            def generation(self):
                return 1

            def worker_num(self):
                return 8

        for r in range(4):  # survivors published; joiners not yet
            d = fleet_monitor.registry_digest(rank=r, world=8, gen=1)
            store[f"fleet/metrics/g1/{r}"] = json.dumps(d).encode()
        view = fleet_monitor.aggregate(_F())
        assert view["missing"] == [4, 5, 6, 7]
        for r in range(4, 8):  # the newcomers' first publish lands
            d = fleet_monitor.registry_digest(rank=r, world=8, gen=1)
            store[f"fleet/metrics/g1/{r}"] = json.dumps(d).encode()
        view = fleet_monitor.aggregate(_F())
        assert view["missing"] == []
        assert set(view["ranks"]) == {str(r) for r in range(8)}
        assert view["dead"] == []
    finally:
        flags.set_flags({"telemetry": False,
                         "fleet_metrics_interval_ms": 1000})
        fleet_monitor.reset()
