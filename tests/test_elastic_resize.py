"""Elastic fleet resize (ISSUE 7): plan_resize spec derivation, the
fleet.resize chaos site, and the end-to-end 8->4 shrink drill — kill
half the world mid-training via a SEEDED fault plan, survivors
re-rendezvous as a 4-worker generation, restore the newest valid
checkpoint (committed by the 8-writer world through the coordinated
commit barrier) and finish, with loss parity against an uninterrupted
single-process run. (Worker compute is replicated — see
fleet_resize_worker.py's docstring for why, and test_checkpoint.py's
mesh matrix for the sharded cross-topology restore proof.)

The multi-process drill is `chaos`-marked: deterministic but expensive
(8 subprocesses + re-exec), deselected from the tier-1 smoke gate; run
with `-m chaos`."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import faults, monitor
from paddle_tpu.incubate.fleet.fleet_base import Fleet

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _chaos_clean():
    faults.disarm()
    yield
    faults.disarm()


# --------------------------------------------------------------------------
# plan_resize: the survivors' agreement function (pure, rank-overridable)
# --------------------------------------------------------------------------

def test_plan_resize_survivors_keep_relative_order():
    f = Fleet()
    spec = f.plan_resize(["worker-3"], rank=1, world=4)
    assert spec == {"survivors": [0, 1, 2], "rank": 1, "world": 3,
                    "dead": [3]}
    # every survivor derives the identical world from the same dead set
    specs = [f.plan_resize(["worker-3"], rank=r, world=4) for r in (0, 1, 2)]
    assert [s["rank"] for s in specs] == [0, 1, 2]
    assert all(s["survivors"] == [0, 1, 2] and s["world"] == 3
               for s in specs)


def test_plan_resize_8_to_4_shrink_spec():
    f = Fleet()
    dead = [f"worker-{r}" for r in (4, 5, 6, 7)]
    spec = f.plan_resize(dead, rank=2, world=8)
    assert spec == {"survivors": [0, 1, 2, 3], "rank": 2, "world": 4,
                    "dead": [4, 5, 6, 7]}


def test_plan_resize_accepts_plain_ranks_and_rejects_dead_self():
    f = Fleet()
    spec = f.plan_resize([0, 2], rank=1, world=4)
    assert spec["survivors"] == [1, 3] and spec["rank"] == 0
    # string plain ranks too: settle_dead's client-less fallback
    # stringifies whatever it was fed, and that output feeds here
    assert f.plan_resize(["0", "2"], rank=1, world=4) == spec
    with pytest.raises(ValueError, match="dead set"):
        f.plan_resize([1], rank=1, world=4)


def test_fleet_resize_fault_site_tears_the_decision():
    """Chaos plans can fail the resize step itself (a survivor dying
    DURING recovery), metered like every injection."""
    monitor.enable()
    f = Fleet()
    inj0 = monitor.counter("pt_fault_injected_total").value(
        labels={"site": "fleet.resize"})
    faults.arm("fleet.resize:raise@1")
    with pytest.raises(faults.InjectedFault):
        f.plan_resize(["worker-3"], rank=0, world=4)
    faults.disarm()
    assert monitor.counter("pt_fault_injected_total").value(
        labels={"site": "fleet.resize"}) == inj0 + 1
    # disarmed: the same call is the plain decision again
    assert f.plan_resize(["worker-3"], rank=0, world=4)["world"] == 3


def test_reexec_resized_preserves_command_line(monkeypatch):
    """Generation N+1 re-runs with the SAME flags as generation N — a
    job launched `python train.py --lr 0.01` must not restart with
    default hyperparameters. (execve is stubbed: the subject is the
    argv/env the re-exec would carry, not the process replacement.)"""
    import paddle_tpu.incubate.fleet.fleet_base as fb

    calls = {}
    monkeypatch.setattr(
        fb._os, "execve",
        lambda exe, args, env: calls.update(exe=exe, args=args, env=env))
    monkeypatch.setattr(
        fb._sys, "argv", ["/work/train.py", "--lr", "0.01", "--cfg", "p.yml"])
    f = Fleet()
    spec = f.plan_resize(["worker-3"], rank=1, world=4)
    f.reexec_resized(spec, coord_endpoint="127.0.0.1:1234")
    assert calls["args"][1:] == ["/work/train.py", "--lr", "0.01",
                                 "--cfg", "p.yml"]
    assert calls["env"]["PT_TRAINER_ID"] == "1"
    assert calls["env"]["PT_TRAINERS"] == "3"
    assert calls["env"]["PT_GEN"] == "1"
    # explicit argv overrides the inherited command line
    f2 = Fleet()
    f2.reexec_resized(spec, coord_endpoint="127.0.0.1:1234",
                      script="/work/other.py", argv=["--resumed"])
    assert calls["args"][1:] == ["/work/other.py", "--resumed"]


# --------------------------------------------------------------------------
# settle_dead: survivors with DIVERGENT partial views agree on one set
# --------------------------------------------------------------------------

class _StubRole:
    def __init__(self, rank, world):
        self._r, self._n = rank, world

    def worker_index(self):
        return self._r

    def worker_num(self):
        return self._n


class _StubClient:
    """In-memory stand-in for the coord KV client: shared store + a
    fixed dead-peer answer, enough to drive settle_dead's poll/publish/
    ack protocol deterministically in one process."""

    def __init__(self, store, lock, dead):
        self._store, self._lock, self._dead = store, lock, dead

    def put(self, key, value):
        with self._lock:
            self._store[key] = bytes(value)

    def get(self, key, timeout_ms=-1, max_len=0):
        import time as _t
        deadline = _t.monotonic() + max(0, timeout_ms) / 1000.0
        while True:
            with self._lock:
                if key in self._store:
                    return self._store[key]
            if _t.monotonic() >= deadline:
                raise TimeoutError(key)
            _t.sleep(0.002)

    def heartbeat(self, worker_id):
        pass

    def dead_peers(self, max_age_ms):
        return list(self._dead)


def _stub_fleet(rank, world, store, lock, dead):
    f = Fleet()
    f._role = _StubRole(rank, world)
    f._client = _StubClient(store, lock, dead)
    f._initialized = True
    return f


def test_settle_dead_repairs_divergent_partial_views():
    """Two survivors of the same 4-worker crash observed DIFFERENT
    partial dead sets (liveness is not atomic); settle_dead converges
    both on the full set — leader publishes, peer adopts and acks — so
    plan_resize derives the SAME world on every survivor."""
    import threading
    store, lock = {}, threading.Lock()
    dead = ["worker-2", "worker-3"]
    f0 = _stub_fleet(0, 4, store, lock, dead)
    f1 = _stub_fleet(1, 4, store, lock, dead)
    out = {}

    def _run(rank, fleet_obj, observed):
        out[rank] = list(fleet_obj.settle_dead(
            observed, max_age_ms=80, poll_ms=10, timeout_ms=5000))

    ts = [threading.Thread(target=_run, args=(0, f0, ["worker-2"])),
          threading.Thread(target=_run, args=(1, f1, dead))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert out == {0: dead, 1: dead}
    assert store["fleet/resize/dead/g0"] == b"worker-2,worker-3"
    assert store["fleet/resize/ack/g0/1"] == b"1"
    specs = [f.plan_resize(out[r], rank=r, world=4)
             for r, f in ((0, f0), (1, f1))]
    assert [s["world"] for s in specs] == [2, 2]
    assert [s["rank"] for s in specs] == [0, 1]


def test_settle_dead_without_client_passes_observed_through():
    f = Fleet()
    assert f.settle_dead(["worker-1", "worker-0"]) == \
        ["worker-0", "worker-1"]


def test_settle_dead_all_stale_raises():
    import threading
    store, lock = {}, threading.Lock()
    dead = [f"worker-{r}" for r in range(2)]
    f = _stub_fleet(0, 2, store, lock, dead)
    with pytest.raises(ValueError, match="every rank is stale"):
        f.settle_dead(dead, max_age_ms=30, poll_ms=10, timeout_ms=500)


# --------------------------------------------------------------------------
# the multi-process shrink drill (ISSUE 7 acceptance)
# --------------------------------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_losses():
    sys.path.insert(0, HERE)
    try:
        import fleet_resize_worker as fw
    finally:
        sys.path.pop(0)
    main, startup, loss, _opt = fw.build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        out = []
        for x, y in fw.global_batches():
            out.append(float(
                exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[loss])[0]))
    return out


@pytest.mark.chaos
def test_fleet_8_to_4_shrink_restores_and_finishes(tmp_path):
    from paddle_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    n, kill_ranks, kill_step = 8, (4, 5, 6, 7), 2
    env_base = {
        **os.environ,
        "PT_TRAINERS": str(n),
        "PT_COORD_ENDPOINT": f"127.0.0.1:{_free_port()}",
        "PT_JAX_COORD_ENDPOINT": f"127.0.0.1:{_free_port()}",
        "PT_RECOVER_PORT": str(_free_port()),
        "PT_RECOVER_JAX_PORT": str(_free_port()),
        "PT_CKPT_DIR": str(tmp_path / "ckpt"),
        "JAX_PLATFORMS": "",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE), os.environ.get("PYTHONPATH", "")]
        ),
    }
    os.makedirs(tmp_path / "ckpt", exist_ok=True)
    procs = []
    for rank in range(n):
        env = {**env_base, "PT_TRAINER_ID": str(rank)}
        if rank in kill_ranks:
            # the SEEDED kill: a fault plan, not test scaffolding — the
            # same plan string replays the same crash (hit kill_step+1
            # of the per-step site = the start of step kill_step)
            env["PT_FLAGS_fault_plan"] = \
                f"elastic.step:raise@{kill_step + 1}"
            env["PT_FLAGS_fault_seed"] = "7"
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "fleet_resize_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        ))
    results = {}
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        if rank in kill_ranks:
            assert p.returncode == 1, \
                f"victim {rank} should have died abruptly:\n{out}\n{err}"
            continue
        assert p.returncode == 0, f"worker {rank} failed:\n{out}\n{err}"
        line = [l for l in out.splitlines()
                if l.startswith("FLEET_RESULT ")]
        assert line, f"no result line from worker {rank}:\n{out}\n{err}"
        results[rank] = json.loads(line[-1][len("FLEET_RESULT "):])

    assert set(results) == {0, 1, 2, 3}
    single = _single_process_losses()
    for r in results.values():
        # every survivor re-rendezvoused at the shrunk world and resumed
        # from the newest valid 8-world checkpoint
        assert r["gen"] == 1 and r["world"] == 4
        assert r["start_step"] == kill_step
        assert sorted(r["dead_seen"]) == [
            f"worker-{k}" for k in kill_ranks]
        np.testing.assert_allclose(r["losses"], single[kill_step:],
                                   rtol=1e-4, atol=1e-5)
    assert results[0]["losses"][-1] < single[0]  # learning resumed


# --------------------------------------------------------------------------
# the multi-process GROW drill (ISSUE 14 acceptance): 4 -> 8 mid-run,
# newcomers warm-start from the compile-cache disk tier (zero fresh
# compiles on rejoin), optimizer slot state reshards, loss parity
# --------------------------------------------------------------------------

@pytest.mark.chaos
def test_fleet_4_to_8_grow_warm_starts_and_matches_loss(tmp_path):
    from paddle_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    n0, n_join, grow_step = 4, 4, 2
    coord_ep = f"127.0.0.1:{_free_port()}"
    env_base = {
        **os.environ,
        "PT_TRAINERS": str(n0),
        "PT_COORD_ENDPOINT": coord_ep,
        "PT_JAX_COORD_ENDPOINT": f"127.0.0.1:{_free_port()}",
        "PT_RECOVER_PORT": str(_free_port()),
        "PT_RECOVER_JAX_PORT": str(_free_port()),
        "PT_CKPT_DIR": str(tmp_path / "ckpt"),
        # the warm-start tier every generation shares: incumbents
        # populate it cold in generation 0, EVERYONE (newcomers
        # included) must resolve from it in generation 1 (telemetry on
        # so the workers' hit/miss accounting actually counts)
        "PT_FLAGS_compile_cache_dir": str(tmp_path / "ccache"),
        "PT_FLAGS_telemetry": "true",
        # coordination-only fleet: this container's CPU jax cannot form
        # a cross-process XLA world anyway (compute is replicated), and
        # single-process jax gives every rank the SAME device identity
        # — the condition (one shared local executable, the TPU-SPMD
        # same-global-program analog) under which newcomers can
        # warm-start incumbents' cache entries
        "PT_COORD_ONLY": "1",
        "JAX_PLATFORMS": "",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE), os.environ.get("PYTHONPATH", "")]
        ),
    }
    os.makedirs(tmp_path / "ckpt", exist_ok=True)
    procs = []
    for rank in range(n0):  # the generation-0 incumbents
        env = {**env_base, "PT_TRAINER_ID": str(rank),
               "PT_GROW_AT_STEP": str(grow_step),
               "PT_EXPECT_JOINERS": str(n_join)}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "fleet_resize_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        ))
    join_procs = []
    for j in range(n_join):  # the newcomers: announce + wait for plan
        env = {**env_base, "PT_JOIN_ID": str(j),
               "PT_JOIN_TARGET": coord_ep}
        env.pop("PT_TRAINER_ID", None)
        join_procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "fleet_resize_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        ))

    def _collect(p, who):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"{who} failed:\n{out}\n{err}"
        return out, err

    results, resize_plans, join_results = {}, [], []
    for rank, p in enumerate(procs):
        out, _err = _collect(p, f"incumbent {rank}")
        plan = [l for l in out.splitlines()
                if l.startswith("RESIZE_PLAN ")]
        assert plan, f"incumbent {rank} never planned the grow:\n{out}"
        resize_plans.append(json.loads(plan[-1][len("RESIZE_PLAN "):]))
        line = [l for l in out.splitlines()
                if l.startswith("FLEET_RESULT ")]
        assert line, f"no result line from incumbent {rank}:\n{out}"
        r = json.loads(line[-1][len("FLEET_RESULT "):])
        results[r["rank"]] = r
    for j, p in enumerate(join_procs):
        out, _err = _collect(p, f"joiner {j}")
        jline = [l for l in out.splitlines()
                 if l.startswith("JOIN_RESULT ")]
        assert jline, f"joiner {j} never admitted:\n{out}"
        join_results.append(json.loads(jline[-1][len("JOIN_RESULT "):]))
        line = [l for l in out.splitlines()
                if l.startswith("FLEET_RESULT ")]
        assert line, f"no result line from joiner {j}:\n{out}"
        r = json.loads(line[-1][len("FLEET_RESULT "):])
        results[r["rank"]] = r

    # every participant reached generation 1 of the 8-world
    assert set(results) == set(range(n0 + n_join))
    # every incumbent derived the SAME grow plan (direction metered)
    assert all(pl["direction"] == "grow" and pl["world"] == 8
               and pl["joins"] == [0, 1, 2, 3] for pl in resize_plans)
    # joiners were assigned the ranks after the survivors, and the
    # join-latency histogram observed each admission
    assert sorted(jr["rank"] for jr in join_results) == [4, 5, 6, 7]
    assert all(jr["join_latency_s"] >= 0 for jr in join_results)

    single = _single_process_losses()
    for r in results.values():
        assert r["gen"] == 1 and r["world"] == 8
        assert r["start_step"] == grow_step
        # THE warm-start acceptance: generation 1 resolved every
        # executable from the disk tier — zero fresh compiles on rejoin
        assert r["ccache"]["misses"] == 0, r
        assert r["ccache"]["hits"] >= 2, r  # startup + train step
        assert all(v == 0 for v in r["ccache"]["errors"].values()), r
        # loss parity vs the uninterrupted run: parameters AND Momentum
        # velocity state survived the grow (a dropped velocity diverges
        # the very first resumed step)
        np.testing.assert_allclose(r["losses"], single[grow_step:],
                                   rtol=1e-4, atol=1e-5)
    assert results[0]["losses"][-1] < single[0]  # learning resumed
