"""Executor compile-cache accounting: exact hit/miss/eviction counts
(pt_executor_cache_* counters) and the ``executor_cache_capacity``
eviction policy — previously untested."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers, monitor


@pytest.fixture(autouse=True)
def _clean():
    monitor.reset()
    flags.set_flags({"telemetry": True, "executor_cache_capacity": 0})
    yield
    monitor.reset()
    flags.set_flags({"telemetry": False, "executor_cache_capacity": 0})


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8], append_batch_size=False,
                        stop_gradient=True)
        h = layers.fc(x, 4)
        loss = layers.mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _counts():
    return (
        monitor.counter("pt_executor_cache_hits_total").value(),
        monitor.counter("pt_executor_cache_misses_total").value(),
        monitor.counter("pt_executor_cache_evictions_total").value(),
    )


def _feed(batch=4):
    return {"x": np.ones((batch, 8), np.float32)}


def test_hit_miss_counts_exact_across_repeated_runs():
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)                       # miss 1
        assert _counts() == (0, 1, 0)
        for i in range(4):                     # miss 2, then 3 hits
            exe.run(main, feed=_feed(), fetch_list=[loss])
        assert _counts() == (3, 2, 0)
        # a different fetch list is a different compiled program
        exe.run(main, feed=_feed(), fetch_list=[])      # miss 3
        assert _counts() == (3, 3, 0)
        exe.run(main, feed=_feed(), fetch_list=[])      # hit 4
        exe.run(main, feed=_feed(), fetch_list=[loss])  # hit 5
        assert _counts() == (5, 3, 0)
        # use_program_cache=False bypasses the cache: no counter movement
        exe.run(main, feed=_feed(), fetch_list=[loss],
                use_program_cache=False)
        assert _counts() == (5, 3, 0)


def test_capacity_eviction_fires_and_is_counted():
    main, startup, loss = _build()
    flags.set_flags({"executor_cache_capacity": 1})
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)                       # miss; cache = {startup}
        assert len(exe._cache) == 1
        # miss; evicts startup (capacity 1)
        exe.run(main, feed=_feed(), fetch_list=[loss])
        assert len(exe._cache) == 1
        assert _counts() == (0, 2, 1)
        # still cached: hit, no eviction
        exe.run(main, feed=_feed(), fetch_list=[loss])
        assert _counts() == (1, 2, 1)
        # alternate between two signatures at capacity 1: every run
        # recompiles and evicts the other — the thrash eviction exists
        # to make visible
        for _ in range(2):
            exe.run(main, feed=_feed(), fetch_list=[])
            exe.run(main, feed=_feed(), fetch_list=[loss])
        assert _counts() == (1, 6, 5)
        assert len(exe._cache) == 1


def test_capacity_eviction_clears_owned_feed_staging_entries():
    """Evicting a run_steps entry at capacity also drops the staged
    feed windows it owns in the keyed LRU — stale staging would pin
    whole device-resident feed windows after the compiled entry is gone
    (and could never hit again without its entry). A victim that is NOT
    an owner leaves other stagings alone."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    frozen = np.arange(32, dtype=np.float32).reshape(4, 8).copy()
    frozen.flags.writeable = False
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_steps(main, feed_list=[{"x": frozen}], steps=2,
                      fetch_list=[loss])
        assert len(exe._staged) == 1
        assert next(iter(exe._staged.values()))["owner"] is not None
        # shrink to capacity 1; the next insert (a fresh run signature)
        # evicts both older entries, including the staging owner — the
        # staged window must go with it
        flags.set_flags({"executor_cache_capacity": 1})
        exe.run(main, feed=_feed(), fetch_list=[loss])
        assert len(exe._staged) == 0
        # at capacity 2 with the window entry RECENT, evicting the
        # older run() entry does not touch the window's staging
        flags.set_flags({"executor_cache_capacity": 2})
        exe.run_steps(main, feed_list=[{"x": frozen}], steps=2,
                      fetch_list=[loss])  # cache: {run, window}
        assert len(exe._staged) == 1
        exe.run(main, feed=_feed(), fetch_list=[])  # evicts the run entry
        assert len(exe._staged) == 1
        assert len(exe._cache) == 2
        exe.close()  # close drops staging with the entries
        assert len(exe._staged) == 0


def test_staged_window_lru_keeps_alternating_rotations():
    """The keyed staging LRU holds several feed rotations at once:
    alternating windows A/B/A/B must both stay staged (the old
    single-slot cache thrashed on exactly this pattern), and the LRU
    cap bounds how many device-resident windows can accumulate."""
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())

    def frozen(seed):
        a = np.random.RandomState(seed).randn(4, 8).astype(np.float32)
        a.flags.writeable = False
        return a

    wa, wb = {"x": frozen(0)}, {"x": frozen(1)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_steps(main, feed_list=[wa], steps=1, fetch_list=[loss])
        exe.run_steps(main, feed_list=[wb], steps=1, fetch_list=[loss])
        assert len(exe._staged) == 2
        staged_a = [e["stacked"]["x"] for e in exe._staged.values()]
        # both rotations hit their staged windows on the second pass
        exe.run_steps(main, feed_list=[wa], steps=1, fetch_list=[loss])
        exe.run_steps(main, feed_list=[wb], steps=1, fetch_list=[loss])
        assert [e["stacked"]["x"] for e in exe._staged.values()] \
            == staged_a
        # the cap bounds device pinning: distinct rotations beyond
        # capacity evict the coldest
        for seed in range(2, 2 + exe.STAGED_WINDOW_CAPACITY):
            exe.run_steps(main, feed_list=[{"x": frozen(seed)}], steps=1,
                          fetch_list=[loss])
        assert len(exe._staged) == exe.STAGED_WINDOW_CAPACITY


def test_failing_step_still_logs_a_record(tmp_path):
    """A raising step (here: NaN scan) must still append its step-log
    record — the crashed step is the record a postmortem needs."""
    import json

    path = tmp_path / "s.jsonl"
    flags.set_flags({"step_log_path": str(path), "check_nan_inf": True})
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed=_feed(), fetch_list=[loss])
            with pytest.raises(FloatingPointError):
                exe.run(main,
                        feed={"x": np.full((4, 8), np.nan, np.float32)},
                        fetch_list=[loss])
    finally:
        flags.set_flags({"check_nan_inf": False, "step_log_path": ""})
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    for r in recs:
        monitor.validate_step_record(r)
    assert len(recs) == 3
    assert recs[1]["nan_check"] == "ok"
    assert recs[2]["nan_check"] == "fail" and recs[2]["wall_ms"] > 0


def test_lru_refresh_keeps_hot_entry():
    main, startup, loss = _build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(), fetch_list=[loss])
        exe.run(main, feed=_feed(), fetch_list=[])
        # touch the loss entry so it is the most recent...
        exe.run(main, feed=_feed(), fetch_list=[loss])
        assert len(exe._cache) == 3
        # ...then shrink capacity to 2; eviction fires on the next INSERT
        # (a fresh signature), dropping the two coldest (startup and the
        # fetch-less entry) and never the refreshed hot entry
        flags.set_flags({"executor_cache_capacity": 2})
        monitor.reset()
        exe.run(main, feed={"x": np.ones((8, 8), np.float32)},
                fetch_list=[loss])  # new batch size: miss + insert
        assert len(exe._cache) == 2
        assert monitor.counter(
            "pt_executor_cache_evictions_total").value() == 2
        # the hot (loss-fetching) entry survived: running it again is
        # a hit, not a recompile
        before = monitor.counter("pt_executor_cache_misses_total").value()
        exe.run(main, feed=_feed(), fetch_list=[loss])
        assert monitor.counter(
            "pt_executor_cache_misses_total").value() == before
        assert monitor.counter(
            "pt_executor_cache_hits_total").value() == 1
