"""Chaos suite: deterministic fault injection (paddle_tpu/faults.py).

Acceptance (ISSUE 5): seeded plans replay exactly, sites arm/disarm
live via the ``fault_plan`` flag, every injection is metered, and the
disabled path allocates nothing (tracemalloc proof, like PRs 1-4)."""

import time
import tracemalloc

import pytest

import paddle_tpu as fluid  # noqa: F401 — registers all builtin sites
from paddle_tpu import faults, flags, monitor


@pytest.fixture(autouse=True)
def _clean():
    faults.disarm()
    monitor.reset()
    yield
    faults.disarm()
    flags.set_flags({"fault_plan": "", "telemetry": False})


# --------------------------------------------------------------------------
# plan parsing
# --------------------------------------------------------------------------

def test_plan_parses_all_action_forms():
    faults.arm("s1:raise@1;s2:raise(boom)@2;s3:delay(0.01)@1,3;"
               "s4:truncate(16)@1;s5:raise@p0.5", seed=0)
    assert faults.active()


@pytest.mark.parametrize("bad", [
    "no_colon@1", "s:frobnicate@1", "s:raise", "s:raise@",
])
def test_bad_plan_entries_raise(bad):
    with pytest.raises(ValueError):
        faults.arm(bad)


def test_empty_plan_means_disarmed():
    faults.arm("")
    assert not faults.active()


# --------------------------------------------------------------------------
# Nth-hit determinism
# --------------------------------------------------------------------------

def test_raise_fires_at_exactly_the_nth_hit():
    faults.arm("det.site:raise@3")
    s = faults.site("det.site")
    s.hit()
    s.hit()
    with pytest.raises(faults.InjectedFault) as ei:
        s.hit()
    assert ei.value.site == "det.site" and ei.value.hit == 3
    s.hit()  # fires ONLY at the 3rd
    assert [r["hit"] for r in faults.records()] == [3]


def test_multiple_triggers_and_message():
    faults.arm("m.site:raise(kaboom)@1,3")
    s = faults.site("m.site")
    with pytest.raises(faults.InjectedFault, match="kaboom"):
        s.hit()
    s.hit()
    with pytest.raises(faults.InjectedFault):
        s.hit()


def test_delay_action_sleeps():
    faults.arm("slow.site:delay(0.05)@2")
    s = faults.site("slow.site")
    t0 = time.perf_counter()
    s.hit()
    fast = time.perf_counter() - t0
    t0 = time.perf_counter()
    s.hit()
    slow = time.perf_counter() - t0
    assert slow >= 0.05 > fast


def test_truncate_action_tears_the_file(tmp_path):
    p = tmp_path / "payload.bin"
    p.write_bytes(b"x" * 100)
    faults.arm("torn.site:truncate(7)@1")
    faults.site("torn.site").hit(path=str(p))
    assert p.stat().st_size == 7
    # a hit with no path safely skips truncation
    faults.site("torn.site").hit()


# --------------------------------------------------------------------------
# seeded probabilistic plans replay exactly
# --------------------------------------------------------------------------

def _fire_pattern(seed, n=200):
    faults.arm("p.site:raise@p0.3", seed=seed)
    s = faults.site("p.site")
    pattern = []
    for _ in range(n):
        try:
            s.hit()
            pattern.append(0)
        except faults.InjectedFault:
            pattern.append(1)
    return pattern


def test_seeded_probability_is_deterministic():
    a = _fire_pattern(seed=11)
    b = _fire_pattern(seed=11)
    assert a == b
    assert 0 < sum(a) < len(a)  # actually probabilistic, not all/none
    c = _fire_pattern(seed=12)
    assert a != c  # a different seed gives a different replay


def test_per_site_streams_are_independent():
    faults.arm("pa:raise@p0.5;pb:raise@p0.5", seed=3)

    def pattern(name):
        s = faults.site(name)
        out = []
        for _ in range(64):
            try:
                s.hit()
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out

    assert pattern("pa") != pattern("pb")


# --------------------------------------------------------------------------
# flag wiring + disarm
# --------------------------------------------------------------------------

def test_flag_arms_and_disarms_live():
    flags.set_flags({"fault_plan": "flag.site:raise@1"})
    assert faults.active()
    with pytest.raises(faults.InjectedFault):
        faults.site("flag.site").hit()
    flags.set_flags({"fault_plan": ""})
    assert not faults.active()
    faults.site("flag.site").hit()  # disarmed: no-op


def test_seed_flag_write_does_not_drop_programmatic_plan():
    """set_flags({'fault_seed': ...}) fires the plan watcher; with
    fault_plan still empty it must NOT disarm a faults.arm()'d plan
    (code-review finding, round 4)."""
    faults.arm("keep.site:raise@2")
    flags.set_flags({"fault_seed": 7})
    assert faults.active()
    s = faults.site("keep.site")
    s.hit()
    with pytest.raises(faults.InjectedFault):
        s.hit()  # hit counters also survived the flag write
    # the flag path still disarms what the flag armed
    flags.set_flags({"fault_plan": "keep.site:raise@1", "fault_seed": 8})
    flags.set_flags({"fault_plan": ""})
    assert not faults.active()


def test_records_survive_disarm_for_postmortems():
    """The natural chaos pattern disarms in a finally block and THEN
    asserts on records() — the log must survive disarm and reset only
    at the next arm (code-review finding, round 6)."""
    faults.arm("pm.site:raise@1")
    with pytest.raises(faults.InjectedFault):
        faults.site("pm.site").hit()
    faults.disarm()
    assert [r["site"] for r in faults.records()] == ["pm.site"]
    faults.arm("pm.site:raise@1")  # fresh plan, fresh log
    assert faults.records() == []
    faults.disarm()


def test_disarm_resets_hit_counters():
    faults.arm("r.site:raise@2")
    faults.site("r.site").hit()
    faults.disarm()
    faults.arm("r.site:raise@2")
    s = faults.site("r.site")
    s.hit()  # counters restarted: this is hit 1 again, no fire
    with pytest.raises(faults.InjectedFault):
        s.hit()


def test_builtin_sites_registered():
    # production sites declared at import of their modules
    import paddle_tpu.contrib.trainer  # noqa: F401
    import paddle_tpu.incubate.fleet.fleet_base  # noqa: F401
    import paddle_tpu.io  # noqa: F401
    import paddle_tpu.parallel.checkpoint  # noqa: F401

    names = set(faults.sites())
    assert {"ckpt.write_shards", "ckpt.commit", "ckpt.read",
            "fleet.kv_get", "fleet.kv_put", "fleet.connect",
            "fleet.heartbeat", "fleet.resize",
            "reader.next", "io.export"} <= names
    # the documented registry stays in sync with the declarations
    assert set(faults.BUILTIN_SITES) <= names


# --------------------------------------------------------------------------
# metrics
# --------------------------------------------------------------------------

def test_all_fault_plane_instruments_registered_for_scrape():
    """ISSUE 5 acceptance: every fault/retry/checkpoint instrument is
    registered eagerly (module import), so a /metrics scrape (which
    serves to_prometheus) shows the full set."""
    import paddle_tpu.contrib.trainer  # noqa: F401
    import paddle_tpu.parallel.checkpoint  # noqa: F401
    import paddle_tpu.retry  # noqa: F401

    text = monitor.to_prometheus()
    for name in ("pt_fault_injected_total", "pt_retry_total",
                 "pt_ckpt_commit_seconds", "pt_ckpt_invalid_skipped_total",
                 "pt_ckpt_async_errors_total",
                 "pt_trainer_auto_resumes_total"):
        assert f"# TYPE {name}" in text, name


def test_injections_are_metered_and_exported():
    monitor.enable()
    faults.arm("met.site:raise@1,2")
    s = faults.site("met.site")
    for _ in range(2):
        with pytest.raises(faults.InjectedFault):
            s.hit()
    c = monitor.counter("pt_fault_injected_total")
    assert c.value(labels={"site": "met.site"}) == 2
    assert 'pt_fault_injected_total{site="met.site"} 2' in \
        monitor.to_prometheus()


# --------------------------------------------------------------------------
# zero-overhead disabled path
# --------------------------------------------------------------------------

def test_disarmed_hit_allocates_nothing():
    """Sites live in hot code (reader.next fires per trainer batch):
    while no plan is armed a hit must be one boolean check."""
    assert not faults.active()
    s = faults.site("hot.site")
    for _ in range(3):  # warm
        s.hit()
    n = 3000
    tracemalloc.start()
    base = tracemalloc.take_snapshot()
    for _ in range(n):
        s.hit()
    snap = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grew = sum(
        st.size_diff for st in snap.compare_to(base, "filename")
        if st.traceback[0].filename.endswith("faults.py")
        and st.size_diff > 0)
    assert grew < n, f"disarmed Site.hit allocated {grew}B over {n} hits"
