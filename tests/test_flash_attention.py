"""Blocked flash-attention kernel correctness (Pallas interpret mode).

Runs the actual K-blocked online-softmax kernels (fwd + dq + dkv) through
the Pallas interpreter on CPU and checks them against the dense
composition — the TPU analog of the reference's CPU-vs-GPU kernel
cross-checks (SURVEY.md section 4.7).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import flash_attention as fa


@pytest.fixture(autouse=True)
def _interpret_mode():
    fa._INTERPRET = True
    yield
    fa._INTERPRET = False


def _rand(shape, seed, dtype=np.float32):
    return np.random.RandomState(seed).randn(*shape).astype(dtype)


def _make_qkv(b=2, h=2, tq=256, tk=256, dh=64):
    q = _rand((b, h, tq, dh), 0) * 0.3
    k = _rand((b, h, tk, dh), 1) * 0.3
    v = _rand((b, h, tk, dh), 2) * 0.3
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def _pad_bias(b, tk, n_pad):
    mask = np.ones((b, tk), np.float32)
    mask[:, tk - n_pad:] = 0.0
    bias = (1.0 - mask) * -1e9
    return jnp.asarray(bias[:, None, None, :])


def _causal_bias(b, t):
    causal = np.triu(np.full((t, t), -1e9, np.float32), k=1)
    return jnp.asarray(np.broadcast_to(causal, (b, 1, t, t)).copy())


def test_forward_matches_reference_no_bias():
    q, k, v = _make_qkv()
    out = fa.flash_attention(q, k, v, q_block=128, k_block=128)
    ref = fa._reference_attention(q, k, v, None, 1.0 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_matches_reference_pad_bias():
    q, k, v = _make_qkv()
    bias = _pad_bias(2, 256, 17)
    out = fa.flash_attention(q, k, v, bias=bias, q_block=128, k_block=128)
    ref = fa._reference_attention(q, k, v, bias, 1.0 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_matches_reference_causal_bias():
    q, k, v = _make_qkv(tq=256, tk=256)
    bias = _causal_bias(2, 256)
    out = fa.flash_attention(q, k, v, bias=bias, q_block=128, k_block=128)
    ref = fa._reference_attention(q, k, v, bias, 1.0 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_backward_matches_reference():
    q, k, v = _make_qkv(b=1, h=2, tq=256, tk=256, dh=64)
    bias = _causal_bias(1, 256)
    scale = 1.0 / np.sqrt(64)

    def f_flash(q, k, v):
        return jnp.sum(
            fa.flash_attention(q, k, v, bias=bias, q_block=128, k_block=128)
            * jnp.cos(jnp.arange(64, dtype=jnp.float32))
        )

    def f_ref(q, k, v):
        return jnp.sum(
            fa._reference_attention(q, k, v, bias, scale)
            * jnp.cos(jnp.arange(64, dtype=jnp.float32))
        )

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5,
            err_msg=f"d{name} mismatch"
        )


def test_uneven_blocks_fall_back_dense():
    """tq=100 does not divide the block size -> dense path, still correct."""
    q, k, v = _make_qkv(tq=100, tk=100)
    out = fa.flash_attention(q, k, v)
    ref = fa._reference_attention(q, k, v, None, 1.0 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_dropout_deterministic_and_normalized():
    q, k, v = _make_qkv(b=1, h=1, tq=128, tk=128, dh=64)
    seed = jnp.asarray(42, jnp.int32)
    try:
        o1 = fa.flash_attention(q, k, v, seed=seed, p_drop=0.3,
                                q_block=128, k_block=128)
        o2 = fa.flash_attention(q, k, v, seed=seed, p_drop=0.3,
                                q_block=128, k_block=128)
    except Exception as e:  # PRNG primitives unsupported in interpreter
        pytest.skip(f"pallas interpret PRNG unsupported: {e}")
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    # Expectation of dropped attention == undropped attention; with 128 keys
    # the row means should be close.
    ref = fa._reference_attention(q, k, v, None, 1.0 / np.sqrt(64))
    assert np.abs(np.asarray(o1) - np.asarray(ref)).mean() < 0.15


def test_dropout_grad_v_is_exact_linear():
    """out is linear in v for a fixed dropout mask, so the analytic dv must
    equal the directional finite difference exactly (up to fp error)."""
    q, k, v = _make_qkv(b=1, h=1, tq=128, tk=128, dh=64)
    seed = jnp.asarray(7, jnp.int32)

    def f(v):
        try:
            return jnp.sum(fa.flash_attention(
                q, k, v, seed=seed, p_drop=0.4, q_block=128, k_block=128))
        except Exception as e:
            pytest.skip(f"pallas interpret PRNG unsupported: {e}")

    dv = jax.grad(f)(v)
    direction = jnp.asarray(_rand(v.shape, 9)) * 0.01
    fd = (f(v + direction) - f(v - direction)) / 2.0
    np.testing.assert_allclose(
        float(jnp.vdot(dv, direction)), float(fd), rtol=5e-3)


# --- BTHD single-block fast path (layout [b, t, h, dh]) ---


def _make_qkv_bthd(b=4, h=2, tq=128, tk=128, dh=64):
    q = _rand((b, tq, h, dh), 0) * 0.3
    k = _rand((b, tk, h, dh), 1) * 0.3
    v = _rand((b, tk, h, dh), 2) * 0.3
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def test_bthd_forward_matches_reference():
    q, k, v = _make_qkv_bthd()
    out, lse = fa.flash_attention_bthd_fwd(q, k, v)
    ref = fa._reference_attention_bthd(q, k, v, None, 1.0 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # lse sanity: logsumexp of scores, [b, tq, h, 1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(64)
    ref_lse = jax.nn.logsumexp(s, axis=-1)[..., None].transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               atol=2e-5)


def test_bthd_forward_with_pad_and_causal_bias():
    q, k, v = _make_qkv_bthd()
    for bias in (_pad_bias(4, 128, 17), _causal_bias(4, 128)):
        out, _ = fa.flash_attention_bthd_fwd(q, k, v, bias=bias)
        ref = fa._reference_attention_bthd(q, k, v, bias, 1.0 / np.sqrt(64))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


def test_bthd_backward_matches_reference():
    q, k, v = _make_qkv_bthd()
    bias = _causal_bias(4, 128)

    def f_flash(q, k, v):
        out, _ = fa.flash_attention_bthd_with_lse(q, k, v, bias)
        return jnp.sum(out * jnp.cos(jnp.arange(64, dtype=jnp.float32)))

    def f_ref(q, k, v):
        return jnp.sum(
            fa._reference_attention_bthd(q, k, v, bias, 1.0 / np.sqrt(64))
            * jnp.cos(jnp.arange(64, dtype=jnp.float32)))

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_bthd_cross_attention_shapes():
    """tq != tk (decoder cross attention)."""
    q, _, _ = _make_qkv_bthd(tq=64)
    _, k, v = _make_qkv_bthd(tk=128)
    out, _ = fa.flash_attention_bthd_fwd(q, k, v)
    ref = fa._reference_attention_bthd(q, k, v, None, 1.0 / np.sqrt(64))
    assert out.shape == (4, 64, 2, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_bthd_dropout_deterministic():
    q, k, v = _make_qkv_bthd(b=2, h=1)
    seed = jnp.asarray(13, jnp.int32)
    try:
        o1, _ = fa.flash_attention_bthd_fwd(q, k, v, seed=seed, p_drop=0.3)
        o2, _ = fa.flash_attention_bthd_fwd(q, k, v, seed=seed, p_drop=0.3)
    except Exception as e:
        pytest.skip(f"pallas interpret PRNG unsupported: {e}")
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    ref = fa._reference_attention_bthd(q, k, v, None, 1.0 / np.sqrt(64))
    assert np.abs(np.asarray(o1) - np.asarray(ref)).mean() < 0.15


def test_bthd_dropout_grad_v_linear():
    q, k, v = _make_qkv_bthd(b=2, h=1)
    seed = jnp.asarray(5, jnp.int32)

    def f(v):
        try:
            out, _ = fa.flash_attention_bthd_with_lse(
                q, k, v, None, seed, None, 0.4)
        except Exception as e:
            pytest.skip(f"pallas interpret PRNG unsupported: {e}")
        return jnp.sum(out)

    dv = jax.grad(f)(v)
    direction = jnp.asarray(_rand(v.shape, 9)) * 0.01
    fd = (f(v + direction) - f(v - direction)) / 2.0
    np.testing.assert_allclose(float(jnp.vdot(dv, direction)), float(fd),
                               rtol=5e-3)


def test_bthd_non_cq_multiple_tq_falls_back_dense():
    """tq=192 does not divide the 128-row chunk -> dense fallback (the
    grid would truncate and leave rows 128+ unwritten)."""
    q, _, _ = _make_qkv_bthd(tq=192)
    _, k, v = _make_qkv_bthd(tk=128)
    out, _ = fa.flash_attention_bthd_fwd(q, k, v)
    ref = fa._reference_attention_bthd(q, k, v, None, 1.0 / np.sqrt(64))
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


# --- K-blocked BTHD path (512 < tk <= _KB_T_MAX, no transposes) ---


import pytest as _pytest


@_pytest.mark.parametrize("tk", [768, 1024])   # nk=3 @256 and nk=2 @512
def test_bthd_kblock_forward_matches_reference(tk):
    b, tq, h, dh = 1, 16, 2, 32
    q = jnp.asarray(_rand((b, tq, h, dh), 3) * 0.3)
    k = jnp.asarray(_rand((b, tk, h, dh), 4) * 0.3)
    v = jnp.asarray(_rand((b, tk, h, dh), 5) * 0.3)
    assert fa._use_bthd_kblock(tq, tk, h, dh)
    out, lse = fa.flash_attention_bthd_fwd(q, k, v)
    ref = fa._reference_attention_bthd(q, k, v, None, 1.0 / np.sqrt(dh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    assert np.isfinite(np.asarray(lse)).all()


@_pytest.mark.parametrize("tk", [768, 1024])
def test_bthd_kblock_backward_matches_reference(tk):
    b, tq, h, dh = 1, 16, 2, 32
    q = jnp.asarray(_rand((b, tq, h, dh), 6) * 0.3)
    k = jnp.asarray(_rand((b, tk, h, dh), 7) * 0.3)
    v = jnp.asarray(_rand((b, tk, h, dh), 8) * 0.3)
    g = jnp.asarray(_rand((b, tq, h, dh), 9) * 0.3)
    bias = _pad_bias(b, tk, 21)
    out, lse = fa.flash_attention_bthd_fwd(q, k, v, bias)
    dq, dk, dv = fa.flash_attention_bthd_bwd(q, k, v, bias, None, out, lse,
                                             g)

    def f(q, k, v):
        return jnp.sum(
            fa._reference_attention_bthd(q, k, v, bias, 1.0 / np.sqrt(dh))
            * g)

    rdq, rdk, rdv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq), atol=3e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk), atol=3e-5)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv), atol=3e-5)


def test_native_causal_fwd_matches_causal_bias():
    """causal=True (in-kernel position mask + dead-block skip) must be
    numerically identical to the old [t, t] causal-bias formulation,
    WITHOUT any [t, t] tensor existing (VERDICT r5: the O(t) HBM claim
    now holds for decoder self-attention too)."""
    q, k, v = _make_qkv(tq=256, tk=256)
    out = fa.flash_attention(q, k, v, q_block=128, k_block=128,
                             causal=True)
    ref = fa.flash_attention(q, k, v, bias=_causal_bias(2, 256),
                             q_block=128, k_block=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_native_causal_with_pad_bias_bwd_matches():
    """fwd+bwd parity of native causal + pad bias vs the combined-bias
    dense reference, through the blocked kernels."""
    q, k, v = _make_qkv(tq=256, tk=256)
    pad = _pad_bias(2, 256, 9)
    combined = pad + _causal_bias(2, 256)

    def f_native(q, k, v):
        return fa.flash_attention(q, k, v, bias=pad, q_block=128,
                                  k_block=128, causal=True).sum()

    def f_ref(q, k, v):
        return fa._reference_attention(
            q, k, v, combined, 1.0 / np.sqrt(64)).sum()

    o1, g1 = jax.value_and_grad(f_native, argnums=(0, 1, 2))(q, k, v)
    o2, g2 = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(o1), float(o2), rtol=1e-4)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_bthd_native_causal_matches_combined_bias():
    """BTHD entry with causal=True routes every sub-path (small,
    k-blocked, long-context BHTD) to the same math as the combined
    causal bias."""
    for tq, tk in ((256, 256), (1024, 1024)):
        b, h, dh = 1, 2, 64
        q = jnp.asarray(_rand((b, tq, h, dh), 3) * 0.3)
        k = jnp.asarray(_rand((b, tk, h, dh), 4) * 0.3)
        v = jnp.asarray(_rand((b, tk, h, dh), 5) * 0.3)
        out, _ = fa.flash_attention_bthd_fwd(q, k, v, causal=True)
        ref = fa._reference_attention_bthd(
            q, k, v, fa._combined_causal_bias(None, tq, tk),
            1.0 / np.sqrt(dh))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, err_msg=f"t={tq}")


def test_bthd_kb_native_causal_backward_matches():
    """k-blocked (t=1024) native-causal backward: dq/dk/dv parity vs
    the dense combined-bias vjp (dead q/k block pairs SKIPPED in-kernel
    must still produce exact gradients)."""
    b, tq, tk, h, dh = 1, 1024, 1024, 2, 64
    q = jnp.asarray(_rand((b, tq, h, dh), 6) * 0.3)
    k = jnp.asarray(_rand((b, tk, h, dh), 7) * 0.3)
    v = jnp.asarray(_rand((b, tk, h, dh), 8) * 0.3)
    out, lse = fa.flash_attention_bthd_fwd(q, k, v, causal=True)
    g = jnp.asarray(_rand((b, tq, h, dh), 9) * 0.1)
    dq, dk, dv = fa.flash_attention_bthd_bwd(
        q, k, v, None, None, out, lse, g, causal=True)

    def f(q, k, v):
        return fa._reference_attention_bthd(
            q, k, v, fa._combined_causal_bias(None, tq, tk),
            1.0 / np.sqrt(dh))

    _, vjp = jax.vjp(f, q, k, v)
    rq, rk, rv = vjp(g)
    for a, r, name in ((dq, rq, "dq"), (dk, rk, "dk"), (dv, rv, "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=5e-5, err_msg=name)


def test_lse_cotangent_flows_through_blocked_backward():
    """The lse OUTPUT is a real differentiated quantity (the ring merge
    weights blocks by exp(lse_blk - lse_comb)); its cotangent folds into
    the blocked backward as delta - phi. Checked against the dense
    (out, lse) vjp through the interpret-mode kernels."""
    q, k, v = _make_qkv(tq=256, tk=256)

    def loss_wrapper(q, k, v):
        out, lse = fa.flash_attention_with_lse(q, k, v, None, None,
                                               None, 0.0)
        return out.sum() + (lse * jnp.linspace(
            0.1, 1.0, lse.shape[2])[None, None, :, None]).sum()

    def loss_dense(q, k, v):
        s = fa._reference_scores(q, k, None, 1.0 / np.sqrt(64), False)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        lse = jax.scipy.special.logsumexp(s, axis=-1, keepdims=True)
        return out.sum() + (lse * jnp.linspace(
            0.1, 1.0, lse.shape[2])[None, None, :, None]).sum()

    g1 = jax.grad(loss_wrapper, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, err_msg=name)
