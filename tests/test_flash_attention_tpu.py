"""TPU-only attention kernel checks (skipped on CPU backends).

These pin the invariants the Pallas interpreter cannot reach:
1. the forward (cq up to 256) and fused backward (cq=128) kernels
   regenerate bit-identical dropout masks from the absolute 128-row-block
   keying (incl. the u32->u16 bitcast shape convention), verified by
   comparing the kernel path against a dense reference fed the kernels'
   OWN masks (dumped via the same helpers);
2. hardware numerical parity of the single-block and K-blocked BTHD
   kernels (fwd + grads) against the dense composition.

The driver runs the suite on TPU each round; on CPU these skip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import flash_attention as fa

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="needs a real TPU backend")


def _dump_masks(b, tq, tk, h, pd, seed):
    """The kernels' dropout masks, reproduced with the kernels' own
    helpers/keys: (b, tq, h, tk) f32 scaled keep masks."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kblock = tk > fa._SMALL_T_MAX
    cq = 128 if tq >= 128 else tq
    nq = tq // cq

    def kern(seed_ref, x_ref, o_ref):
        i, j = pl.program_id(0), pl.program_id(1)
        for hi in range(h):
            if kblock:
                bk = fa._pick_bk(tk, h, 64)
                parts = [fa._kb_dropout(seed_ref, i, j, cq, hi, kk, bk, pd)
                         for kk in range(tk // bk)]
                m = jnp.concatenate(parts, axis=-1)
            else:
                m = fa._small_dropout_abs(seed_ref, i, j, cq, hi, tk, pd)
            o_ref[0, :, hi * tk:(hi + 1) * tk] = m.astype(jnp.float32)

    out = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(b, nq),
            in_specs=[pl.BlockSpec((1, 8, 128), lambda i, j, *_: (0, 0, 0))],
            out_specs=[pl.BlockSpec((1, cq, h * tk),
                                    lambda i, j, *_: (i, j, 0))]),
        out_shape=[jax.ShapeDtypeStruct((b, tq, h * tk), jnp.float32)])(
            jnp.asarray([seed], jnp.uint32),
            jnp.zeros((1, 8, 128), jnp.float32))[0]
    return np.asarray(out).reshape(b, tq, h, tk)


@pytest.mark.parametrize("b,tq,tk,h,dh,pd", [
    (2, 256, 256, 3, 64, 0.3),     # single-block, fwd cq=256 vs bwd 128
    (1, 128, 1024, 2, 64, 0.3),    # K-blocked
])
def test_dropout_fwd_bwd_mask_consistency(b, tq, tk, h, dh, pd):
    seedv = 11
    r = np.random.RandomState(7)
    masks = _dump_masks(b, tq, tk, h, pd, seedv)
    q = jnp.asarray(r.normal(0, 1, (b, tq, h, dh))).astype(jnp.bfloat16)
    k = jnp.asarray(r.normal(0, 1, (b, tk, h, dh))).astype(jnp.bfloat16)
    v = jnp.asarray(r.normal(0, 1, (b, tk, h, dh))).astype(jnp.bfloat16)
    w = jnp.asarray(r.normal(0, 1, (b, tq, h, dh)).astype(np.float32))
    mask_bhqk = jnp.asarray(masks).transpose(0, 2, 1, 3)

    def fk(q, k, v):
        o, _ = fa.flash_attention_bthd_with_lse(
            q, k, v, None, jnp.uint32(seedv), None, pd)
        return jnp.sum(o.astype(jnp.float32) * w)

    def fr(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(dh)
        p = jax.nn.softmax(s, -1) * mask_bhqk
        o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
        return jnp.sum(o.astype(jnp.float32) * w)

    l1, g1 = jax.value_and_grad(fk, (0, 1, 2))(q, k, v)
    l2, g2 = jax.value_and_grad(fr, (0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32), atol=0.05)


@pytest.mark.parametrize("b,tq,tk,h,dh", [
    (2, 256, 256, 8, 64),
    (1, 256, 1024, 4, 64),
    (1, 1024, 768, 4, 64),
])
def test_hw_parity_vs_dense(b, tq, tk, h, dh):
    r = np.random.RandomState(3)
    q = jnp.asarray(r.normal(0, 1, (b, tq, h, dh))).astype(jnp.bfloat16)
    k = jnp.asarray(r.normal(0, 1, (b, tk, h, dh))).astype(jnp.bfloat16)
    v = jnp.asarray(r.normal(0, 1, (b, tk, h, dh))).astype(jnp.bfloat16)
    bias = jnp.asarray(r.normal(0, 1, (b, 1, tq, tk)).astype(np.float32))
    w = jnp.asarray(r.normal(0, 1, (b, tq, h, dh)).astype(np.float32))

    def f(q, k, v):
        o, _ = fa.flash_attention_bthd_with_lse(q, k, v, bias)
        return jnp.sum(o.astype(jnp.float32) * w)

    def ref(q, k, v):
        o = fa._reference_attention_bthd(q, k, v, bias, 1.0 / np.sqrt(dh))
        return jnp.sum(o.astype(jnp.float32) * w)

    g1 = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32), atol=0.05)
