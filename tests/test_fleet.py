"""Fleet multi-host bootstrap tests.

The subprocess-on-localhost pattern of the reference
(tests/unittests/test_dist_base.py:311-684): spawn 2 worker processes that
rendezvous through the native coordination service (csrc/coord.cc),
bring up the PJRT distributed runtime on a 2x2-device CPU mesh, train
data-parallel, and assert per-step loss parity against a single-process
run of the same deterministic model.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.incubate.fleet import UserDefinedRoleMaker, fleet as _fleet

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_losses():
    sys.path.insert(0, HERE)
    try:
        import fleet_worker as fw
    finally:
        sys.path.pop(0)
    main, startup, loss = fw.build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        out = []
        for x, y in fw.global_batches():
            out.append(float(
                exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[loss])[0]))
    return out


@pytest.mark.parametrize("n_workers", [
    2, pytest.param(4, marks=pytest.mark.full)])
def test_fleet_multi_process_loss_parity(n_workers):
    from paddle_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    port = _free_port()
    env_base = {
        **os.environ,
        "PT_TRAINERS": str(n_workers),
        "PT_COORD_ENDPOINT": f"127.0.0.1:{port}",
        "PT_JAX_COORD_ENDPOINT": f"127.0.0.1:{_free_port()}",
        # workers configure jax themselves; drop any pytest leakage
        "JAX_PLATFORMS": "",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE), os.environ.get("PYTHONPATH", "")]
        ),
    }
    procs = []
    for rank in range(n_workers):
        env = {**env_base, "PT_TRAINER_ID": str(rank)}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "fleet_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        ))
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        line = [l for l in out.splitlines() if l.startswith("FLEET_RESULT ")]
        assert line, f"no result line:\n{out}\n{err}"
        r = json.loads(line[-1][len("FLEET_RESULT "):])
        results[r["rank"]] = r["losses"]

    assert set(results) == set(range(n_workers))
    # every worker fetches the same (global-mean) loss
    for r in range(1, n_workers):
        np.testing.assert_allclose(results[0], results[r], rtol=1e-5)
    # and it matches the single-process run over the full global batch
    single = _single_process_losses()
    np.testing.assert_allclose(single, results[0], rtol=1e-4, atol=1e-5)
    assert results[0][-1] < results[0][0]  # learning


def test_fleet_single_worker_noop():
    f = _fleet.__class__()
    f.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    assert f.worker_num() == 1 and f.is_first_worker()
    assert f.dead_workers() == []
    f.barrier()  # no-op without a client
    f.stop_worker()


def test_fleet_kv_and_liveness_single_process():
    """Coord-backed KV/heartbeat through the fleet façade (one process
    hosting the server and connecting as its own client)."""
    from paddle_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    port = _free_port()
    f = _fleet.__class__()
    role = UserDefinedRoleMaker(
        current_id=0, worker_num=2,  # pretend, to exercise the server path
        coord_endpoint=f"127.0.0.1:{port}",
    )
    # init would block on the 2-worker barrier + jax.distributed; drive the
    # pieces directly instead.
    f._role = role
    f._server = native.CoordServer(port)
    f._client = native.CoordClient("127.0.0.1", port)
    try:
        f.put("k", b"v")
        assert f.get("k", timeout_ms=1000) == b"v"
        f.heartbeat()
        assert f.dead_workers(max_age_ms=60_000) == []
    finally:
        f.stop_worker()


def test_barrier_or_dead_epochs_and_key_reclamation():
    """Every barrier_or_dead call is its own epoch (a per-client
    sequence number namespaces the arrive keys, so a reused NAME can
    never pass on a stale arrival), and keys are reclaimed two
    fully-completed barriers later (bounded KV growth)."""
    from paddle_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    port = _free_port()
    f = _fleet.__class__()
    f._role = UserDefinedRoleMaker(current_id=0, worker_num=1,
                                   coord_endpoint=f"127.0.0.1:{port}")
    f._server = native.CoordServer(port)
    f._client = native.CoordClient("127.0.0.1", port)
    try:
        assert f.barrier_or_dead("s") == []   # epoch 1
        assert f.barrier_or_dead("s") == []   # SAME name, epoch 2: fresh
        assert f._client.get("fleet/arrive/1:s/0", timeout_ms=0) == b"1"
        assert f._client.get("fleet/arrive/2:s/0", timeout_ms=0) == b"1"
        assert f.barrier_or_dead("s") == []   # epoch 3 reclaims epoch 1
        with pytest.raises(TimeoutError):
            f._client.get("fleet/arrive/1:s/0", timeout_ms=0)
        assert f._client.get("fleet/arrive/3:s/0", timeout_ms=0) == b"1"
    finally:
        f.stop_worker()
