"""Fleet observability plane (ISSUE 9 tentpole): cross-rank digest
publish/aggregate, the /fleet cluster view, straggler detection, dead
-worker marking, device-memory watermarks, OOM forensics, and the
zero-alloc disabled-path contract.

In-process tests drive the plane through a stub KV client (the
test_elastic_resize pattern); the multi-process tests spawn 4 real
workers against the native coord service (tests/fleet_obs_worker.py)
WITHOUT jax.distributed — the digest plane needs only the KV/heartbeat
half of the fleet."""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import tracemalloc
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import faults, fleet_monitor, flags, layers, monitor
from paddle_tpu.incubate.fleet.fleet_base import Fleet

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    faults.disarm()
    flags.set_flags({"telemetry": False, "step_log_path": "",
                     "stall_dump_dir": "", "fault_plan": "",
                     "device_memory_budget_bytes": 0,
                     "fleet_metrics_interval_ms": 1000,
                     "fleet_straggler_factor": 2.0,
                     "fleet_straggler_min_ms": 20,
                     "device_memory_every_n_steps": 16,
                     "step_phases_every_n": 1})
    yield
    monitor.stop_server()
    monitor.reset()
    faults.disarm()
    flags.set_flags({"telemetry": False, "step_log_path": "",
                     "stall_dump_dir": "", "fault_plan": "",
                     "device_memory_budget_bytes": 0,
                     "fleet_metrics_interval_ms": 1000,
                     "fleet_straggler_factor": 2.0,
                     "fleet_straggler_min_ms": 20,
                     "device_memory_every_n_steps": 16,
                     "step_phases_every_n": 1})


# --------------------------------------------------------------------------
# stub KV plumbing (the test_elastic_resize pattern, + non-blocking get)
# --------------------------------------------------------------------------

class _StubRole:
    def __init__(self, rank, world):
        self._r, self._n = rank, world

    def worker_index(self):
        return self._r

    def worker_num(self):
        return self._n


class _StubClient:
    def __init__(self, store, lock, dead=()):
        self._store, self._lock, self._dead = store, lock, list(dead)

    def put(self, key, value):
        with self._lock:
            self._store[key] = bytes(value)

    def get(self, key, timeout_ms=-1, max_len=0):
        with self._lock:
            if key in self._store:
                return self._store[key]
        raise TimeoutError(key)

    def heartbeat(self, worker_id):
        pass

    def dead_peers(self, max_age_ms):
        return list(self._dead)


def _stub_fleet(rank, world, store, lock, dead=()):
    f = Fleet()
    f._role = _StubRole(rank, world)
    f._client = _StubClient(store, lock, dead)
    f._initialized = True
    return f


def _run_some_steps(n=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(n):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[y])
    return exe


def _digest_for(rank, wall_ms, phases=None, steps=20, ts=None, world=4):
    """Hand-crafted schema-valid digest for detector/aggregation tests."""
    d = fleet_monitor.registry_digest(rank=rank, world=world, gen=0)
    d["step_wall_ms"] = wall_ms
    d["phases_ms"] = phases
    d["steps"] = steps
    if ts is not None:
        d["ts"] = ts
    monitor.validate_fleet_digest(d)
    return d


# --------------------------------------------------------------------------
# digest assembly + schema
# --------------------------------------------------------------------------

def test_registry_digest_schema_and_trailing_medians():
    monitor.enable()
    _run_some_steps(3)
    d = fleet_monitor.registry_digest(rank=2, world=4, gen=1)
    monitor.validate_fleet_digest(d)
    assert d["rank"] == 2 and d["world"] == 4 and d["gen"] == 1
    # counters carry values, histograms only sum/count
    steps_cells = d["counters"]["pt_executor_steps_total"]
    assert steps_cells[0]["value"] == 4.0  # startup + 3
    phase_cells = d["hists"]["pt_step_phase_seconds"]
    assert all(set(c) == {"labels", "sum", "count"} for c in phase_cells)
    # trailing medians + the last step record with phases and verdict
    assert d["step_wall_ms"] > 0
    assert set(d["phases_ms"]) == set(monitor.STEP_PHASES)
    monitor.validate_step_record(d["last_step"])
    assert d["bound"]["verdict"] in monitor.BOUND_VERDICTS
    assert d["steps"] == 4


def test_registry_digest_roofline_section_optional_and_validated():
    """The digest's `roofline` section (optional field — schema stays
    v1): absent before the first device profile, a per-program
    {measured_mfu, verdict, source} rollup after one, and digests
    WITHOUT the field still validate (backward compatibility with
    pre-roofline publishers)."""
    from paddle_tpu import roofline

    monitor.enable()
    d = fleet_monitor.registry_digest(rank=0, world=2)
    assert "roofline" not in d  # no profile recorded yet
    monitor.validate_fleet_digest(d)  # pre-roofline shape still valid
    prog = fluid.Program()
    roofline.record_profile(roofline.build_device_profile(
        prog, source="estimate", device_seconds=0.1, steps=1,
        compile_report={"flops": 1e9, "bytes_accessed": 1e7,
                        "op_histogram": {"mul": 1}},
        backend="cpu"))
    d = fleet_monitor.registry_digest(rank=1, world=2)
    monitor.validate_fleet_digest(d)
    cell = d["roofline"][f"program{prog._uid}"]
    assert set(cell) == {"measured_mfu", "verdict", "source"}
    assert cell["source"] == "estimate"
    assert cell["measured_mfu"] > 0
    # the rollup rides aggregation into the per-rank /fleet rows
    store, lock = {}, threading.Lock()
    store["fleet/metrics/g0/0"] = json.dumps(d).encode()
    f = _stub_fleet(0, 1, store, lock)
    view = fleet_monitor.aggregate(f)
    assert view["ranks"]["0"]["roofline"] == d["roofline"]


def test_registry_digest_serving_section_optional_and_validated():
    """The digest's `serving` section (optional field — schema stays
    v1): absent on ranks that never served, a per-replica request-plane
    rollup once the recently-terminated ring has a record, and digests
    WITHOUT the field still validate (backward compatibility with
    pre-serving publishers)."""
    import types

    from paddle_tpu import serving, serving_trace

    monitor.enable()
    assert not list(serving._ENGINES)  # a leaked engine is a test bug
    d = fleet_monitor.registry_digest(rank=0, world=2)
    assert "serving" not in d  # this rank never served
    monitor.validate_fleet_digest(d)

    # one terminal request through the real recording path
    now = time.perf_counter()
    req = types.SimpleNamespace(
        outcome="completed", ttft_s=0.01, tokens=[5, 7], decode_s=0.02,
        fetch_s=0.001, queue_wait_s=0.005, prefill_s=0.004,
        submit_ts=now - 0.05, deadline_ts=None, replays=0, capped=False,
        censored=False, deadline_attr=None, trace_id="r777", id=777,
        engine_id=9, trace_tid=None)
    serving_trace.note_terminal(req)

    d = fleet_monitor.registry_digest(rank=1, world=2)
    monitor.validate_fleet_digest(d)
    sec = d["serving"]
    assert sec["recent"] == 1 and sec["engines"] == {}
    assert set(sec["slo"]) == {"targets_ms", "ttft", "token",
                               "ttft_censored", "burn"}
    assert set(sec["ttft_ms"]) == {"p50", "p95", "p99"}
    # the rollup rides aggregation into the per-rank /fleet rows
    store, lock = {}, threading.Lock()
    store["fleet/metrics/g0/1"] = json.dumps(d).encode()
    f = _stub_fleet(1, 2, store, lock)
    view = fleet_monitor.aggregate(f)
    assert view["ranks"]["1"]["serving"]["recent"] == 1
    # backward compatibility: a digest without the section validates
    del d["serving"]
    monitor.validate_fleet_digest(d)


def test_publish_rides_heartbeat_and_rate_limits():
    monitor.enable()
    store, lock = {}, threading.Lock()
    f = _stub_fleet(1, 2, store, lock)
    flags.set_flags({"fleet_metrics_interval_ms": 0})
    f.heartbeat()
    key = "fleet/metrics/g0/1"
    assert key in store
    first = json.loads(store[key].decode())
    monitor.validate_fleet_digest(first)
    f.heartbeat()
    assert json.loads(store[key].decode())["seq"] == first["seq"] + 1
    # a large interval rate-limits: the next heartbeat publishes nothing
    flags.set_flags({"fleet_metrics_interval_ms": 3_600_000})
    before = store[key]
    f.heartbeat()
    assert store[key] is before
    assert monitor.counter(
        "pt_fleet_digests_published_total").value() == 2


def test_publish_failure_drops_one_digest_never_raises():
    monitor.enable()
    flags.set_flags({"fleet_metrics_interval_ms": 0})

    class _DeadPut(_StubClient):
        def put(self, key, value):
            raise OSError("kv down")

    f = Fleet()
    f._role = _StubRole(0, 2)
    f._client = _DeadPut({}, threading.Lock())
    f._initialized = True
    with pytest.warns(RuntimeWarning, match="digest publish failed"):
        f.heartbeat()  # must not raise
    assert monitor.counter(
        "pt_fleet_digest_publish_drops_total").value() == 1


# --------------------------------------------------------------------------
# aggregation: cluster view, staleness, stragglers
# --------------------------------------------------------------------------

def test_aggregate_shows_all_ranks_and_merged_prometheus():
    monitor.enable()
    _run_some_steps(2)
    store, lock = {}, threading.Lock()
    flags.set_flags({"fleet_metrics_interval_ms": 0})
    for r in range(3):
        _stub_fleet(r, 3, store, lock).heartbeat()
    f0 = _stub_fleet(0, 3, store, lock)
    view = fleet_monitor.aggregate(f0)
    assert set(view["ranks"]) == {"0", "1", "2"}
    assert view["missing"] == [] and view["dead"] == []
    for row in view["ranks"].values():
        assert row["age_ms"] >= 0 and row["dead"] is False
        assert row["last_step"] is not None
    # merged exposition: every rank's samples, rank-labelled
    text = fleet_monitor.to_prometheus_fleet(view)
    for r in range(3):
        assert f'pt_executor_steps_total{{rank="{r}"}}' in text
    assert 'pt_step_phase_seconds_sum{phase="device",rank="0"}' in text
    # a metric's OWN rank label must survive as exported_rank, not be
    # clobbered into naming the publisher: rank 0's registry carries a
    # straggler detection naming rank 2
    monitor.counter("pt_fleet_straggler_total").inc(labels={"rank": 2})
    _stub_fleet(0, 3, store, lock).heartbeat()  # republish rank 0
    text = fleet_monitor.to_prometheus_fleet(fleet_monitor.aggregate(f0))
    assert ('pt_fleet_straggler_total{exported_rank="2",rank="0"} 1'
            in text)
    assert 'pt_fleet_straggler_total{rank="2"}' not in text


def test_aggregate_marks_stale_rank_dead_not_stale_rows():
    monitor.enable()
    store, lock = {}, threading.Lock()
    now = time.time()
    phases = {"feed": 1.0, "dispatch": 2.0, "device": 1.0, "fetch": 0.5}
    store["fleet/metrics/g0/0"] = json.dumps(
        _digest_for(0, 5.0, phases, ts=now)).encode()
    store["fleet/metrics/g0/1"] = json.dumps(
        _digest_for(1, 5.0, phases, ts=now - 60.0)).encode()  # stale
    f0 = _stub_fleet(0, 3, store, lock)  # rank 2 never published
    view = fleet_monitor.aggregate(f0, max_age_ms=2_000)
    assert view["dead"] == [1]
    assert view["ranks"]["1"]["dead"] is True
    assert view["missing"] == [2]
    # a dead rank must not feed the skew detector either
    assert view["stragglers"] == []


def test_staleness_uses_observed_publish_age_not_publisher_clock():
    """A publisher with a skewed-behind wall clock (broken NTP) must
    not flap dead: once the aggregator OBSERVES a fresh publish (seq
    advanced), age is measured on the aggregator's own clock. A frozen
    seq keeps aging and still goes dead."""
    monitor.enable()
    store, lock = {}, threading.Lock()
    skewed = _digest_for(0, 5.0, None, ts=time.time() - 60.0, world=2)
    store["fleet/metrics/g0/0"] = json.dumps(skewed).encode()
    f0 = _stub_fleet(0, 2, store, lock)
    # first sight: only the self-reported ts exists -> dead
    view = fleet_monitor.aggregate(f0, max_age_ms=2_000)
    assert view["dead"] == [0]
    # re-aggregation with seq unchanged: the observation anchor was
    # BACKDATED by the first-sight age, so the stale digest keeps
    # aging instead of resurrecting as "just seen"
    view = fleet_monitor.aggregate(f0, max_age_ms=2_000)
    assert view["dead"] == [0]
    assert view["ranks"]["0"]["age_ms"] >= 59_000
    # a NEW publish lands (seq advances), ts still 60s behind: the
    # observed publish is what counts -> alive
    skewed2 = dict(skewed, seq=skewed["seq"] + 1, ts=time.time() - 60.0)
    store["fleet/metrics/g0/0"] = json.dumps(skewed2).encode()
    view = fleet_monitor.aggregate(f0, max_age_ms=2_000)
    assert view["dead"] == [] and view["ranks"]["0"]["age_ms"] == 0.0
    # seq frozen: age grows on the aggregator's clock -> dead again
    time.sleep(0.05)
    view = fleet_monitor.aggregate(f0, max_age_ms=40)
    assert view["dead"] == [0]
    assert view["ranks"]["0"]["age_ms"] >= 50


def test_straggler_detector_names_rank_and_inflated_phase():
    monitor.enable()
    store, lock = {}, threading.Lock()
    base = {"feed": 1.0, "dispatch": 2.0, "device": 1.5, "fetch": 0.5}
    slow = {"feed": 1.0, "dispatch": 82.0, "device": 1.5, "fetch": 0.5}
    for r in range(4):
        store[f"fleet/metrics/g0/{r}"] = json.dumps(_digest_for(
            r, 85.0 if r == 2 else 5.0, slow if r == 2 else base,
            steps=12)).encode()
    f0 = _stub_fleet(0, 4, store, lock)
    with pytest.warns(RuntimeWarning, match="straggler: rank 2"):
        view = fleet_monitor.aggregate(f0)
    (rec,) = view["stragglers"]
    assert rec["v"] == monitor.STRAGGLER_RECORD_SCHEMA_VERSION
    assert rec["rank"] == 2
    assert rec["phase"] == "dispatch"
    assert rec["steps"] == 12  # detection latency is step-bounded
    assert rec["factor"] > 2.0
    assert monitor.counter("pt_fleet_straggler_total").value(
        labels={"rank": 2}) == 1
    # re-detection of the SAME (rank, phase) streak (every /fleet
    # scrape re-aggregates): the live view still names it, but the
    # counter/buffer/warning tick once per streak — their rate must not
    # be a function of whoever is polling
    view2 = fleet_monitor.aggregate(f0)
    assert view2["stragglers"][0]["rank"] == 2
    assert monitor.counter("pt_fleet_straggler_total").value(
        labels={"rank": 2}) == 1
    assert len(fleet_monitor.straggler_records()) == 1
    # the stall watchdog's flight-recorder section carries them
    s = fleet_monitor.summary()
    assert s["stragglers"][-1]["rank"] == 2
    assert set(s["view"]["ranks"]) == {"0", "1", "2", "3"}


def test_straggler_floor_suppresses_subms_jitter():
    """3x skew on a sub-ms step is noise, not a straggler: the
    fleet_straggler_min_ms floor gates it."""
    monitor.enable()
    store, lock = {}, threading.Lock()
    for r, wall in enumerate((0.4, 0.4, 1.4)):
        store[f"fleet/metrics/g0/{r}"] = json.dumps(
            _digest_for(r, wall, None, world=3)).encode()
    view = fleet_monitor.aggregate(_stub_fleet(0, 3, store, lock))
    assert view["stragglers"] == []  # 3.5x median but only +1 ms


def test_local_view_without_fleet():
    """/fleet answers the same shape for single-process jobs."""
    monitor.enable()
    _run_some_steps(1)
    view = fleet_monitor.cluster_view()
    assert view["world"] == 1 and list(view["ranks"]) == ["0"]
    assert view["ranks"]["0"]["dead"] is False


# --------------------------------------------------------------------------
# device-memory watermarks + OOM forensics
# --------------------------------------------------------------------------

def test_device_memory_degrades_silently_on_cpu():
    """CPU devices expose no memory_stats(): sampling must neither
    raise nor invent gauge cells."""
    monitor.enable()
    monitor.sample_device_memory(0)
    assert monitor.gauge("pt_device_bytes_in_use")._cells == {}
    assert monitor.gauge("pt_device_bytes_peak")._cells == {}


def test_device_memory_gauges_with_stats_api(monkeypatch):
    monitor.enable()

    class _Dev:
        def __str__(self):
            return "TPU_0"

        def memory_stats(self):
            return {"bytes_in_use": 1234, "peak_bytes_in_use": 9999}

    import jax

    monkeypatch.setattr(jax, "local_devices", lambda: [_Dev()])
    monitor.sample_device_memory(0)
    assert monitor.gauge("pt_device_bytes_in_use").value(
        labels={"device": "TPU_0"}) == 1234
    assert monitor.gauge("pt_device_bytes_peak").value(
        labels={"device": "TPU_0"}) == 9999
    # sampling period honored (the trace_step_sampled convention)
    flags.set_flags({"device_memory_every_n_steps": 8})
    calls = []

    def _counting_devices():
        calls.append(1)
        return [_Dev()]

    monkeypatch.setattr(jax, "local_devices", _counting_devices)
    monitor.sample_device_memory(3)  # 3 % 8 != 0: no device read
    monitor.sample_device_memory(5, steps=2)  # window [5,7): no sample
    assert calls == []
    monitor.sample_device_memory(6, steps=3)  # window [6,9) spans 8
    monitor.sample_device_memory(8)  # a sample point itself
    assert len(calls) == 2


def test_oom_forensics_report_on_injected_resource_exhausted(tmp_path):
    flags.set_flags({"telemetry": True, "stall_dump_dir": str(tmp_path),
                     "device_memory_budget_bytes": 7777})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        faults.arm("executor.step:raise(RESOURCE_EXHAUSTED: fake OOM)@1")
        with pytest.raises(faults.InjectedFault), \
                pytest.warns(RuntimeWarning, match="device OOM during run"):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[y])
    (rec,) = monitor.oom_records()
    monitor.validate_oom_report(rec)
    assert rec["phase"] == "run"
    assert rec["budget_bytes"] == 7777
    assert "RESOURCE_EXHAUSTED" in rec["error"]
    assert rec["last_steps"]  # the startup step at least
    dumps = [p for p in os.listdir(tmp_path) if p.startswith("oom-")]
    assert len(dumps) == 1
    on_disk = json.load(open(tmp_path / dumps[0]))
    monitor.validate_oom_report(on_disk)
    # /fleet surfaces the forensics reports
    view = fleet_monitor.cluster_view()
    assert view["oom_reports"][0]["phase"] == "run"


def test_oom_forensics_with_step_phases_off(monkeypatch):
    """With step_phases off there is no pre-commit block_until_ready:
    an async-dispatched device OOM surfaces inside _commit's transfer
    and must still produce a forensics record (the bench metrics-only
    config is exactly telemetry on + phases off)."""
    flags.set_flags({"telemetry": True, "step_phases": False})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)

        def _boom(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: deferred device OOM")

        monkeypatch.setattr(exe, "_commit", _boom)
        with pytest.raises(RuntimeError), \
                pytest.warns(RuntimeWarning, match="device OOM during run"):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[y])
    (rec,) = monitor.oom_records()
    assert rec["phase"] == "run"
    flags.set_flags({"step_phases": True})


def test_oom_forensics_compile_phase_and_non_oom_ignored():
    monitor.enable()
    monitor.maybe_record_oom(RuntimeError("some other crash"))
    assert monitor.oom_records() == []
    monitor.maybe_record_oom(
        RuntimeError("RESOURCE_EXHAUSTED: 2GB on device"), phase="compile")
    (rec,) = monitor.oom_records()
    assert rec["phase"] == "compile" and rec["program"] is None
    assert monitor.counter("pt_oom_events_total").value(
        labels={"phase": "compile"}) == 1


# --------------------------------------------------------------------------
# disabled-path contract: tracemalloc-proven zero-alloc
# --------------------------------------------------------------------------

def _grew_in(snap, base, filename):
    stats = snap.compare_to(base, "filename")
    return sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith(filename)
               and s.size_diff > 0)


def test_disabled_path_zero_alloc_telemetry_off():
    """Telemetry off: the executor hot loop (now incl. the faults site,
    device-memory gate and OOM hook) plus the heartbeat publish gate
    must allocate nothing in monitor.py or fleet_monitor.py."""
    assert not monitor.enabled()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 4), np.float32)}
    store, lock = {}, threading.Lock()
    f = _stub_fleet(0, 2, store, lock)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[y])
            f.heartbeat()
        n_runs = 30
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            exe.run(main, feed=feed, fetch_list=[y])
            f.heartbeat()
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    for fname in ("monitor.py", "fleet_monitor.py", "faults.py"):
        grew = _grew_in(snap, base, fname)
        assert grew < n_runs * 16, (
            f"disabled hot loop allocated {grew}B in {fname} over "
            f"{n_runs} runs")
    assert store == {}  # nothing published with telemetry off


def test_disabled_path_zero_alloc_single_worker_telemetry_on():
    """Telemetry ON but single-worker (no client): the fleet plane must
    stay out of the hot loop entirely."""
    monitor.enable()
    f = Fleet()  # no client
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[y])
            f.heartbeat()
        n_runs = 30
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            exe.run(main, feed=feed, fetch_list=[y])
            f.heartbeat()
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    grew = _grew_in(snap, base, "fleet_monitor.py")
    assert grew < n_runs * 16, (
        f"single-worker hot loop allocated {grew}B in fleet_monitor.py")


# --------------------------------------------------------------------------
# the multi-process drills (ISSUE 9 acceptance)
# --------------------------------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_fleet(n, extra_env_per_rank, steps=30):
    port = _free_port()
    env_base = {
        **os.environ,
        "PT_TRAINERS": str(n),
        "PT_COORD_ENDPOINT": f"127.0.0.1:{port}",
        "PT_OBS_STEPS": str(steps),
        "JAX_PLATFORMS": "",
        "PT_FLAGS_telemetry": "1",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE), os.environ.get("PYTHONPATH", "")]),
    }
    procs = []
    for rank in range(n):
        env = {**env_base, "PT_TRAINER_ID": str(rank),
               **extra_env_per_rank.get(rank, {})}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "fleet_obs_worker.py")],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    return procs


def _read_port(proc, timeout=120):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("OBS_PORT "):
            return int(line.split()[1])
    raise AssertionError("rank 0 never printed OBS_PORT")


def _scrape(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.read()


def _finish(procs, timeout=60):
    # signal every worker FIRST: reaping rank 0 (the coord server)
    # before a slow peer finished its steps would otherwise yank the
    # server out from under it
    for p in procs:
        try:
            p.stdin.write("exit\n")
            p.stdin.flush()
        except OSError:
            pass  # already dead (the dead-worker drill's victim)
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
        outs.append((p.returncode, out, err))
    return outs


def _poll_fleet(port, predicate, timeout=60, interval=0.2):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = json.loads(_scrape(port, "/fleet"))
            if predicate(last):
                return last
        except Exception:
            pass
        time.sleep(interval)
    raise AssertionError(f"/fleet never satisfied predicate; last: "
                         f"{json.dumps(last)[:2000] if last else None}")


def test_four_worker_fleet_view_and_straggler_drill():
    """4 workers publish digests; rank 0's /fleet shows every rank with
    a phase breakdown; a seeded faults.py delay on rank 2 is detected
    and attributed (rank 2, dispatch phase) within 16 steps."""
    from paddle_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    procs = _spawn_fleet(4, {
        2: {"PT_FLAGS_fault_plan": "executor.step:delay(0.08)@p1.0",
            "PT_FLAGS_fault_seed": "7"},
    }, steps=30)
    try:
        port = _read_port(procs[0])

        def _all_ranks_with_phases(view):
            if set(view["ranks"]) != {"0", "1", "2", "3"}:
                return False
            return all(isinstance(row.get("phases_ms"), dict)
                       for row in view["ranks"].values())

        view = _poll_fleet(port, _all_ranks_with_phases)
        for row in view["ranks"].values():
            assert set(row["phases_ms"]) == set(monitor.STEP_PHASES)
            assert row["dead"] is False

        view = _poll_fleet(
            port, lambda v: any(r["rank"] == 2 for r in v["stragglers"]))
        rec = next(r for r in view["stragglers"] if r["rank"] == 2)
        assert rec["phase"] == "dispatch"  # the delay lands there
        assert rec["factor"] > 2.0

        # merged Prometheus exposition carries every rank
        text = _scrape(port, "/metrics?fleet=1").decode()
        for r in range(4):
            assert f'pt_executor_steps_total{{rank="{r}"}}' in text
        # the JSON index (satellite): the new routes are discoverable
        index = json.loads(_scrape(port, "/"))
        assert "/fleet" in index["routes"]
    finally:
        outs = _finish(procs)
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} failed:\n{out}\n{err}"
    # rank 0's final aggregate round-trips the digest schema
    line = [l for l in outs[0][1].splitlines()
            if l.startswith("OBS_RESULT ")][-1]
    result = json.loads(line[len("OBS_RESULT "):])
    for r, row in result["view"]["ranks"].items():
        digest = {k: v for k, v in row.items()
                  if k not in ("age_ms", "dead")}
        monitor.validate_fleet_digest(digest)
    # detection latency bound (acceptance): rank 0 aggregates every
    # step, and the FIRST record naming rank 2 must land within 16 of
    # rank 2's steps — the drill delays it from its very first step
    first = next(r for r in result["stragglers"] if r["rank"] == 2)
    assert 0 < first["steps"] <= 16
    assert first["phase"] == "dispatch"


def test_dead_worker_marked_by_heartbeat_age():
    """Rank 3 dies abruptly mid-run: /fleet marks it dead via digest/
    heartbeat age instead of serving its stale row as live, while the
    survivors stay alive."""
    from paddle_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    procs = _spawn_fleet(4, {
        3: {"PT_OBS_DIE_RANK": "3", "PT_OBS_DIE_STEP": "5"},
    }, steps=40)
    try:
        port = _read_port(procs[0])
        view = _poll_fleet(
            port,
            lambda v: 3 in v.get("dead", []) and all(
                r in v.get("ranks", {}) and not v["ranks"][r]["dead"]
                for r in ("0", "1", "2")),
            timeout=90)
        assert view["ranks"]["3"]["dead"] is True
        assert view["ranks"]["3"]["age_ms"] > 0
        # survivors serve fresh rows
        for r in ("0", "1", "2"):
            assert view["ranks"][r]["dead"] is False
        # the dead rank is never named a straggler for being silent
        assert all(rec["rank"] != 3 for rec in view["stragglers"])
    finally:
        outs = _finish(procs)
    for rank in (0, 1, 2):
        rc, out, err = outs[rank]
        assert rc == 0, f"rank {rank} failed:\n{out}\n{err}"
