"""End-to-end failure recovery (VERDICT r4 item 6): kill one of 4 fleet
workers mid-train; survivors detect the death through coord liveness
(csrc/coord.cc op 'L' via fleet.barrier_or_dead), re-rendezvous as a
3-worker world, restore the per-step checkpoint, and finish training —
with per-step loss parity against an uninterrupted single-process run
of the same global batches.

Reference bar: SURVEY.md §5 failure-detection bullet (the reference's
heartbeat plane plus the recovery loop it never demonstrates)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_losses():
    sys.path.insert(0, HERE)
    try:
        import fleet_recover_worker as fw
    finally:
        sys.path.pop(0)
    main, startup, loss = fw.build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        out = []
        for x, y in fw.global_batches():
            out.append(float(
                exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[loss])[0]))
    return out


def test_fleet_kill_one_worker_recover(tmp_path):
    from paddle_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    n, kill_rank, kill_step = 4, 3, 2
    env_base = {
        **os.environ,
        "PT_TRAINERS": str(n),
        "PT_COORD_ENDPOINT": f"127.0.0.1:{_free_port()}",
        "PT_JAX_COORD_ENDPOINT": f"127.0.0.1:{_free_port()}",
        "PT_RECOVER_PORT": str(_free_port()),
        "PT_RECOVER_JAX_PORT": str(_free_port()),
        "PT_CKPT_DIR": str(tmp_path / "ckpt"),
        "PT_KILL_RANK": str(kill_rank),
        "PT_KILL_STEP": str(kill_step),
        "JAX_PLATFORMS": "",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE), os.environ.get("PYTHONPATH", "")]
        ),
    }
    os.makedirs(tmp_path / "ckpt", exist_ok=True)
    procs = []
    for rank in range(n):
        env = {**env_base, "PT_TRAINER_ID": str(rank)}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "fleet_recover_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        ))
    results = {}
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        if rank == kill_rank:
            assert p.returncode == 1, \
                f"victim should have died abruptly:\n{out}\n{err}"
            continue
        assert p.returncode == 0, f"worker {rank} failed:\n{out}\n{err}"
        line = [l for l in out.splitlines()
                if l.startswith("FLEET_RESULT ")]
        assert line, f"no result line from worker {rank}:\n{out}\n{err}"
        r = json.loads(line[-1][len("FLEET_RESULT "):])
        results[rank] = r

    assert set(results) == {0, 1, 2}
    single = _single_process_losses()
    for r in results.values():
        # every survivor went through recovery: generation 1, shrunk
        # world, resumed exactly at the kill step, having SEEN the dead
        # worker through the liveness query
        assert r["gen"] == 1 and r["world"] == n - 1
        assert r["start_step"] == kill_step
        assert r["dead_seen"] == [f"worker-{kill_rank}"]
        # the resumed trajectory matches the uninterrupted run
        np.testing.assert_allclose(r["losses"], single[kill_step:],
                                   rtol=1e-4, atol=1e-5)
    assert results[0]["losses"][-1] < single[0]  # learning resumed
