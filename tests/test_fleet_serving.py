"""Fleet front door (fleet_serving.py): routed multi-replica serving
with failover replay, autoscaling, and zero-downtime rolling rollout.

The load-bearing drills:

- **routing**: requests spread across replicas by estimated
  time-to-first-token, every stream byte-identical to an undisturbed
  single-engine run; a replica's refusal (QueueFull / deadline) moves
  the request to the next candidate, and the fleet sheds only when
  EVERY replica refuses.
- **kill-one-replica** (the acceptance drill): 3 replicas under load,
  one hard-killed mid-decode via ``router.replica_crash`` — every
  in-flight request still completes with byte-identical greedy tokens,
  the client-visible stream is MONOTONE across the failover (no
  duplicate, no gap), and each request's whole life stays on ONE trace
  tid.
- **journal edge cases**: replica dies mid-prefill (replay from
  scratch), mid-decode (continuation), and during a drain handoff
  (torn ``router.handoff`` degrades to hard harvest — nothing lost).
- **rollout**: a rolling weight rollout rotates every replica to the
  new generation with zero rejected-for-rollout requests; responses
  carry the generation that served them.
- **autoscale**: sustained queue saturation spins a replica up,
  sustained idleness drains-then-retires one; the warm spin-up adds
  zero compile-cache misses (subprocess drill via
  tests/fleet_serve_worker.py).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import faults, fleet_serving, flags, monitor, serving
from paddle_tpu.models import transformer as T

BOS, EOS = 0, 1
HERE = os.path.dirname(os.path.abspath(__file__))


def tiny_cfg():
    return T.TransformerConfig(
        src_vocab_size=37, trg_vocab_size=41, max_length=64,
        d_model=16, d_inner=32, n_head=2, n_layer=1,
        dropout=0.0, label_smooth_eps=0.0,
    )


@pytest.fixture(scope="module")
def weights():
    cfg = tiny_cfg()
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return cfg, scope


def _srcs(k, seed=0, lens=(5, 3, 7, 4, 6, 2, 8, 5)):
    r = np.random.RandomState(seed)
    return [r.randint(2, 37, (lens[i % len(lens)],)).astype(np.int64)
            for i in range(k)]


def _undisturbed(cfg, scope, srcs, slots=2, max_new_tokens=None):
    """Token streams of an undisturbed single-engine run at the SAME
    slot geometry as the fleet's replicas (the byte-identity oracle is
    compared executable-for-executable)."""
    eng = serving.ServingEngine(cfg, scope, slots=slots, src_len=8,
                                max_len=12, bos_id=BOS, end_id=EOS)
    out = []
    for s in srcs:
        q = eng.submit(s, max_new_tokens=max_new_tokens)
        eng.run_until_idle()
        out.append(list(q.tokens))
    eng.close()
    return out


def _fleet(cfg, scope, replicas=3, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("src_len", 8)
    kw.setdefault("max_len", 12)
    kw.setdefault("bos_id", BOS)
    kw.setdefault("end_id", EOS)
    kw.setdefault("poll_s", 0.005)
    return fleet_serving.ServingFleet(cfg, scope, replicas=replicas,
                                      **kw)


def _wait_tokens(frs, n=1, timeout=60.0):
    """Block until every request has streamed >= n tokens (the drill's
    'mid-decode' gate) — or is already terminal."""
    t0 = time.time()
    while time.time() - t0 < timeout:
        if all(len(fr.tokens) >= n or fr.done for fr in frs):
            return
        time.sleep(0.002)
    raise TimeoutError("requests never reached mid-decode")


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache(tmp_path_factory):
    """Every fleet in this module shares one persistent compile-cache
    dir: replica spin-ups after the first resolve their executables
    from disk (the warm-start path the autoscaler rides) instead of
    re-compiling per test."""
    d = tmp_path_factory.mktemp("fleet_cc")
    old = flags.get_flag("compile_cache_dir")
    flags.set_flags({"compile_cache_dir": str(d)})
    try:
        yield
    finally:
        flags.set_flags({"compile_cache_dir": old})


@pytest.fixture()
def telemetry():
    flags.set_flags({"telemetry": True})
    try:
        yield
    finally:
        flags.set_flags({"telemetry": False})


# --------------------------------------------------------------------------
# routing
# --------------------------------------------------------------------------

def test_fleet_streams_byte_identical_and_spread(weights):
    """Requests routed across the fleet produce streams byte-identical
    to an undisturbed single-engine run, and a cold fleet spreads load
    instead of piling everything on one replica."""
    cfg, scope = weights
    srcs = _srcs(6, seed=7)
    clean = _undisturbed(cfg, scope, srcs)
    fleet = _fleet(cfg, scope, replicas=3)
    try:
        frs = [fleet.submit(s) for s in srcs]
        streams = [fr.result(timeout=60) for fr in frs]
        assert streams == clean
        assert len({fr.replica_id for fr in frs}) > 1
        assert all(fr.generation == 0 for fr in frs)
        assert all(fr.outcome in ("completed", "length") for fr in frs)
    finally:
        fleet.close()


def test_router_prefers_less_loaded_replica(weights):
    """With one replica's queue stuffed, a new submit lands on the
    other (the estimated-TTFT score reads queue + in-flight backlog)."""
    cfg, scope = weights
    fleet = _fleet(cfg, scope, replicas=2, slots=1)
    try:
        faults.arm("serve.decode:delay(0.05)@p1.0", seed=3)
        try:
            first = [fleet.submit(s, max_new_tokens=6)
                     for s in _srcs(2, seed=9)]
            loaded = {fr.replica_id for fr in first}
            # both replicas now hold work; the next submit must land on
            # the one with the SMALLER backlog, never error
            nxt = fleet.submit(_srcs(1, seed=10)[0], max_new_tokens=2)
            assert nxt.replica_id in {r["replica"]
                                      for r in fleet.stats()["replicas"]}
            assert len(loaded) == 2  # the cold spread
        finally:
            faults.disarm()
        for fr in first + [nxt]:
            fr.result(timeout=60)
    finally:
        fleet.close()


def test_fleet_sheds_only_when_every_replica_refuses(weights,
                                                     telemetry):
    """Backpressure failover: submits beyond one replica's capacity
    spill to the next; once EVERY replica's queue is at capacity the
    fleet raises QueueFull (metered pt_fleet_serve_shed_total)."""
    cfg, scope = weights
    shed0 = monitor.counter("pt_fleet_serve_shed_total").value(
        labels={"kind": "queue_full"})
    fleet = _fleet(cfg, scope, replicas=2, slots=1, queue_depth=1)
    try:
        faults.arm("serve.decode:delay(0.1)@p1.0", seed=5)
        try:
            # capacity: 2 replicas x (1 slot + 1 queue entry) = 4
            admitted = []
            srcs = _srcs(8, seed=21)
            with pytest.raises(serving.QueueFull):
                for s in srcs:
                    admitted.append(
                        fleet.submit(s, max_new_tokens=4))
        finally:
            faults.disarm()
        assert len(admitted) >= 3  # spilled across BOTH replicas
        assert len({fr.replica_id for fr in admitted}) == 2
        assert monitor.counter("pt_fleet_serve_shed_total").value(
            labels={"kind": "queue_full"}) > shed0
        for fr in admitted:
            fr.result(timeout=120)
    finally:
        fleet.close()


def test_router_route_site_failure_surfaces(weights):
    """router.route:raise drills a routing-plane failure: the caller
    sees the fault, no replica is charged, and the NEXT submit routes
    normally."""
    cfg, scope = weights
    fleet = _fleet(cfg, scope, replicas=2)
    try:
        clean = _undisturbed(cfg, scope, _srcs(1, seed=33))
        faults.arm("router.route:raise(routing torn)@1")
        try:
            with pytest.raises(faults.InjectedFault):
                fleet.submit(_srcs(1, seed=33)[0])
            assert fleet.stats()["in_flight"] == 0
            fr = fleet.submit(_srcs(1, seed=33)[0])  # hit 2: clean
        finally:
            faults.disarm()
        assert fr.result(timeout=60) == clean[0]
    finally:
        fleet.close()


# --------------------------------------------------------------------------
# the kill-one-replica acceptance drill + journal edge cases
# --------------------------------------------------------------------------

def test_kill_one_replica_mid_decode_chaos_drill(weights, telemetry,
                                                 tmp_path):
    """THE acceptance drill: 3 replicas under load, one hard-killed
    mid-decode (router.replica_crash). Every in-flight request
    completes with byte-identical greedy tokens, the client-visible
    stream never shrinks or duplicates across the failover, and each
    request's whole life — including the replay on the survivor —
    stays on ONE trace tid."""
    cfg, scope = weights
    flags.set_flags({"trace_dir": str(tmp_path)})
    srcs = _srcs(6, seed=41)
    clean = _undisturbed(cfg, scope, srcs, max_new_tokens=8)
    fleet = _fleet(cfg, scope, replicas=3)
    try:
        # slow decode keeps the fleet mid-flight while the kill lands
        faults.arm("serve.decode:delay(0.03)@p1.0", seed=11)
        frs = [fleet.submit(s, max_new_tokens=8) for s in srcs]
        _wait_tokens(frs, n=1)
        snapshots = {id(fr): list(fr.tokens) for fr in frs}
        # re-arm with the kill riding along (hit 1 = next pump tick);
        # replica=0 is the lowest-id live replica
        faults.arm("serve.decode:delay(0.03)@p1.0;"
                   "router.replica_crash:raise(replica=0)@1", seed=11)
        try:
            streams = []
            for fr in frs:
                streams.append(fr.result(timeout=120))
                # monotone across the failover: the final stream
                # extends what the client had already seen
                pre = snapshots[id(fr)]
                assert streams[-1][:len(pre)] == pre
        finally:
            faults.disarm()
        assert streams == clean
        assert fleet.failovers >= 1
        assert fleet.stats()["replica_count"] == 2
        rehomed = [fr for fr in frs if fr.failovers >= 1]
        assert rehomed, "the kill landed on a replica with no work"
        for fr in rehomed:
            evs = [e for e in monitor.trace_events()
                   if e.get("args", {}).get("req") == fr.trace_id]
            tids = {e["tid"] for e in evs}
            assert tids == {fr.trace_tid}, (
                f"{fr.trace_id} smeared over tracks {tids}")
            assert [e["name"] for e in evs].count("submit") == 1
    finally:
        fleet.close()
        flags.set_flags({"trace_dir": ""})


def test_replica_dies_mid_prefill_replays_from_scratch(weights):
    """A request still queued (zero tokens — 'mid-prefill') on the
    killed replica replays from scratch on a survivor and emits the
    full byte-identical stream."""
    cfg, scope = weights
    srcs = _srcs(6, seed=55)
    clean = _undisturbed(cfg, scope, srcs, slots=1, max_new_tokens=6)
    # slots=1 per replica: with 6 requests over 2 replicas, several
    # are still queued (no tokens) when the kill lands
    fleet = _fleet(cfg, scope, replicas=2, slots=1)
    try:
        faults.arm("serve.decode:delay(0.04)@p1.0;"
                   "router.replica_crash:raise(replica=0)@3", seed=13)
        try:
            frs = [fleet.submit(s, max_new_tokens=6) for s in srcs]
            streams = [fr.result(timeout=120) for fr in frs]
        finally:
            faults.disarm()
        assert streams == clean
        assert fleet.failovers >= 1
        rehomed = [fr for fr in frs if fr.failovers >= 1]
        assert rehomed
        # the replay wiped nothing the client had: every re-homed
        # request's final stream is complete
        for fr in rehomed:
            assert fr.outcome in ("completed", "length")
    finally:
        fleet.close()


def test_replica_dies_during_drain_handoff(weights):
    """router.handoff tears a rolling-rollout drain mid-handoff: the
    draining replica is hard-harvested instead, and its requests still
    re-home and complete byte-identically — nothing finishes 'drained'
    or 'error'."""
    cfg, scope = weights
    srcs = _srcs(4, seed=61)
    clean = _undisturbed(cfg, scope, srcs, max_new_tokens=8)
    fleet = _fleet(cfg, scope, replicas=2)
    try:
        faults.arm("serve.decode:delay(0.03)@p1.0;"
                   "router.handoff:raise(handoff torn)@1", seed=17)
        try:
            frs = [fleet.submit(s, max_new_tokens=8) for s in srcs]
            _wait_tokens(frs, n=1)
            out = fleet.rollout(scope)
        finally:
            faults.disarm()
        assert out["replicas_rotated"] == 2
        streams = [fr.result(timeout=120) for fr in frs]
        assert streams == clean
        assert all(fr.outcome in ("completed", "length") for fr in frs)
    finally:
        fleet.close()


def test_budget_exhausted_supervisor_hands_off_to_fleet(weights):
    """A supervisor whose restart budget is exhausted no longer fails
    its pending requests: the on_handoff seam gives them to the fleet,
    which replays them on the survivor (outcome completed, stream
    byte-identical); the pump reaps the dead replica."""
    cfg, scope = weights
    srcs = _srcs(4, seed=71)
    clean = _undisturbed(cfg, scope, srcs, max_new_tokens=6)
    fleet = _fleet(cfg, scope, replicas=2, max_restarts=0)
    try:
        # unhinted decode raise = engine-fatal on whichever replica
        # takes hit 4; with max_restarts=0 its supervisor goes
        # terminal immediately
        faults.arm("serve.decode:delay(0.02)@p1.0;"
                   "serve.decode:raise(engine fatal)@4", seed=19)
        try:
            frs = [fleet.submit(s, max_new_tokens=6) for s in srcs]
            streams = [fr.result(timeout=120) for fr in frs]
        finally:
            faults.disarm()
        assert streams == clean
        assert all(fr.outcome in ("completed", "length") for fr in frs)
        t0 = time.time()
        while fleet.stats()["replica_count"] != 1 and \
                time.time() - t0 < 10:
            time.sleep(0.01)
        assert fleet.stats()["replica_count"] == 1
        assert fleet.failovers >= 1
    finally:
        fleet.close()


# --------------------------------------------------------------------------
# rolling rollout + autoscale
# --------------------------------------------------------------------------

def test_rolling_rollout_zero_downtime(weights):
    """rollout() rotates every replica to the new generation while
    requests keep flowing: zero rejected-for-rollout outcomes, streams
    byte-identical, and responses tag the generation that served them
    (mixed tags mid-rollout are the detectability contract)."""
    cfg, scope = weights
    srcs = _srcs(8, seed=81)
    clean = _undisturbed(cfg, scope, srcs, max_new_tokens=6)
    fleet = _fleet(cfg, scope, replicas=2)
    try:
        faults.arm("serve.decode:delay(0.02)@p1.0", seed=23)
        try:
            pre = [fleet.submit(s, max_new_tokens=6)
                   for s in srcs[:4]]
            _wait_tokens(pre, n=1)
            out = fleet.rollout(scope)  # same weights, new generation
            post = [fleet.submit(s, max_new_tokens=6)
                    for s in srcs[4:]]
            streams = [fr.result(timeout=120) for fr in pre + post]
        finally:
            faults.disarm()
        assert streams == clean
        assert out == {"generation": 1, "replicas_rotated": 2,
                       "replicas": 2}
        # nothing was rejected for the rollout's sake
        assert all(fr.outcome in ("completed", "length")
                   for fr in pre + post)
        # post-rollout admissions carry the new generation tag
        assert all(fr.generation == 1 for fr in post)
        assert all(r["generation"] == 1
                   for r in fleet.stats()["replicas"])
        assert fleet.stats()["generation"] == 1
    finally:
        fleet.close()


def test_autoscale_up_under_saturation_and_down_when_idle(weights):
    """The autoscaler's both directions, driven deterministically via
    autoscale_tick(): sustained queue saturation spins a replica up;
    sustained idleness drains-then-retires back to the floor."""
    cfg, scope = weights
    flags.set_flags({"serve_fleet_autoscale_window": 2,
                     "serve_fleet_scale_down_idle_ticks": 3,
                     "serve_fleet_scale_up_queue_factor": 0.5})
    fleet = _fleet(cfg, scope, replicas=1, slots=1, queue_depth=2,
                   min_replicas=1, max_replicas=2)
    try:
        faults.arm("serve.decode:delay(0.05)@p1.0", seed=29)
        try:
            srcs = _srcs(3, seed=91)
            # first request must reach the slot BEFORE the queue is
            # stuffed: 3 rapid submits against queue_depth=2 would shed
            # the third whenever the loop thread hasn't admitted yet
            frs = [fleet.submit(srcs[0], max_new_tokens=4)]
            t0 = time.time()
            while (fleet.stats()["queue_depth"] > 0
                   and time.time() - t0 < 30):
                time.sleep(0.002)
            frs += [fleet.submit(s, max_new_tokens=4)
                    for s in srcs[1:]]
            acts = [fleet.autoscale_tick() for _ in range(2)]
            assert acts[-1] == "up"
            assert fleet.stats()["replica_count"] == 2
            assert fleet.scale_ups == 1
        finally:
            faults.disarm()
        for fr in frs:
            fr.result(timeout=120)
        fleet.drain(timeout_s=60)
        acts = [fleet.autoscale_tick() for _ in range(3)]
        assert acts[-1] == "down"
        assert fleet.stats()["replica_count"] == 1
        assert fleet.scale_downs == 1
        # the retired replica drained: nothing errored, and a fresh
        # submit still serves
        fr = fleet.submit(_srcs(1, seed=92)[0], max_new_tokens=2)
        assert fr.result(timeout=60) is not None
    finally:
        faults.disarm()
        fleet.close()
        flags.set_flags({
            name: flags._DEFS[name][1]
            for name in ("serve_fleet_autoscale_window",
                         "serve_fleet_scale_down_idle_ticks",
                         "serve_fleet_scale_up_queue_factor")})


def test_warm_spinup_zero_fresh_compiles(tmp_path):
    """Two fresh 'fleet host' processes (tests/fleet_serve_worker.py)
    against one compile-cache dir: scaling out a replica in-process
    adds zero disk-tier misses (the spin-up resolves from the cache
    the first replica populated), and the warm process resolves EVERY
    executable from disk — misses == 0 — with byte-identical tokens."""
    cache_d = str(tmp_path / "cc")
    env = {**os.environ, "PYTHONPATH": os.path.dirname(HERE)}

    def launch():
        out = subprocess.run(
            [sys.executable, os.path.join(HERE, "fleet_serve_worker.py"),
             cache_d],
            capture_output=True, text=True, timeout=600, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = launch()
    assert cold["stats"]["misses"] > 0
    assert cold["spinup_misses"] == 0, cold
    assert cold["replica_count"] == 2
    assert cold["scaled_tokens"] == cold["tokens"]

    warm = launch()
    assert warm["stats"]["misses"] == 0, warm
    assert warm["spinup_misses"] == 0
    assert warm["tokens"] == cold["tokens"]
    assert warm["scaled_tokens"] == cold["tokens"]


# --------------------------------------------------------------------------
# observability + lifecycle
# --------------------------------------------------------------------------

def test_fleet_view_and_request_records(weights, telemetry):
    """fleet_view() (the /fleet route's serving_fleet section) exposes
    per-replica state, queue depth, generation and heartbeat age; the
    fleet metrics tick; request records carry the serving replica."""
    cfg, scope = weights
    assert fleet_serving.fleet_view() is None  # no fleet up
    routed0 = monitor.counter("pt_fleet_serve_routed_total").value()
    fleet = _fleet(cfg, scope, replicas=2)
    try:
        frs = [fleet.submit(s) for s in _srcs(3, seed=95)]
        for fr in frs:
            fr.result(timeout=60)
        view = fleet_serving.fleet_view()
        assert view is not None and view["fleet_count"] == 1
        row = view["fleets"][0]
        assert row["replica_count"] == 2
        assert row["generation"] == 0
        for rep in row["replicas"]:
            assert rep["state"] == "serving"
            assert {"queue_depth", "generation",
                    "heartbeat_age_ms"} <= set(rep)
        assert sum(r["routed"] for r in row["replicas"]) == 3
        assert monitor.counter(
            "pt_fleet_serve_routed_total").value() == routed0 + 3
        # every handle knows which replica served it
        assert all(fr.replica_id in
                   {r["replica"] for r in row["replicas"]}
                   for fr in frs)
    finally:
        fleet.close()
    assert fleet_serving.fleet_view() is None  # closed fleets drop out


def test_close_finishes_every_handle(weights):
    """close() on a fleet with work in flight: every handle reaches a
    terminal outcome — result() never hangs on a closed fleet."""
    cfg, scope = weights
    fleet = _fleet(cfg, scope, replicas=2)
    faults.arm("serve.decode:delay(0.05)@p1.0", seed=31)
    try:
        frs = [fleet.submit(s, max_new_tokens=8)
               for s in _srcs(4, seed=97)]
    finally:
        faults.disarm()
    fleet.close(drain_timeout_s=0.2)
    for fr in frs:
        assert fr.result(timeout=10) is not None
        assert fr.outcome is not None


def test_router_fault_sites_registered():
    """The router.* chaos sites are declaratively discoverable."""
    names = set(faults.sites())
    assert {"router.route", "router.replica_crash",
            "router.handoff"} <= names
    for s in ("router.route", "router.replica_crash",
              "router.handoff"):
        assert faults.BUILTIN_SITES[s]
