"""Program IR construction, shape inference, serialization round-trip."""


import paddle_tpu as fluid
from paddle_tpu import layers


def _build_mlp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.fc(x, 32, act="relu")
        out = layers.fc(h, 4)
        loss = layers.mean(layers.softmax(out))
        drop = layers.dropout(h, 0.5)
    return main, startup, x, h, out


def test_shape_inference():
    main, startup, x, h, out = _build_mlp()
    assert x.shape == (-1, 8)
    assert h.shape == (-1, 32)
    assert out.shape == (-1, 4)


def test_parameters_created():
    main, startup, *_ = _build_mlp()
    params = main.all_parameters()
    names = sorted(p.name for p in params)
    assert len(params) == 4  # 2x (w, b)
    shapes = {p.name: p.shape for p in params}
    assert (8, 32) in shapes.values()
    assert (32, 4) in shapes.values()
    # startup program initializes every parameter
    startup_outs = {
        n for op in startup.global_block().ops for n in op.output_arg_names
    }
    for p in params:
        assert p.name in startup_outs


def test_proto_roundtrip():
    main, *_ = _build_mlp()
    s = main.desc_str()
    clone = fluid.Program.parse_from_string(s)
    assert len(clone.global_block().ops) == len(main.global_block().ops)
    assert sorted(clone.global_block().vars) == sorted(main.global_block().vars)
    for a, b in zip(main.global_block().ops, clone.global_block().ops):
        assert a.type == b.type
        assert a.inputs == b.inputs
        assert a.outputs == b.outputs
        assert a.attrs == b.attrs


def test_clone_for_test_sets_is_test():
    main, *_ = _build_mlp()
    test_prog = main.clone(for_test=True)
    drops = [op for op in test_prog.global_block().ops if op.type == "dropout"]
    assert drops and all(op.attrs["is_test"] for op in drops)
    # original untouched
    drops0 = [op for op in main.global_block().ops if op.type == "dropout"]
    assert all(not op.attrs.get("is_test") for op in drops0)


def test_operator_accessors():
    main, *_ = _build_mlp()
    op = main.global_block().ops[0]
    assert op.type == "mul"
    assert op.input("X") and op.input("Y")
    assert op.output("Out")


def test_variable_arithmetic_builds_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[4], dtype="float32")
        b = layers.data("b", shape=[4], dtype="float32")
        c = a + b
        d = c * 2.0
    types = [op.type for op in main.global_block().ops]
    assert "elementwise_add" in types
    assert "elementwise_mul" in types
