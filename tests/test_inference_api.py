"""Predictor API tests (reference: inference/api/analysis_predictor.cc,
api demos using create_paddle_predictor)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import inference, io, layers


@pytest.fixture()
def saved_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        h = layers.fc(x, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="p1.w"),
                      bias_attr=fluid.ParamAttr(name="p1.b"))
        logits = layers.fc(h, 4,
                           param_attr=fluid.ParamAttr(name="p2.w"),
                           bias_attr=fluid.ParamAttr(name="p2.b"))
        probs = layers.softmax(logits)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    d = str(tmp_path / "model")
    xv = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[probs])
        io.save_inference_model(d, ["x"], [probs], exe, main)
    return d, xv, ref


def test_predictor_matches_direct_run(saved_model):
    d, xv, ref = saved_model
    pred = inference.create_predictor(inference.Config(d).disable_tpu())
    assert pred.get_input_names() == ["x"]
    assert len(pred.get_output_names()) == 1
    (out,) = pred.run([xv])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    # dict-keyed feeds too
    (out2,) = pred.run({"x": xv})
    np.testing.assert_allclose(out2, ref, rtol=1e-5, atol=1e-6)


def test_predictor_shape_polymorphism(saved_model):
    """Each new batch shape compiles once and caches (the executor cache
    replaces the reference's per-shape TRT engine rebuild)."""
    d, xv, _ = saved_model
    pred = inference.create_predictor(inference.Config(d).disable_tpu())
    for b in (1, 3, 8):
        (out,) = pred.run([xv[:b]])
        assert out.shape == (b, 4)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_predictor_input_validation(saved_model):
    d, xv, _ = saved_model
    pred = inference.create_predictor(inference.Config(d).disable_tpu())
    with pytest.raises(ValueError, match="expected 1 inputs"):
        pred.run([xv, xv])
    with pytest.raises(KeyError, match="missing"):
        pred.run({"not_x": xv})


def test_predictor_isolated_scopes(saved_model):
    """Two predictors don't share state (reference: per-predictor scope)."""
    d, xv, ref = saved_model
    p1 = inference.create_predictor(inference.Config(d).disable_tpu())
    p2 = inference.create_predictor(inference.Config(d).disable_tpu())
    p2.scope.set("p1.w", np.zeros_like(p2.scope.find_var("p1.w")))
    (out1,) = p1.run([xv])
    np.testing.assert_allclose(out1, ref, rtol=1e-5, atol=1e-6)
