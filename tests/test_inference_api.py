"""Predictor API tests (reference: inference/api/analysis_predictor.cc,
api demos using create_paddle_predictor)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import inference, io, layers


@pytest.fixture()
def saved_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        h = layers.fc(x, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="p1.w"),
                      bias_attr=fluid.ParamAttr(name="p1.b"))
        logits = layers.fc(h, 4,
                           param_attr=fluid.ParamAttr(name="p2.w"),
                           bias_attr=fluid.ParamAttr(name="p2.b"))
        probs = layers.softmax(logits)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    d = str(tmp_path / "model")
    xv = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[probs])
        io.save_inference_model(d, ["x"], [probs], exe, main)
    return d, xv, ref


def test_predictor_matches_direct_run(saved_model):
    d, xv, ref = saved_model
    pred = inference.create_predictor(inference.Config(d).disable_tpu())
    assert pred.get_input_names() == ["x"]
    assert len(pred.get_output_names()) == 1
    (out,) = pred.run([xv])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    # dict-keyed feeds too
    (out2,) = pred.run({"x": xv})
    np.testing.assert_allclose(out2, ref, rtol=1e-5, atol=1e-6)


def test_predictor_shape_polymorphism(saved_model):
    """Each new batch shape compiles once and caches (the executor cache
    replaces the reference's per-shape TRT engine rebuild)."""
    d, xv, _ = saved_model
    pred = inference.create_predictor(inference.Config(d).disable_tpu())
    for b in (1, 3, 8):
        (out,) = pred.run([xv[:b]])
        assert out.shape == (b, 4)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_predictor_input_validation(saved_model):
    d, xv, _ = saved_model
    pred = inference.create_predictor(inference.Config(d).disable_tpu())
    with pytest.raises(ValueError, match="expected 1 inputs"):
        pred.run([xv, xv])
    with pytest.raises(KeyError, match="missing"):
        pred.run({"not_x": xv})


def test_predictor_isolated_scopes(saved_model):
    """Two predictors don't share state (reference: per-predictor scope)."""
    d, xv, ref = saved_model
    p1 = inference.create_predictor(inference.Config(d).disable_tpu())
    p2 = inference.create_predictor(inference.Config(d).disable_tpu())
    p2.scope.set("p1.w", np.zeros_like(p2.scope.find_var("p1.w")))
    (out1,) = p1.run([xv])
    np.testing.assert_allclose(out1, ref, rtol=1e-5, atol=1e-6)


def test_predictor_warmup_and_run_batch(saved_model):
    d, xv, ref = saved_model
    pred = inference.create_predictor(inference.Config(d))
    pred.warmup(shapes={"x": (4, 16)})
    # arbitrary batch through fixed-signature executables: 11 rows with
    # max_batch_size 4 -> 2 full chunks + padded tail, padding dropped
    big = np.concatenate([xv, xv[:3]])
    out = pred.run_batch({"x": big}, max_batch_size=4)[0]
    assert out.shape[0] == 11
    np.testing.assert_allclose(out[:8], ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out[8:], ref[:3], rtol=1e-5, atol=1e-6)
    # steady state: only signature (4,16) compiled — no per-size
    # compiles. The canonical-fingerprint cache key folds the feed
    # signature into the entry fingerprint, so one signature (and one
    # fetch list/scope) means exactly one compiled entry.
    assert len(pred._exe._cache) == 1


def test_predictor_close_releases_entries_and_blocks_run(saved_model):
    """close() releases the predictor's compiled entries + its scope
    (mirroring Executor.close scoped to this predictor) and a later run
    raises instead of recompiling against a cleared scope."""
    d, xv, ref = saved_model
    pred = inference.create_predictor(inference.Config(d).disable_tpu())
    (out,) = pred.run([xv])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    assert len(pred._exe._cache) == 1
    assert len(pred.scope.var_names()) > 0
    pred.close()
    assert len(pred._exe._cache) == 0
    assert pred.scope.var_names() == []
    with pytest.raises(RuntimeError, match="close"):
        pred.run([xv])
    pred.close()  # idempotent


def test_release_scope_drops_only_that_scope_entries(saved_model):
    """Executor.release_scope is per-tenant: two predictor-style scopes
    through ONE executor; retiring one must not cold-start the other."""
    import paddle_tpu as fluid
    from paddle_tpu import layers, monitor

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.softmax(layers.fc(x, 2))
    exe = fluid.Executor(fluid.CPUPlace())
    s1, s2 = fluid.Scope(), fluid.Scope()
    xv = np.ones((2, 4), np.float32)
    for s in (s1, s2):
        with fluid.scope_guard(s):
            exe.run(startup)
            exe.run(main, feed={"x": xv}, fetch_list=[y])
    n0 = len(exe._cache)
    assert exe.release_scope(s1) >= 1
    assert len(exe._cache) < n0
    # the survivor still hits: no fresh compile for scope 2
    misses0 = monitor.counter("pt_executor_cache_misses_total").value()
    with fluid.scope_guard(s2):
        exe.run(main, feed={"x": xv}, fetch_list=[y])
    assert monitor.counter(
        "pt_executor_cache_misses_total").value() == misses0


def test_batch_bucketing_bounds_compiled_shapes(saved_model):
    """set_batch_buckets: a randomized batch-size sweep must compile at
    most one executable per bucket (today's alternative: one per
    observed size) while matching the exact-shape outputs."""
    d, xv, _ = saved_model
    exact = inference.create_predictor(inference.Config(d).disable_tpu())
    pred = inference.create_predictor(
        inference.Config(d).disable_tpu().set_batch_buckets([2, 4, 8]))
    rng = np.random.RandomState(7)
    sizes = list(rng.randint(1, 11, size=12)) + [1, 10, 8, 3]
    for n in sizes:
        x = rng.randn(int(n), 16).astype(np.float32)
        (out,) = pred.run([x])
        assert out.shape[0] == n
        (want,) = exact.run([x])
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
    # the whole sweep compiled at most len(buckets) executables
    assert len(pred._exe._cache) <= 3
    # the exact-shape predictor compiled one per observed size
    assert len(exact._exe._cache) == len({int(n) for n in sizes})


def test_batch_bucket_validation():
    with pytest.raises(ValueError, match="positive"):
        inference.Config("x").set_batch_buckets([0, 2])
    with pytest.raises(ValueError, match="positive"):
        inference.Config("x").set_batch_buckets([])


@pytest.mark.full
def test_zoo_export_predictor_parity(tmp_path):
    """Every zoo family round-trips save_inference_model -> Predictor
    with numeric parity vs the in-process test program (VERDICT r2
    item 10)."""
    from paddle_tpu.models import resnet, vgg
    from paddle_tpu.models import transformer as T

    cases = {}

    # mnist-style MLP
    def build_mlp():
        img = layers.data("img", shape=[64], dtype="float32")
        probs = layers.softmax(layers.fc(layers.fc(img, 32, act="relu"), 10))
        feed = {"img": np.random.RandomState(0).randn(4, 64).astype(
            np.float32)}
        return ["img"], [probs], feed

    # conv net from the zoo (cifar-shape resnet)
    def build_resnet():
        img = layers.data("data", shape=[3, 32, 32], dtype="float32")
        logits = resnet.resnet_cifar10(img, class_dim=10, depth=20,
                                       is_test=True)
        feed = {"data": np.random.RandomState(1).randn(2, 3, 32, 32).astype(
            np.float32)}
        return ["data"], [logits], feed

    # vgg (small input)
    def build_vgg():
        img = layers.data("pixel", shape=[3, 32, 32], dtype="float32")
        logits = vgg.vgg16(img, class_dim=10, is_test=True, fc_dim=64)
        feed = {"pixel": np.random.RandomState(2).randn(2, 3, 32, 32).astype(
            np.float32)}
        return ["pixel"], [logits], feed

    # transformer encoder-decoder forward (is_test build)
    def build_transformer():
        cfg = T.TransformerConfig(
            src_vocab_size=100, trg_vocab_size=100, d_model=32, d_inner=64,
            n_head=2, n_layer=1, max_length=20, dropout=0.0)
        model = T.build(cfg, is_test=True)
        feed = T.make_batch(cfg, batch=2, src_len=8, trg_len=8, seed=3)
        feed.pop("lbl_word", None)
        feed.pop("lbl_weight", None)
        names = sorted(feed.keys())
        return names, [model["logits"]], feed

    builders = {"mlp": build_mlp, "resnet": build_resnet,
                "vgg": build_vgg, "transformer": build_transformer}
    for name, build in builders.items():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            feeds, fetches, feed = build()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        d = str(tmp_path / name)
        with fluid.scope_guard(scope):
            exe.run(startup)
            ref = exe.run(main, feed=feed, fetch_list=fetches)
            io.save_inference_model(d, feeds, fetches, exe, main)
        pred = inference.create_predictor(inference.Config(d))
        got = pred.run({k: feed[k] for k in pred.get_input_names()})
        for r, g in zip(ref, got):
            np.testing.assert_allclose(
                r, g, rtol=1e-4, atol=1e-5,
                err_msg=f"zoo model '{name}' predictor mismatch")


def test_export_is_staged_and_crash_leaves_no_partial_dir(tmp_path):
    """Satellite (ISSUE 5): a crash between the export's metadata and
    parameter writes must not publish a dir load_inference_model starts
    loading and then dies on — and a crash in the publish-swap window
    (previous export parked at <dir>.old.tmp) is recovered by the next
    export."""
    from paddle_tpu import faults
    import os

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        probs = layers.softmax(layers.fc(x, 4))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    d = str(tmp_path / "model")
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            faults.arm("io.export:raise@1")
            with pytest.raises(faults.InjectedFault):
                io.save_inference_model(d, ["x"], [probs], exe, main)
            faults.disarm()
            assert not os.path.isdir(d)  # torn export stayed staged
            io.save_inference_model(d, ["x"], [probs], exe, main)
            # crash in the swap window: dir gone, old export parked —
            # the LOAD path recovers it (a serving-only host never
            # exports again)
            os.rename(d, d + ".old.tmp")
            with fluid.scope_guard(fluid.Scope()):
                io.load_inference_model(d, exe)
            assert os.path.isdir(d) and not os.path.isdir(d + ".old.tmp")
            os.rename(d, d + ".old.tmp")  # and the save path recovers too
            io.save_inference_model(d, ["x"], [probs], exe, main)
        assert not os.path.isdir(d + ".tmp")
        assert not os.path.isdir(d + ".old.tmp")
        with fluid.scope_guard(fluid.Scope()):
            program, feeds, fetches = io.load_inference_model(d, exe)
            out = exe.run(program,
                          feed={"x": np.ones((2, 8), np.float32)},
                          fetch_list=fetches)
        assert np.asarray(out[0]).shape == (2, 4)
    finally:
        faults.disarm()
