"""Layer-API parity tail (layers/more.py): one big program exercising
the wrappers in a single compile (suite-time budget), plus semantic
spot checks against numpy."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def test_more_layers_one_program():
    main, startup = fluid.Program(), fluid.Program()
    B = 4
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[B, 6], append_batch_size=False,
                        stop_gradient=True)
        img = layers.data("img", shape=[B, 4, 8, 8], append_batch_size=False,
                          stop_gradient=True)
        seq = layers.data("seq", shape=[B, 5, 3], append_batch_size=False,
                          stop_gradient=True)
        length = layers.data("length", shape=[B], dtype="int64",
                             append_batch_size=False)
        lbl = layers.data("lbl", shape=[B, 1], dtype="int64",
                          append_batch_size=False)

        fetches = {}
        fetches["brelu"] = layers.brelu(x, 0.0, 1.0)
        fetches["soft_relu"] = layers.soft_relu(x)
        fetches["stanh"] = layers.stanh(x)
        fetches["selu"] = layers.selu(x)
        fetches["sign"] = layers.sign(x)
        fetches["cos_sim"] = layers.cos_sim(x, x)
        fetches["reduce_all"] = layers.reduce_all(
            layers.greater_equal(x, layers.scale(x, scale=1.0)))
        fetches["reduce_any"] = layers.reduce_any(
            layers.not_equal(x, layers.scale(x, scale=0.0)))
        fetches["isfinite"] = layers.isfinite(x)
        fetches["has_inf"] = layers.has_inf(x)
        fetches["has_nan"] = layers.has_nan(x)
        fetches["reverse"] = layers.reverse(x, axis=1)
        out_sorted, idx = layers.argsort(x, axis=1)
        fetches["argsort"] = out_sorted
        fetches["diag"] = layers.diag(
            layers.reshape(layers.slice(x, axes=[0], starts=[0], ends=[1]),
                           [6]))
        fetches["rank"] = layers.rank(x)

        probs = layers.softmax(x)
        fetches["bpr_loss"] = layers.bpr_loss(x, lbl)
        fetches["dice_loss"] = layers.dice_loss(probs, lbl)
        fetches["kldiv"] = layers.kldiv_loss(x, probs)
        fetches["log_loss"] = layers.log_loss(
            layers.sigmoid(layers.slice(x, axes=[1], starts=[0], ends=[1])),
            layers.cast(lbl, "float32"))
        half = layers.slice(x, axes=[1], starts=[0], ends=[1])
        other = layers.slice(x, axes=[1], starts=[1], ends=[2])
        fetches["margin_rank"] = layers.margin_rank_loss(
            layers.cast(lbl, "float32"), half, other)
        fetches["rank_loss"] = layers.rank_loss(
            layers.cast(lbl, "float32"), half, other)
        fetches["npair"] = layers.npair_loss(x, x, lbl)
        fetches["ts_loss"] = layers.teacher_student_sigmoid_loss(
            half, layers.cast(lbl, "float32"))

        fetches["apool2d"] = layers.adaptive_pool2d(img, [3, 2], "avg")
        fetches["pad2d"] = layers.pad2d(img, [1, 1, 2, 2])
        fetches["crop"] = layers.crop(img, shape=[B, 4, 4, 4],
                                      offsets=[0, 0, 1, 1])
        fetches["pixshuf"] = layers.pixel_shuffle(img, 2)
        fetches["shufch"] = layers.shuffle_channel(img, 2)
        fetches["s2d"] = layers.space_to_depth(img, 2)
        fetches["tshift"] = layers.temporal_shift(img, seg_num=2)
        ch_scale = layers.fill_constant(shape=[4], dtype="float32",
                                        value=2.0)
        ch_bias = layers.fill_constant(shape=[4], dtype="float32",
                                       value=0.5)
        fetches["affch"] = layers.affine_channel(img, ch_scale, ch_bias)
        fetches["resize"] = layers.resize_bilinear(img, out_shape=[4, 4])
        fetches["resize_n"] = layers.resize_nearest(img, out_shape=[4, 4])
        fetches["resize_s"] = layers.image_resize_short(img, 4)
        fetches["fsp"] = layers.fsp_matrix(img, img)

        fetches["seq_first"] = layers.sequence_first_step(seq, length)
        fetches["seq_last"] = layers.sequence_last_step(seq, length)
        fetches["seq_rev"] = layers.sequence_reverse(seq)
        fetches["seq_reshape"] = layers.sequence_reshape(seq, 15)
        fetches["seq_enum"] = layers.sequence_enumerate(
            layers.cast(layers.reduce_sum(seq, dim=2), "int64"),
            win_size=2)

        fetches["fill_bsl"] = layers.fill_constant_batch_size_like(
            x, [0, 7], "float32", 3.5)
        fetches["uniform_bsl"] = layers.uniform_random_batch_size_like(
            x, [0, 3])
        fetches["counter"] = layers.autoincreased_step_counter()
        fetches["lod_reset"] = layers.lod_reset(x)

        arr = layers.create_array("float32", 4, template=x)
        i0 = layers.fill_constant(shape=[], dtype="int64", value=1)
        arr = layers.array_write(x, i0, arr)
        fetches["arr_read"] = layers.array_read(arr, i0)
        fetches["arr_len"] = layers.array_length(arr)

        h0 = layers.fill_constant(shape=[B, 4], dtype="float32", value=0.0)
        c0 = layers.fill_constant(shape=[B, 4], dtype="float32", value=0.0)
        h1, c1 = layers.lstm_unit(x, h0, c0)
        fetches["lstm_unit"] = h1
        xg = layers.fc(x, 12)
        hh, _r, _g = layers.gru_unit(xg, h0, 12)
        fetches["gru_unit"] = hh
        xi = layers.fc(x, 16)
        proj, cell = layers.dynamic_lstmp(
            layers.expand(layers.unsqueeze(xi, [1]), [1, 5, 1]),
            size=16, proj_size=6)
        fetches["lstmp"] = proj

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    r = np.random.RandomState(0)
    feed = {
        "x": r.randn(B, 6).astype(np.float32),
        "img": r.randn(B, 4, 8, 8).astype(np.float32),
        "seq": r.randn(B, 5, 3).astype(np.float32),
        "length": np.array([5, 3, 1, 4], np.int64),
        "lbl": r.randint(0, 2, (B, 1)).astype(np.int64),
    }
    names = list(fetches)
    with fluid.scope_guard(scope):
        exe.run(startup)
        outs = exe.run(main, feed=feed,
                       fetch_list=[fetches[n] for n in names])
    got = dict(zip(names, [np.asarray(o) for o in outs]))

    xv = feed["x"]
    np.testing.assert_allclose(got["brelu"], np.clip(xv, 0, 1), rtol=1e-6)
    np.testing.assert_allclose(got["sign"], np.sign(xv), rtol=1e-6)
    np.testing.assert_allclose(got["cos_sim"].ravel(), np.ones(B), rtol=1e-5)
    assert bool(got["reduce_all"]) and bool(got["isfinite"])
    assert not bool(got["has_inf"]) and not bool(got["has_nan"])
    np.testing.assert_allclose(got["reverse"], xv[:, ::-1], rtol=1e-6)
    np.testing.assert_allclose(got["argsort"], np.sort(xv, 1), rtol=1e-6)
    assert got["rank"].ravel()[0] == 2
    assert got["apool2d"].shape == (B, 4, 3, 2)
    # exact adaptive-avg check on one cell: rows [0:3) x cols [0:4)
    np.testing.assert_allclose(
        got["apool2d"][:, :, 0, 0], feed["img"][:, :, 0:3, 0:4].mean(
            axis=(2, 3)), rtol=1e-5)
    assert got["pad2d"].shape == (B, 4, 10, 12)
    assert got["crop"].shape == (B, 4, 4, 4)
    assert got["pixshuf"].shape == (B, 1, 16, 16)
    assert got["s2d"].shape == (B, 16, 4, 4)
    assert got["resize"].shape == (B, 4, 4, 4)
    assert got["resize_s"].shape == (B, 4, 4, 4)
    assert got["fsp"].shape == (B, 4, 4)
    # first/last step respect the per-row lengths
    np.testing.assert_allclose(got["seq_first"], feed["seq"][:, 0],
                               rtol=1e-6)
    expect_last = np.stack([feed["seq"][b, l - 1]
                            for b, l in enumerate(feed["length"])])
    np.testing.assert_allclose(got["seq_last"], expect_last, rtol=1e-6)
    assert got["fill_bsl"].shape == (B, 7) and got["fill_bsl"][0, 0] == 3.5
    assert got["uniform_bsl"].shape == (B, 3)
    assert got["counter"].ravel()[0] == 1
    np.testing.assert_allclose(got["arr_read"], xv, rtol=1e-6)
    assert got["arr_len"].ravel()[0] == 4
    assert got["lstm_unit"].shape == (B, 4)  # x [B,6] isn't 4*4: see below
    for k, v in got.items():
        assert np.isfinite(v.astype(np.float64)).all() or v.dtype == bool, k


def test_beam_search_layer_roundtrip():
    B, K, T, V = 2, 3, 4, 7
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[B, K, T], dtype="int64",
                          append_batch_size=False)
        scores = layers.data("scores", shape=[B, K], dtype="float32",
                             append_batch_size=False)
        logp = layers.data("logp", shape=[B, K, V], dtype="float32",
                           append_batch_size=False)
        fin = layers.data("fin", shape=[B, K], dtype="bool",
                          append_batch_size=False)
        step = layers.fill_constant(shape=[], dtype="int64", value=1)
        nids, nscores, nfin = layers.beam_search(
            ids, scores, None, None, beam_size=K, end_id=V - 1,
            log_probs=logp, finished=fin, step_idx=step)
        # reference-style call with default finished/step_idx
        dids, dscores, dfin = layers.beam_search(
            ids, scores, None, logp, beam_size=K, end_id=V - 1)
        best_ids, best_scores = layers.beam_search_decode(nids, nscores)
    exe = fluid.Executor(fluid.CPUPlace())
    r = np.random.RandomState(3)
    feed = {
        "ids": r.randint(0, V, (B, K, T)).astype(np.int64),
        "scores": r.randn(B, K).astype(np.float32),
        "logp": np.log(r.dirichlet(np.ones(V), (B, K)).astype(np.float32)),
        "fin": np.zeros((B, K), bool),
    }
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        out = exe.run(main, feed=feed,
                      fetch_list=[best_ids, best_scores, nscores, dscores])
    bi, bs, ns, ds = [np.asarray(o) for o in out]
    assert ds.shape == (B, K) and np.isfinite(ds).all()
    assert bi.shape == (B, T) and bs.shape == (B,)
    # the decoded score is the max over beams
    np.testing.assert_allclose(bs, np.asarray(ns).max(axis=1), rtol=1e-6)


def test_lstm_layer_and_tensor_array_to_tensor():
    B, T, D, H = 2, 5, 6, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[B, T, D], append_batch_size=False,
                        stop_gradient=True)
        h0 = layers.fill_constant(shape=[1, B, H], dtype="float32",
                                  value=0.0)
        out, last_h, last_c = layers.lstm(x, h0, h0, T, H, num_layers=2,
                                          is_bidirec=True)
        arr = layers.create_array("float32", 3, template=x)
        t_out, sizes = layers.tensor_array_to_tensor(arr, axis=1)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    r = np.random.RandomState(1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        o, lh, lc, ta = [np.asarray(v) for v in exe.run(
            main, feed={"x": r.randn(B, T, D).astype(np.float32)},
            fetch_list=[out, last_h, last_c, t_out])]
    assert o.shape == (B, T, 2 * H)
    # reference cudnn_lstm layout: [num_layers*dirs, B, H]
    assert lh.shape == (4, B, H) and lc.shape == (4, B, H)
    assert np.isfinite(o).all()
    assert ta.shape == (B, 3, T, D)
