"""Metric + tail ops added in round 3 (reference:
operators/{chunk_eval_op.h, metrics/precision_recall_op.h,
positive_negative_pair_op.h, ctc_align_op.h,
detection/polygon_box_transform_op.cc, detection/psroi_pool_op.cc,
optimizers/proximal_*_op.cc, cross_entropy_op.h kernel2})."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.registry import get_op_def


def _c(op, ins, attrs=None):
    return get_op_def(op).compute(
        {k: [np.asarray(v)] for k, v in ins.items()}, attrs or {})


def test_chunk_eval_iob_perfect_and_partial():
    # labels encoded type*2 + tag (B=0, I=1); other = 2*num_chunk_types+
    perfect = _c("chunk_eval",
                 {"Inference": [[0, 1, 2, 0, 1, 6]],
                  "Label": [[0, 1, 2, 0, 1, 6]]},
                 {"num_chunk_types": 3})
    assert float(perfect["F1-Score"][0][0]) == 1.0
    # chunks: [B I](type0), [B](type1), [B I](type0) = 3
    assert int(perfect["NumLabelChunks"][0][0]) == 3
    part = _c("chunk_eval",
              {"Inference": [[0, 1, 6, 2, 0, 1]],
               "Label": [[0, 1, 2, 0, 1, 6]]},
              {"num_chunk_types": 3})
    # only the first chunk [0,1] matches exactly
    assert int(part["NumCorrectChunks"][0][0]) == 1
    assert 0 < float(part["Precision"][0][0]) < 1


def test_chunk_eval_respects_seq_length():
    out = _c("chunk_eval",
             {"Inference": [[0, 1, 0, 0]], "Label": [[0, 1, 0, 0]],
              "SeqLength": [2]},
             {"num_chunk_types": 1})
    assert int(out["NumLabelChunks"][0][0]) == 1     # tail masked out
    assert float(out["F1-Score"][0][0]) == 1.0


def test_chunk_eval_layer_on_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inf = layers.data("inf", shape=[6], dtype="int64")
        lab = layers.data("lab", shape=[6], dtype="int64")
        p, r, f1, ni, nl, nc = layers.chunk_eval(
            inf, lab, chunk_scheme="IOB", num_chunk_types=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        vals = exe.run(main, feed={
            "inf": np.array([[0, 1, 2, 0, 1, 6]], np.int64),
            "lab": np.array([[0, 1, 2, 0, 1, 6]], np.int64)},
            fetch_list=[f1, nc])
    assert float(vals[0][0]) == 1.0 and int(vals[1][0]) == 3


def test_precision_recall_metrics():
    out = _c("precision_recall",
             {"Indices": [[0], [1], [1]], "Labels": [[0], [1], [0]]},
             {"class_number": 2})
    batch = np.asarray(out["BatchMetrics"][0])
    # micro: tp=2, fp=1, fn=1 -> P=R=2/3
    np.testing.assert_allclose(batch[3:5], [2 / 3, 2 / 3], rtol=1e-6)
    states = np.asarray(out["AccumStatesInfo"][0])
    assert states.shape == (2, 4)
    # streaming: feeding states back doubles the counts
    out2 = _c("precision_recall",
              {"Indices": [[0], [1], [1]], "Labels": [[0], [1], [0]],
               "StatesInfo": states},
              {"class_number": 2})
    np.testing.assert_allclose(np.asarray(out2["AccumStatesInfo"][0]),
                               2 * states, rtol=1e-6)


def test_positive_negative_pair():
    out = _c("positive_negative_pair",
             {"Score": [0.9, 0.1, 0.5], "Label": [1.0, 0.0, 0.0],
              "QueryID": [1, 1, 1]}, {})
    assert float(out["PositivePair"][0][0]) == 2.0
    assert float(out["NegativePair"][0][0]) == 0.0


def test_ctc_align_merge_and_blank():
    out = _c("ctc_align", {"Input": [[1, 1, 0, 2, 2, 3],
                                     [0, 0, 0, 0, 0, 0]]}, {"blank": 0})
    dec = np.asarray(out["Output"][0])
    lens = np.asarray(out["OutputLength"][0]).ravel()
    assert dec[0, :3].tolist() == [1, 2, 3] and lens[0] == 3
    assert dec[1, 0] == -1 and lens[1] == 0    # empty-sequence convention


def test_polygon_box_transform():
    x = np.zeros((1, 2, 2, 3), np.float32)
    out = np.asarray(_c("polygon_box_transform", {"Input": x})["Output"][0])
    np.testing.assert_allclose(out[0, 0, 0], [0, 4, 8])    # x offsets: 4*w
    np.testing.assert_allclose(out[0, 1, :, 0], [0, 4])    # y offsets: 4*h


def test_psroi_pool_position_sensitive():
    # each bin reads its own channel group: constant-per-channel input
    # makes bin (i, j) of output channel k equal channel k*4 + i*2 + j
    x = np.arange(8, dtype=np.float32)[None, :, None, None] * np.ones(
        (1, 8, 4, 4), np.float32)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)
    out = np.asarray(_c("psroi_pool", {"X": x, "ROIs": rois},
                        {"output_channels": 2, "pooled_height": 2,
                         "pooled_width": 2, "spatial_scale": 1.0})["Out"][0])
    np.testing.assert_allclose(out[0, 0].ravel(), [0, 1, 2, 3])
    np.testing.assert_allclose(out[0, 1].ravel(), [4, 5, 6, 7])


def test_proximal_optimizers_shrink():
    o = _c("proximal_gd",
           {"Param": np.ones(3, np.float32), "Grad": np.zeros(3, np.float32),
            "LearningRate": [1.0]}, {"l1": 0.5, "l2": 0.0})
    np.testing.assert_allclose(np.asarray(o["ParamOut"][0]), 0.5)
    o = _c("proximal_adagrad",
           {"Param": np.ones(3, np.float32),
            "Grad": np.ones(3, np.float32),
            "Moment": np.zeros(3, np.float32),
            "LearningRate": [0.1]}, {"l1": 0.0, "l2": 0.0})
    np.testing.assert_allclose(np.asarray(o["ParamOut"][0]), 0.9, rtol=1e-5)


def test_cross_entropy2_matches_reference_formula():
    x = np.array([[0.2, 0.8], [0.5, 0.5]], np.float32)
    lab = np.array([[1], [0]], np.int64)
    o = _c("cross_entropy2", {"X": x, "Label": lab}, {})
    np.testing.assert_allclose(np.asarray(o["Y"][0]).ravel(),
                               -np.log([0.8, 0.5]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(o["MatchX"][0]).ravel(),
                               [0.8, 0.5], rtol=1e-6)


def test_fake_qdq_moving_average_ste():
    import jax
    import jax.numpy as jnp

    def f(x):
        o = get_op_def(
            "fake_quantize_dequantize_moving_average_abs_max").compute(
            {"X": [x], "InScale": [jnp.asarray([1.0])],
             "InState": [jnp.asarray([1.0])],
             "InAccum": [jnp.asarray([1.0])]}, {"bit_length": 8})
        return jnp.sum(o["Out"][0] * jnp.asarray([1.0, 2.0, 3.0]))

    x = jnp.asarray([0.5, -1.0, 0.25])
    g = np.asarray(jax.grad(f)(x))
    np.testing.assert_allclose(g, [1.0, 2.0, 3.0])  # straight-through


def test_ctc_greedy_decoder_layer():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        probs = layers.data("p", shape=[4, 3], dtype="float32")
        dec, dec_len = layers.ctc_greedy_decoder(probs, blank=0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    pv = np.zeros((1, 4, 3), np.float32)
    pv[0, :, :] = [[0.1, 0.8, 0.1], [0.1, 0.8, 0.1],
                   [0.8, 0.1, 0.1], [0.1, 0.1, 0.8]]
    with fluid.scope_guard(scope):
        exe.run(startup)
        d, ln = exe.run(main, feed={"p": pv}, fetch_list=[dec, dec_len])
    assert d[0, :2].tolist() == [1, 2] and ln[0, 0] == 2

