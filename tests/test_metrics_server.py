"""Live observability endpoint (PR 2 tentpole, piece 2): the stdlib
http.server thread behind monitor.serve — /metrics, /healthz, /steps,
/compile scraped over localhost and matched against the in-process
registry / ring buffer."""

import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers, monitor


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    flags.set_flags({"telemetry": False, "step_log_path": "",
                     "metrics_dump_path": "", "compile_report_dir": "",
                     "metrics_port": 0})
    yield
    monitor.stop_server()
    monitor.reset()
    flags.set_flags({"telemetry": False, "step_log_path": "",
                     "metrics_dump_path": "", "compile_report_dir": "",
                     "metrics_port": 0})


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def test_metrics_endpoint_matches_registry():
    monitor.enable()
    monitor.counter("t_srv_c", "scraped counter").inc(3,
                                                      labels={"k": "v"})
    h = monitor.histogram("t_srv_h", "scraped hist", buckets=(0.1, 1.0))
    h.observe(0.05)
    port = monitor.serve(0)  # ephemeral port: parallel-safe
    assert monitor.server_address() == ("127.0.0.1", port)

    status, ctype, body = _get(port, "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    text = body.decode()
    # the scrape IS the exporter output for the live registry
    assert text == monitor.to_prometheus()
    assert 't_srv_c{k="v"} 3.0' in text
    assert 't_srv_h_bucket{le="0.1"} 1' in text
    # builtin instruments are pre-registered, so their TYPE lines appear
    # on a scrape even before first use
    assert "# TYPE pt_stall_total counter" in text
    assert "# TYPE pt_span_seconds histogram" in text


def test_healthz_and_404():
    monitor.enable()
    port = monitor.serve(0)
    status, ctype, body = _get(port, "/healthz")
    assert status == 200 and ctype == "application/json"
    health = json.loads(body)
    assert health["status"] == "ok"
    assert health["telemetry"] is True
    assert health["uptime_s"] >= 0
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/no/such/route")
    assert ei.value.code == 404


def test_root_serves_json_route_index():
    """`/` (previously a 404) serves a JSON index of every route, and
    the index cannot silently miss one: it IS the handler's table."""
    monitor.enable()
    port = monitor.serve(0)
    status, ctype, body = _get(port, "/")
    assert status == 200 and ctype == "application/json"
    index = json.loads(body)
    assert index == {"routes": monitor.ROUTES}
    # every indexed route actually answers (the index is not aspirational)
    for route in index["routes"]:
        status, _, _ = _get(port, route)
        assert status == 200, route


def test_fleet_route_serves_local_view_single_process():
    """/fleet without a multi-worker fleet: the single-rank local view,
    same shape as the aggregated one."""
    monitor.enable()
    port = monitor.serve(0)
    status, ctype, body = _get(port, "/fleet")
    assert status == 200 and ctype == "application/json"
    view = json.loads(body)
    assert view["world"] == 1 and list(view["ranks"]) == ["0"]
    assert view["ranks"]["0"]["dead"] is False
    assert view["stragglers"] == [] and view["oom_reports"] == []
    # the merged exposition answers too (this rank's samples, rank="0")
    monitor.counter("t_fleet_local_c", "merged-view counter").inc(2)
    status, ctype, body = _get(port, "/metrics?fleet=1")
    assert status == 200 and ctype.startswith("text/plain")
    assert 't_fleet_local_c{rank="0"} 2.0' in body.decode()


def test_lint_endpoint_serves_latest_findings():
    from paddle_tpu import analysis

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        prog.global_block().append_op(
            "relu", inputs={"X": ["ghost"]}, outputs={"Out": ["o"]})
    analysis.lint(prog)
    monitor.enable()
    port = monitor.serve(0)
    status, ctype, body = _get(port, "/lint")
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert doc["mode"] in ("off", "warn", "error")
    rec = doc["reports"][str(prog._uid)]
    assert rec["counts"].get("error", 0) >= 1
    assert any(f["check"] == "dataflow.uninitialized_read"
               for f in rec["findings"])


def test_profile_endpoint_serves_latest_device_profiles():
    """/profile: the roofline plane's latest device profile per
    program plus the peaks its verdicts were scored against."""
    from paddle_tpu import roofline

    prog = fluid.Program()
    prof = roofline.build_device_profile(
        prog, source="estimate", device_seconds=0.25, steps=1,
        compile_report={"flops": 1e9, "bytes_accessed": 1e7,
                        "op_histogram": {"mul": 1}},
        backend="cpu")
    roofline.record_profile(prof)
    monitor.enable()
    port = monitor.serve(0)
    status, ctype, body = _get(port, "/profile")
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert set(doc) == {"profiles", "peak_flops", "peak_bytes_per_sec"}
    served = doc["profiles"][f"program{prog._uid}"]
    roofline.validate_device_profile(served)
    assert served["source"] == "estimate"
    assert served["measured_mfu"] == pytest.approx(prof["measured_mfu"])


def test_trace_endpoint_serves_live_timeline():
    """A running server alone makes tracing visible (no trace_dir
    needed): /trace returns loadable Chrome-trace JSON of the ring."""
    monitor.enable()
    port = monitor.serve(0)
    assert monitor.trace_active()  # server IS the visibility sink
    with monitor.span("served.from.ring"):
        pass
    status, ctype, body = _get(port, "/trace")
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    events = doc["traceEvents"]
    assert any(e.get("name") == "served.from.ring" for e in events)
    for e in events:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e)


def test_steps_endpoint_serves_ring_buffer():
    """Executor steps land in the bounded ring even with NO step_log_path
    — the /steps route is the zero-config live view."""
    monitor.enable()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main,
                    feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[y])

    port = monitor.serve(0)
    status, ctype, body = _get(port, "/steps")
    assert status == 200 and ctype == "application/json"
    served = json.loads(body)
    assert served == json.loads(json.dumps(monitor.recent_steps(),
                                           default=str))
    # startup + 3 steps; every record schema-valid with cache accounting
    assert len(served) == 4
    for rec in served:
        monitor.validate_step_record(rec)
    assert [r["cache"] for r in served] == ["miss", "miss", "hit", "hit"]
    # ?n= trims to the newest n
    _, _, body = _get(port, "/steps?n=2")
    assert json.loads(body) == served[-2:]


def test_compile_endpoint_serves_latest_reports(tmp_path):
    flags.set_flags({"telemetry": True,
                     "compile_report_dir": str(tmp_path)})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[y])
    port = monitor.serve(0)
    _, _, body = _get(port, "/compile")
    served = json.loads(body)
    assert set(served) == set(monitor.compile_reports())
    for rep in served.values():
        monitor.validate_compile_report(rep)


def test_server_makes_compile_reports_active_and_stops_cleanly():
    flags.set_flags({"telemetry": True})
    assert not monitor.compile_reports_active()
    port = monitor.serve(0)
    # a live endpoint is a consumer: reports turn on without a dir
    assert monitor.compile_reports_active()
    monitor.stop_server()
    assert monitor.server_address() is None
    assert not monitor.compile_reports_active()
    with pytest.raises(Exception):
        _get(port, "/healthz")


def test_metrics_port_flag_autostarts_server():
    # flag set while telemetry off: nothing listens yet
    flags.set_flags({"metrics_port": 0})
    flags.set_flags({"telemetry": True})
    assert monitor.server_address() is None
    # choosing a real port via flag would race parallel suites, so bind
    # ephemeral first, then verify the watcher path is a no-op re-entry
    port = monitor.serve(0)
    flags.set_flags({"metrics_port": port})  # watcher: server already up
    assert monitor.server_address() == ("127.0.0.1", port)


def test_requests_and_serve_routes_round_trip():
    """/requests serves the live request plane (in-flight table +
    recently-terminated ring + SLO rollup) and /serve the engine
    summary, both matching the in-process views after real traffic."""
    from paddle_tpu import serving, serving_trace
    from paddle_tpu.models import transformer as T

    flags.set_flags({"telemetry": True})
    cfg = T.TransformerConfig(
        src_vocab_size=37, trg_vocab_size=41, max_length=64,
        d_model=16, d_inner=32, n_head=2, n_layer=1,
        dropout=0.0, label_smooth_eps=0.0)
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8,
                                max_len=10, bos_id=0, end_id=1)
    rng = np.random.RandomState(5)
    reqs = [eng.submit(rng.randint(2, 37, (6,)).astype(np.int64),
                       max_new_tokens=3) for _ in range(3)]
    eng.run_until_idle()
    port = monitor.serve(0)
    status, ctype, body = _get(port, "/requests")
    assert status == 200 and ctype == "application/json"
    served = json.loads(body)
    assert served["v"] == serving_trace.REQUEST_RECORD_SCHEMA_VERSION
    assert served["inflight"] == []
    assert {r["trace_id"] for r in served["recent"]} == {
        q.trace_id for q in reqs}
    for rec in served["recent"]:
        assert rec["outcome"] in ("completed", "length")
        assert set(rec["phases_ms"]) == set(serving_trace.PHASES)
    assert served["slo"] == json.loads(
        json.dumps(serving_trace.slo_summary()))
    # /serve still answers with the aggregate engine summary
    status, ctype, body = _get(port, "/serve")
    assert status == 200 and ctype == "application/json"
    summary = json.loads(body)
    assert any(row["engine_id"] == eng.engine_id
               for row in summary["engines"])
    eng.close()
