"""py_func / print / hash / tree_conv (the round-4 op tails;
reference: py_func_op.cc, print_op.cc, hash_op.cc, tree_conv_op.cc)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.registry import get_op_def


def _exe():
    return fluid.Executor(fluid.CPUPlace())


# --------------------------------------------------------------------------
# py_func
# --------------------------------------------------------------------------


def test_py_func_forward_and_backward():
    def fwd_tanh(x):
        return np.tanh(np.asarray(x))

    # forward input x is skipped; grad from y and dy alone
    def bwd_tanh(y, dy):
        return np.asarray(dy) * (1.0 - np.square(np.asarray(y)))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(2, 3), dtype="float32", stop_gradient=False)
        out = main.global_block().create_var(
            name="y", shape=(2, 3), dtype="float32")
        layers.py_func(fwd_tanh, x, out, backward_func=bwd_tanh,
                       skip_vars_in_backward_input=x)
        loss = layers.reduce_sum(out)
        grads = fluid.gradients(loss, x)
    exe = _exe()
    xv = np.linspace(-1, 1, 6).astype(np.float32).reshape(2, 3)
    y, dx = exe.run(main, feed={"x": xv}, fetch_list=[out, grads[0]])
    np.testing.assert_allclose(np.asarray(y), np.tanh(xv), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(dx), 1.0 - np.tanh(xv) ** 2, rtol=1e-5)


def test_py_func_no_output_debug(capfd):
    seen = []

    def dbg(x):
        seen.append(np.asarray(x).copy())

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(2,), dtype="float32")
        layers.py_func(dbg, x, None)
        out = layers.scale(x, scale=3.0)
    exe = _exe()
    r = exe.run(main, feed={"x": np.array([1.0, 2.0], np.float32)},
                fetch_list=[out])
    np.testing.assert_allclose(np.asarray(r[0]), [3.0, 6.0])
    assert seen and np.allclose(seen[0], [1.0, 2.0])


def test_py_func_backward_with_stop_gradient_input():
    # backward_func returns one grad per forward input (the natural
    # contract); the grad for the stop_gradient input is discarded.
    def fwd(a, b):
        return np.asarray(a) * np.asarray(b)

    def bwd(a, b, y, dy):
        return np.asarray(dy) * np.asarray(b), np.asarray(dy) * np.asarray(a)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = main.global_block().create_var(
            name="a", shape=(2, 3), dtype="float32", stop_gradient=False)
        b = main.global_block().create_var(
            name="b", shape=(2, 3), dtype="float32", stop_gradient=True)
        out = main.global_block().create_var(
            name="ab", shape=(2, 3), dtype="float32")
        layers.py_func(fwd, [a, b], out, backward_func=bwd)
        loss = layers.reduce_sum(out)
        grads = fluid.gradients(loss, a)
    exe = _exe()
    av = np.arange(6, dtype=np.float32).reshape(2, 3)
    bv = np.full((2, 3), 2.0, np.float32)
    da, = exe.run(main, feed={"a": av, "b": bv}, fetch_list=[grads[0]])
    np.testing.assert_allclose(np.asarray(da), bv, rtol=1e-6)


def test_print_first_n_counts_phases_separately(capfd):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(2,), dtype="float32", stop_gradient=False)
        y = layers.Print(x, message="phase-probe", first_n=2,
                         print_phase="both")
        loss = layers.reduce_sum(y)
        fluid.gradients(loss, x)
    exe = _exe()
    for _ in range(3):
        exe.run(main, feed={"x": np.ones(2, np.float32)}, fetch_list=[loss])
    err = capfd.readouterr().err
    # 2 forward + 2 backward prints, not 2 total
    assert err.count("phase-probe") == 4


def test_py_func_skip_var_validation():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(2,), dtype="float32")
        other = main.global_block().create_var(
            name="other", shape=(2,), dtype="float32")
        out = main.global_block().create_var(
            name="o", shape=(2,), dtype="float32")
        with pytest.raises(ValueError):
            layers.py_func(lambda a: a, x, out,
                           backward_func=lambda a, b, c: None,
                           skip_vars_in_backward_input=other)


# --------------------------------------------------------------------------
# print
# --------------------------------------------------------------------------


def test_print_forward_and_backward(capfd):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(3,), dtype="float32", stop_gradient=False)
        shown = layers.Print(x, message="round4-print", summarize=2,
                             print_phase="both")
        loss = layers.reduce_sum(layers.scale(shown, scale=2.0))
        grads = fluid.gradients(loss, x)
    exe = _exe()
    r = exe.run(main, feed={"x": np.array([1., 2., 3.], np.float32)},
                fetch_list=[loss, grads[0]])
    assert float(np.asarray(r[0])) == pytest.approx(12.0)
    np.testing.assert_allclose(np.asarray(r[1]), [2.0, 2.0, 2.0])
    err = capfd.readouterr().err
    assert "round4-print" in err
    assert "@GRAD" in err  # backward phase printed the gradient


def test_print_first_n(capfd):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(2,), dtype="float32")
        y = layers.Print(x, message="first-n-probe", first_n=2,
                         print_phase="forward")
        out = layers.scale(y, scale=1.0)
    exe = _exe()
    for _ in range(4):
        exe.run(main, feed={"x": np.ones(2, np.float32)}, fetch_list=[out])
    err = capfd.readouterr().err
    assert err.count("first-n-probe") == 2


# --------------------------------------------------------------------------
# hash
# --------------------------------------------------------------------------


def test_hash_shape_range_determinism():
    x = np.array([[1, 2], [3, 4], [1, 2]], np.int64)
    outs = get_op_def("hash").compute(
        {"X": [x]}, {"num_hash": 4, "mod_by": 10000})
    h = np.asarray(outs["Out"][0])
    assert h.shape == (3, 4, 1)
    assert (h >= 0).all() and (h < 10000).all()
    # deterministic; equal rows hash equal, different rows differ
    h2 = np.asarray(get_op_def("hash").compute(
        {"X": [x]}, {"num_hash": 4, "mod_by": 10000})["Out"][0])
    np.testing.assert_array_equal(h, h2)
    np.testing.assert_array_equal(h[0], h[2])
    assert (h[0] != h[1]).any()
    # seeds decorrelate: the 4 hashes of one row are not all equal
    assert len(set(h[0, :, 0].tolist())) > 1


def _py_xxh64(data: bytes, seed: int) -> int:
    """Pure-python XXH64 from the public spec (Yann Collet), used as the
    oracle for bucket parity with the reference's xxhash library."""
    M = (1 << 64) - 1
    P1, P2, P3 = 0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9
    P4, P5 = 0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    def rnd(acc, lane):
        return (rotl((acc + lane * P2) & M, 31) * P1) & M

    n, i = len(data), 0
    if n >= 32:
        v = [(seed + P1 + P2) & M, (seed + P2) & M, seed & M,
             (seed - P1) & M]
        while i + 32 <= n:
            for k in range(4):
                lane = int.from_bytes(data[i:i + 8], "little")
                v[k] = rnd(v[k], lane)
                i += 8
        h = (rotl(v[0], 1) + rotl(v[1], 7) + rotl(v[2], 12)
             + rotl(v[3], 18)) & M
        for k in range(4):
            h = ((h ^ rnd(0, v[k])) * P1 + P4) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i + 8 <= n:
        lane = int.from_bytes(data[i:i + 8], "little")
        h = (rotl(h ^ rnd(0, lane), 27) * P1 + P4) & M
        i += 8
    if i + 4 <= n:
        w = int.from_bytes(data[i:i + 4], "little")
        h = (rotl(h ^ (w * P1) & M, 23) * P2 + P3) & M
        i += 4
    while i < n:
        h = (rotl(h ^ (data[i] * P5) & M, 11) * P1) & M
        i += 1
    h ^= h >> 33
    h = (h * P2) & M
    h ^= h >> 29
    h = (h * P3) & M
    return h ^ (h >> 32)


@pytest.mark.full
def test_hash_xxh64_parity_under_x64():
    """Under x64 the op is bit-exact XXH64 % mod_by — the reference's
    bucket values (operators/hash_op.h: XXH64(row, sizeof(int)*d, seed)
    % mod_by), including the 4-bytes-per-element prefix quirk for int64
    rows. Covers d spanning the <32B lane/word path and the >=32B
    stripe path."""
    import jax
    import jax.numpy as jnp

    r = np.random.RandomState(7)
    old_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        for d in (1, 2, 3, 8, 9, 11):
            x = r.randint(0, 2**31 - 1, (5, d)).astype(np.int64)
            out = np.asarray(get_op_def("hash").compute(
                {"X": [jnp.asarray(x, dtype=jnp.int64)]},
                {"num_hash": 3, "mod_by": 100000})["Out"][0])
            for row in range(5):
                # the reference reads sizeof(int)*d bytes of the int64 row
                data = x[row].tobytes()[:4 * d]
                for s in range(3):
                    assert out[row, s, 0] == _py_xxh64(data, s) % 100000, (
                        d, row, s)
        # int32 rows: the full row's bytes
        xi = r.randint(0, 2**31 - 1, (4, 6)).astype(np.int32)
        out = np.asarray(get_op_def("hash").compute(
            {"X": [jnp.asarray(xi)]},
            {"num_hash": 2, "mod_by": 997})["Out"][0])
        for row in range(4):
            for s in range(2):
                assert out[row, s, 0] == \
                    _py_xxh64(xi[row].tobytes(), s) % 997
    finally:
        jax.config.update("jax_enable_x64", old_x64)


def test_hash_layer():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="ids", shape=(4, 3), dtype="int64")
        out = layers.hash(x, hash_size=500, num_hash=2)
    exe = _exe()
    ids = np.random.RandomState(0).randint(0, 1000, (4, 3)).astype(np.int64)
    r = exe.run(main, feed={"ids": ids}, fetch_list=[out])
    h = np.asarray(r[0])
    assert h.shape == (4, 2, 1) and (h >= 0).all() and (h < 500).all()


# --------------------------------------------------------------------------
# tree_conv
# --------------------------------------------------------------------------


def _ref_tree_conv(nodes, edges, filt, max_depth):
    """Literal numpy re-derivation of the reference tree2col + conv
    (math/tree2col.cc construct_patch / Tree2ColFunctor) for parity."""
    bsz, n, f = nodes.shape
    _, _, out_size, nf = filt.shape
    out = np.zeros((bsz, n, out_size, nf), np.float32)
    md = float(max_depth)
    for b in range(bsz):
        children = {i: [] for i in range(1, n + 1)}
        node_count = 0
        for (u, v) in edges[b]:
            if u == 0 or v == 0:
                break
            children[int(u)].append(int(v))
            node_count += 1
        node_count += 1

        def collect(u, depth):
            got = []
            if depth + 1 < max_depth:
                ch = children[u]
                for i, v in enumerate(ch):
                    got.append((v, i + 1, len(ch), depth + 1))
                    got += collect(v, depth + 1)
            return got

        for u in range(1, node_count + 1):
            patch = [(u, 1, 1, 0)] + collect(u, 0)
            acc = np.zeros((out_size, nf), np.float32)
            for (v, index, pclen, depth) in patch:
                eta_t = (md - depth) / md
                frac = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
                eta_l = (1.0 - eta_t) * frac
                eta_r = (1.0 - eta_t) * (1.0 - eta_l)
                feat = nodes[b, v - 1]                       # [f]
                acc += np.einsum("f,fod->od", feat * eta_l, filt[:, 0])
                acc += np.einsum("f,fod->od", feat * eta_r, filt[:, 1])
                acc += np.einsum("f,fod->od", feat * eta_t, filt[:, 2])
            out[b, u - 1] = acc
    return out


def test_tree_conv_matches_reference_semantics():
    rng = np.random.RandomState(7)
    bsz, n, f, out_size, nf, md = 2, 8, 4, 5, 3, 3
    nodes = rng.randn(bsz, n, f).astype(np.float32)
    # batch 0: root 1 with children 2,3; 2 has children 4,5. batch 1: chain
    edges = np.zeros((bsz, 6, 2), np.int32)
    edges[0, :4] = [[1, 2], [1, 3], [2, 4], [2, 5]]
    edges[1, :3] = [[1, 2], [2, 3], [3, 4]]
    filt = rng.randn(f, 3, out_size, nf).astype(np.float32)
    outs = get_op_def("tree_conv").compute(
        {"NodesVector": [nodes], "EdgeSet": [edges], "Filter": [filt]},
        {"max_depth": md})
    got = np.asarray(outs["Out"][0])
    want = _ref_tree_conv(nodes, edges, filt, md)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_tree_conv_layer_trains():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        nodes = main.global_block().create_var(
            name="nodes", shape=(2, 6, 4), dtype="float32",
            stop_gradient=False)
        edges = main.global_block().create_var(
            name="edges", shape=(2, 4, 2), dtype="int32", stop_gradient=True)
        out = layers.tree_conv(nodes, edges, output_size=5, num_filters=2,
                               max_depth=2)
        loss = layers.reduce_sum(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = _exe()
    exe.run(startup)
    rng = np.random.RandomState(3)
    feed = {
        "nodes": rng.randn(2, 6, 4).astype(np.float32),
        "edges": np.tile(np.array([[1, 2], [1, 3], [2, 4], [0, 0]],
                                  np.int32), (2, 1, 1)),
    }
    l0 = float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss])[0]))
    for _ in range(5):
        l1 = float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss])[0]))
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0
