"""Model-zoo smoke/training tests for SE-ResNeXt, LSTM NMT seq2seq, and
BERT (reference acceptance style: tests/book + benchmark model smoke)."""

import pytest
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import bert, se_resnext, seq2seq


@pytest.mark.full
def test_se_resnext50_trains_one_step():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = se_resnext.get_model(data_shape=(3, 48, 48), class_dim=10)
        fluid.optimizer.Momentum(0.01, 0.9).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        stem = "stem_conv.w"
        w0 = np.array(scope.find_var(stem))
        fd = {
            "data": rng.randn(2, 3, 48, 48).astype(np.float32),
            "label": rng.randint(0, 10, (2, 1)).astype(np.int64),
        }
        (loss,) = exe.run(main, feed=fd, fetch_list=[model["loss"]])
        assert np.isfinite(loss).all()
        w1 = np.array(scope.find_var(stem))
    assert not np.allclose(w0, w1)  # grads reach the stem through SE gates


def test_seq2seq_attention_learns_copy_task():
    cfg = seq2seq.Seq2SeqConfig(
        src_vocab_size=40, trg_vocab_size=40, embed_dim=24, hidden_dim=32,
        num_layers=2)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = seq2seq.build(cfg)
        fluid.optimizer.Adam(1e-2).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for step in range(80):
            fd = seq2seq.make_batch(cfg, 16, 8, 8, seed=step % 2)
            losses.append(float(
                exe.run(main, feed=fd, fetch_list=[model["loss"]])[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[-1]


def test_bert_tiny_pretrains():
    cfg = bert.BertConfig(
        vocab_size=100, max_position=32, d_model=32, d_inner=64,
        n_head=2, n_layer=2, dropout=0.0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = bert.build(cfg)
        fluid.optimizer.Adam(1e-3).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses, mlms, nsps = [], [], []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for step in range(30):
            fd = bert.make_batch(cfg, 8, 16, seed=step % 3)
            l, m, n = exe.run(
                main, feed=fd,
                fetch_list=[model["loss"], model["mlm_loss"],
                            model["nsp_loss"]])
            losses.append(float(l))
            mlms.append(float(m))
            nsps.append(float(n))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert mlms[-1] < mlms[0]  # memorizes the 3 synthetic batches


def test_bert_tensor_parallel_forward_parity():
    """BERT reuses the transformer's TP parameter naming, so the standard
    transformer_rules shard it; loss must match single device."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.strategy import (
        DistributedStrategy, ShardingRule, transformer_rules)

    cfg = bert.BertConfig(
        vocab_size=64, max_position=16, d_model=16, d_inner=32,
        n_head=2, n_layer=1, dropout=0.0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = bert.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fd = bert.make_batch(cfg, 4, 8, seed=0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed=fd, fetch_list=[model["loss"]])

        import jax
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4),
                    ("data", "model"))
        rules = transformer_rules() + [
            ShardingRule(r"^bert_(tok|seg|pos)_emb\.w(_|$)", P()),
            ShardingRule(r"^(mlm_ln|bert_emb_ln)\.", P()),
            ShardingRule(r"^nsp\.", P()),
        ]
        strategy = DistributedStrategy(mesh, data_axis="data", rules=rules)
        compiled = fluid.CompiledProgram(main).with_strategy(strategy)
        exe2 = fluid.Executor(fluid.CPUPlace())
        (got,) = exe2.run(compiled, feed=fd, fetch_list=[model["loss"]])
    np.testing.assert_allclose(float(ref), float(got), rtol=2e-4)
