"""Expert-parallel MoE tests on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.parallel import moe

RS = np.random.RandomState


def _expert_fn(params, x):
    return jnp.tanh(x @ params["w1"]) @ params["w2"]


def _setup(e, d, dh, seed=0):
    r = RS(seed)
    gate_w = jnp.asarray(r.normal(0, 1.0, (d, e)), jnp.float32)
    params = {
        "w1": jnp.asarray(r.normal(0, 0.3, (e, d, dh)), jnp.float32),
        "w2": jnp.asarray(r.normal(0, 0.3, (e, dh, d)), jnp.float32),
    }
    return gate_w, params


@pytest.mark.full
def test_moe_matches_dense_reference_full_capacity():
    e, d, dh, n = 4, 8, 16, 32
    mesh = Mesh(np.asarray(jax.devices()[:e]), ("expert",))
    gate_w, params = _setup(e, d, dh)
    x = jnp.asarray(RS(1).normal(0, 1, (n, d)), jnp.float32)

    ref = moe.moe_reference(x, gate_w, params, _expert_fn)
    # capacity_factor = e makes capacity = n, so nothing truncates
    got, aux = moe.moe_ffn(x, gate_w, params, _expert_fn, mesh,
                           capacity_factor=float(e))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert float(aux) > 0.0  # load-balance loss is positive


@pytest.mark.full
def test_moe_dp_x_ep_mesh():
    """Tokens sharded over data axis, experts over expert axis."""
    e, d, dh, n = 4, 8, 16, 32
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "expert"))
    gate_w, params = _setup(e, d, dh, seed=2)
    x = jnp.asarray(RS(3).normal(0, 1, (n, d)), jnp.float32)

    ref = moe.moe_reference(x, gate_w, params, _expert_fn)
    got, _ = moe.moe_ffn(x, gate_w, params, _expert_fn, mesh,
                         data_axis="data", capacity_factor=float(e))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_capacity_overflow_identity_path():
    """With capacity 0-ish, overflow tokens must pass through unchanged
    (GShard/Switch overflow handling), not crash or zero out."""
    e, d, dh, n = 4, 8, 16, 16
    mesh = Mesh(np.asarray(jax.devices()[:e]), ("expert",))
    _, params = _setup(e, d, dh, seed=4)
    # zero gate -> uniform logits -> argmax ties break to expert 0 for
    # every token: deterministic all-to-one routing
    gate_w = jnp.zeros((d, e), jnp.float32)
    x = jnp.asarray(RS(5).normal(0, 1, (n, d)), jnp.float32)
    got, _ = moe.moe_ffn(x, gate_w, params, _expert_fn, mesh,
                         capacity_factor=0.5)
    # capacity = 0.5 * 16 / 4 = 2 tokens; the other 14 take identity
    changed = np.abs(np.asarray(got) - np.asarray(x)).sum(axis=-1) > 1e-6
    assert changed.sum() == 2, changed.sum()


@pytest.mark.full
def test_moe_gradients_flow():
    """Every expert leaf AND the router get nonzero finite grads
    (round-4 fold reversed: its own test again for failure isolation;
    the smoke-tier MoE gradient gate)."""
    e, d, dh, n = 2, 8, 8, 16
    mesh = Mesh(np.asarray(jax.devices()[:e]), ("expert",))
    gate_w, params = _setup(e, d, dh, seed=6)
    x = jnp.asarray(RS(7).normal(0, 1, (n, d)), jnp.float32)

    def loss(params, gw):
        out, aux = moe.moe_ffn(x, gw, params, _expert_fn, mesh,
                               capacity_factor=float(e))
        return jnp.mean(out ** 2) + 0.01 * aux

    grads, ggate = jax.grad(loss, argnums=(0, 1))(params, gate_w)
    for k, g in grads.items():
        g = np.asarray(g)
        assert np.isfinite(g).all() and np.abs(g).max() > 0, k
    assert np.isfinite(np.asarray(ggate)).all()
    assert np.abs(np.asarray(ggate)).max() > 0  # router learns too


@pytest.mark.full
def test_moe_trains_to_specialize():
    """End-to-end: a 2-expert MoE learns a task where the two halves of
    the input space need different linear maps."""
    e, d, dh, n = 2, 4, 8, 64
    mesh = Mesh(np.asarray(jax.devices()[:e]), ("expert",))
    gate_w, params = _setup(e, d, dh, seed=8)
    r = RS(9)
    x = jnp.asarray(r.normal(0, 1, (n, d)), jnp.float32)
    # targets: sign of first feature decides the transform
    t = jnp.where(x[:, :1] > 0, x * 2.0, -x)

    def loss(state):
        out, aux = moe.moe_ffn(x, state["gate"], state["params"],
                               _expert_fn, mesh, capacity_factor=float(e))
        return jnp.mean((out - t) ** 2) + 0.01 * aux

    state = {"gate": gate_w, "params": params}
    lr = 0.15
    l0 = float(loss(state))
    g = jax.jit(jax.grad(loss))
    for _ in range(60):
        grads = g(state)
        state = jax.tree.map(lambda p, gr: p - lr * gr, state, grads)
    l1 = float(loss(state))
    # top-1 hard routing limits how far SGD specializes on this toy task;
    # halving the loss shows the experts + router genuinely train
    assert l1 < l0 * 0.55, (l0, l1)
    # both experts get traffic after training (no collapse)
    probs = jax.nn.softmax(x @ state["gate"], axis=-1)
    counts = np.bincount(np.asarray(jnp.argmax(probs, -1)), minlength=e)
    assert (counts > 0).all(), counts
