"""Expert-parallel MoE through the Program IR.

Round-2 follow-up to the ring-attention/sharded-table wiring (STATUS.md
known gap "MoE/pipeline are parallel-layer APIs, not yet reachable from
the Program IR"): ``layers.switch_moe`` must run via
``exe.run(CompiledProgram)`` under a strategy expert axis, with loss
parity against the identical-math single-device path (reference parity
harness analog: tests/unittests/parallel_executor_test_base.py).
"""

import numpy as np
from jax.sharding import Mesh

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel.strategy import DistributedStrategy, moe_rules

E = 8  # experts == virtual device count


def _mesh(shape, names):
    import jax

    devs = np.asarray(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def _moe_program(d=16, d_ff=32, capacity_factor=4.0, num_experts=E,
                 optimizer="adam"):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[d], dtype="float32")
        y = layers.data("y", shape=[d], dtype="float32")
        out, aux = layers.switch_moe(
            x, num_experts=num_experts, d_ff=d_ff,
            capacity_factor=capacity_factor, name="moe",
        )
        mse = layers.reduce_mean(layers.square_error_cost(out, y))
        loss = layers.elementwise_add(
            mse, layers.scale(aux, scale=0.01)
        )
        # Parity tests use SGD: Adam's g/(|g|+eps) normalization amplifies
        # last-ulp reduction-order differences between the single-device
        # and GSPMD-partitioned programs into per-step drift.
        if optimizer == "adam":
            fluid.optimizer.Adam(1e-2).minimize(loss)
        else:
            fluid.optimizer.SGD(0.5).minimize(loss)
    return main, startup, loss


def _batches(n_batches, batch, d, seed=0):
    r = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        x = r.normal(0, 1, (batch, d)).astype(np.float32)
        # learnable target: per-coordinate affine of x
        out.append({"x": x, "y": (0.5 * x + 0.25).astype(np.float32)})
    return out


def _snapshot(prog):
    return {
        p.name: np.array(fluid.global_scope().find_var(p.name))
        for p in prog.all_parameters()
    }


def _restore(snap):
    for k, v in snap.items():
        fluid.global_scope().set(k, v)


def test_switch_moe_trains_single_device():
    main, startup, loss = _moe_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    batches = _batches(8, 64, 16)
    losses = [
        float(exe.run(main, feed=batches[i % 8], fetch_list=[loss])[0])
        for i in range(80)
    ]
    assert losses[-1] < 0.4 * losses[0], f"MoE did not learn: {losses[::8]}"


def test_switch_moe_expert_parallel_loss_parity():
    """expert_axis=8 all_to_all dispatch vs single device: identical
    dispatch math (shared _gate_and_dispatch) => per-step loss parity."""
    main, startup, loss = _moe_program(optimizer="sgd")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    snap = _snapshot(main)
    batches = _batches(6, 64, 16)

    single = [
        float(exe.run(main, feed=fd, fetch_list=[loss])[0])
        for fd in batches
    ]

    _restore(snap)
    mesh = _mesh((E,), ("expert",))
    strategy = DistributedStrategy(
        mesh, data_axis=None, rules=moe_rules("expert"),
        expert_axis="expert",
    )
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    exe2 = fluid.Executor(fluid.CPUPlace())
    sharded = [
        float(exe2.run(compiled, feed=fd, fetch_list=[loss])[0])
        for fd in batches
    ]
    np.testing.assert_allclose(single, sharded, rtol=2e-4, atol=2e-4)


def test_switch_moe_dp_times_ep_parity():
    """2-way data x 4-way expert: batch sharded over data, experts over
    the expert axis (capacity follows the per-data-rank token count)."""
    main, startup, loss = _moe_program(capacity_factor=8.0, num_experts=4,
                                       optimizer="sgd")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    snap = _snapshot(main)
    # NOTE: with data sharding the dispatch cumsum runs per data shard, so
    # parity needs capacity large enough that no token overflows in either
    # run (capacity_factor=8 => capacity >= tokens routed anywhere).
    batches = _batches(4, 64, 16, seed=7)

    single = [
        float(exe.run(main, feed=fd, fetch_list=[loss])[0])
        for fd in batches
    ]

    _restore(snap)
    mesh = _mesh((2, 4), ("data", "expert"))
    strategy = DistributedStrategy(
        mesh, data_axis="data", rules=moe_rules("expert"),
        expert_axis="expert",
    )
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    exe2 = fluid.Executor(fluid.CPUPlace())
    sharded = [
        float(exe2.run(compiled, feed=fd, fetch_list=[loss])[0])
        for fd in batches
    ]
    np.testing.assert_allclose(single, sharded, rtol=1e-3, atol=1e-3)
