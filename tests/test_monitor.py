"""Telemetry plane (paddle_tpu/monitor.py): registry semantics, exporter
round-trips, disabled-path overhead, span unification, step-log schema,
label-cardinality cap, quantile summaries, the step ring buffer, the
profiler's no-native degrade path, metric doc coverage, and the flags
plane's self-documentation contract."""

import json
import os
import tracemalloc

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers, monitor, profiler


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    flags.set_flags({"telemetry": False, "step_log_path": "",
                     "metrics_dump_path": ""})
    yield
    monitor.reset()
    flags.set_flags({"telemetry": False, "step_log_path": "",
                     "metrics_dump_path": ""})


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    monitor.enable()
    c = monitor.counter("t_c", "a counter")
    c.inc()
    c.inc(2, labels={"k": "a"})
    c.inc(3, labels={"k": "a"})
    assert c.value() == 1
    assert c.value(labels={"k": "a"}) == 5

    g = monitor.gauge("t_g", "a gauge")
    g.set(7.5)
    g.add(0.5)
    assert g.value() == 8.0

    h = monitor.histogram("t_h", "a histogram", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    assert h.count() == 3
    assert h.sum() == pytest.approx(2.55)


def test_same_name_returns_same_instrument_and_kind_conflict_raises():
    c1 = monitor.counter("t_dup", "doc")
    assert monitor.counter("t_dup") is c1
    with pytest.raises(TypeError):
        monitor.gauge("t_dup")


def test_histogram_bucket_conflict_raises():
    h = monitor.histogram("t_hb", "h", buckets=(1.0, 2.0))
    assert monitor.histogram("t_hb", buckets=(2.0, 1.0)) is h  # same set
    with pytest.raises(ValueError, match="buckets"):
        monitor.histogram("t_hb", buckets=(5.0,))


def test_disabled_calls_are_inert_and_allocation_free():
    """With telemetry off (the default), instrument calls must return
    after the flag check: no label cells materialize and no allocations
    are attributable to monitor.py — the hot-path contract that lets the
    executor stay permanently instrumented."""
    assert not monitor.enabled()
    c = monitor.counter("t_off_c", "off")
    g = monitor.gauge("t_off_g", "off")
    h = monitor.histogram("t_off_h", "off")
    # warm up (first calls may touch lazy interpreter state)
    c.inc()
    g.set(1)
    h.observe(1)

    n_calls = 5 * 1000
    tracemalloc.start()
    base = tracemalloc.take_snapshot()
    for _ in range(1000):
        c.inc()
        c.inc(2)
        g.set(3)
        g.add(1)
        h.observe(0.5)
    snap = tracemalloc.take_snapshot()
    tracemalloc.stop()

    stats = snap.compare_to(base, "filename")
    grew = sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith("monitor.py")
               and s.size_diff > 0)
    # any real per-call allocation would show as >= n_calls * 16 bytes;
    # allow constant interpreter noise (~hundreds of bytes), not growth
    assert grew < n_calls, f"disabled path allocated {grew}B/{n_calls} calls"
    assert c.value() == 0 and g.value() == 0 and h.count() == 0
    assert not c._cells and not g._cells and not h._cells


def test_gauge_replace_swaps_cells_and_honors_label_cap():
    """Gauge.replace (the roofline plane's wholesale top-K mirror):
    the swap is total — no stale cells survive — and the
    MAX_LABEL_SETS cap applies exactly like every other mutator (an
    unclamped device_profile_top_k must not grow the registry without
    bound): first-listed values win, drops warn once and count into
    pt_metric_label_overflow_total."""
    monitor.enable()
    g = monitor.gauge("t_repl_g", "replaced gauge")
    g.set(1.0, labels={"op": "stale"})
    g.replace([({"op": "a"}, 2.0), ({"op": "b"}, 3.0)])
    assert g.value(labels={"op": "a"}) == 2.0
    assert g.value(labels={"op": "stale"}) == 0.0  # swap is total
    assert len(g._cells) == 2
    with pytest.warns(RuntimeWarning, match="label-sets"):
        g.replace([({"i": i}, float(i))
                   for i in range(monitor.MAX_LABEL_SETS + 7)])
    assert len(g._cells) == monitor.MAX_LABEL_SETS
    # rank order: the first N values win, the tail is dropped
    assert g.value(labels={"i": 1}) == 1.0
    assert g.value(labels={"i": monitor.MAX_LABEL_SETS + 1}) == 0.0
    assert monitor.counter("pt_metric_label_overflow_total").value(
        labels={"metric": "t_repl_g"}) == 7
    # disabled: replace is a no-op like every mutator
    monitor.disable()
    g.replace([({"op": "z"}, 9.0)])
    assert g.value(labels={"op": "z"}) == 0.0
    monitor.enable()


def test_label_cardinality_cap_collapses_into_overflow_bucket():
    """A mis-labelled hot-path metric (step index in a label) must not
    grow registry memory without bound: past MAX_LABEL_SETS distinct
    label-sets, mutations collapse into one overflow='true' cell, the
    first drop warns, and every drop counts into
    pt_metric_label_overflow_total."""
    monitor.enable()
    c = monitor.counter("t_card_c", "capped counter")
    with pytest.warns(RuntimeWarning, match="label-sets"):
        for i in range(monitor.MAX_LABEL_SETS + 10):
            c.inc(labels={"i": i})
    # the capped cells + exactly one overflow cell
    assert len(c._cells) == monitor.MAX_LABEL_SETS + 1
    assert c.value(labels={"overflow": "true"}) == 10
    assert monitor.counter("pt_metric_label_overflow_total").value(
        labels={"metric": "t_card_c"}) == 10
    # existing label-sets keep mutating normally past the cap
    c.inc(labels={"i": 0})
    assert c.value(labels={"i": 0}) == 2

    # same contract for gauges and histograms
    g = monitor.gauge("t_card_g", "capped gauge")
    h = monitor.histogram("t_card_h", "capped hist", buckets=(1.0,))
    with pytest.warns(RuntimeWarning, match="label-sets"):
        for i in range(monitor.MAX_LABEL_SETS + 3):
            g.set(i, labels={"i": i})
            h.observe(0.5, labels={"i": i})
    assert len(g._cells) == monitor.MAX_LABEL_SETS + 1
    assert len(h._cells) == monitor.MAX_LABEL_SETS + 1
    assert h.count(labels={"overflow": "true"}) == 3


def test_runtime_flag_flip_takes_effect_immediately():
    c = monitor.counter("t_flip", "flip")
    c.inc()
    assert c.value() == 0
    flags.set_flags({"telemetry": True})
    c.inc()
    assert c.value() == 1
    flags.set_flags({"telemetry": False})
    c.inc()
    assert c.value() == 1


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------

def _parse_prometheus(text):
    """sample name+labels -> float value (enough to verify round-trip)."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, val = line.rsplit(" ", 1)
        out[key] = float(val)
    return out


def test_dump_metrics_round_trips_prometheus_and_json(tmp_path):
    monitor.enable()
    monitor.counter("t_exp_c", "requests").inc(4, labels={"route": "a/b"})
    monitor.gauge("t_exp_g", "depth").set(2.5)
    h = monitor.histogram("t_exp_h", "lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    # JSON: parseable, values intact
    j = json.loads(monitor.dump_metrics(fmt="json"))
    assert j["t_exp_c"]["kind"] == "counter"
    assert j["t_exp_c"]["values"][0] == {
        "labels": {"route": "a/b"}, "value": 4.0}
    assert j["t_exp_g"]["values"][0]["value"] == 2.5
    hist = j["t_exp_h"]["values"][0]
    assert hist["count"] == 3
    assert hist["buckets"] == [[0.1, 1], [1.0, 2], ["+Inf", 3]]

    # Prometheus text: parseable, same numbers, cumulative buckets
    prom = _parse_prometheus(monitor.dump_metrics(fmt="prometheus"))
    assert prom['t_exp_c{route="a/b"}'] == 4.0
    assert prom["t_exp_g"] == 2.5
    assert prom['t_exp_h_bucket{le="0.1"}'] == 1
    assert prom['t_exp_h_bucket{le="1.0"}'] == 2
    assert prom['t_exp_h_bucket{le="+Inf"}'] == 3
    assert prom["t_exp_h_count"] == 3
    assert prom["t_exp_h_sum"] == pytest.approx(5.55)

    # file write path (explicit arg and flag-driven)
    p = tmp_path / "m.prom"
    monitor.dump_metrics(path=str(p))
    assert _parse_prometheus(p.read_text())["t_exp_g"] == 2.5
    flags.set_flags({"metrics_dump_path": str(tmp_path / "m.json")})
    monitor.dump_metrics(fmt="json")
    assert json.loads((tmp_path / "m.json").read_text())["t_exp_g"]


def test_bad_format_raises():
    with pytest.raises(ValueError):
        monitor.dump_metrics(fmt="xml")


def test_histogram_quantile_summaries_in_json_and_prometheus():
    """p50/p95/p99 ride to_json and the Prometheus text as _p50/_p95/_p99
    samples, so latency tails are readable without a Prometheus server
    running histogram_quantile for you."""
    monitor.enable()
    h = monitor.histogram("t_q_h", "latencies", buckets=(1.0, 2.0, 4.0))
    for v in [0.5] * 50 + [1.5] * 40 + [3.0] * 10:
        h.observe(v)
    # linear interpolation inside the target bucket
    assert h.quantile(0.50) == pytest.approx(1.0)
    assert h.quantile(0.95) == pytest.approx(3.0)
    assert h.quantile(0.99) == pytest.approx(3.8)
    assert h.quantile(0.5, labels={"no": "cell"}) is None

    cell = json.loads(monitor.to_json())["t_q_h"]["values"][0]
    assert cell["p50"] == pytest.approx(1.0)
    assert cell["p95"] == pytest.approx(3.0)
    assert cell["p99"] == pytest.approx(3.8)

    prom = _parse_prometheus(monitor.dump_metrics(fmt="prometheus"))
    assert prom["t_q_h_p50"] == pytest.approx(1.0)
    assert prom["t_q_h_p95"] == pytest.approx(3.0)
    assert prom["t_q_h_p99"] == pytest.approx(3.8)

    # +Inf-bucket observations clamp to the top finite bound
    h2 = monitor.histogram("t_q_inf", "h", buckets=(1.0,))
    h2.observe(50.0)
    assert h2.quantile(0.99) == 1.0


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

def test_span_feeds_histogram_when_enabled():
    monitor.enable()
    with monitor.span("test.scope"):
        pass
    h = monitor.histogram("pt_span_seconds")
    assert h.count(labels={"span": "test.scope"}) == 1

    flags.set_flags({"telemetry": False})
    with monitor.span("test.scope"):
        pass
    assert h.count(labels={"span": "test.scope"}) == 1  # unchanged


# --------------------------------------------------------------------------
# step log
# --------------------------------------------------------------------------

def test_log_step_writes_versioned_jsonl(tmp_path):
    path = tmp_path / "steps.jsonl"
    monitor.enable(step_log_path=str(path))
    base = {"kind": "step", "step": 0, "wall_ms": 1.0, "compile_ms": None,
            "cache": "hit", "evictions": 0, "feed_bytes": 0,
            "fetch_bytes": 0, "nan_check": None, "strategy": None}
    monitor.log_step(dict(base))
    monitor.log_step(dict(base, step=1))
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["seq"] for r in recs] == [0, 1]
    for r in recs:
        assert r["v"] == monitor.STEP_LOG_SCHEMA_VERSION
        monitor.validate_step_record(r)


def test_validate_step_record_rejects_bad_records():
    good = {"v": monitor.STEP_LOG_SCHEMA_VERSION, "ts": 0.0, "seq": 0,
            "kind": "step", "step": 0, "wall_ms": 1.0, "compile_ms": None,
            "cache": "miss", "evictions": 0, "feed_bytes": 0,
            "fetch_bytes": 0, "nan_check": "ok", "strategy": None}
    monitor.validate_step_record(good)
    with pytest.raises(ValueError, match="missing field"):
        monitor.validate_step_record({k: v for k, v in good.items()
                                      if k != "cache"})
    with pytest.raises(ValueError, match="type"):
        monitor.validate_step_record(dict(good, step="zero"))
    with pytest.raises(ValueError, match="unknown fields"):
        monitor.validate_step_record(dict(good, bogus=1))
    with pytest.raises(ValueError, match="schema"):
        monitor.validate_step_record(dict(good, v=999))
    # PR-3 optional fields: the numerics summary and a window's
    # first-bad-step index validate when present, stay optional when not
    monitor.validate_step_record(dict(
        good, nan_check="fail", nan_step=7,
        numerics={"vars": 3, "nonfinite_vars": 1,
                  "first_bad": {"op": 2, "op_type": "elementwise_sub",
                                "var": "t"}}))
    with pytest.raises(ValueError, match="type"):
        monitor.validate_step_record(dict(good, nan_step="seven"))
    with pytest.raises(ValueError, match="type"):
        monitor.validate_step_record(dict(good, numerics="not-a-dict"))
    # PR-4 optional fields: the phase breakdown and boundedness verdict
    # validate when present, stay optional when not
    monitor.validate_step_record(dict(
        good, phases={"feed": 0.1, "dispatch": 0.2, "device": 0.3,
                      "fetch": 0.05},
        bound="device_bound"))
    with pytest.raises(ValueError, match="type"):
        monitor.validate_step_record(dict(good, phases=[0.1, 0.2]))
    with pytest.raises(ValueError, match="type"):
        monitor.validate_step_record(dict(good, bound=3))
    # PR-10 optional field: the sampled marker (async-dispatch plane)
    monitor.validate_step_record(dict(good, sampled=False))
    monitor.validate_step_record(dict(good, sampled=True))
    with pytest.raises(ValueError, match="type"):
        monitor.validate_step_record(dict(good, sampled="no"))


def test_log_step_unwritable_path_warns_once_never_raises(tmp_path):
    """Executors call log_step from finally blocks: a bad path must not
    mask the step's real result (or a propagating exception)."""
    monitor.enable(step_log_path=str(tmp_path / "no" / "such" / "s.jsonl"))
    rec = {"kind": "step", "step": 0, "wall_ms": 1.0, "compile_ms": None,
           "cache": "hit", "evictions": 0, "feed_bytes": 0,
           "fetch_bytes": 0, "nan_check": None, "strategy": None}
    with pytest.warns(RuntimeWarning, match="step log"):
        monitor.log_step(dict(rec))
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        monitor.log_step(dict(rec))  # warn-once: silent, and no raise


def test_log_step_noop_without_path_or_telemetry(tmp_path):
    monitor.log_step({"kind": "step"})  # no telemetry: no error, no file
    flags.set_flags({"telemetry": True})
    monitor.log_step({"kind": "step"})  # no path: rings, writes nothing
    assert not monitor.step_log_active()
    assert len(monitor.recent_steps()) == 1  # ring still fed


def test_step_ring_buffer_is_bounded_and_ordered():
    monitor.enable()
    n = monitor.STEP_RING_CAPACITY
    for i in range(n + 10):
        monitor.log_step({"kind": "step", "step": i})
    recs = monitor.recent_steps()
    assert len(recs) == n  # the bound IS the memory contract
    assert recs[0]["step"] == 10 and recs[-1]["step"] == n + 9
    assert [r["seq"] for r in recs] == list(range(10, n + 10))
    assert monitor.recent_steps(5) == recs[-5:]
    assert monitor.recent_steps(0) == []  # not the recs[-0:] full dump
    assert monitor.recent_steps(-3) == []
    monitor.reset()
    assert monitor.recent_steps() == []


# --------------------------------------------------------------------------
# flags plane self-documentation (satellite)
# --------------------------------------------------------------------------

def test_describe_flags_covers_every_flag_with_docs():
    table = flags.describe_flags()
    names = [row["name"] for row in table]
    assert names == sorted(names)
    assert set(names) == set(flags.get_flags())
    for row in table:
        assert row["type"] in ("bool", "int", "float", "str"), row
        assert isinstance(row["doc"], str) and row["doc"].strip(), (
            f"flag '{row['name']}' has no doc string")
        assert row["value"] == flags.get_flag(row["name"])
    by_name = {r["name"]: r for r in table}
    assert by_name["telemetry"]["default"] is False
    # the numerics plane's flags ride the same self-documentation
    # contract: present, typed, defaulted off/every-step/unfiltered
    assert by_name["numerics"]["type"] == "bool"
    assert by_name["numerics"]["default"] is False
    assert by_name["numerics_every_n_steps"]["type"] == "int"
    assert by_name["numerics_every_n_steps"]["default"] == 1
    assert by_name["numerics_vars"]["type"] == "str"
    assert by_name["numerics_vars"]["default"] == ""
    # the time-attribution plane's flags: phases on with telemetry,
    # tracing off / every-step by default
    assert by_name["step_phases"]["type"] == "bool"
    assert by_name["step_phases"]["default"] is True
    assert by_name["trace_dir"]["type"] == "str"
    assert by_name["trace_dir"]["default"] == ""
    assert by_name["trace_every_n_steps"]["type"] == "int"
    assert by_name["trace_every_n_steps"]["default"] == 1
    # the async-dispatch plane's flags: phases sampled every 16 steps,
    # trainer prefetch two batches deep
    assert by_name["step_phases_every_n"]["type"] == "int"
    assert by_name["step_phases_every_n"]["default"] == 16
    assert by_name["prefetch_depth"]["type"] == "int"
    assert by_name["prefetch_depth"]["default"] == 2


def test_watch_flag_fires_immediately_and_on_change():
    seen = []
    flags.watch_flag("benchmark", seen.append)
    assert seen == [False]
    flags.set_flags({"benchmark": True})
    assert seen == [False, True]
    flags.set_flags({"benchmark": False})
    assert seen == [False, True, False]
    with pytest.raises(KeyError):
        flags.watch_flag("no_such_flag", seen.append)


# --------------------------------------------------------------------------
# end-to-end: 3 training steps of the MNIST model produce a valid step
# log whose cache accounting matches ground truth
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_mnist_three_step_train_emits_valid_step_log(tmp_path):
    from paddle_tpu.models import mnist as mnist_model

    path = tmp_path / "mnist_steps.jsonl"
    monitor.enable(step_log_path=str(path))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = mnist_model.get_model(use_conv=False)
        fluid.optimizer.SGD(0.1).minimize(model["loss"])

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            feed = {
                "pixel": rng.rand(16, 784).astype(np.float32),
                "label": rng.randint(0, 10, (16, 1)).astype(np.int64),
            }
            exe.run(main, feed=feed, fetch_list=[model["loss"]])

    recs = [json.loads(l) for l in path.read_text().splitlines()]
    for r in recs:
        monitor.validate_step_record(r)
    # startup + 3 train steps, one record each
    assert len(recs) == 4
    assert [r["kind"] for r in recs] == ["step"] * 4
    train = recs[1:]
    # ground truth: first train step compiles, the rest hit the cache
    assert [r["cache"] for r in train] == ["miss", "hit", "hit"]
    assert train[0]["compile_ms"] is not None and train[0]["compile_ms"] > 0
    assert all(r["compile_ms"] is None for r in train[1:])
    assert all(r["feed_bytes"] == 16 * 784 * 4 + 16 * 8 for r in train)
    assert all(r["fetch_bytes"] > 0 for r in train)
    assert all(r["wall_ms"] > 0 for r in train)
    assert [r["step"] for r in recs] == [0, 1, 2, 3]

    # registry agrees with the log
    assert monitor.counter(
        "pt_executor_cache_hits_total").value() == 2
    assert monitor.counter(
        "pt_executor_cache_misses_total").value() == 2  # startup + train
    # exporters round-trip on the live registry
    assert json.loads(monitor.dump_metrics(fmt="json"))
    assert "pt_executor_cache_hits_total 2.0" in monitor.dump_metrics(
        fmt="prometheus")


# --------------------------------------------------------------------------
# profiler degrade path (satellite): no native collector, no crash
# --------------------------------------------------------------------------

def test_profiler_degrades_cleanly_without_native(tmp_path, monkeypatch):
    """With the C++ profiler unavailable, `with profiler.profiler(...)`
    must be a structural no-op: no chrome-trace file, no crash, and
    monitor.span events still round-trip into pt_span_seconds."""
    from paddle_tpu import native

    monkeypatch.setattr(native, "available", lambda: False)
    monitor.enable()
    path = tmp_path / "prof"
    with profiler.profiler(profile_path=str(path)):
        with monitor.span("degrade.scope"):
            pass
        with profiler.record_event("raw.event"):  # host span: plain yield
            pass
    assert not path.with_suffix(".json").exists()
    assert not (tmp_path / "prof.json").exists()
    # telemetry half of the unified span still recorded
    assert monitor.histogram("pt_span_seconds").count(
        labels={"span": "degrade.scope"}) == 1
    # start/stop entry points take the same degrade path
    profiler.start_profiler()
    profiler.stop_profiler(profile_path=str(tmp_path / "prof2"))
    assert not (tmp_path / "prof2.json").exists()


# --------------------------------------------------------------------------
# metric doc coverage (satellite): every builtin instrument documented,
# README's Observability table complete
# --------------------------------------------------------------------------

def test_every_builtin_metric_has_doc_and_readme_entry():
    # importing the instrumented modules registers their instruments
    import paddle_tpu.contrib.trainer  # noqa: F401
    import paddle_tpu.core.interp  # noqa: F401
    import paddle_tpu.executor  # noqa: F401
    import paddle_tpu.incubate.fleet.fleet_base  # noqa: F401
    import paddle_tpu.parallel.pipeline  # noqa: F401
    import paddle_tpu.parallel.ring_attention  # noqa: F401

    snap = monitor.snapshot()
    builtin = {n: m for n, m in snap.items() if n.startswith("pt_")}
    assert len(builtin) >= 25, sorted(builtin)
    readme = open(os.path.join(os.path.dirname(fluid.__file__), "..",
                               "README.md")).read()
    for name, m in sorted(builtin.items()):
        assert m["doc"].strip(), f"metric '{name}' has no doc string"
        assert name in readme, (
            f"metric '{name}' missing from README's Observability "
            f"metrics table")


# --------------------------------------------------------------------------
# executor hot path with telemetry off: the one-boolean-check contract
# --------------------------------------------------------------------------

def test_executor_run_disabled_path_allocates_nothing_in_monitor():
    """The PR-2 instrumentation (ring buffer, compile reports, budget
    pre-flight) must not add allocations to Executor.run while telemetry
    is off — same contract the raw instruments honor."""
    assert not monitor.enabled()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # warm the compile cache + lazy interp state
            exe.run(main, feed=feed, fetch_list=[y])
        n_runs = 30
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            exe.run(main, feed=feed, fetch_list=[y])
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    stats = snap.compare_to(base, "filename")
    grew = sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith("monitor.py")
               and s.size_diff > 0)
    # per-run allocations would show as >= n_runs * 16B growth; allow
    # constant interpreter noise only
    assert grew < n_runs * 16, (
        f"disabled Executor.run allocated {grew}B in monitor.py over "
        f"{n_runs} runs")
