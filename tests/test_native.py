"""Native runtime components: RecordIO, coordination service, arena,
profiler (C++ via ctypes; analogs of reference recordio/*_test.cc,
rpc_server_test.cc, best_fit_allocator_test.cc)."""

import json
import os
import threading
import time

import pytest

from paddle_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library failed to build"
)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "data.rio")
    records = [os.urandom(n) for n in (1, 100, 5000, 0, 70000)]
    with native.RecordIOWriter(path) as w:
        for r in records:
            w.write(r)
    with native.RecordIOScanner(path) as s:
        got = list(s)
    assert got == records


def test_recordio_zlib_and_corruption_skip(tmp_path):
    path = str(tmp_path / "data.rio")
    w = native.RecordIOWriter(path, compressor="zlib")
    payloads = [os.urandom(300_000) for _ in range(12)]  # ~4 chunks
    for p in payloads:
        w.write(p)
    w.close()
    # roundtrip through zlib chunks
    assert list(native.RecordIOScanner(path)) == payloads
    size = os.path.getsize(path)
    # corrupt bytes in the middle: the damaged chunk is skipped via CRC,
    # other chunks still scan (reference: recordio/README torn-write
    # tolerance)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff" * 8)
    got = list(native.RecordIOScanner(path))
    assert 0 < len(got) < 12


def test_coord_kv_barrier_heartbeat():
    port = 45671
    srv = native.CoordServer(port)
    try:
        c1 = native.CoordClient("127.0.0.1", port)
        c2 = native.CoordClient("127.0.0.1", port)
        c1.put("mesh/topology", b"4x2")
        assert c2.get("mesh/topology") == b"4x2"
        # blocking get: value arrives from the other client
        result = {}

        def getter():
            result["v"] = c2.get("late_key", timeout_ms=5000)

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(0.1)
        c1.put("late_key", b"hello")
        t.join(timeout=5)
        assert result["v"] == b"hello"
        # timeout path
        with pytest.raises(TimeoutError):
            c1.get("never", timeout_ms=100)
        # 2-party barrier
        done = []

        def barrier_worker(c):
            c.barrier("step1", 2)
            done.append(1)

        t1 = threading.Thread(target=barrier_worker, args=(c1,))
        t1.start()
        time.sleep(0.1)
        assert not done  # first waiter blocked
        barrier_worker(c2)
        t1.join(timeout=5)
        assert len(done) == 2
        # heartbeats / liveness
        c1.heartbeat("worker0")
        assert c1.dead_peers(max_age_ms=60000) == []
        time.sleep(0.15)
        assert c1.dead_peers(max_age_ms=50) == ["worker0"]
        c1.close()
        c2.close()
    finally:
        srv.stop()


def test_arena_best_fit_and_coalesce():
    a = native.Arena(1 << 16)
    p1 = a.alloc(1000)
    p2 = a.alloc(2000)
    p3 = a.alloc(3000)
    assert a.in_use >= 6000
    a.free(p2)
    # best-fit: a 1500-byte alloc reuses p2's hole, not the tail
    p4 = a.alloc(1500)
    assert p4 == p2
    a.free(p1)
    a.free(p3)
    a.free(p4)
    assert a.in_use == 0
    # full coalescing: can now allocate nearly everything in one block
    big = a.alloc((1 << 16) - 128)
    a.free(big)
    with pytest.raises(MemoryError):
        a.alloc(1 << 20)
    assert a.peak > 0
    a.destroy()


def test_profiler_chrome_trace(tmp_path):
    native.profiler_enable()
    native.profiler_begin("outer")
    native.profiler_begin("inner")
    time.sleep(0.01)
    native.profiler_end()
    native.profiler_end()
    native.profiler_disable()
    path = str(tmp_path / "trace.json")
    n = native.profiler_dump(path)
    assert n == 2
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert names == {"outer", "inner"}
    inner = [e for e in trace["traceEvents"] if e["name"] == "inner"][0]
    assert inner["dur"] >= 9000  # ~10ms in microseconds
