"""Device-side numerics plane (paddle_tpu/numerics.py + the
instrument_numerics pass): in-graph tensor stats fetched as one auxiliary
bundle, NaN/Inf provenance naming the first bad op, every-N sampling,
AMP/clip aux decode, the /numerics route, the run_steps first-bad-step
tracker, and the zero-allocation disabled hot path."""

import json
import tracemalloc
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import (
    debugger,
    flags,
    layers,
    monitor,
    numerics,
    passes,
)


@pytest.fixture(autouse=True)
def _clean_numerics():
    monitor.reset()
    flags.set_flags({"telemetry": False, "numerics": False,
                     "numerics_every_n_steps": 1, "numerics_vars": "",
                     "check_nan_inf": False, "step_log_path": ""})
    yield
    monitor.stop_server()
    monitor.reset()
    flags.set_flags({"telemetry": False, "numerics": False,
                     "numerics_every_n_steps": 1, "numerics_vars": "",
                     "check_nan_inf": False, "step_log_path": ""})


def _enable():
    flags.set_flags({"telemetry": True, "numerics": True})


def _small_program():
    """3-op program: scale -> elementwise_sub -> mean."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[4], dtype="float32")
        a = layers.scale(x, scale=2.0)
        t = layers.elementwise_sub(a, y)
        out = layers.mean(t)
    return main, startup, out, t


# --------------------------------------------------------------------------
# the pass + plan
# --------------------------------------------------------------------------

def test_instrument_pass_appends_one_stats_op_with_decode_plan():
    main, _startup, _out, _t = _small_program()
    n_ops = len(main.global_block().ops)
    version = main.version
    plan = passes.apply_pass("instrument_numerics", main)._numerics_plan
    block = main.global_block()
    assert len(block.ops) == n_ops + 1
    assert block.ops[-1].type == "numerics_stats"
    assert main.version > version  # compiled-step cache invalidates
    # every float op output is a stats entry, mapped to its producer
    assert len(plan.entries) == 3
    by_var = {v: (idx, op_type) for v, idx, op_type, _k in plan.entries}
    for var, (idx, op_type) in by_var.items():
        assert block.ops[idx].type == op_type
        assert var in block.ops[idx].output_arg_names
    assert plan.bundle_size == 3 * len(numerics.STAT_FIELDS)
    # idempotent: re-applying returns the same plan, appends nothing
    assert passes.apply_pass(
        "instrument_numerics", main)._numerics_plan is plan
    assert len(block.ops) == n_ops + 1


def test_numerics_vars_flag_filters_instrumented_vars():
    flags.set_flags({"numerics_vars": "mean_*"})
    main, _startup, _out, _t = _small_program()
    plan = numerics.instrument(main)
    assert [v for v, _i, _t2, _k in plan.entries] == [
        main.global_block().ops[2].output_arg_names[0]]


def test_stats_values_match_ground_truth():
    _enable()
    main, startup, out, t = _small_program()
    numerics.instrument(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    x = np.array([[1.0, 2.0, -4.0, 0.5]], np.float32)
    y = np.zeros((1, 4), np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[out])
    stats = numerics.latest_stats()[main._uid]["stats"]
    cell = stats[t.name]  # t = 2*x - 0 = [2, 4, -8, 1]
    assert cell["nonfinite"] == 0
    assert cell["maxabs"] == pytest.approx(8.0)
    assert cell["rms"] == pytest.approx(
        float(np.sqrt(np.mean(np.square([2.0, 4.0, -8.0, 1.0])))), rel=1e-5)
    assert monitor.gauge("pt_tensor_maxabs").value(
        labels={"var": t.name}) == pytest.approx(8.0)
    assert monitor.gauge("pt_tensor_rms").value(
        labels={"var": t.name}) == pytest.approx(cell["rms"])
    # summary landed in the step record too
    rec = monitor.recent_steps()[-1]
    assert rec["numerics"]["vars"] == 3
    assert rec["numerics"]["first_bad"] is None
    monitor.validate_step_record(rec)


def test_rms_and_maxabs_computed_over_finite_values_only():
    """Stats must describe the FINITE values exactly when the tensor is
    partly non-finite — the moment the gauges actually get read."""
    _enable()
    main, startup, out, t = _small_program()
    numerics.instrument(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    x = np.array([[1.0, 2.0, -4.0, 0.5]], np.float32)
    y = np.array([[np.inf, 0.0, 0.0, 0.0]], np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[out])
    cell = numerics.latest_stats()[main._uid]["stats"][t.name]
    # t = 2x - y = [-inf, 4, -8, 1]: one bad element, finite rest
    assert cell["nonfinite"] == 1
    assert cell["maxabs"] == pytest.approx(8.0)
    assert cell["rms"] == pytest.approx(
        float(np.sqrt((16.0 + 64.0 + 1.0) / 3.0)), rel=1e-5)


def test_optional_histogram_buckets_count_finite_nonzero_elements():
    _enable()
    main, startup, out, t = _small_program()
    numerics.instrument(main, histogram_bins=8)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    x = np.array([[1.0, 2.0, -4.0, 0.0]], np.float32)  # one zero
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": x, "y": np.zeros((1, 4), np.float32)},
                fetch_list=[out])
    cell = numerics.latest_stats()[main._uid]["stats"][t.name]
    assert len(cell["hist"]) == 8
    assert sum(cell["hist"]) == 3  # zero excluded from magnitude buckets


# --------------------------------------------------------------------------
# NaN provenance (acceptance: injected inf - inf mid-graph)
# --------------------------------------------------------------------------

def test_nan_provenance_names_the_inf_minus_inf_op_via_run():
    _enable()
    main, startup, out, t = _small_program()
    numerics.instrument(main)
    sub_idx = next(i for i, op in enumerate(main.global_block().ops)
                   if op.type == "elementwise_sub")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    inf = np.full((1, 4), np.inf, np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        # scale(inf) = inf feeds the sub, but the FEEDS are not op
        # outputs: the first instrumented op producing non-finite values
        # is scale; use finite x and inf y so the sub alone goes bad
        exe.run(main, feed={"x": np.ones((1, 4), np.float32), "y": inf},
                fetch_list=[out])
    recs = numerics.provenance_records()
    assert len(recs) == 1
    rec = recs[0]
    assert rec["op_idx"] == sub_idx
    assert rec["op_type"] == "elementwise_sub"
    assert rec["var"] == t.name
    assert rec["nonfinite"] == 4
    assert rec["program_uid"] == main._uid
    assert numerics.provenance_for(main._uid)["op_idx"] == sub_idx
    # the step record names the same op
    srec = monitor.recent_steps()[-1]
    assert srec["numerics"]["first_bad"] == {
        "op": sub_idx, "op_type": "elementwise_sub", "var": t.name}
    assert srec["numerics"]["nonfinite_vars"] >= 1
    # provenance fires once per episode: a second bad step adds nothing
    with fluid.scope_guard(scope):
        exe.run(main, feed={"x": np.ones((1, 4), np.float32), "y": inf},
                fetch_list=[out])
    assert len(numerics.provenance_records()) == 1
    # ...and a clean step re-arms it
    with fluid.scope_guard(scope):
        exe.run(main, feed={"x": np.ones((1, 4), np.float32),
                            "y": np.zeros((1, 4), np.float32)},
                fetch_list=[out])
        exe.run(main, feed={"x": np.ones((1, 4), np.float32), "y": inf},
                fetch_list=[out])
    assert len(numerics.provenance_records()) == 2


def test_nan_provenance_via_run_steps_window_with_nan_step():
    _enable()
    flags.set_flags({"check_nan_inf": True})
    main, startup, out, t = _small_program()
    numerics.instrument(main)
    sub_idx = next(i for i, op in enumerate(main.global_block().ops)
                   if op.type == "elementwise_sub")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    ones = np.ones((1, 4), np.float32)
    zeros = np.zeros((1, 4), np.float32)
    inf = np.full((1, 4), np.inf, np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)  # step 0
        with pytest.raises(FloatingPointError, match="step 3"):
            exe.run_steps(
                main,
                feed_list=[{"x": ones, "y": zeros},
                           {"x": ones, "y": zeros},
                           {"x": ones, "y": inf},
                           {"x": ones, "y": inf}],
                steps=4, fetch_list=[out])
    # the in-graph tracker named the first bad step of the window
    rec = monitor.recent_steps()[-1]
    assert rec["kind"] == "window"
    assert rec["nan_check"] == "fail"
    assert rec["nan_step"] == 3  # window starts at step 1 (startup = 0)
    monitor.validate_step_record(rec)
    assert monitor.counter(
        "pt_executor_nan_check_failures_total").value() == 1
    # provenance decoded from the window's bundle names the op and step
    prec = numerics.provenance_for(main._uid)
    assert prec is not None
    assert prec["op_idx"] == sub_idx
    assert prec["op_type"] == "elementwise_sub"
    assert prec["var"] == t.name
    assert prec["kind"] == "window"
    assert prec["nan_step"] == 3


def test_run_steps_clean_window_reports_ok_without_nan_step():
    _enable()
    flags.set_flags({"check_nan_inf": True})
    main, startup, out, _t = _small_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    ones = np.ones((1, 4), np.float32)
    zeros = np.zeros((1, 4), np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_steps(main, feed_list=[{"x": ones, "y": zeros}],
                      steps=3, fetch_list=[out])
    rec = monitor.recent_steps()[-1]
    assert rec["nan_check"] == "ok"
    assert "nan_step" not in rec
    assert monitor.counter(
        "pt_executor_nan_check_failures_total").value() == 0


def test_pprint_program_annotates_first_nonfinite_op():
    _enable()
    main, startup, out, t = _small_program()
    numerics.instrument(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main,
                feed={"x": np.ones((1, 4), np.float32),
                      "y": np.full((1, 4), np.inf, np.float32)},
                fetch_list=[out])
    text = debugger.pprint_program(main)
    assert "numerics provenance" in text
    assert "!! first non-finite" in text
    assert t.name in text
    # opting out removes the annotation
    clean = debugger.pprint_program(main, with_numerics=False)
    assert "first non-finite" not in clean


# --------------------------------------------------------------------------
# sampling + the single-transfer contract
# --------------------------------------------------------------------------

def test_every_n_sampling_bounds_decodes():
    _enable()
    flags.set_flags({"numerics_every_n_steps": 2})
    main, startup, out, _t = _small_program()
    numerics.instrument(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((1, 4), np.float32),
            "y": np.zeros((1, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)  # step 0: startup has no plan -> no decode
        for _ in range(4):  # steps 1..4: steps 2 and 4 sample
            exe.run(main, feed=feed, fetch_list=[out])
    assert monitor.counter("pt_numerics_decodes_total").value() == 2
    recs = monitor.recent_steps()
    assert ["numerics" in r for r in recs] == [
        False, False, True, False, True]


def test_sampled_step_performs_exactly_one_auxiliary_transfer(monkeypatch):
    """Acceptance: the instrumented step's stats ride ONE fetched array —
    numerics._to_host (the only device->host sync in the decode path)
    runs exactly once per sampled step and never on unsampled ones."""
    _enable()
    flags.set_flags({"numerics_every_n_steps": 2})
    calls = []
    real = numerics._to_host
    monkeypatch.setattr(numerics, "_to_host",
                        lambda x: (calls.append(np.shape(x)), real(x))[1])
    main, startup, out, _t = _small_program()
    plan = numerics.instrument(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((1, 4), np.float32),
            "y": np.zeros((1, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)          # step 0, no plan
        exe.run(main, feed=feed, fetch_list=[out])   # step 1: unsampled
        assert calls == []
        exe.run(main, feed=feed, fetch_list=[out])   # step 2: sampled
    # one transfer, of the one concatenated bundle
    assert calls == [(plan.bundle_size,)]


def test_user_fetches_unchanged_by_instrumentation():
    _enable()
    main, startup, out, t = _small_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((1, 4), np.float32),
            "y": np.zeros((1, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        plain = exe.run(main, feed=feed, fetch_list=[out, t])
    numerics.instrument(main)
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        inst = exe.run(main, feed=feed, fetch_list=[out, t])
    assert len(inst) == 2  # the bundle never leaks into user fetches
    for a, b in zip(plain, inst):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------------------
# aux plumbing (AMP / clip values ride the same bundle)
# --------------------------------------------------------------------------

def test_aux_only_plan_builds_lazily_for_amp_programs():
    """A program whose graph code registered aux vars (amp.decorate,
    clip) gets a lazy aux-only bundle on first run — no explicit pass
    needed for the AMP gauges."""
    from paddle_tpu import amp

    _enable()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        loss = layers.mean(layers.fc(x, 2))
        opt = amp.decorate(fluid.optimizer.SGD(0.1), init_loss_scaling=8.0,
                           use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
    plan = main._numerics_plan
    assert plan.entries == ()  # aux-only: no stats vars were selected
    kinds = [k for k, _v in plan.aux]
    assert "amp_loss_scale" in kinds and "amp_found_inf" in kinds
    assert monitor.gauge("pt_amp_loss_scale").value() == 8.0


def test_numerics_route_serves_provenance_and_stats():
    _enable()
    main, startup, out, t = _small_program()
    numerics.instrument(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main,
                feed={"x": np.ones((1, 4), np.float32),
                      "y": np.full((1, 4), np.inf, np.float32)},
                fetch_list=[out])
    port = monitor.serve(0)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/numerics", timeout=10) as r:
        assert r.status == 200
        payload = json.loads(r.read())
    assert payload["active"] is True
    assert payload["provenance"][0]["op_type"] == "elementwise_sub"
    assert t.name in payload["programs"][str(main._uid)]["stats"]


# --------------------------------------------------------------------------
# disabled hot path (acceptance: tracemalloc proof)
# --------------------------------------------------------------------------

def test_disabled_executor_run_allocates_nothing_in_numerics():
    """With the numerics flag off (the default), Executor.run must not
    allocate a single attributable byte in numerics.py — the same
    one-boolean-check contract monitor.py honors."""
    assert not numerics.active()
    main, startup, out, _t = _small_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((1, 4), np.float32),
            "y": np.zeros((1, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # warm the compile cache + lazy interp state
            exe.run(main, feed=feed, fetch_list=[out])
        n_runs = 30
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            exe.run(main, feed=feed, fetch_list=[out])
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    stats = snap.compare_to(base, "filename")
    grew = sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith("numerics.py")
               and s.size_diff > 0)
    assert grew < n_runs * 16, (
        f"disabled Executor.run allocated {grew}B in numerics.py over "
        f"{n_runs} runs")


def test_flag_flip_activates_and_deactivates_decoding():
    main, startup, out, _t = _small_program()
    numerics.instrument(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((1, 4), np.float32),
            "y": np.zeros((1, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[out])  # off: no decode
        assert monitor.counter("pt_numerics_decodes_total").value() == 0
        _enable()
        exe.run(main, feed=feed, fetch_list=[out])
        assert monitor.counter("pt_numerics_decodes_total").value() == 1
        flags.set_flags({"numerics": False})
        exe.run(main, feed=feed, fetch_list=[out])
        assert monitor.counter("pt_numerics_decodes_total").value() == 1


# --------------------------------------------------------------------------
# MNIST e2e (slow tier): trainer-level auto-instrumentation
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_mnist_numerics_e2e_step_log_and_stats(tmp_path):
    from paddle_tpu.models import mnist as mnist_model

    path = tmp_path / "steps.jsonl"
    _enable()
    flags.set_flags({"step_log_path": str(path),
                     "numerics_vars": "*@GRAD"})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = mnist_model.get_model(use_conv=False)
        fluid.optimizer.SGD(0.1).minimize(model["loss"])
    plan = passes.apply_pass("instrument_numerics", main)._numerics_plan
    assert plan.entries and all(
        v.endswith("@GRAD") for v, _i, _t, _k in plan.entries)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            feed = {
                "pixel": rng.rand(16, 784).astype(np.float32),
                "label": rng.randint(0, 10, (16, 1)).astype(np.int64),
            }
            exe.run(main, feed=feed, fetch_list=[model["loss"]])
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    train = [r for r in recs if "numerics" in r]
    assert len(train) == 3
    for r in train:
        monitor.validate_step_record(r)
        assert r["numerics"]["nonfinite_vars"] == 0
        assert r["numerics"]["vars"] == len(plan.entries)
    # gradient stats are live in the registry
    g = monitor.gauge("pt_tensor_rms")
    assert any(g.value(labels={"var": v}) > 0
               for v, _i, _t, _k in plan.entries)
    assert numerics.provenance_records() == []
