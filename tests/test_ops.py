"""Per-op output + numeric-gradient checks through the OpHarness
(reference test strategy: SURVEY.md section 4 item 1)."""

import numpy as np

from op_test import OpHarness

RS = np.random.RandomState


def test_matmul_output_and_grad():
    x = RS(0).randn(3, 4)
    y = RS(1).randn(4, 5)
    h = OpHarness("matmul", {"X": x, "Y": y})
    h.check_output({"Out": x @ y})
    h.check_grad(["x_0", "y_0"])


def test_matmul_transpose():
    x = RS(0).randn(4, 3)
    y = RS(1).randn(5, 4)
    h = OpHarness("matmul", {"X": x, "Y": y},
                  attrs={"transpose_X": True, "transpose_Y": True})
    h.check_output({"Out": x.T @ y.T})
    h.check_grad(["x_0", "y_0"])


def test_matmul_batched():
    x = RS(0).randn(2, 3, 4)
    y = RS(1).randn(2, 4, 5)
    h = OpHarness("matmul", {"X": x, "Y": y})
    h.check_output({"Out": x @ y})
    h.check_grad(["x_0", "y_0"])


def test_mul_flatten():
    x = RS(0).randn(2, 3, 4)   # flattened to [2, 12]
    y = RS(1).randn(12, 5)
    h = OpHarness("mul", {"X": x, "Y": y}, attrs={"x_num_col_dims": 1})
    h.check_output({"Out": (x.reshape(2, 12) @ y).reshape(2, 5)})
    h.check_grad(["x_0", "y_0"])


def test_elementwise_add_broadcast_axis():
    x = RS(0).randn(2, 3, 4)
    y = RS(1).randn(3)
    h = OpHarness("elementwise_add", {"X": x, "Y": y}, attrs={"axis": 1})
    h.check_output({"Out": x + y[None, :, None]})
    h.check_grad(["x_0", "y_0"])


def test_elementwise_div_grad():
    x = RS(0).randn(3, 4)
    y = RS(1).randn(3, 4) + 3.0
    h = OpHarness("elementwise_div", {"X": x, "Y": y})
    h.check_output({"Out": x / y})
    h.check_grad(["x_0", "y_0"])


def test_softmax():
    x = RS(0).randn(4, 7)
    h = OpHarness("softmax", {"X": x})
    e = np.exp(x - x.max(-1, keepdims=True))
    h.check_output({"Out": e / e.sum(-1, keepdims=True)})
    h.check_grad(["x_0"])


def test_relu_grad():
    x = RS(0).randn(4, 5) + 0.1 * np.sign(RS(0).randn(4, 5))
    x[np.abs(x) < 0.05] = 0.5  # keep away from kink
    h = OpHarness("relu", {"X": x})
    h.check_output({"Out": np.maximum(x, 0)})
    h.check_grad(["x_0"])


def test_tanh_sigmoid_grad():
    x = RS(0).randn(3, 4)
    OpHarness("tanh", {"X": x}).check_grad(["x_0"])
    OpHarness("sigmoid", {"X": x}).check_grad(["x_0"])


def test_reduce_sum():
    x = RS(0).randn(3, 4, 5)
    h = OpHarness("reduce_sum", {"X": x}, attrs={"dim": [1], "keep_dim": True})
    h.check_output({"Out": x.sum(1, keepdims=True)})
    h.check_grad(["x_0"])


def test_reduce_mean_all():
    x = RS(0).randn(3, 4)
    h = OpHarness("reduce_mean", {"X": x}, attrs={"reduce_all": True})
    h.check_output({"Out": np.asarray(x.mean())})
    h.check_grad(["x_0"])


def test_layer_norm_grad():
    x = RS(0).randn(4, 6)
    scale = RS(1).rand(6) + 0.5
    bias = RS(2).randn(6)
    h = OpHarness(
        "layer_norm",
        {"X": x, "Scale": scale, "Bias": bias},
        attrs={"begin_norm_axis": 1, "epsilon": 1e-5},
        out_slots=("Y",),
    )
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
    h.check_output({"Y": ref}, atol=1e-4)
    h.check_grad(["x_0", "scale_0", "bias_0"], delta=1e-4)


def test_batch_norm_train_grad():
    x = RS(0).randn(4, 3, 2, 2)
    scale = RS(1).rand(3) + 0.5
    bias = RS(2).randn(3)
    mean = np.zeros(3)
    var = np.ones(3)
    h = OpHarness(
        "batch_norm",
        {"X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
        attrs={"epsilon": 1e-5, "momentum": 0.9, "is_test": False},
        out_slots=("Y",),
    )
    mu = x.mean((0, 2, 3))
    v = x.var((0, 2, 3))
    ref = (x - mu[None, :, None, None]) / np.sqrt(v + 1e-5)[None, :, None, None]
    ref = ref * scale[None, :, None, None] + bias[None, :, None, None]
    h.check_output({"Y": ref}, atol=1e-4)
    h.check_grad(["x_0", "scale_0", "bias_0"], delta=1e-4)


def test_conv2d_grad():
    x = RS(0).randn(2, 3, 5, 5)
    w = RS(1).randn(4, 3, 3, 3)
    h = OpHarness(
        "conv2d",
        {"Input": x, "Filter": w},
        attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
               "groups": 1},
        out_slots=("Output",),
    )
    h.check_grad(["input_0", "filter_0"], delta=1e-3, rtol=5e-3)


def test_pool2d_avg_grad():
    x = RS(0).randn(2, 2, 4, 4)
    h = OpHarness(
        "pool2d", {"X": x},
        attrs={"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2],
               "paddings": [0, 0]},
    )
    ref = x.reshape(2, 2, 2, 2, 2, 2).mean(axis=(3, 5))
    h.check_output({"Out": ref})
    h.check_grad(["x_0"])


def test_pool2d_max():
    x = RS(0).randn(2, 2, 4, 4)
    h = OpHarness(
        "pool2d", {"X": x},
        attrs={"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
               "paddings": [0, 0]},
    )
    ref = x.reshape(2, 2, 2, 2, 2, 2).max(axis=(3, 5))
    h.check_output({"Out": ref})


def test_softmax_with_cross_entropy_grad():
    logits = RS(0).randn(5, 7)
    label = RS(1).randint(0, 7, (5, 1)).astype(np.int64)
    h = OpHarness(
        "softmax_with_cross_entropy",
        {"Logits": logits, "Label": label},
        out_slots=("Loss",),
    )
    shifted = logits - logits.max(-1, keepdims=True)
    logp = shifted - np.log(np.exp(shifted).sum(-1, keepdims=True))
    ref = -np.take_along_axis(logp, label, axis=-1)
    h.check_output({"Loss": ref}, atol=1e-5)
    h.check_grad(["logits_0"])


def test_cross_entropy_grad():
    p = RS(0).rand(4, 5) + 0.1
    p = p / p.sum(-1, keepdims=True)
    label = RS(1).randint(0, 5, (4, 1)).astype(np.int64)
    h = OpHarness("cross_entropy", {"X": p, "Label": label}, out_slots=("Y",))
    ref = -np.log(np.take_along_axis(p, label, -1) + 1e-8)
    h.check_output({"Y": ref}, atol=1e-5)
    h.check_grad(["x_0"])


def test_lookup_table_grad():
    w = RS(0).randn(10, 4)
    ids = np.array([[1], [3], [3], [7]], dtype=np.int64)
    h = OpHarness("lookup_table", {"W": w, "Ids": ids})
    h.check_output({"Out": w[ids[:, 0]]})
    h.check_grad(["w_0"])


def test_gather_grad():
    x = RS(0).randn(6, 3)
    idx = np.array([0, 2, 2, 5], dtype=np.int64)
    h = OpHarness("gather", {"X": x, "Index": idx})
    h.check_output({"Out": x[idx]})
    h.check_grad(["x_0"])


def test_concat_and_split():
    a = RS(0).randn(2, 3)
    b = RS(1).randn(2, 4)
    h = OpHarness("concat", {"X": [a, b]}, attrs={"axis": 1},
                  multi_input_slots=("X",))
    h.check_output({"Out": np.concatenate([a, b], 1)})
    h.check_grad(["x_0", "x_1"])


def test_transpose_reshape_grad():
    x = RS(0).randn(2, 3, 4)
    h = OpHarness("transpose2", {"X": x}, attrs={"axis": [2, 0, 1]})
    h.check_output({"Out": x.transpose(2, 0, 1)})
    h.check_grad(["x_0"])
    h2 = OpHarness("reshape2", {"X": x}, attrs={"shape": [2, 12]})
    h2.check_output({"Out": x.reshape(2, 12)})
    h2.check_grad(["x_0"])


def test_scale_op():
    x = RS(0).randn(3, 3)
    h = OpHarness("scale", {"X": x}, attrs={"scale": 2.0, "bias": 1.0})
    h.check_output({"Out": 2 * x + 1})
    h.check_grad(["x_0"])


def test_sum_op():
    xs = [RS(i).randn(3, 3) for i in range(3)]
    h = OpHarness("sum", {"X": xs}, multi_input_slots=("X",))
    h.check_output({"Out": sum(xs)})
    h.check_grad(["x_0", "x_1", "x_2"])


def test_sequence_pool_masked():
    x = RS(0).randn(3, 5, 4)
    length = np.array([2, 5, 3], dtype=np.int64)
    h = OpHarness("sequence_pool", {"X": x, "Length": length},
                  attrs={"pooltype": "AVERAGE"})
    ref = np.stack([x[i, : length[i]].mean(0) for i in range(3)])
    h.check_output({"Out": ref}, atol=1e-5)
    h.check_grad(["x_0"])


def test_dropout_eval_and_train():
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1000], dtype="float32")
        out_train = layers.dropout(x, 0.3, dropout_implementation="upscale_in_train")
        out_eval = layers.dropout(x, 0.3, is_test=True,
                                  dropout_implementation="upscale_in_train")
    exe = fluid.Executor(fluid.CPUPlace())
    xb = np.ones((2, 1000), dtype=np.float32)
    tr, ev = exe.run(main, feed={"x": xb}, fetch_list=[out_train, out_eval])
    np.testing.assert_allclose(ev, xb)
    frac_zero = float((tr == 0).mean())
    assert 0.2 < frac_zero < 0.4
    # kept entries upscaled
    kept = tr[tr != 0]
    np.testing.assert_allclose(kept, 1.0 / 0.7, rtol=1e-5)


def test_prelu_op_modes():
    x = RS(0).randn(2, 3, 4, 4)
    for mode, alpha in (
        ("all", RS(1).randn(1)),
        ("channel", RS(2).randn(3)),
        ("element", RS(3).randn(3, 4, 4)),
    ):
        h = OpHarness("prelu", {"X": x, "Alpha": alpha}, attrs={"mode": mode})
        if mode == "channel":
            a = alpha.reshape(1, 3, 1, 1)
        elif mode == "element":
            a = alpha.reshape(1, 3, 4, 4)
        else:
            a = alpha.reshape(())
        h.check_output({"Out": np.where(x > 0, x, a * x)})
        h.check_grad(["x_0", "alpha_0"])


def test_group_norm_op():
    x = RS(0).randn(2, 6, 4, 4)
    scale, bias = RS(1).randn(6), RS(2).randn(6)
    h = OpHarness(
        "group_norm",
        {"X": x, "Scale": scale, "Bias": bias},
        attrs={"groups": 3, "epsilon": 1e-5},
        out_slots=("Y",),
    )
    xg = x.reshape(2, 3, 2, 4, 4)
    mean = xg.mean(axis=(2, 3, 4), keepdims=True)
    var = xg.var(axis=(2, 3, 4), keepdims=True)
    y = ((xg - mean) / np.sqrt(var + 1e-5)).reshape(2, 6, 4, 4)
    y = y * scale.reshape(1, 6, 1, 1) + bias.reshape(1, 6, 1, 1)
    h.check_output({"Y": y})
    h.check_grad(["x_0", "scale_0", "bias_0"])


def test_gru_unit_op():
    b, hsz = 2, 4
    x = RS(0).randn(b, 3 * hsz)
    hp = RS(1).randn(b, hsz)
    w = RS(2).randn(hsz, 3 * hsz) * 0.5
    bias = RS(3).randn(3 * hsz) * 0.1
    h = OpHarness(
        "gru_unit",
        {"Input": x, "HiddenPrev": hp, "Weight": w, "Bias": bias},
        out_slots=("Hidden",),
    )

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    xb = x + bias
    xu, xr, xc = xb[:, :hsz], xb[:, hsz : 2 * hsz], xb[:, 2 * hsz :]
    wu, wr, wc = w[:, :hsz], w[:, hsz : 2 * hsz], w[:, 2 * hsz :]
    u = sig(xu + hp @ wu)
    r = sig(xr + hp @ wr)
    c = np.tanh(xc + (r * hp) @ wc)
    expected = u * hp + (1 - u) * c
    h.check_output({"Hidden": expected})
    h.check_grad(["input_0", "hiddenprev_0", "weight_0", "bias_0"])


def test_dropout_prob_zero_is_identity_in_train_mode():
    """p=0 must not overflow the uint16 keep threshold (regression)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        y = layers.dropout(x, 0.0, is_test=False,
                           dropout_implementation="upscale_in_train")
        loss = layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xa = np.random.RandomState(0).normal(0, 1, (4, 8)).astype(np.float32)
    out = exe.run(main, feed={"x": xa}, fetch_list=[y.name])
    np.testing.assert_allclose(np.asarray(out[0]), xa, rtol=1e-6)
