"""OpTest coverage for the round-2 op-breadth tranche: sequence ops,
activations, pairwise losses, tensor/vision/detection ops
(reference harness pattern: tests/unittests/test_*_op.py)."""

import numpy as np
import pytest

from tests.op_test import OpHarness

RS = np.random.RandomState


# --- sequence ops (padded + Length semantics) ---


def test_sequence_pad_unpad():
    x = RS(0).randn(2, 4, 3)
    ln = np.array([3, 2], np.int64)
    h = OpHarness("sequence_pad", {"X": x, "Length": ln},
                  out_slots=("Out",))
    exp = x.copy()
    exp[0, 3:] = 0
    exp[1, 2:] = 0
    h.check_output({"Out": exp})
    h.check_grad(["x_0"])

    h2 = OpHarness("sequence_unpad", {"X": x, "Length": ln},
                   out_slots=("Out",))
    h2.check_output({"Out": exp})


def test_sequence_concat():
    a = RS(1).randn(2, 3)
    b = RS(2).randn(2, 4)
    la = np.array([2, 3], np.int64)
    lb = np.array([4, 1], np.int64)
    h = OpHarness(
        "sequence_concat",
        {"X": [a, b], "Length": [la, lb]},
        out_slots=("Out",),
        multi_input_slots=("X", "Length"),
    )
    exp = np.zeros((2, 7))
    exp[0, :2] = a[0, :2]
    exp[0, 2:6] = b[0, :4]
    exp[1, :3] = a[1, :3]
    exp[1, 3:4] = b[1, :1]
    h.check_output({"Out": exp})


def test_sequence_slice():
    x = RS(3).randn(2, 5, 2)
    off = np.array([1, 0], np.int64)
    ln = np.array([3, 2], np.int64)
    h = OpHarness("sequence_slice",
                  {"X": x, "Offset": off, "Length": ln}, out_slots=("Out",))
    exp = np.zeros_like(x)
    exp[0, :3] = x[0, 1:4]
    exp[1, :2] = x[1, 0:2]
    h.check_output({"Out": exp})
    h.check_grad(["x_0"])


def test_sequence_erase():
    x = np.array([[2, 0, 2, 5, 9], [3, 3, 3, 1, 0]], np.int64)
    ln = np.array([5, 4], np.int64)
    h = OpHarness("sequence_erase", {"X": x, "Length": ln},
                  attrs={"tokens": [2, 3]}, out_slots=("Out",))
    exp = np.array([[0, 5, 9, 0, 0], [1, 0, 0, 0, 0]], np.int64)
    h.check_output({"Out": exp})


def test_sequence_enumerate():
    x = np.array([[1, 2, 3, 4]], np.int64)
    ln = np.array([3], np.int64)
    h = OpHarness("sequence_enumerate", {"X": x, "Length": ln},
                  attrs={"win_size": 2, "pad_value": 0},
                  out_slots=("Out",))
    exp = np.array([[[1, 2], [2, 3], [3, 0], [0, 0]]], np.int64)
    h.check_output({"Out": exp})


def test_sequence_expand_as():
    x = RS(4).randn(2, 3)
    y = RS(5).randn(2, 4, 3)
    ln = np.array([4, 2], np.int64)
    h = OpHarness("sequence_expand_as",
                  {"X": x, "Y": y, "Length": ln}, out_slots=("Out",))
    exp = np.repeat(x[:, None, :], 4, axis=1)
    exp[1, 2:] = 0
    h.check_output({"Out": exp})
    h.check_grad(["x_0"])


# --- activations ---


@pytest.mark.parametrize("op,fn,attrs", [
    ("tanh_shrink", lambda x: x - np.tanh(x), {}),
    ("softshrink",
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0)),
     {"lambda": 0.5}),
    ("hard_shrink", lambda x: np.where(np.abs(x) > 0.5, x, 0),
     {"threshold": 0.5}),
    ("brelu", lambda x: np.clip(x, 0.1, 0.9),
     {"t_min": 0.1, "t_max": 0.9}),
    ("stanh", lambda x: 1.7159 * np.tanh(0.67 * x), {}),
    ("thresholded_relu", lambda x: np.where(x > 1.0, x, 0),
     {"threshold": 1.0}),
])
def test_new_activations(op, fn, attrs):
    x = RS(6).randn(3, 4) * 2
    h = OpHarness(op, {"X": x}, attrs=attrs)
    h.check_output({"Out": fn(x)})


def test_soft_relu_and_selu_grads():
    x = RS(7).randn(3, 4)
    h = OpHarness("soft_relu", {"X": x})
    h.check_output({"Out": np.log1p(np.exp(np.clip(x, -40, 40)))})
    h.check_grad(["x_0"])
    # keep x away from selu's kink at 0 (finite differences straddle it)
    x_off = x + np.where(x >= 0, 0.5, -0.5)
    h2 = OpHarness("selu", {"X": x_off})
    h2.check_grad(["x_0"])


# --- losses ---


def test_log_loss():
    p = RS(8).uniform(0.05, 0.95, (4, 1))
    y = RS(9).randint(0, 2, (4, 1)).astype(np.float64)
    h = OpHarness("log_loss", {"Predicted": p, "Labels": y},
                  out_slots=("Loss",))
    eps = 1e-4
    exp = -(y * np.log(p + eps) + (1 - y) * np.log(1 - p + eps))
    h.check_output({"Loss": exp})
    h.check_grad(["predicted_0"])


def test_rank_and_margin_rank_loss():
    l_, r_ = RS(10).randn(4, 1), RS(11).randn(4, 1)
    y = RS(12).randint(0, 2, (4, 1)).astype(np.float64)
    h = OpHarness("rank_loss", {"Label": y, "Left": l_, "Right": r_})
    exp = np.logaddexp(0, l_ - r_) - y * (l_ - r_)
    h.check_output({"Out": exp})
    h.check_grad(["left_0", "right_0"])

    y2 = np.where(y > 0, 1.0, -1.0)
    h2 = OpHarness("margin_rank_loss",
                   {"Label": y2, "X1": l_, "X2": r_},
                   attrs={"margin": 0.1})
    exp2 = np.maximum(0, -y2 * (l_ - r_) + 0.1)
    h2.check_output({"Out": exp2})


def test_hinge_kldiv_bpr_cos_sim():
    logits = RS(13).randn(4, 1)
    y = RS(14).randint(0, 2, (4, 1)).astype(np.float64)
    OpHarness("hinge_loss", {"Logits": logits, "Labels": y},
              out_slots=("Loss",)).check_output(
        {"Loss": np.maximum(0, 1 - (2 * y - 1) * logits)})

    x = np.log(RS(15).dirichlet(np.ones(5), 3))
    t = RS(16).dirichlet(np.ones(5), 3)
    h = OpHarness("kldiv_loss", {"X": x, "Target": t},
                  attrs={"reduction": "mean"}, out_slots=("Loss",))
    exp = np.mean(np.where(t > 0, t * (np.log(t) - x), 0.0))
    h.check_output({"Loss": exp})
    h.check_grad(["x_0"])

    scores = RS(17).randn(3, 4)
    label = np.array([[1], [0], [3]], np.int64)
    hb = OpHarness("bpr_loss", {"X": scores, "Label": label},
                   out_slots=("Y",))
    pos = np.take_along_axis(scores, label, 1)
    lo = np.logaddexp(0, -(pos - scores))
    mask = np.zeros_like(scores)
    np.put_along_axis(mask, label, 1.0, 1)
    exp = (lo * (1 - mask)).sum(1, keepdims=True) / 3
    hb.check_output({"Y": exp})
    hb.check_grad(["x_0"])

    a, b = RS(18).randn(3, 5), RS(19).randn(3, 5)
    hc = OpHarness("cos_sim", {"X": a, "Y": b}, out_slots=("Out",))
    exp = (a * b).sum(-1, keepdims=True) / (
        np.linalg.norm(a, axis=-1, keepdims=True)
        * np.linalg.norm(b, axis=-1, keepdims=True))
    hc.check_output({"Out": exp})
    hc.check_grad(["x_0", "y_0"])


# --- tensor / vision ---


def test_reverse_argsort_diag_linspace():
    x = RS(20).randn(3, 4)
    OpHarness("reverse", {"X": x}, attrs={"axis": [1]}).check_output(
        {"Out": x[:, ::-1]})
    h = OpHarness("argsort", {"X": x}, out_slots=("Out", "Indices"))
    h.check_output({"Out": np.sort(x, -1),
                    "Indices": np.argsort(x, -1)})
    d = RS(21).randn(4)
    OpHarness("diag", {"Diagonal": d}).check_output({"Out": np.diag(d)})
    OpHarness("linspace", {
        "Start": np.array([0.0]), "Stop": np.array([1.0])},
        attrs={"num": 5}).check_output(
        {"Out": np.linspace(0, 1, 5)})


def test_gather_scatter_nd():
    x = RS(22).randn(3, 4)
    idx = np.array([[0, 1], [2, 3]], np.int64)
    h = OpHarness("gather_nd", {"X": x, "Index": idx})
    h.check_output({"Out": x[[0, 2], [1, 3]]})
    h.check_grad(["x_0"])

    upd = RS(23).randn(2)
    h2 = OpHarness("scatter_nd_add", {"X": x, "Index": idx, "Updates": upd})
    exp = x.copy()
    exp[0, 1] += upd[0]
    exp[2, 3] += upd[1]
    h2.check_output({"Out": exp})
    h2.check_grad(["x_0", "updates_0"])


def test_pad_crop_family():
    x = RS(24).randn(1, 2, 3, 3)
    h = OpHarness("pad2d", {"X": x},
                  attrs={"paddings": [1, 1, 2, 2], "mode": "constant",
                         "pad_value": 0.5})
    exp = np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)), constant_values=0.5)
    h.check_output({"Out": exp})
    h.check_grad(["x_0"])

    big = RS(25).randn(3, 4)
    small = RS(26).randn(2, 3)
    OpHarness("pad_constant_like", {"X": big, "Y": small},
              attrs={"pad_value": 1.0}).check_output(
        {"Out": np.pad(small, ((0, 1), (0, 1)), constant_values=1.0)})

    OpHarness("crop", {"X": big},
              attrs={"offsets": [1, 1], "shape": [2, 2]}).check_output(
        {"Out": big[1:3, 1:3]})


def test_channel_shuffles():
    x = RS(27).randn(1, 4, 2, 2)
    h = OpHarness("shuffle_channel", {"X": x}, attrs={"group": 2})
    exp = x.reshape(1, 2, 2, 2, 2).swapaxes(1, 2).reshape(1, 4, 2, 2)
    h.check_output({"Out": exp})

    x2 = RS(28).randn(1, 4, 2, 2)
    h2 = OpHarness("pixel_shuffle", {"X": x2}, attrs={"upscale_factor": 2})
    ps = np.transpose(x2.reshape(1, 1, 2, 2, 2, 2), (0, 1, 4, 2, 5, 3)
                      ).reshape(1, 1, 4, 4)
    h2.check_output({"Out": ps})
    h2.check_grad(["x_0"])

    # space_to_depth round-trips pixel_shuffle's spatial blocks: its output
    # holds exactly x2's values (block layout permutes the channel order)
    from paddle_tpu.core.registry import get_op_def

    out3 = np.asarray(
        get_op_def("space_to_depth").compute(
            {"X": [ps]}, {"blocksize": 2})["Out"][0]
    )
    assert out3.shape == (1, 4, 2, 2)
    np.testing.assert_allclose(np.sort(out3.ravel()), np.sort(x2.ravel()))


def test_multiplex_and_shard_index():
    a, b = RS(29).randn(3, 2), RS(30).randn(3, 2)
    ids = np.array([[0], [1], [0]], np.int64)
    h = OpHarness("multiplex", {"X": [a, b], "Ids": ids},
                  multi_input_slots=("X",))
    exp = np.stack([a[0], b[1], a[2]])
    h.check_output({"Out": exp})

    x = np.array([[1], [7], [15]], np.int64)
    h2 = OpHarness("shard_index", {"X": x},
                   attrs={"index_num": 16, "nshards": 2, "shard_id": 0,
                          "ignore_value": -1})
    h2.check_output({"Out": np.array([[1], [7], [-1]], np.int64)})


def test_interp_ops():
    x = RS(31).randn(1, 1, 2, 2)
    h = OpHarness("nearest_interp", {"X": x},
                  attrs={"out_h": 4, "out_w": 4, "align_corners": False})
    exp = x.repeat(2, axis=2).repeat(2, axis=3)
    h.check_output({"Out": exp})

    hb = OpHarness("bilinear_interp", {"X": x},
                   attrs={"out_h": 3, "out_w": 3, "align_corners": True})
    ys = np.linspace(0, 1, 3)
    exp2 = np.zeros((1, 1, 3, 3))
    for i, fy in enumerate(ys):
        for j, fx in enumerate(ys):
            y0, x0 = int(np.floor(fy)), int(np.floor(fx))
            y1, x1 = min(y0 + 1, 1), min(x0 + 1, 1)
            wy, wx = fy - y0, fx - x0
            exp2[0, 0, i, j] = (
                x[0, 0, y0, x0] * (1 - wy) * (1 - wx)
                + x[0, 0, y1, x0] * wy * (1 - wx)
                + x[0, 0, y0, x1] * (1 - wy) * wx
                + x[0, 0, y1, x1] * wy * wx)
    hb.check_output({"Out": exp2})
    hb.check_grad(["x_0"])


def test_norm_affine_channel_row_conv():
    x = RS(32).randn(2, 3, 2)
    h = OpHarness("norm", {"X": x}, attrs={"axis": 1}, out_slots=("Out",))
    n = np.sqrt((x * x).sum(1, keepdims=True) + 1e-10)
    h.check_output({"Out": x / n})
    h.check_grad(["x_0"])

    xc = RS(33).randn(2, 3, 2, 2)
    s, b = RS(34).randn(3), RS(35).randn(3)
    h2 = OpHarness("affine_channel", {"X": xc, "Scale": s, "Bias": b})
    h2.check_output(
        {"Out": xc * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1)})
    h2.check_grad(["x_0", "scale_0", "bias_0"])

    xt = RS(36).randn(2, 5, 3)
    f = RS(37).randn(2, 3)
    h3 = OpHarness("row_conv", {"X": xt, "Filter": f})
    xp = np.pad(xt, ((0, 0), (0, 1), (0, 0)))
    exp = xp[:, 0:5] * f[0] + xp[:, 1:6] * f[1]
    h3.check_output({"Out": exp})
    h3.check_grad(["x_0", "filter_0"])


def test_iou_similarity_and_box_coder():
    x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float64)
    y = np.array([[1, 1, 2, 2]], np.float64)
    h = OpHarness("iou_similarity", {"X": x, "Y": y})
    h.check_output({"Out": np.array([[1.0 / 4.0], [1.0 / 4.0]])})

    prior = np.array([[0.0, 0.0, 1.0, 1.0]], np.float64)
    target = np.array([[0.25, 0.25, 0.75, 0.75]], np.float64)
    he = OpHarness("box_coder", {"PriorBox": prior, "TargetBox": target},
                   attrs={"code_type": "encode_center_size"},
                   out_slots=("OutputBox",))
    # center offsets 0, log size ratio log(0.5)
    exp = np.array([[[0.0, 0.0, np.log(0.5), np.log(0.5)]]])
    he.check_output({"OutputBox": exp})

    code = exp
    hd = OpHarness("box_coder", {"PriorBox": prior, "TargetBox": code},
                   attrs={"code_type": "decode_center_size"},
                   out_slots=("OutputBox",))
    hd.check_output({"OutputBox": target[None, :, :].transpose(1, 0, 2)})


def test_sync_batch_norm_alias():
    x = RS(38).randn(4, 3, 2, 2)
    scale, bias = np.ones(3), np.zeros(3)
    mean, var = np.zeros(3), np.ones(3)
    h = OpHarness(
        "sync_batch_norm",
        {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
         "Variance": var},
        attrs={"is_test": False}, out_slots=("Y",),
    )
    mu = x.mean(axis=(0, 2, 3))
    v = x.var(axis=(0, 2, 3))
    exp = (x - mu.reshape(1, 3, 1, 1)) / np.sqrt(
        v.reshape(1, 3, 1, 1) + 1e-5)
    h.check_output({"Y": exp})


def test_prior_box_and_anchor_generator_shapes():
    feat = np.zeros((1, 8, 4, 4), np.float32)
    img = np.zeros((1, 3, 64, 64), np.float32)
    h = OpHarness("prior_box", {"Input": feat, "Image": img},
                  attrs={"min_sizes": [16.0], "aspect_ratios": [2.0],
                         "flip": True, "clip": True},
                  out_slots=("Boxes", "Variances"))
    main_out = h  # shapes checked through check_output with computed exp?
    # 1 min_size x (1 + 2 flipped ratios) = 3 priors per cell
    import jax

    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.core.registry import get_op_def

    outs = get_op_def("prior_box").compute(
        {"Input": [feat], "Image": [img]},
        {"min_sizes": [16.0], "aspect_ratios": [2.0], "flip": True,
         "clip": True})
    assert outs["Boxes"][0].shape == (4, 4, 3, 4)
    assert outs["Variances"][0].shape == (4, 4, 3, 4)
    assert (np.asarray(outs["Boxes"][0]) >= 0).all()

    outs2 = get_op_def("anchor_generator").compute(
        {"Input": [feat]},
        {"anchor_sizes": [32.0], "aspect_ratios": [1.0],
         "stride": [16.0, 16.0]})
    assert outs2["Anchors"][0].shape == (4, 4, 1, 4)
    a = np.asarray(outs2["Anchors"][0])
    np.testing.assert_allclose(a[0, 0, 0], [8 - 16, 8 - 16, 8 + 16, 8 + 16])


def test_nearest_interp_mixed_axes_align_corners():
    """align_corners must apply independently per axis (code-review
    finding, round 2: out_h==1 must not disable width alignment)."""
    x = RS(40).randn(1, 1, 1, 4)
    h = OpHarness("nearest_interp", {"X": x},
                  attrs={"out_h": 1, "out_w": 7, "align_corners": True})
    xs = np.round(np.linspace(0, 3, 7)).astype(int)
    h.check_output({"Out": x[:, :, :, xs]})


def test_grid_sampler_zero_pads_out_of_bounds():
    x = np.ones((1, 1, 2, 2))
    grid = np.full((1, 1, 1, 2), -5.0)  # all 4 corners out of bounds
    from paddle_tpu.core.registry import get_op_def

    out = np.asarray(get_op_def("grid_sampler").compute(
        {"X": [x], "Grid": [grid]}, {})["Output"][0])
    np.testing.assert_allclose(out, 0.0)

    # half-a-pixel outside: only the in-bounds corner contributes (0.25)
    grid2 = np.full((1, 1, 1, 2), -2.0)
    out2 = np.asarray(get_op_def("grid_sampler").compute(
        {"X": [x], "Grid": [grid2]}, {})["Output"][0])
    np.testing.assert_allclose(out2, 0.25)


def test_sequence_pad_vector_pad_value():
    x = RS(41).randn(2, 3, 2)
    ln = np.array([2, 1], np.int64)
    pv = np.array([7.0, -7.0])
    h = OpHarness("sequence_pad", {"X": x, "PadValue": pv, "Length": ln},
                  out_slots=("Out",))
    exp = x.copy()
    exp[0, 2:] = pv
    exp[1, 1:] = pv
    h.check_output({"Out": exp})


def test_prior_box_max_size_index_pairing():
    """max_sizes pair index-wise with min_sizes (code-review finding,
    round 2): 2 min x (1+2 ars) + 2 paired max = 8 priors, not 10."""
    from paddle_tpu.core.registry import get_op_def

    feat = np.zeros((1, 8, 2, 2), np.float32)
    img = np.zeros((1, 3, 32, 32), np.float32)
    outs = get_op_def("prior_box").compute(
        {"Input": [feat], "Image": [img]},
        {"min_sizes": [30.0, 60.0], "max_sizes": [60.0, 111.0],
         "aspect_ratios": [2.0], "flip": True, "clip": False})
    assert outs["Boxes"][0].shape == (2, 2, 8, 4)


def test_box_coder_variances_roundtrip():
    prior = np.array([[0.0, 0.0, 1.0, 1.0]], np.float64)
    target = np.array([[0.25, 0.25, 0.75, 0.75]], np.float64)
    var = [0.1, 0.1, 0.2, 0.2]
    from paddle_tpu.core.registry import get_op_def

    enc = np.asarray(get_op_def("box_coder").compute(
        {"PriorBox": [prior], "TargetBox": [target]},
        {"code_type": "encode_center_size", "variance": var})["OutputBox"][0])
    np.testing.assert_allclose(
        enc[0, 0], [0.0, 0.0, np.log(0.5) / 0.2, np.log(0.5) / 0.2])
    dec = np.asarray(get_op_def("box_coder").compute(
        {"PriorBox": [prior], "TargetBox": [enc]},
        {"code_type": "decode_center_size", "variance": var})["OutputBox"][0])
    np.testing.assert_allclose(dec[0, 0], target[0], atol=1e-12)


def test_sequence_pad_2d_with_unit_pad_value():
    x = np.array([[5, 6, 7], [8, 9, 1]], np.float64)
    ln = np.array([2, 1], np.int64)
    h = OpHarness("sequence_pad",
                  {"X": x, "PadValue": np.array([0.5]), "Length": ln},
                  out_slots=("Out",))
    exp = x.copy()
    exp[0, 2:] = 0.5
    exp[1, 1:] = 0.5
    h.check_output({"Out": exp})


def test_interp_scale_attr():
    from paddle_tpu.core.registry import get_op_def

    x = RS(44).randn(1, 1, 2, 2)
    out = np.asarray(get_op_def("nearest_interp").compute(
        {"X": [x]}, {"scale": 2.0, "align_corners": False})["Out"][0])
    np.testing.assert_allclose(out, x.repeat(2, 2).repeat(2, 3))


def test_multiprocess_reader_interleaves_all_samples():
    """reference: decorator.py multiprocess_reader — one process per
    reader, all samples delivered."""
    from paddle_tpu.reader import decorator

    def make(lo, hi):
        def r():
            for i in range(lo, hi):
                yield (i, np.arange(3) + i)
        return r

    mr = decorator.multiprocess_reader([make(0, 20), make(100, 120)])
    got = sorted(s[0] for s in mr())
    assert got == list(range(0, 20)) + list(range(100, 120))

    with pytest.raises(ValueError):
        decorator.multiprocess_reader([])


def test_multiprocess_reader_ndarray_samples_and_errors():
    """Bare ndarray samples work, worker exceptions surface, and early
    exit doesn't stall (code-review findings, round 2)."""
    import time

    from paddle_tpu.reader import decorator

    def arr_reader():
        for i in range(5):
            yield np.arange(3) + i  # bare ndarray payload

    got = list(decorator.multiprocess_reader([arr_reader])())
    assert len(got) == 5

    def bad_reader():
        yield np.zeros(2)
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="worker failed"):
        list(decorator.multiprocess_reader([bad_reader])())

    def big_reader():
        for i in range(100000):
            yield np.zeros(16)

    t0 = time.perf_counter()
    it = decorator.multiprocess_reader([big_reader, big_reader],
                                       queue_size=8)()
    for _, _s in zip(range(3), it):
        pass
    it.close()  # early exit must terminate workers promptly
    assert time.perf_counter() - t0 < 5.0


def test_bilinear_tensor_product_op():
    x = RS(50).randn(3, 4)
    y = RS(51).randn(3, 5)
    w = RS(52).randn(2, 4, 5)
    b = RS(53).randn(2)
    h = OpHarness("bilinear_tensor_product",
                  {"X": x, "Y": y, "Weight": w, "Bias": b})
    exp = np.einsum("bi,kij,bj->bk", x, w, y) + b[None, :]
    h.check_output({"Out": exp})
    h.check_grad(["x_0", "y_0", "weight_0", "bias_0"])


def test_nce_layer_trains():
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        emb = layers.fc(x, 24, act="relu",
                        param_attr=fluid.ParamAttr(name="nce_h.w"))
        cost = layers.nce(emb, label, num_total_classes=50,
                          num_neg_samples=8,
                          param_attr=fluid.ParamAttr(name="nce.w"))
        loss = layers.mean(cost)
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = RS(0)
    probe = RS(1).randn(16, 50)
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(40):
            xv = rng.randn(64, 16).astype(np.float32)
            yv = np.argmax(xv @ probe, 1).astype(np.int64)[:, None]
            losses.append(float(
                exe.run(main, feed={"x": xv, "label": yv},
                        fetch_list=[loss])[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8  # NCE cost decreasing
