"""Op tests for the round-2 breadth push: vision/RoI/detection, 3-D
conv/pool, quantization, and misc math/sequence/rnn ops — each against a
numpy reference, differentiable ones through the numeric-grad harness
(reference test strategy: unittests/op_test.py)."""

import numpy as np

from op_test import OpHarness


def _run(h):
    outs = h.forward()
    return {slot: [np.asarray(o)] for slot, o in zip(h.out_slots, outs)}


def _r(shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(
        np.float32)


# --- misc math ---


def test_sign():
    x = _r((3, 4), 1)
    OpHarness("sign", {"X": x}).check_output({"Out": np.sign(x)})


def test_minus_and_grad():
    x, y = _r((3, 4), 1), _r((3, 4), 2)
    h = OpHarness("minus", {"X": x, "Y": y})
    h.check_output({"Out": x - y})
    h.check_grad(["x_0", "y_0"])


def test_l1_norm_grad():
    x = _r((4, 5), 3)
    h = OpHarness("l1_norm", {"X": x})
    h.check_output({"Out": np.abs(x).sum()})
    h.check_grad(["x_0"])


def test_squared_l2_distance():
    x, y = _r((4, 6), 1), _r((4, 6), 2)
    h = OpHarness("squared_l2_distance", {"X": x, "Y": y},
                  out_slots=("Out",))
    h.check_output({"Out": ((x - y) ** 2).sum(axis=1, keepdims=True)})
    h.check_grad(["x_0", "y_0"])


def test_modified_huber_loss():
    x = _r((8, 1), 4)
    y = (np.random.RandomState(5).rand(8, 1) > 0.5).astype(np.float32)
    t = 2 * y - 1
    z = x * t
    exp = np.where(z < -1, -4 * z, np.where(z < 1, (1 - z) ** 2, 0.0))
    OpHarness("modified_huber_loss", {"X": x, "Y": y},
              out_slots=("Out",)).check_output({"Out": exp.astype(np.float32)})


def test_cvm():
    x = np.abs(_r((4, 8), 6)) + 0.1
    out = OpHarness("cvm", {"X": x}, attrs={"use_cvm": True},
                    out_slots=("Y",))
    show = np.log(x[:, :1] + 1)
    click = np.log(x[:, 1:2] + 1) - show
    exp = np.concatenate([show, click, x[:, 2:]], axis=1)
    out.check_output({"Y": exp})


def test_fsp_grad():
    x, y = _r((2, 3, 4, 4), 1), _r((2, 5, 4, 4), 2)
    h = OpHarness("fsp", {"X": x, "Y": y})
    exp = np.einsum("ncl,nkl->nck", x.reshape(2, 3, 16),
                    y.reshape(2, 5, 16)) / 16.0
    h.check_output({"Out": exp.astype(np.float32)})
    h.check_grad(["x_0", "y_0"])


def test_fill_constant_batch_size_like():
    ref = _r((5, 3), 1)
    h = OpHarness("fill_constant_batch_size_like", {"Input": ref},
                  attrs={"shape": [2, 7], "value": 3.5})
    h.check_output({"Out": np.full((5, 7), 3.5, np.float32)})


def test_spectral_norm_normalizes():
    w = _r((6, 4), 7)
    u = _r((6,), 8)
    v = _r((4,), 9)
    h = OpHarness("spectral_norm", {"Weight": w, "U": u, "V": v},
                  attrs={"power_iters": 20})
    out = _run(h)["Out"][0]
    s = np.linalg.svd(np.asarray(out), compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)


# --- v1 aliases ---


def test_v1_shape_aliases():
    x = _r((2, 3, 4), 1)
    OpHarness("reshape", {"X": x}, attrs={"shape": [2, 12]}).check_output(
        {"Out": x.reshape(2, 12)})
    OpHarness("transpose", {"X": x}, attrs={"axis": [1, 0, 2]}).check_output(
        {"Out": x.transpose(1, 0, 2)})
    OpHarness("unsqueeze", {"X": x}, attrs={"axes": [0]}).check_output(
        {"Out": x[None]})
    OpHarness("squeeze", {"X": x[None]}, attrs={"axes": [0]}).check_output(
        {"Out": x})


# --- pooling / conv variants ---


def test_pool3d_avg():
    x = _r((1, 2, 4, 4, 4), 1)
    h = OpHarness("pool3d", {"X": x},
                  attrs={"ksize": [2, 2, 2], "strides": [2, 2, 2],
                         "pooling_type": "avg"})
    exp = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7))
    h.check_output({"Out": exp.astype(np.float32)})
    h.check_grad(["x_0"])


def test_conv3d_matches_manual():
    x = _r((1, 1, 3, 3, 3), 2)
    w = _r((2, 1, 2, 2, 2), 3)
    h = OpHarness("conv3d", {"Input": x, "Filter": w},
                  out_slots=("Output",))
    out = _run(h)["Output"][0]
    assert out.shape == (1, 2, 2, 2, 2)
    # corner value check
    manual = (x[0, 0, :2, :2, :2] * w[0, 0]).sum()
    np.testing.assert_allclose(np.asarray(out)[0, 0, 0, 0, 0], manual,
                               rtol=1e-5)
    h.check_grad(["input_0", "filter_0"], atol=5e-4)


def test_max_pool2d_with_index_and_unpool_roundtrip():
    x = _r((1, 1, 4, 4), 5)
    h = OpHarness("max_pool2d_with_index", {"X": x},
                  attrs={"ksize": [2, 2], "strides": [2, 2]},
                  out_slots=("Out", "Mask"))
    res = _run(h)
    out, mask = np.asarray(res["Out"][0]), np.asarray(res["Mask"][0])
    exp = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    np.testing.assert_allclose(out, exp, rtol=1e-6)
    h2 = OpHarness("unpool", {"X": out, "Indices": mask},
                   attrs={"unpooled_height": 4, "unpooled_width": 4})
    unp = np.asarray(_run(h2)["Out"][0])
    assert unp.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(unp.sum(), out.sum(), rtol=1e-6)


def test_spp_shape():
    x = _r((2, 3, 8, 8), 6)
    h = OpHarness("spp", {"X": x}, attrs={"pyramid_height": 2})
    out = np.asarray(_run(h)["Out"][0])
    assert out.shape == (2, 3 * (1 + 4))


def test_lrn_matches_manual():
    x = np.abs(_r((1, 5, 2, 2), 7))
    h = OpHarness("lrn", {"X": x}, attrs={"n": 3, "alpha": 0.1,
                                          "beta": 0.75, "k": 1.0},
                  out_slots=("Out",))
    sq = x ** 2
    pad = np.pad(sq, ((0, 0), (1, 1), (0, 0), (0, 0)))
    acc = pad[:, 0:5] + pad[:, 1:6] + pad[:, 2:7]
    exp = x / (1.0 + 0.1 * acc) ** 0.75
    h.check_output({"Out": exp.astype(np.float32)}, atol=1e-5)
    h.check_grad(["x_0"])


# --- RoI / detection ---


def test_roi_align_uniform_image():
    """On a constant image every aligned value equals the constant."""
    x = np.full((1, 2, 8, 8), 3.0, np.float32)
    rois = np.array([[0.0, 0.0, 7.0, 7.0], [2.0, 2.0, 6.0, 6.0]], np.float32)
    h = OpHarness("roi_align", {"X": x, "ROIs": rois},
                  attrs={"pooled_height": 2, "pooled_width": 2,
                         "spatial_scale": 1.0})
    out = np.asarray(_run(h)["Out"][0])
    assert out.shape == (2, 2, 2, 2)
    np.testing.assert_allclose(out, 3.0, rtol=1e-5)
    # rtol loosened for the test backend's reduced XLA optimization level
    # (tests/conftest.py): f32 association differences vs the numeric
    # reference reach ~0.3%
    h.check_grad(["x_0"], rtol=6e-3)


def test_roi_pool_picks_max():
    x = np.zeros((1, 1, 6, 6), np.float32)
    x[0, 0, 1, 1] = 5.0
    x[0, 0, 4, 4] = 7.0
    rois = np.array([[0.0, 0.0, 5.0, 5.0]], np.float32)
    h = OpHarness("roi_pool", {"X": x, "ROIs": rois},
                  attrs={"pooled_height": 2, "pooled_width": 2,
                         "spatial_scale": 1.0})
    out = np.asarray(_run(h)["Out"][0])
    assert out[0, 0, 0, 0] == 5.0
    assert out[0, 0, 1, 1] == 7.0


def test_box_clip():
    boxes = np.array([[[-5.0, -5.0, 20.0, 20.0]]], np.float32)
    im_info = np.array([[10.0, 12.0, 1.0]], np.float32)
    h = OpHarness("box_clip", {"Input": boxes, "ImInfo": im_info},
                  out_slots=("Output",))
    out = np.asarray(_run(h)["Output"][0])
    np.testing.assert_allclose(out[0, 0], [0.0, 0.0, 11.0, 9.0])


def test_multiclass_nms_suppresses_overlaps():
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                     np.float32)
    scores = np.array([[[0.9, 0.85, 0.6]]], np.float32)  # one class
    h = OpHarness("multiclass_nms", {"BBoxes": boxes, "Scores": scores},
                  attrs={"nms_threshold": 0.5, "keep_top_k": 3,
                         "score_threshold": 0.1, "background_label": -1})
    out = np.asarray(_run(h)["Out"][0])
    labels = out[0, :, 0]
    kept = labels >= 0
    assert kept.sum() == 2  # the 0.85 box is suppressed by the 0.9 box
    np.testing.assert_allclose(sorted(out[0, kept, 1]), [0.6, 0.9])


def test_yolo_box_shapes():
    n, an, cls, hw = 1, 2, 3, 4
    x = _r((n, an * (5 + cls), hw, hw), 8, 0.1)
    img = np.array([[128, 128]], np.int32)
    h = OpHarness("yolo_box", {"X": x, "ImgSize": img},
                  attrs={"anchors": [10, 13, 16, 30], "class_num": cls,
                         "downsample_ratio": 32},
                  out_slots=("Boxes", "Scores"))
    res = _run(h)
    assert np.asarray(res["Boxes"][0]).shape == (1, an * hw * hw, 4)
    assert np.asarray(res["Scores"][0]).shape == (1, an * hw * hw, cls)


def test_bipartite_match_greedy():
    dist = np.array([[0.9, 0.1], [0.8, 0.7], [0.2, 0.95]], np.float32)
    h = OpHarness("bipartite_match", {"DistMat": dist},
                  out_slots=("ColToRowMatchIndices", "ColToRowMatchDist"))
    res = _run(h)
    match = np.asarray(res["ColToRowMatchIndices"][0])[0]
    # per-COLUMN matched rows (reference semantics): greedy picks
    # (row 2, col 1)=0.95 first, then (row 0, col 0)=0.9
    assert match.shape == (2,)
    assert match[1] == 2 and match[0] == 0


def test_affine_grid_identity():
    theta = np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (1, 1, 1))
    h = OpHarness("affine_grid", {"Theta": theta},
                  attrs={"output_shape": [1, 1, 3, 3]},
                  out_slots=("Output",))
    out = np.asarray(_run(h)["Output"][0])
    np.testing.assert_allclose(out[0, 0, 0], [-1, -1], atol=1e-6)
    np.testing.assert_allclose(out[0, 2, 2], [1, 1], atol=1e-6)
    h.check_grad(["theta_0"])


# --- quantization ---


def test_fake_quantize_abs_max_and_ste_grad():
    x = _r((4, 4), 9)
    h = OpHarness("fake_quantize_abs_max", {"X": x},
                  attrs={"bit_length": 8}, out_slots=("Out", "OutScale"))
    res = _run(h)
    out = np.asarray(res["Out"][0])
    scale = float(np.asarray(res["OutScale"][0]))
    np.testing.assert_allclose(scale, np.abs(x).max(), rtol=1e-6)
    q = np.clip(np.round(x / scale * 127), -127, 127) * scale / 127
    np.testing.assert_allclose(out, q, rtol=1e-5, atol=1e-6)


def test_fake_channel_wise_quantize():
    x = _r((3, 8), 10)
    h = OpHarness("fake_channel_wise_quantize_abs_max", {"X": x},
                  out_slots=("Out", "OutScale"))
    res = _run(h)
    scales = np.asarray(res["OutScale"][0])
    np.testing.assert_allclose(scales, np.abs(x).max(axis=1), rtol=1e-6)


def test_quant_dequant_roundtrip():
    x = _r((4, 4), 11)
    scale = 127.0 / np.abs(x).max()
    hq = OpHarness("quantize", {"Input": x}, attrs={"Scale": float(scale)},
                   out_slots=("Output",))
    q = _run(hq)["Output"][0]
    assert q.dtype == np.int8
    hd = OpHarness("dequantize", {"Input": q}, attrs={"Scale": float(scale)},
                   out_slots=("Output",))
    dq = _run(hd)["Output"][0]
    np.testing.assert_allclose(dq, x, atol=1.0 / scale)


# --- sequence / rnn ---


def test_sequence_conv_matches_manual():
    x = _r((2, 5, 3), 12)
    w = _r((9, 4), 13)  # ctx_len 3 * d 3 -> 4
    h = OpHarness("sequence_conv", {"X": x, "Filter": w},
                  attrs={"contextLength": 3, "contextStart": -1})
    cols = []
    for off in (-1, 0, 1):
        sh = np.zeros_like(x)
        if off < 0:
            sh[:, -off:] = x[:, :off]
        elif off > 0:
            sh[:, :-off] = x[:, off:]
        else:
            sh = x
        cols.append(sh)
    im = np.concatenate(cols, axis=-1)
    h.check_output({"Out": (im @ w).astype(np.float32)}, atol=1e-5)
    h.check_grad(["x_0", "filter_0"])


def test_add_position_encoding_grad():
    x = _r((2, 6, 8), 14)
    h = OpHarness("add_position_encoding", {"X": x},
                  attrs={"alpha": 1.0, "beta": 0.5})
    out = np.asarray(_run(h)["Out"][0])
    assert out.shape == x.shape
    h.check_grad(["x_0"])


def test_conv_shift_circular():
    x = _r((2, 8), 15)
    y = _r((2, 3), 16)
    h = OpHarness("conv_shift", {"X": x, "Y": y})
    exp = np.zeros_like(x)
    for j in range(3):
        exp += np.roll(x, 1 - j, axis=1) * y[:, j:j + 1]
    h.check_output({"Out": exp.astype(np.float32)}, atol=1e-5)
    h.check_grad(["x_0", "y_0"])


def test_lstm_unit_step():
    x = _r((3, 16), 17)
    c = _r((3, 4), 18)
    h = OpHarness("lstm_unit", {"X": x, "C_prev": c},
                  out_slots=("C", "H"))
    res = _run(h)

    def sig(a):
        return 1 / (1 + np.exp(-a))

    # reference lstm_unit gate order: (i, f, o, g)
    i, f, o, g = x[:, :4], x[:, 4:8], x[:, 8:12], x[:, 12:]
    c_new = sig(f) * c + sig(i) * np.tanh(g)
    np.testing.assert_allclose(np.asarray(res["C"][0]), c_new, atol=1e-5)
    np.testing.assert_allclose(np.asarray(res["H"][0]),
                               sig(o) * np.tanh(c_new), atol=1e-5)
    h.check_grad(["x_0", "c_prev_0"])


def test_lstmp_shapes_and_grad():
    x = _r((2, 4, 16), 19, 0.3)
    w = _r((3, 16), 20, 0.3)     # p=3
    wp = _r((4, 3), 21, 0.3)     # d=4 -> p=3
    h = OpHarness("lstmp", {"Input": x, "Weight": w, "ProjWeight": wp},
                  out_slots=("Projection",))
    out = np.asarray(_run(h)["Projection"][0])
    assert out.shape == (2, 4, 3)
    h.check_grad(["input_0", "weight_0", "projweight_0"])


def _compute(op, ins, attrs=None):
    from paddle_tpu.core.registry import get_op_def

    return get_op_def(op).compute(
        {k: [np.asarray(v)] for k, v in ins.items()}, attrs or {})


def test_similarity_focus_greedy_exclusive():
    x = np.zeros((1, 2, 3, 3), np.float32)
    x[0, 0] = [[5, 1, 1], [1, 4, 1], [1, 1, 3]]
    o = _compute("similarity_focus", {"X": x}, {"axis": 1, "indexes": [0]})
    m = np.asarray(o["Out"][0])
    # greedy picks the diagonal (5, 4, 3) with row/col exclusivity and
    # broadcasts the mask over the focus axis
    np.testing.assert_allclose(m[0, 0], np.eye(3))
    np.testing.assert_allclose(m[0, 1], np.eye(3))


def test_roi_perspective_transform_identity_quad():
    img = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 0, 3, 3, 0, 3]], np.float32)
    o = _compute("roi_perspective_transform", {"X": img, "ROIs": rois},
           {"transformed_height": 4, "transformed_width": 4,
            "spatial_scale": 1.0})
    np.testing.assert_allclose(np.asarray(o["Out"][0])[0, 0], img[0, 0],
                               atol=1e-4)
