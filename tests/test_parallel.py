"""Data-parallel (GSPMD) correctness on the virtual 8-device mesh.

Analog of the reference's multi-device loss-parity tests
(reference: tests/unittests/test_parallel_executor_mnist.py via
parallel_executor_test_base.py): same program, single device vs 8-device
CompiledProgram, per-step losses must match.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _build(optimizer):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[32], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, 64, act="relu")
        logits = layers.fc(h, 8)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer().minimize(loss)
    return main, startup, loss


def _batches(n, bs=64):
    rng = np.random.RandomState(0)
    W = np.random.RandomState(7).randn(32, 8)
    out = []
    for _ in range(n):
        x = rng.randn(bs, 32).astype(np.float32)
        y = np.argmax(x @ W, 1).astype(np.int64)[:, None]
        out.append({"img": x, "label": y})
    return out


def _snapshot(prog):
    return {
        p.name: np.array(fluid.global_scope().find_var(p.name))
        for p in prog.all_parameters()
    }


def _restore(snap):
    for k, v in snap.items():
        fluid.global_scope().set(k, v)


def test_data_parallel_loss_parity_sgd():
    import jax

    assert len(jax.devices()) == 8
    main, startup, loss = _build(lambda: fluid.optimizer.SGD(0.1))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    snap = _snapshot(main)
    batches = _batches(10)

    single = [float(exe.run(main, feed=fd, fetch_list=[loss])[0]) for fd in batches]

    _restore(snap)
    compiled = fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe2 = fluid.Executor(fluid.CPUPlace())
    parallel = [
        float(exe2.run(compiled, feed=fd, fetch_list=[loss])[0]) for fd in batches
    ]

    np.testing.assert_allclose(single, parallel, atol=2e-4)
    assert parallel[-1] < parallel[0]  # actually learning


def test_data_parallel_grad_matches_single_device():
    main, startup, loss = _build(lambda: fluid.optimizer.SGD(0.1))
    w = [p for p in main.all_parameters() if p.shape == (32, 64)][0]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    snap = _snapshot(main)
    fd = _batches(1)[0]

    g1 = exe.run(main, feed=fd, fetch_list=[w.name + "@GRAD"])[0]
    _restore(snap)
    compiled = fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    g2 = fluid.Executor(fluid.CPUPlace()).run(
        compiled, feed=fd, fetch_list=[w.name + "@GRAD"]
    )[0]
    np.testing.assert_allclose(g1, g2, atol=1e-6)


def test_feed_sharding_divides_batch():
    """Feeds shard over the mesh: per-device shard count must divide batch."""
    import jax

    main, startup, loss = _build(lambda: fluid.optimizer.SGD(0.1))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    compiled = fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    fd = _batches(1, bs=16)[0]  # 16 divides 8
    out = exe.run(compiled, feed=fd, fetch_list=[loss])
    assert np.isfinite(out[0]).all()
