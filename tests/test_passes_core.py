"""CSE + constant-fold program passes (reference analogs:
framework/ir constant folding and the SSA-graph-level dedup; ours run
at Program altitude for serialized/inference programs — whole-program
XLA gets both from the compiler)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.passes import apply_pass


def _count(prog, t):
    return sum(1 for op in prog.global_block().ops if op.type == t)


def test_cse_collapses_duplicate_chains():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        # two identical pure chains (the per-layer rebuilt-bias shape)
        a = layers.scale(layers.relu(x), scale=2.0)
        b = layers.scale(layers.relu(x), scale=2.0)
        c = layers.scale(layers.relu(x), scale=3.0)  # differs: kept
        out = layers.elementwise_add(layers.elementwise_add(a, b), c)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fd = {"x": np.random.RandomState(0).randn(4, 8).astype(np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed=fd, fetch_list=[out])

        assert _count(main, "relu") == 3 and _count(main, "scale") == 3
        apply_pass("cse", main, fetch_targets=[out])
        # all three relu(x) collapse to one; the 2.0-scales collapse,
        # the 3.0-scale stays distinct
        assert _count(main, "relu") == 1
        assert _count(main, "scale") == 2
        (got,) = exe.run(main, feed=fd, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref))


def test_cse_never_touches_stateful_or_random():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        d1 = layers.dropout(x, 0.5)
        d2 = layers.dropout(x, 0.5)  # SAME attrs but independent masks
        out = layers.elementwise_add(d1, d2)
    n = _count(main, "dropout")
    apply_pass("cse", main, fetch_targets=[out])
    assert _count(main, "dropout") == n  # not deduplicated


def test_constant_fold_evaluates_pure_subgraph():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        c1 = layers.fill_constant([4], "float32", 2.0)
        c2 = layers.scale(c1, scale=3.0)           # foldable -> 6.0
        c3 = layers.elementwise_add(c1, c2)        # foldable -> 8.0
        out = layers.elementwise_add(x, c3)        # depends on feed: kept

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fd = {"x": np.ones((2, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed=fd, fetch_list=[out])
        apply_pass("constant_fold", main, fetch_targets=[out])
        types = [op.type for op in main.global_block().ops]
        assert "scale" not in types          # folded to a literal
        # the constant add folded to a literal; only the feed-dependent
        # add survives
        assert types.count("elementwise_add") == 1
        folded = [op for op in main.global_block().ops
                  if op.type == "assign_value"
                  and op.outputs["Out"][0] == c3.name]
        assert folded and folded[0].attrs["values"] == [8.0] * 4
        (got,) = exe.run(main, feed=fd, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref))


def test_cse_respects_var_reassignment():
    """A name rewritten between two textually identical ops (assign
    output=) denotes DIFFERENT values — CSE must not alias them."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        a = layers.scale(x, scale=2.0)
        layers.assign(layers.scale(x, scale=0.0), output=x)
        b = layers.scale(x, scale=2.0)   # reads the ZEROED x
        out = layers.elementwise_add(a, b)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fd = {"x": np.ones((2, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed=fd, fetch_list=[out])
        apply_pass("cse", main, fetch_targets=[out])
        (got,) = exe.run(main, feed=fd, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref))
    assert float(np.asarray(ref)[0, 0]) == 2.0  # a=2, b=0


def test_constant_fold_respects_var_reassignment():
    """A constant-seeded var mutated at runtime (assign output=) is not
    a constant; folding its readers would bake the stale value."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        c = layers.fill_constant([4], "float32", 1.0)
        layers.assign(x, output=c)       # c now holds the feed
        y = layers.scale(c, scale=3.0)
        out = layers.elementwise_add(x, y)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fd = {"x": np.full((2, 4), 2.0, np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed=fd, fetch_list=[out])
        apply_pass("constant_fold", main, fetch_targets=[out])
        types = [op.type for op in main.global_block().ops]
        assert "scale" in types          # NOT folded: c is reassigned
        (got,) = exe.run(main, feed=fd, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref))
    assert float(np.asarray(ref)[0, 0]) == 8.0  # 2 + 3*2
