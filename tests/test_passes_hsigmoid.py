"""Pass framework + large-vocab classifier ops (hsigmoid, sample_logits)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, passes


def test_pass_registry_and_manager():
    assert "conv_bn_fuse" in passes.registered_passes()
    assert "amp" in passes.registered_passes()
    main = fluid.Program()
    out = passes.PassManager(["amp"]).apply(main)
    assert out._amp is True
    with pytest.raises(KeyError, match="unknown pass"):
        passes.apply_pass("nope", main)


def test_conv_bn_fuse_pass_matches_transpiler(tmp_path):
    """The registered pass produces the same program rewrite the
    transpiler API does (same op-type counts)."""

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[3, 8, 8], dtype="float32")
        x = layers.conv2d(img, 4, 3, padding=1, bias_attr=False)
        x = layers.batch_norm(x, is_test=True)
        _ = layers.mean(x)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    n_bn_before = sum(1 for op in main.global_block().ops
                      if op.type == "batch_norm")
    passes.apply_pass("conv_bn_fuse", main, scope=scope)
    n_bn_after = sum(1 for op in main.global_block().ops
                     if op.type == "batch_norm")
    assert n_bn_before == 1 and n_bn_after == 0


def test_hsigmoid_trains():
    """log2(C) path-node classifier learns a separable task."""
    vocab = 32
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        y = layers.data("y", shape=[1], dtype="int64")
        cost = layers.hsigmoid(x, y, vocab)
        loss = layers.mean(cost)
        fluid.optimizer.Adam(5e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    protos = r.normal(0, 2, (vocab, 16)).astype(np.float32)
    losses = []
    for step in range(120):
        lbl = r.randint(0, vocab, (64, 1)).astype(np.int64)
        xv = protos[lbl[:, 0]] + r.normal(0, 0.1, (64, 16)).astype(
            np.float32)
        losses.append(float(exe.run(main, feed={"x": xv, "y": lbl},
                                    fetch_list=[loss])[0]))
    # path length ~5 nodes; random init ~5*log(2)=3.47 -> must drop hard
    assert np.mean(losses[-10:]) < 0.65, losses[::24]


def test_hsigmoid_matches_manual_power_of_two():
    """C=8: every label has a 3-node path; compare against the explicit
    per-node logistic losses."""

    vocab, d, b = 8, 4, 5
    r = np.random.RandomState(1)
    x = r.normal(0, 1, (b, d)).astype(np.float32)
    w = r.normal(0, 1, (vocab - 1, d)).astype(np.float32)
    bias = r.normal(0, 1, (vocab - 1,)).astype(np.float32)
    lbl = np.arange(b).astype(np.int64)[:, None]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[d], dtype="float32")
        yv = layers.data("y", shape=[1], dtype="int64")
        cost = layers.hsigmoid(
            xv, yv, vocab,
            param_attr=fluid.ParamAttr(
                name="hs.w",
                initializer=fluid.initializer.NumpyArrayInitializer(w)),
            bias_attr=fluid.ParamAttr(
                name="hs.b",
                initializer=fluid.initializer.NumpyArrayInitializer(bias)))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got = exe.run(main, feed={"x": x, "y": lbl}, fetch_list=[cost])[0]

    def softplus(v):
        return np.log1p(np.exp(v))

    exp = np.zeros((b, 1), np.float32)
    for i in range(b):
        code = int(lbl[i, 0]) + vocab          # 4-bit code, 3 path nodes
        for j in range(3):
            shift = 2 - j
            node = (code >> (shift + 1)) - 1
            bit = (code >> shift) & 1
            pre = float(x[i] @ w[node] + bias[node])
            exp[i, 0] += softplus(pre) - bit * pre
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_sample_logits_shapes_and_hits():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        logits = layers.data("logits", shape=[64], dtype="float32")
        lbl = layers.data("y", shape=[1], dtype="int64")
        s_logits, s_label = layers.sample_logits(logits, lbl, 16)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(s_logits, s_label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    out = exe.run(
        main,
        feed={"logits": r.normal(0, 1, (4, 64)).astype(np.float32),
              "y": r.randint(0, 64, (4, 1)).astype(np.int64)},
        fetch_list=[s_logits, loss])
    assert out[0].shape == (4, 17)  # 1 true + 16 sampled
    assert np.isfinite(out[1]).all()


def test_hsigmoid_large_vocab_boundary():
    """C=2^20 with boundary labels: integer bit-length must be exact
    (f32 log2 over-counts near 2^k and corrupted the tree path)."""
    from paddle_tpu.core.registry import get_op_def

    op = get_op_def("hierarchical_sigmoid")
    C, d = 1 << 20, 4
    r = np.random.RandomState(0)
    x = r.normal(0, 1, (2, d)).astype(np.float32)
    w = r.normal(0, 1, (C - 1, d)).astype(np.float32)
    lbl = np.array([[C - 1], [0]], np.int64)
    out = op.compute({"X": [x], "W": [w], "Label": [lbl], "Bias": [None]},
                     {"num_classes": C})
    got = np.asarray(out["Out"][0])

    def softplus(v):
        return np.log1p(np.exp(v))

    for i, lab in enumerate([C - 1, 0]):
        code = lab + C
        length = code.bit_length()
        exp = 0.0
        for j in range(length - 1):
            shift = length - 2 - j
            node = (code >> (shift + 1)) - 1
            bit = (code >> shift) & 1
            pre = float(x[i] @ w[node])
            exp += softplus(pre) - bit * pre
        np.testing.assert_allclose(got[i, 0], exp, rtol=1e-4)
