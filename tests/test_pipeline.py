"""GPipe pipeline-parallelism tests on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.parallel import pipeline as pp

RS = np.random.RandomState


def _mesh(n, name="pipe"):
    return Mesh(np.asarray(jax.devices()[:n]), (name,))


def _stage_fn(params, x):
    w, b = params["w"], params["b"]
    return x + jnp.tanh(x @ w + b)


def _params(n_stages, d, seed=0):
    r = RS(seed)
    return {
        "w": jnp.asarray(r.normal(0, 0.3, (n_stages, d, d)), jnp.float32),
        "b": jnp.asarray(r.normal(0, 0.1, (n_stages, d)), jnp.float32),
    }


@pytest.mark.parametrize("n_micro", [4, 8, 16])
def test_gpipe_matches_sequential(n_micro):
    n_stages, d, batch = 4, 8, 16
    mesh = _mesh(n_stages)
    params = _params(n_stages, d)
    x = jnp.asarray(RS(1).normal(0, 1, (batch, d)), jnp.float32)

    ref = pp.sequential_reference(_stage_fn, params, x)
    got = pp.gpipe(_stage_fn, params, x, mesh, n_micro=n_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_eight_stages():
    n_stages, d, batch = 8, 4, 8
    mesh = _mesh(n_stages)
    params = _params(n_stages, d, seed=2)
    x = jnp.asarray(RS(3).normal(0, 1, (batch, d)), jnp.float32)
    ref = pp.sequential_reference(_stage_fn, params, x)
    got = pp.gpipe(_stage_fn, params, x, mesh, n_micro=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.full
def test_gpipe_gradients_match_sequential():
    """jax.grad flows through ppermute/scan: pipeline grads == sequential
    grads, so the Program-IR autodiff can ride the pipeline unchanged."""
    n_stages, d, batch = 4, 6, 8
    mesh = _mesh(n_stages)
    params = _params(n_stages, d, seed=4)
    x = jnp.asarray(RS(5).normal(0, 1, (batch, d)), jnp.float32)

    def loss_pipe(p):
        return jnp.mean(pp.gpipe(_stage_fn, p, x, mesh, n_micro=4) ** 2)

    def loss_seq(p):
        return jnp.mean(pp.sequential_reference(_stage_fn, p, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
            rtol=1e-4, atol=1e-5, err_msg=k)


def test_gpipe_transformer_layer_stack():
    """Pipelined homogeneous transformer blocks (the PP use case):
    pre-LN self-attention + FFN with stacked per-stage weights."""
    n_stages, b, t, d, h = 4, 4, 8, 16, 2
    mesh = _mesh(n_stages)
    r = RS(6)

    params = {
        "qkv": jnp.asarray(r.normal(0, 0.1, (n_stages, d, 3 * d)),
                           jnp.float32),
        "out": jnp.asarray(r.normal(0, 0.1, (n_stages, d, d)), jnp.float32),
        "ff1": jnp.asarray(r.normal(0, 0.1, (n_stages, d, 4 * d)),
                           jnp.float32),
        "ff2": jnp.asarray(r.normal(0, 0.1, (n_stages, 4 * d, d)),
                           jnp.float32),
    }

    def block(p, x):
        def ln(z):
            m = z.mean(-1, keepdims=True)
            v = ((z - m) ** 2).mean(-1, keepdims=True)
            return (z - m) * jax.lax.rsqrt(v + 1e-5)

        qkv = ln(x) @ p["qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):
            return z.reshape(z.shape[:-1] + (h, d // h)).swapaxes(1, 2)

        s = jnp.einsum("bhqd,bhkd->bhqk", heads(q), heads(k))
        a = jax.nn.softmax(s / np.float32(np.sqrt(d // h)), axis=-1)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", a, heads(v))
        ctx = ctx.swapaxes(1, 2).reshape(x.shape)
        x = x + ctx @ p["out"]
        return x + jax.nn.gelu(ln(x) @ p["ff1"]) @ p["ff2"]

    x = jnp.asarray(r.normal(0, 1, (b, t, d)), jnp.float32)
    ref = pp.sequential_reference(block, params, x)
    got = pp.gpipe(block, params, x, mesh, n_micro=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gpipe_rejects_bad_microbatch():
    mesh = _mesh(4)
    params = _params(4, 4)
    x = jnp.zeros((10, 4), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        pp.gpipe(_stage_fn, params, x, mesh, n_micro=4)
