"""Pipeline parallelism through the Program IR.

STATUS.md round-2 gap: "GPipe is a parallel-layer API, not yet reachable
from the Program IR". The transformer's scan-over-layers build marks its
layer scans ``pipelinable``; under a strategy declaring ``pipe_axis`` the
scan op runs the GPipe microbatch schedule (one layer per rank, stacked
weights sharded P(pipe)) instead of lax.scan — same math, so the
acceptance test is per-step loss parity through the Executor."""

import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as fluid
from paddle_tpu.models import transformer as T
from paddle_tpu.parallel.strategy import (
    DistributedStrategy,
    pipeline_rules,
)


def _mesh(n, name):
    import jax

    return Mesh(np.asarray(jax.devices()[:n]), (name,))


def _build(dropout=0.0):
    cfg = T.TransformerConfig(
        src_vocab_size=400, trg_vocab_size=400, d_model=32, d_inner=64,
        n_head=2, n_layer=4, max_length=20, dropout=dropout,
    )
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = T.build_scan(cfg)
        fluid.optimizer.SGD(0.1).minimize(model["loss"])
    return cfg, main, startup, model


def _snapshot(prog):
    return {
        p.name: np.array(fluid.global_scope().find_var(p.name))
        for p in prog.all_parameters()
    }


def _restore(snap):
    for k, v in snap.items():
        fluid.global_scope().set(k, v)


@pytest.mark.full
def test_pipeline_scan_loss_parity():
    """4 layers over a 4-rank pipe axis vs plain lax.scan: same losses.
    (dropout=0: the GPipe microbatch mask stream differs from the
    full-batch lax.scan stream by construction.)"""
    cfg, main, startup, model = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    snap = _snapshot(main)
    batches = [T.make_batch(cfg, 8, 16, 16, seed=s) for s in range(4)]

    plain = [
        float(exe.run(main, feed=fd, fetch_list=[model["loss"]])[0])
        for fd in batches
    ]

    _restore(snap)
    mesh = _mesh(4, "pipe")
    strategy = DistributedStrategy(
        mesh, data_axis=None, rules=pipeline_rules("pipe"),
        pipe_axis="pipe", pipe_micro=4,
    )
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    exe2 = fluid.Executor(fluid.CPUPlace())
    piped = [
        float(exe2.run(compiled, feed=fd, fetch_list=[model["loss"]])[0])
        for fd in batches
    ]
    np.testing.assert_allclose(plain, piped, rtol=2e-4, atol=2e-4)


def test_pipeline_stage_mismatch_raises():
    """n_layer=4 on a 2-rank pipe axis must raise, not silently skip."""
    cfg, main, startup, model = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mesh = _mesh(2, "pipe")
    strategy = DistributedStrategy(
        mesh, data_axis=None, rules=pipeline_rules("pipe"),
        pipe_axis="pipe",
    )
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    exe2 = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(Exception, match="pipe axis|must match"):
        exe2.run(compiled, feed=T.make_batch(cfg, 8, 16, 16, seed=0),
                 fetch_list=[model["loss"]])
