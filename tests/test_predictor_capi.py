"""Native predictor C API (csrc/predictor_capi.cc): a pure-C binary
loads an exported zoo model through the stable ABI and checks outputs
against the Python Predictor (reference: inference/api/api.cc +
paddle_fluid.map — the reference's native serving surface)."""

import os
import shutil
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io, layers

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
CSRC = os.path.join(REPO, "csrc")
BIN = os.path.join(CSRC, "predictor_capi_test")


def test_c_api_serves_exported_model(tmp_path):
    if not (shutil.which("make") and shutil.which("g++")
            and shutil.which("cc") and shutil.which("python3-config")):
        pytest.skip("native toolchain unavailable")
    r = subprocess.run(["make", "-C", CSRC, "predictor_capi_test"],
                       capture_output=True, text=True)
    assert r.returncode == 0 and os.path.exists(BIN), r.stderr[-800:]

    # export a small MLP from the zoo path
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", shape=[12], dtype="float32")
        h = layers.fc(x, 24, act="relu")
        logits = layers.fc(h, 5)
        prob = layers.softmax(logits)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = str(tmp_path / "model")
        io.save_inference_model(model_dir, ["img"], [prob], exe,
                                main_program=main)

        # expected outputs from the Python Predictor
        from paddle_tpu.inference import Config, create_predictor

        batch = np.random.RandomState(0).randn(4, 12).astype(np.float32)
        pred = create_predictor(Config(model_dir))
        (expected,) = pred.run({"img": batch})
    expected = np.asarray(expected, np.float32)

    input_bin = str(tmp_path / "input.bin")
    expected_bin = str(tmp_path / "expected.bin")
    batch.tofile(input_bin)
    expected.tofile(expected_bin)

    env = {**os.environ, "PT_REPO": REPO, "PT_CAPI_PLATFORM": "cpu"}
    out = subprocess.run(
        [BIN, model_dir, input_bin, "2", "4", "12", "img", expected_bin],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, (out.stdout + "\n" + out.stderr)[-1200:]
    assert "max_err" in out.stdout
