"""Input-pipeline telemetry (PR 4) + the reader error-propagation
satellites: buffered()'s swallowed producer exception, xmap_readers()'s
hanging consumer on a raising mapper (ordered AND unordered), queue
depth/wait instruments, and the feed-build -> boundedness wiring."""

import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers, monitor
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.reader import buffered, xmap_readers
from paddle_tpu.reader.pipeline import DeviceLoader


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    flags.set_flags({"telemetry": False})
    yield
    monitor.reset()
    flags.set_flags({"telemetry": False})


def _consume(gen_fn, timeout=10.0):
    """Drain a reader on a worker thread with a deadline: propagation
    must be BOUNDED — a hang is the regression these tests pin down."""
    out = {"items": [], "exc": None}

    def run():
        try:
            for x in gen_fn():
                out["items"].append(x)
        except BaseException as e:
            out["exc"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "reader hung instead of propagating"
    return out


class _Boom(Exception):
    pass


# --------------------------------------------------------------------------
# buffered(): producer exceptions reach the consumer (satellite)
# --------------------------------------------------------------------------

def test_buffered_propagates_producer_exception():
    def bad_reader():
        yield 1
        yield 2
        raise _Boom("producer died")

    out = _consume(buffered(bad_reader, size=4))
    assert out["items"] == [1, 2]  # items before the failure still flow
    assert isinstance(out["exc"], _Boom)


def test_buffered_happy_path_unchanged():
    out = _consume(buffered(lambda: iter(range(20)), size=3))
    assert out["items"] == list(range(20))
    assert out["exc"] is None


def test_buffered_error_propagates_with_full_queue():
    """The failure mode behind the bug: a producer that dies while the
    consumer is slow must still surface, not truncate the epoch."""
    def bad_reader():
        yield from range(8)
        raise _Boom("late death")

    out = _consume(buffered(bad_reader, size=2))
    assert out["items"] == list(range(8))
    assert isinstance(out["exc"], _Boom)


# --------------------------------------------------------------------------
# xmap_readers(): raising mappers propagate in both modes (satellite)
# --------------------------------------------------------------------------

def _mapper(x):
    if x == 5:
        raise _Boom(f"mapper choked on {x}")
    return x * 10


@pytest.mark.parametrize("order", [False, True])
def test_xmap_raising_mapper_propagates(order):
    reader = xmap_readers(_mapper, lambda: iter(range(10)),
                          process_num=2, buffer_size=4, order=order)
    out = _consume(reader)
    assert isinstance(out["exc"], _Boom)
    # unordered mode may deliver some mapped samples first; none of
    # them can be the poisoned one
    assert 50 not in out["items"]


@pytest.mark.parametrize("order", [False, True])
def test_xmap_happy_path(order):
    reader = xmap_readers(lambda x: x * 2, lambda: iter(range(16)),
                          process_num=4, buffer_size=4, order=order)
    out = _consume(reader)
    assert out["exc"] is None
    expected = [x * 2 for x in range(16)]
    assert (out["items"] == expected if order
            else sorted(out["items"]) == expected)


def test_xmap_source_reader_error_propagates():
    def bad_source():
        yield 1
        raise _Boom("source died")

    reader = xmap_readers(lambda x: x, bad_source,
                          process_num=2, buffer_size=4)
    out = _consume(reader)
    assert isinstance(out["exc"], _Boom)


# --------------------------------------------------------------------------
# queue depth + wait instruments
# --------------------------------------------------------------------------

def test_buffered_feeds_queue_instruments():
    monitor.enable()
    out = _consume(buffered(lambda: iter(range(10)), size=4))
    assert out["items"] == list(range(10))
    h = monitor.histogram("pt_reader_wait_seconds")
    assert h.count(labels={"site": "buffered", "role": "consumer"}) == 11
    assert h.count(labels={"site": "buffered", "role": "producer"}) == 10
    # depth gauge has a cell for the site (last observed depth)
    g = monitor.gauge("pt_reader_queue_depth")
    assert ("site", "buffered") in [
        kv for key in g._cells for kv in key]


def test_device_loader_consumer_wait_counts_as_input_wait():
    monitor.enable()
    loader = DeviceLoader(
        lambda: iter([{"x": np.ones((2, 4), np.float32)}] * 3),
        feed_names=["x"], depth=2)
    batches = list(loader)
    assert len(batches) == 3
    h = monitor.histogram("pt_reader_wait_seconds")
    waits = h.count(labels={"site": "device_loader", "role": "consumer"})
    assert waits == 4  # 3 batches + the END marker
    # consumer waits accumulated toward the verdict: a step recorded now
    # sees a nonzero input score
    monitor.record_step_phases(0.0, 0.0, 0.0, 0.0)
    assert monitor.boundedness()["shares"]["input"] == pytest.approx(1.0)


def test_data_feeder_build_time_observed():
    monitor.enable()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
    feeder = DataFeeder([x])
    batch = feeder.feed([(np.ones(4, np.float32),)] * 8)
    assert batch["x"].shape == (8, 4)
    assert monitor.histogram("pt_feed_build_seconds").count() == 1
    # disabled: no observation, identical output
    flags.set_flags({"telemetry": False})
    batch2 = feeder.feed([(np.ones(4, np.float32),)] * 8)
    np.testing.assert_array_equal(batch["x"], batch2["x"])
    assert monitor.histogram("pt_feed_build_seconds").count() == 1


def test_reader_instruments_silent_when_disabled():
    assert not monitor.enabled()
    out = _consume(buffered(lambda: iter(range(5)), size=2))
    assert out["items"] == list(range(5))
    assert monitor.histogram("pt_reader_wait_seconds")._cells == {}
    assert monitor.gauge("pt_reader_queue_depth")._cells == {}
