"""Request-scoped tracing & SLO plane (serving_trace.py): per-phase
latency decomposition on every terminal request, deadline attribution
on expired/rejected_early outcomes, censored-TTFT survivorship-bias
metering, SLO met/missed/burn accounting, per-request Chrome-trace
tracks that survive a supervised engine restart (one request, ONE
trace), the /requests view, and the telemetry-off zero-allocation
contract for the new hooks."""

import tracemalloc
import types

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import faults, flags, monitor, serving, serving_trace
from paddle_tpu.models import transformer as T

BOS, EOS = 0, 1

_RESET_FLAGS = {"telemetry": False, "trace_dir": "",
                "trace_every_n_steps": 1, "serve_slo_ttft_ms": 0.0,
                "serve_slo_token_ms": 0.0, "serve_recent_requests": 256,
                "serve_admission_control": True}


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    flags.set_flags(dict(_RESET_FLAGS))
    yield
    monitor.stop_server()
    monitor.reset()
    flags.set_flags(dict(_RESET_FLAGS))


def tiny_cfg(n_layer=1):
    return T.TransformerConfig(
        src_vocab_size=37, trg_vocab_size=41, max_length=64,
        d_model=16, d_inner=32, n_head=2, n_layer=n_layer,
        dropout=0.0, label_smooth_eps=0.0,
    )


@pytest.fixture(scope="module")
def weights():
    cfg = tiny_cfg()
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return cfg, scope


def _srcs(k, seed=0, lens=(5, 3, 7, 4, 6, 2, 8, 5)):
    r = np.random.RandomState(seed)
    return [r.randint(2, 37, (lens[i % len(lens)],)).astype(np.int64)
            for i in range(k)]


def _engine(cfg, scope, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 10)
    return serving.ServingEngine(cfg, scope, src_len=8, bos_id=BOS,
                                 end_id=EOS, **kw)


# --------------------------------------------------------------------------
# per-phase latency decomposition
# --------------------------------------------------------------------------

def test_phase_decomposition_recorded_per_outcome(weights):
    """Every terminal request lands on the recently-terminated ring
    with measured queue-wait/prefill/decode/fetch milliseconds, TTFT,
    and (absent SLO targets) a null SLO scorecard."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    eng = _engine(cfg, scope)
    reqs = [eng.submit(s, max_new_tokens=5) for s in _srcs(3, seed=7)]
    eng.run_until_idle()
    eng.close()

    view = serving_trace.requests_view()
    assert view["inflight"] == []
    by_id = {r["trace_id"]: r for r in view["recent"]}
    assert set(by_id) == {q.trace_id for q in reqs}
    for q in reqs:
        rec = by_id[q.trace_id]
        assert rec["v"] == serving_trace.REQUEST_RECORD_SCHEMA_VERSION
        assert rec["outcome"] in ("completed", "length")
        assert set(rec["phases_ms"]) == set(serving_trace.PHASES)
        assert rec["phases_ms"]["prefill"] > 0.0
        assert rec["phases_ms"]["decode"] > 0.0
        assert rec["ttft_ms"] is not None and rec["ttft_ms"] > 0.0
        assert rec["wall_ms"] > 0.0
        assert rec["tokens"] == len(q.tokens)
        # no targets configured: scored as None, no attribution
        assert rec["slo"] == {"ttft": None, "token": None}
        assert rec["deadline_attribution"] is None
        assert rec["censored"] is False


@pytest.mark.slow
def test_phase_sums_cover_wall_time(weights):
    """The decomposition is honest: per-request phase milliseconds sum
    to the request's wall time within 20% — queue wait absorbs
    everything before admission and decode/fetch are measured per
    dispatch, so nothing material is double-counted or dropped."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    eng = _engine(cfg, scope)
    reqs = [eng.submit(s, max_new_tokens=6) for s in _srcs(4, seed=9)]
    eng.run_until_idle()
    eng.close()
    by_id = {r["trace_id"]: r
             for r in serving_trace.requests_view()["recent"]}
    for q in reqs:
        rec = by_id[q.trace_id]
        total = sum(rec["phases_ms"].values())
        assert total == pytest.approx(rec["wall_ms"],
                                      rel=0.20, abs=2.0), (
            f"{q.trace_id}: phases {rec['phases_ms']} sum {total} vs "
            f"wall {rec['wall_ms']}")


# --------------------------------------------------------------------------
# deadline attribution + SLO burn
# --------------------------------------------------------------------------

def test_deadline_attribution_under_overload(weights):
    """The overload half of the acceptance drill: a rejected_early
    refusal and an expired-in-queue request BOTH carry deadline
    attribution naming queue wait as the phase that ate the budget,
    and the deadline burn counter matches the outcome counts."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    eng = _engine(cfg, scope, slots=1, max_len=32, queue_depth=8)
    eng._token_ewma_s = 0.05  # white-box primed latency estimator
    a = eng.submit(_srcs(1, seed=51)[0], max_new_tokens=10)
    with pytest.raises(serving.DeadlineUnmeetable) as ei:
        eng.submit(_srcs(1, seed=52)[0], deadline_ms=20)
    rej = ei.value.request
    assert rej.outcome == "rejected_early"
    attr = rej.deadline_attr
    assert attr is not None and attr["phase"] == "queue_wait"
    assert attr["budget_ms"] == pytest.approx(20.0)
    assert set(attr["phases_ms"]) == set(serving_trace.PHASES)

    # expired in queue: admission control off lets a dead-on-arrival
    # deadline queue up; the admit-time check expires it before prefill
    flags.set_flags({"serve_admission_control": False})
    exp = eng.submit(_srcs(1, seed=53)[0], deadline_ms=0.001)
    flags.set_flags({"serve_admission_control": True})
    eng.run_until_idle()
    eng.close()
    assert a.outcome in ("completed", "length")
    assert exp.outcome == "expired"
    assert exp.deadline_attr["phase"] == "queue_wait"
    assert exp.deadline_attr["phase_ms"] > 0.0

    burn = monitor.counter("pt_slo_burn_total")
    assert burn.value(labels={"slo": "deadline",
                              "outcome": "rejected_early"}) == 1
    assert burn.value(labels={"slo": "deadline",
                              "outcome": "expired"}) == 1
    # the ring records carry the attribution too
    recs = {r["trace_id"]: r
            for r in serving_trace.requests_view()["recent"]}
    assert recs[rej.trace_id]["deadline_attribution"][
        "phase"] == "queue_wait"
    assert recs[exp.trace_id]["deadline_attribution"][
        "phase"] == "queue_wait"


def test_deadline_attribution_names_dominant_phase():
    """Attribution picks the dominant MEASURED phase, not always queue
    wait: a request whose decode ate the budget says so."""
    req = types.SimpleNamespace(queue_wait_s=0.01, prefill_s=0.02,
                                decode_s=0.5, fetch_s=0.01,
                                submit_ts=0.0, deadline_ts=0.3)
    attr = serving_trace._attribute_deadline(req, now=0.6)
    assert attr["phase"] == "decode"
    assert attr["phase_ms"] == pytest.approx(500.0)
    assert attr["budget_ms"] == pytest.approx(300.0)


# --------------------------------------------------------------------------
# censored TTFT (survivorship bias) + SLO scoring
# --------------------------------------------------------------------------

def test_censored_ttft_counts_against_slo_target(weights):
    """A request that expires before its first token never observes
    pt_serve_ttft_seconds — without the censored meter, p99 TTFT would
    IMPROVE as overload worsens. It must be metered censored and count
    against the TTFT target."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True, "serve_slo_ttft_ms": 10_000.0,
                     "serve_admission_control": False})
    eng = _engine(cfg, scope)
    exp = eng.submit(_srcs(1, seed=61)[0], deadline_ms=0.001)
    ok = eng.submit(_srcs(1, seed=62)[0], max_new_tokens=3)
    eng.run_until_idle()
    eng.close()
    assert exp.outcome == "expired" and exp.ttft_s is None
    assert exp.censored is True
    assert ok.outcome in ("completed", "length")

    assert monitor.counter("pt_serve_ttft_censored_total").value(
        labels={"outcome": "expired"}) == 1
    slo = serving_trace.slo_summary()
    assert slo["targets_ms"]["ttft"] == pytest.approx(10_000.0)
    assert slo["ttft"]["censored"] == 1
    assert slo["ttft"]["met"] == 1  # the survivor scored normally
    assert monitor.counter("pt_slo_burn_total").value(
        labels={"slo": "ttft", "outcome": "expired"}) == 1
    # refusals are NOT censored: never entered service
    assert "rejected_early" not in serving_trace.CENSORED_OUTCOMES


def test_slo_met_and_missed_scoring(weights):
    """Generous targets score met/met with zero burn; impossibly tight
    targets score missed/missed and burn both SLOs."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True, "serve_slo_ttft_ms": 60_000.0,
                     "serve_slo_token_ms": 60_000.0})
    eng = _engine(cfg, scope)
    ok = eng.submit(_srcs(1, seed=71)[0], max_new_tokens=3)
    eng.run_until_idle()
    assert ok.outcome in ("completed", "length")
    slo = serving_trace.slo_summary()
    assert slo["ttft"] == {"met": 1, "missed": 0, "censored": 0}
    assert slo["token"] == {"met": 1, "missed": 0}
    assert slo["burn"] == {}

    flags.set_flags({"serve_slo_ttft_ms": 0.0001,
                     "serve_slo_token_ms": 0.0001})
    bad = eng.submit(_srcs(1, seed=72)[0], max_new_tokens=3)
    eng.run_until_idle()
    eng.close()
    assert bad.outcome in ("completed", "length")
    slo = serving_trace.slo_summary()
    assert slo["ttft"]["missed"] == 1 and slo["token"]["missed"] == 1
    burn = monitor.counter("pt_slo_burn_total")
    assert burn.value(labels={"slo": "ttft", "outcome": bad.outcome}) == 1
    assert burn.value(labels={"slo": "token",
                              "outcome": bad.outcome}) == 1
    # the ring scorecards disagree across the flag flip
    recs = {r["trace_id"]: r
            for r in serving_trace.requests_view()["recent"]}
    assert recs[ok.trace_id]["slo"] == {"ttft": "met", "token": "met"}
    assert recs[bad.trace_id]["slo"] == {"ttft": "missed",
                                         "token": "missed"}


# --------------------------------------------------------------------------
# per-request trace tracks: one request, ONE trace across a restart
# --------------------------------------------------------------------------

def test_request_track_timeline_events(weights, tmp_path):
    """A request's life lands on one dynamic timeline track: queue +
    prefill + sampled decode/fetch spans and the terminal instant all
    share a tid >= REQUEST_TRACK_BASE, labeled by thread_name
    metadata."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    eng = _engine(cfg, scope)
    req = eng.submit(_srcs(1, seed=81)[0], max_new_tokens=4)
    eng.run_until_idle()
    eng.close()
    assert req.outcome in ("completed", "length")

    evs = [e for e in monitor.trace_events()
           if e.get("args", {}).get("req") == req.trace_id]
    names = {e["name"] for e in evs}
    assert {"submit", "queue", "prefill", "decode",
            "fetch", f"outcome:{req.outcome}"} <= names
    tids = {e["tid"] for e in evs}
    assert tids == {req.trace_tid}
    assert req.trace_tid >= monitor.REQUEST_TRACK_BASE
    by_name = {e["name"]: e for e in evs}
    assert by_name["queue"]["ph"] == "X"
    assert by_name["prefill"]["ph"] == "X"
    assert by_name[f"outcome:{req.outcome}"]["ph"] == "i"
    # decode spans are annotated with the emitted token + its logit
    dec = by_name["decode"]["args"]
    assert dec["token"] == req.tokens[-1] or "token" in dec
    assert isinstance(dec["logit"], float)
    # the track is labeled in the exportable snapshot
    metas = [e for e in monitor.trace_snapshot()["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert any(e["tid"] == req.trace_tid
               and e["args"]["name"] == f"req {req.trace_id}"
               for e in metas)


def test_supervised_restart_replays_as_one_trace(weights, tmp_path):
    """The restart half of the acceptance drill: an engine-killing
    decode fault triggers a supervised warm restart; the replayed
    request's tokens are byte-identical, its events before AND after
    the restart share ONE track, and the restart itself is annotated
    as a span on that track."""
    cfg, scope = weights
    srcs = _srcs(2, seed=41)
    clean_eng = _engine(cfg, scope)
    clean_reqs = [clean_eng.submit(s) for s in srcs]
    clean_eng.run_until_idle()
    clean = [list(q.tokens) for q in clean_reqs]
    clean_eng.close()

    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    sup = serving.EngineSupervisor(
        cfg, scope, slots=2, src_len=8, max_len=10, bos_id=BOS,
        end_id=EOS, poll_s=0.005, wedge_timeout_ms=60_000,
        max_restarts=2)
    try:
        warm = sup.submit(_srcs(1, seed=42)[0], max_new_tokens=2)
        assert warm.result(timeout=60) is not None
        faults.arm("serve.decode:raise@2")
        try:
            reqs = [sup.submit(s) for s in srcs]
            streams = [r.result(timeout=120) for r in reqs]
        finally:
            faults.disarm()
    finally:
        sup.close(drain_timeout_s=5.0)
    assert streams == clean
    replayed = [r for r in reqs if r.replays >= 1]
    assert replayed, "no request was replayed"
    for r in replayed:
        evs = [e for e in monitor.trace_events()
               if e.get("args", {}).get("req") == r.trace_id]
        tids = {e["tid"] for e in evs}
        assert tids == {r.trace_tid}, (
            f"{r.trace_id} smeared over tracks {tids}")
        names = [e["name"] for e in evs]
        assert names.count("submit") == 1  # ONE trace, not re-submit
        restarts = [e for e in evs if e["name"] == "restart"]
        assert restarts and all(e["ph"] == "X" for e in restarts)
        assert restarts[0]["args"]["replay"] == r.replays
        assert f"outcome:{r.outcome}" in names


def test_eviction_lands_on_victims_track(weights, tmp_path):
    """Containment epilogue: a slot-hinted decode fault's eviction and
    scrub instants land on the VICTIM's own track."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    eng = _engine(cfg, scope, max_len=32)
    reqs = [eng.submit(s, max_new_tokens=8) for s in _srcs(2, seed=91)]
    faults.arm("serve.decode:raise(poisoned slot=1)@3")
    try:
        eng.run_until_idle()
    finally:
        faults.disarm()
    eng.close()
    victims = [r for r in reqs if r.outcome == "evicted"]
    assert victims, "fault did not evict"
    v = victims[0]
    evs = [e for e in monitor.trace_events()
           if e.get("args", {}).get("req") == v.trace_id]
    names = {e["name"] for e in evs}
    assert {"evicted", "scrub", "outcome:evicted"} <= names
    assert {e["tid"] for e in evs} == {v.trace_tid}
    ev = next(e for e in evs if e["name"] == "evicted")
    assert ev["args"]["cause"] == "fault"


# --------------------------------------------------------------------------
# /requests view + ring bounds
# --------------------------------------------------------------------------

def test_requests_view_inflight_states_and_ring(weights):
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    eng = _engine(cfg, scope)
    reqs = [eng.submit(s, max_new_tokens=5) for s in _srcs(4, seed=11)]
    view = serving_trace.requests_view()
    assert len(view["inflight"]) == 4
    assert all(r["state"] == "queued" and r["slot"] is None
               for r in view["inflight"])
    eng.step()  # admissions fill the 2 slots
    view = serving_trace.requests_view()
    rows = {r["trace_id"]: r for r in view["inflight"]}
    states = [r["state"] for r in rows.values()]
    assert states.count("decoding") == 2 and states.count("queued") == 2
    for r in rows.values():
        if r["state"] == "decoding":
            assert isinstance(r["slot"], int)
        assert r["age_ms"] >= 0.0
        assert set(r["phases_ms"]) == set(serving_trace.PHASES)
    eng.run_until_idle()
    eng.close()
    view = serving_trace.requests_view()
    assert view["inflight"] == []
    assert len(view["recent"]) == 4
    assert view["recent_cap"] == 256
    assert {q.trace_id for q in reqs} == {
        r["trace_id"] for r in view["recent"]}


def test_recent_ring_bounded_by_flag(weights):
    cfg, scope = weights
    flags.set_flags({"telemetry": True, "serve_recent_requests": 3})
    eng = _engine(cfg, scope)
    reqs = [eng.submit(s, max_new_tokens=2) for s in _srcs(5, seed=13)]
    eng.run_until_idle()
    eng.close()
    assert all(q.done for q in reqs)
    view = serving_trace.requests_view()
    assert view["recent_cap"] == 3
    assert len(view["recent"]) == 3  # oldest evicted, newest kept


# --------------------------------------------------------------------------
# telemetry-off: the zero-allocation contract for the new hooks
# --------------------------------------------------------------------------

def test_disabled_serving_allocates_nothing_in_request_plane(weights):
    """With telemetry off, the request-plane hooks wired through
    submit/admit/decode/finish must add zero allocations attributable
    to serving_trace.py — the serving hot loop stays permanently
    instrumented for free."""
    cfg, scope = weights
    assert not monitor.enabled() and not monitor.trace_active()
    eng = _engine(cfg, scope)
    warm = eng.submit(_srcs(1, seed=21)[0], max_new_tokens=2)
    eng.run_until_idle()  # warm compiles + lazy state
    assert warm.done
    n_reqs = 10
    srcs = _srcs(n_reqs, seed=22)
    tracemalloc.start()
    base = tracemalloc.take_snapshot()
    reqs = [eng.submit(s, max_new_tokens=3) for s in srcs]
    eng.run_until_idle()
    snap = tracemalloc.take_snapshot()
    tracemalloc.stop()
    eng.close()
    assert all(q.done for q in reqs)
    stats = snap.compare_to(base, "filename")
    grew = sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith("serving_trace.py")
               and s.size_diff > 0)
    assert grew < n_reqs * 16, (
        f"disabled serving run allocated {grew}B in serving_trace.py "
        f"over {n_reqs} requests")
    # and the ring stayed empty: nothing was recorded
    assert serving_trace.requests_view()["recent"] == []
