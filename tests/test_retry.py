"""Unified retry/backoff policy tests (paddle_tpu/retry.py) and its
fleet threading: decorrelated-jitter backoff under a deadline budget,
``pt_retry_total`` accounting, and the coord KV-get timeout contract
(retried with backoff, raising at the deadline)."""

import random
import socket
import time
import tracemalloc

import pytest

import paddle_tpu as fluid  # noqa: F401
from paddle_tpu import flags, monitor, retry


@pytest.fixture(autouse=True)
def _clean():
    monitor.reset()
    yield
    flags.set_flags({"telemetry": False,
                     "retry_base_delay_ms": 100,
                     "retry_max_delay_ms": 5000,
                     "retry_max_attempts": 0})


@pytest.fixture
def sleeps(monkeypatch):
    out = []
    monkeypatch.setattr(retry, "_sleep", out.append)
    return out


def test_first_try_success_no_sleep_no_metric(sleeps):
    monitor.enable()
    assert retry.call(lambda: 7, site="t") == 7
    assert sleeps == []
    snap = monitor.snapshot()["pt_retry_total"]
    assert snap["values"] == [] or not any(
        v for v in snap["values"])  # no cells at all


def test_retries_then_success_with_backoff(sleeps):
    monitor.enable()
    state = {"n": 0}

    def fn():
        state["n"] += 1
        if state["n"] < 4:
            raise OSError("flaky")
        return "ok"

    p = retry.RetryPolicy(base_delay=0.1, max_delay=2.0)
    out = retry.call(fn, site="flaky", policy=p, rng=random.Random(0))
    assert out == "ok" and state["n"] == 4
    assert len(sleeps) == 3
    # decorrelated jitter: first sleep is the base, then uniform in
    # [base, 3*prev] capped — always within [base, max_delay]
    assert sleeps[0] == pytest.approx(0.1)
    for s in sleeps:
        assert 0.1 <= s <= 2.0
    c = monitor.counter("pt_retry_total")
    assert c.value(labels={"site": "flaky", "outcome": "retry"}) == 3
    assert c.value(labels={"site": "flaky", "outcome": "success"}) == 1


def test_seeded_rng_makes_backoff_deterministic(sleeps):
    def run():
        del sleeps[:]
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            if calls["n"] < 6:
                raise OSError()
            return 1

        retry.call(fn, site="d", rng=random.Random(42),
                   policy=retry.RetryPolicy(base_delay=0.01, max_delay=1.0))
        return list(sleeps)

    assert run() == run()


def test_deadline_budget_raises_the_original_error():
    monitor.enable()

    def fn():
        raise TimeoutError("not yet")

    p = retry.RetryPolicy(base_delay=0.02, max_delay=0.05)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="not yet"):
        retry.call(fn, site="dl", policy=p, deadline_s=0.2)
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0  # gave up at the budget, not much later
    c = monitor.counter("pt_retry_total")
    assert c.value(labels={"site": "dl", "outcome": "exhausted"}) == 1
    assert c.value(labels={"site": "dl", "outcome": "retry"}) >= 1


def test_max_attempts_cap(sleeps):
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise OSError()

    p = retry.RetryPolicy(base_delay=0.001, max_attempts=3)
    with pytest.raises(OSError):
        retry.call(fn, site="cap", policy=p)
    assert calls["n"] == 3


def test_non_retryable_exception_propagates_immediately(sleeps):
    def fn():
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry.call(fn, site="nr")
    assert sleeps == []


def test_default_policy_tracks_flags():
    flags.set_flags({"retry_base_delay_ms": 7, "retry_max_delay_ms": 70,
                     "retry_max_attempts": 2})
    p = retry.default_policy()
    assert p.base_delay == pytest.approx(0.007)
    assert p.max_delay == pytest.approx(0.070)
    assert p.max_attempts == 2


def test_sleeps_never_overshoot_the_deadline(monkeypatch):
    slept = []

    def fake_sleep(s):
        slept.append(s)

    monkeypatch.setattr(retry, "_sleep", fake_sleep)

    def fn():
        raise OSError()

    p = retry.RetryPolicy(base_delay=10.0, max_delay=100.0)
    with pytest.raises(OSError):
        retry.call(fn, site="clamp", policy=p, deadline_s=0.05)
    assert all(s <= 0.05 + 1e-6 for s in slept)


# --------------------------------------------------------------------------
# fleet threading: kv-get timeout retried with backoff, raising at the
# deadline (ISSUE 5 acceptance)
# --------------------------------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_fleet_kv_get_retries_then_raises_at_deadline():
    from paddle_tpu import native
    from paddle_tpu.incubate.fleet import UserDefinedRoleMaker
    from paddle_tpu.incubate.fleet.fleet_base import Fleet

    if not native.available():
        pytest.skip("native library not built")
    monitor.enable()
    flags.set_flags({"retry_base_delay_ms": 20, "retry_max_delay_ms": 100})
    port = _free_port()
    f = Fleet()
    f._role = UserDefinedRoleMaker(current_id=0, worker_num=1)
    f._server = native.CoordServer(port)
    f._client = native.CoordClient("127.0.0.1", port)
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            f.get("never/published", timeout_ms=300)
        elapsed = time.monotonic() - t0
        assert 0.2 <= elapsed < 3.0  # spent ~the budget, then gave up
        c = monitor.counter("pt_retry_total")
        assert c.value(labels={"site": "fleet.kv_get",
                               "outcome": "retry"}) >= 1
        assert c.value(labels={"site": "fleet.kv_get",
                               "outcome": "exhausted"}) == 1
        # a published key still comes straight back
        f.put("k", b"v")
        assert f.get("k", timeout_ms=1000) == b"v"
        # timeout_ms=0 is a real non-blocking present-check, not a
        # synthesized timeout (code-review finding, round 5)
        assert f.get("k", timeout_ms=0) == b"v"
        with pytest.raises(TimeoutError):
            f.get("still/missing", timeout_ms=0)
    finally:
        f.stop_worker()


def test_fleet_connect_uses_retry_policy(monkeypatch):
    """_connect_retry keeps polling until the server exists, under the
    policy (no fixed 0.1 s spin)."""
    from paddle_tpu import native
    from paddle_tpu.incubate.fleet import fleet_base

    if not native.available():
        pytest.skip("native library not built")
    monitor.enable()
    flags.set_flags({"retry_base_delay_ms": 10, "retry_max_delay_ms": 50})
    port = _free_port()
    server = {}

    real_sleep = time.sleep

    def sleep_then_start(s):
        real_sleep(s)
        if "s" not in server:  # bring the server up after the 1st backoff
            server["s"] = native.CoordServer(port)

    monkeypatch.setattr(retry, "_sleep", sleep_then_start)
    try:
        client = fleet_base._connect_retry("127.0.0.1", port,
                                           timeout_ms=5000)
        client.close()
        c = monitor.counter("pt_retry_total")
        assert c.value(labels={"site": "fleet.connect",
                               "outcome": "success"}) == 1
    finally:
        if "s" in server:
            server["s"].stop()


# --------------------------------------------------------------------------
# zero-overhead contract: a first-try success allocates nothing in
# retry.py (the coordination hot loop — heartbeats — rides this path)
# --------------------------------------------------------------------------

def test_success_path_allocates_nothing_in_retry():
    assert not monitor.enabled()

    def fn():
        return None

    for _ in range(3):
        retry.call(fn, site="hot")  # warm the cached default policy
    n = 2000
    tracemalloc.start()
    base = tracemalloc.take_snapshot()
    for _ in range(n):
        retry.call(fn, site="hot")
    snap = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grew = sum(
        st.size_diff for st in snap.compare_to(base, "filename")
        if st.traceback[0].filename.endswith("retry.py")
        and st.size_diff > 0)
    assert grew < n, f"retry.call success path allocated {grew}B"
