"""Ring attention vs dense reference on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()), ("sp",))


def _qkv(seed, b=2, h=2, t=32, dh=8):
    r = np.random.RandomState(seed)
    mk = lambda: r.randn(b, h, t, dh).astype(np.float32)
    return mk(), mk(), mk()


def test_ring_attention_matches_dense(mesh):
    q, k, v = _qkv(0)
    out = ring_attention(q, k, v, mesh, "sp", causal=False)
    ref = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_ring_attention_causal(mesh):
    q, k, v = _qkv(1)
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


@pytest.mark.full
def test_ring_attention_grad_matches(mesh):
    q, k, v = _qkv(2, t=16)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh, "sp", causal=True).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=True).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-3)


def test_sharded_embedding_lookup(mesh):
    from paddle_tpu.parallel.embedding import sharded_embedding_lookup

    r = np.random.RandomState(0)
    table = r.randn(64, 16).astype(np.float32)  # 8 rows per device
    ids = r.randint(0, 64, (4, 7)).astype(np.int32)
    out = sharded_embedding_lookup(table, ids, mesh, "sp")
    np.testing.assert_allclose(np.asarray(out), table[ids], atol=1e-6)


@pytest.mark.full
def test_ring_attention_blocked_scale(mesh):
    """Parity at a shape where the per-device chunk (t/8 = 1024) exceeds
    the production flash kernel's 512-wide k-block, so the ring path is
    truly blocked (VERDICT r4 item 2): the global [t, t] score matrix
    (268 MB f32/head here) never materializes on any rank, while the
    dense reference builds it whole."""
    r = np.random.RandomState(7)
    b, h, t, dh = 1, 2, 8192, 64
    mk = lambda: (r.randn(b, h, t, dh) * 0.2).astype(np.float32)
    q, k, v = mk(), mk(), mk()
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-5, rtol=1e-3)


def test_ring_attention_dropout(mesh):
    """Attention dropout through the ring (round 5: sequence-parallel
    TRAINING no longer falls back to the dense path): deterministic for
    a fixed seed, different across seeds, E[out] tracks the no-dropout
    output, and gradients flow."""
    r = np.random.RandomState(4)
    b, h, t, dh = 1, 2, 64, 16
    q = jnp.asarray(r.randn(b, h, t, dh) * 0.3, jnp.float32)
    k = jnp.asarray(r.randn(b, h, t, dh) * 0.3, jnp.float32)
    v = jnp.asarray(r.randn(b, h, t, dh) * 0.3, jnp.float32)

    o1 = ring_attention(q, k, v, mesh, "sp", p_drop=0.3, seed=7)
    o1b = ring_attention(q, k, v, mesh, "sp", p_drop=0.3, seed=7)
    o2 = ring_attention(q, k, v, mesh, "sp", p_drop=0.3, seed=8)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o1b))
    assert np.abs(np.asarray(o1) - np.asarray(o2)).max() > 1e-6

    # inverted dropout preserves the mean over seeds
    outs = [np.asarray(ring_attention(q, k, v, mesh, "sp",
                                      p_drop=0.3, seed=s))
            for s in range(24)]
    ref = np.asarray(ring_attention(q, k, v, mesh, "sp"))
    err = np.abs(np.mean(outs, axis=0) - ref).mean() / np.abs(ref).mean()
    assert err < 0.25, err

    g = jax.grad(lambda v: ring_attention(
        q, k, v, mesh, "sp", p_drop=0.3, seed=7).sum())(v)
    assert np.isfinite(np.asarray(g)).all()


def test_ring_attention_causal_unequal_lengths(mesh):
    """Causal with tq != tk (both ring-sharded) masks by GLOBAL
    positions — rank-level diagonal routing would misalign."""
    r = np.random.RandomState(9)
    b, h, dh, tq, tk = 1, 2, 8, 64, 32
    q = jnp.asarray(r.randn(b, h, tq, dh) * 0.3, jnp.float32)
    k = jnp.asarray(r.randn(b, h, tk, dh) * 0.3, jnp.float32)
    v = jnp.asarray(r.randn(b, h, tk, dh) * 0.3, jnp.float32)
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(dh)
    mask = (jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :])
    s = jnp.where(mask[None, None], s, -1e9)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=1e-4)
