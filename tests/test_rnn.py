"""Fused lstm/gru ops + dynamic_lstm/dynamic_gru layers.

Parity model: numpy step-by-step recurrence (the reference validates
lstm_op against a python reference the same way,
reference: tests/unittests/test_lstm_op.py).
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_lstm(x_proj, w, bias, h0, c0, lengths=None):
    """x_proj: [B,T,4H] pre-projected gates; returns hidden [B,T,H]."""
    b, t, four_h = x_proj.shape
    h_dim = four_h // 4
    h, c = h0.copy(), c0.copy()
    out = np.zeros((b, t, h_dim), np.float32)
    for i in range(t):
        g = x_proj[:, i] + bias + h @ w
        ii, ff, cc, oo = np.split(g, 4, axis=-1)
        ii, ff, oo = _sigmoid(ii), _sigmoid(ff), _sigmoid(oo)
        c_new = ff * c + ii * np.tanh(cc)
        h_new = oo * np.tanh(c_new)
        if lengths is not None:
            m = (i < lengths)[:, None].astype(np.float32)
            c = m * c_new + (1 - m) * c
            out[:, i] = (m * h_new)[:, :]
            h = m * h_new + (1 - m) * h
        else:
            h, c = h_new, c_new
            out[:, i] = h
    return out


def test_lstm_matches_numpy():
    b, t, h_dim = 3, 7, 4
    rs = np.random.RandomState(0)
    xp = rs.randn(b, t, 4 * h_dim).astype(np.float32) * 0.5
    wv = rs.randn(h_dim, 4 * h_dim).astype(np.float32) * 0.3
    bv = rs.randn(4 * h_dim).astype(np.float32) * 0.1

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(b, t, 4 * h_dim), dtype="float32"
        )
        hidden, cell = layers.dynamic_lstm(x, size=4 * h_dim, name="l0")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    from paddle_tpu.executor import global_scope

    params = [p.name for p in main.global_block().all_parameters()]
    wname = [p for p in params if ".w" in p][0]
    bname = [p for p in params if ".b" in p][0]
    global_scope().set(wname, wv)
    global_scope().set(bname, bv)
    (hv,) = exe.run(main, feed={"x": xp}, fetch_list=[hidden])
    expect = _np_lstm(
        xp, wv, bv, np.zeros((b, h_dim), np.float32),
        np.zeros((b, h_dim), np.float32),
    )
    np.testing.assert_allclose(hv, expect, rtol=1e-5, atol=1e-5)


def test_lstm_length_masking():
    b, t, h_dim = 2, 6, 3
    rs = np.random.RandomState(1)
    xp = rs.randn(b, t, 4 * h_dim).astype(np.float32)
    lengths = np.array([4, 6], np.int32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(b, t, 4 * h_dim), dtype="float32"
        )
        ln = main.global_block().create_var(
            name="ln", shape=(b,), dtype="int32"
        )
        hidden, _ = layers.dynamic_lstm(
            x, size=4 * h_dim, length=ln, bias_attr=False, name="l1"
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (hv,) = exe.run(
        main, feed={"x": xp, "ln": lengths}, fetch_list=[hidden]
    )
    # Padded steps emit zeros.
    assert np.all(hv[0, 4:] == 0)
    assert np.any(hv[1, 4:] != 0)


def test_gru_shapes_and_grad():
    b, t, h_dim = 2, 5, 4
    rs = np.random.RandomState(2)
    xp = rs.randn(b, t, 3 * h_dim).astype(np.float32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(b, t, 3 * h_dim), dtype="float32",
            stop_gradient=False,
        )
        hidden = layers.dynamic_gru(x, size=h_dim, name="g0")
        loss = layers.reduce_sum(hidden)
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    hv, gx = exe.run(
        main, feed={"x": xp}, fetch_list=[hidden, "x@GRAD"]
    )
    assert hv.shape == (b, t, h_dim)
    assert gx.shape == xp.shape
    assert np.abs(gx).sum() > 0
    wname = [p.name for p in main.global_block().all_parameters()
             if ".w" in p.name][0]
    assert main.global_block().has_var(wname + "@GRAD")


def test_lstm_language_model_trains():
    """Char-level LSTM LM: embed -> fc(4H) -> lstm -> fc(V); loss drops.

    This is the `stacked_dynamic_lstm` benchmark family's core path
    (reference: benchmark/fluid/models/stacked_dynamic_lstm.py).
    """
    b, t, v, h_dim = 8, 12, 30, 16
    rs = np.random.RandomState(3)
    tokens = rs.randint(0, v, size=(b, t + 1)).astype(np.int64)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = main.global_block().create_var(
            name="x", shape=(b, t), dtype="int64"
        )
        y = main.global_block().create_var(
            name="y", shape=(b, t), dtype="int64"
        )
        emb = layers.embedding(x, size=[v, h_dim])
        proj = layers.fc(emb, size=4 * h_dim, num_flatten_dims=2,
                         bias_attr=False)
        hidden, _ = layers.dynamic_lstm(proj, size=4 * h_dim)
        logits = layers.fc(hidden, size=v, num_flatten_dims=2)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(
                logits, layers.unsqueeze(y, [2])
            )
        )
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for _ in range(40):
        (lv,) = exe.run(
            main,
            feed={"x": tokens[:, :-1], "y": tokens[:, 1:]},
            fetch_list=[loss],
        )
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
