"""Device-time roofline attribution plane (roofline.py): xplane wire
parsing, HLO -> framework op mapping, roofline verdicts, measured MFU,
the executor sampling hooks and their documented degrades."""

import json
import os
import tempfile
import tracemalloc
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers, monitor, profiler, roofline
from paddle_tpu import debugger


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    _defaults = {
        "telemetry": False, "step_log_path": "", "compile_report_dir": "",
        "metrics_port": 0, "step_phases": True, "step_phases_every_n": 16,
        "device_profile_every_n_steps": 0, "device_profile_top_k": 10,
        "device_profile_xplane": False, "device_peak_flops": 0.0,
        "device_peak_bytes_per_sec": 0.0,
    }
    flags.set_flags(_defaults)
    yield
    monitor.stop_server()
    monitor.reset()
    flags.set_flags(_defaults)


# --------------------------------------------------------------------------
# xplane wire-format synthesis (test-side encoder for the parser)
# --------------------------------------------------------------------------

def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        out += bytes([b | (0x80 if v else 0)])
        if not v:
            return out


def _vfield(fnum: int, v: int) -> bytes:
    return _varint(fnum << 3) + _varint(v)


def _lfield(fnum: int, payload: bytes) -> bytes:
    return _varint((fnum << 3) | 2) + _varint(len(payload)) + payload


def make_xspace(planes) -> bytes:
    """Encode an XSpace: ``planes`` = [(plane_name, lines)] where
    ``lines`` is either [(op, dur_ps, count), ...] (one 'XLA Ops'
    line) or {line_name: [(op, dur_ps, count), ...]} (the multi-line
    TPU plane shape); one metadata entry per distinct op per plane."""
    out = b""
    for plane_name, lines in planes:
        if not isinstance(lines, dict):
            lines = {"XLA Ops": lines}
        meta = b""
        line_bufs = b""
        mid = 0
        for line_name, events in lines.items():
            evs = b""
            for name, dur_ps, count in events:
                mid += 1
                em = _vfield(1, mid) + _lfield(2, name.encode())
                meta += _lfield(4, _vfield(1, mid) + _lfield(2, em))
                for _ in range(count):
                    evs += _lfield(4, _vfield(1, mid)
                                   + _vfield(3, dur_ps))
            line_bufs += _lfield(
                3, _lfield(2, line_name.encode()) + evs)
        out += _lfield(
            1, _lfield(2, plane_name.encode()) + meta + line_bufs)
    return out


def _write_capture(tmp_path, planes, name="host.xplane.pb"):
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True, exist_ok=True)
    (d / name).write_bytes(make_xspace(planes))
    return str(tmp_path)


PS = int(1e12)  # picoseconds per second


# --------------------------------------------------------------------------
# parsing
# --------------------------------------------------------------------------

def test_parse_xplane_roundtrip_aggregates_device_planes(tmp_path):
    path = _write_capture(tmp_path, [
        ("/host:CPU", [("$python host.py", 5 * PS, 3)]),  # ignored
        ("/device:TPU:0", [("fusion.1", PS // 2, 2),
                           ("dot.7", PS // 4, 1)]),
        ("/device:TPU:1", [("dot.7", PS // 4, 1)]),
    ])
    ops = roofline.parse_xplane(path)
    assert ops is not None
    assert ops["fusion.1"] == {"seconds": pytest.approx(1.0),
                               "count": 2}
    # summed across device planes; the host plane contributed nothing
    assert ops["dot.7"] == {"seconds": pytest.approx(0.5), "count": 2}
    assert set(ops) == {"fusion.1", "dot.7"}


def test_multi_device_capture_device_seconds_is_max_plane(tmp_path):
    """Concurrent device planes overlap in wall time: the profile's
    device_seconds is the MAX per-plane total (not the 8x-inflated
    sum that would deflate measured MFU), while per-op seconds and
    shares aggregate work across every plane."""
    flags.set_flags({"device_peak_flops": 1e12,
                     "device_peak_bytes_per_sec": 1e10})
    path = _write_capture(tmp_path, [
        ("/device:TPU:0", [("dot.1", PS, 1)]),          # 1.0 s
        ("/device:TPU:1", [("dot.1", PS // 2, 1),       # 1.0 s total
                           ("all-reduce-start.2", PS // 2, 1)]),
    ])
    prof = roofline.profile_from_xplane(
        path, fluid.Program(),
        compile_report=_report(8e11, 8e8), record=False)
    assert prof["device_seconds"] == pytest.approx(1.0)  # NOT 2.0
    # measured MFU against the wall interval: 8e11 / 1.0 / 1e12
    assert prof["measured_mfu"] == pytest.approx(0.8)
    # per-op work still aggregates across planes, shares sum to 1
    by_name = {o["name"]: o for o in prof["top_ops"]}
    assert by_name["dot.1"]["seconds"] == pytest.approx(1.5)
    assert by_name["dot.1"]["share"] == pytest.approx(0.75)
    assert sum(o["share"] for o in prof["top_ops"]) == pytest.approx(1.0)
    # async collective pairs land in the collective group
    assert prof["groups"]["collective"]["seconds"] == pytest.approx(0.5)


def test_parse_xplane_multi_line_tpu_plane_counts_ops_line_only(
        tmp_path):
    """A real TPU device plane carries 'XLA Modules' / 'XLA Ops' /
    'Steps' lines covering the SAME wall interval — aggregation must
    use only the op-level line, not sum every granularity."""
    path = _write_capture(tmp_path, [
        ("/device:TPU:0", {
            "XLA Modules": [("jit_step_fn", 2 * PS, 1)],
            "XLA Ops": [("dot.7", PS, 1), ("copy.2", PS, 1)],
            "Steps": [("step 0", 2 * PS, 1)],
        }),
    ])
    ops = roofline.parse_xplane(path)
    assert set(ops) == {"dot.7", "copy.2"}
    total = sum(c["seconds"] for c in ops.values())
    assert total == pytest.approx(2.0)  # NOT 6.0 (triple-counted)
    # a plane with no op-level line (GPU stream rows) still aggregates
    # its non-excluded lines
    path2 = _write_capture(tmp_path / "gpu", [
        ("/device:GPU:0", {
            "Stream #14(Compute)": [("kernel_a", PS, 2)],
            "XLA Modules": [("jit_step_fn", 2 * PS, 1)],
        }),
    ])
    ops2 = roofline.parse_xplane(path2)
    assert set(ops2) == {"kernel_a"}
    assert ops2["kernel_a"]["count"] == 2


def test_parse_xplane_empty_dir_degrades_with_one_warning(tmp_path):
    with pytest.warns(RuntimeWarning, match="no .xplane.pb") as rec:
        assert roofline.parse_xplane(str(tmp_path)) is None
    assert len(rec) == 1


def test_parse_xplane_corrupt_file_degrades_with_one_warning(tmp_path):
    _write_capture(tmp_path, [("/device:TPU:0", [("dot.1", PS, 1)])])
    # truncate mid-message: the wire reader must degrade, not crash
    f = next(p for p in (tmp_path / "plugins" / "profile"
                         / "run1").iterdir())
    f.write_bytes(f.read_bytes()[:-5])
    with pytest.warns(RuntimeWarning, match="parse") as rec:
        assert roofline.parse_xplane(str(tmp_path)) is None
    assert len(rec) == 1


def test_parse_xplane_host_only_capture_degrades_with_one_warning(
        tmp_path):
    """The no-TPU container case: a real capture exists but has only
    host planes — unavailable, one warning."""
    path = _write_capture(tmp_path, [
        ("/host:CPU", [("$python host.py", PS, 1)])])
    with pytest.warns(RuntimeWarning, match="no /device") as rec:
        assert roofline.parse_xplane(path) is None
    assert len(rec) == 1


def test_parse_xplane_warn_false_is_silent(tmp_path):
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert roofline.parse_xplane(str(tmp_path), warn=False) is None


def test_profiler_xplane_capture_on_cpu_degrades_to_estimate():
    """profiler.profiler(with_xplane=True) on the CPU container: the
    capture itself succeeds (jax's profiler runs everywhere) but holds
    no /device:* plane, so the profile degrades to source="estimate"
    with one warning — the documented no-TPU degrade, end to end."""
    import jax
    import jax.numpy as jnp

    prog = fluid.Program()
    with tempfile.TemporaryDirectory() as d:
        with profiler.profiler(profile_path=os.path.join(d, "prof"),
                               with_xplane=True):
            jnp.ones((64, 64)).sum().block_until_ready()
        cap_dir = profiler.last_xplane_dir()
        assert cap_dir == os.path.join(d, "prof") + "_xplane"
        with pytest.warns(RuntimeWarning) as rec:
            prof = roofline.profile_from_xplane(
                cap_dir, prog, device_seconds=0.5, record=False)
        assert len(rec) == 1
    assert prof["source"] == "estimate"
    assert prof["device_seconds"] == 0.5
    roofline.validate_device_profile(prof)
    del jax


# --------------------------------------------------------------------------
# classification + framework mapping
# --------------------------------------------------------------------------

def test_classify_hlo():
    assert roofline.classify_hlo("%dot.5") == "matmul"
    assert roofline.classify_hlo("convolution.12") == "matmul"
    assert roofline.classify_hlo("fusion.130") == "fusion"
    assert roofline.classify_hlo("add.3") == "elementwise"
    assert roofline.classify_hlo("reduce.9") == "reduction"
    assert roofline.classify_hlo("copy.2") == "data_movement"
    assert roofline.classify_hlo("all-reduce.1") == "collective"
    assert roofline.classify_hlo("infeed") == "overhead"
    assert roofline.classify_hlo("frobnicate.77") == "other"
    # async pairs (modern XLA's default collective lowering) fall back
    # to the root opcode's group...
    assert roofline.classify_hlo("all-reduce-start.3") == "collective"
    assert roofline.classify_hlo("all-reduce-done.3") == "collective"
    assert roofline.classify_hlo("collective-permute-start.1") == (
        "collective")
    assert roofline.classify_hlo("all-gather-done.8") == "collective"
    # ...unless registered explicitly (copy-start/done are the async
    # HBM<->host transfers, overhead by design)
    assert roofline.classify_hlo("copy-start.2") == "overhead"
    assert roofline.classify_hlo("copy-done.2") == "overhead"


def test_map_to_framework_ops_uses_program_histogram():
    hist = {"mul": 2, "elementwise_add": 2, "relu": 1, "mean": 1}
    assert roofline.map_to_framework_ops("dot.4", hist) == ["mul"]
    assert roofline.map_to_framework_ops("add.1", hist) == [
        "elementwise_add", "relu"]
    # no candidate of the group in the program -> empty shortlist
    assert roofline.map_to_framework_ops("all-reduce.2", hist) == []
    assert roofline.map_to_framework_ops("dot.4", None) == []


# --------------------------------------------------------------------------
# profile schema + verdicts
# --------------------------------------------------------------------------

def _report(flops, bytes_accessed, hist=None, window_steps=None):
    rep = {"flops": flops, "bytes_accessed": bytes_accessed,
           "op_histogram": hist or {"mul": 1}}
    if window_steps is not None:
        rep["window_steps"] = window_steps
    return rep


def test_profile_schema_roundtrip_and_validation():
    prog = fluid.Program()
    prof = roofline.build_device_profile(
        prog, source="estimate", device_seconds=0.1, steps=2,
        compile_report=_report(1e9, 1e7), backend="cpu")
    roofline.validate_device_profile(prof)
    # JSON round-trip survives validation (the /profile + digest path)
    roofline.validate_device_profile(json.loads(json.dumps(prof)))
    bad = dict(prof)
    bad["source"] = "guess"
    with pytest.raises(ValueError, match="source"):
        roofline.validate_device_profile(bad)
    bad = dict(prof)
    bad["verdict"] = "gpu_bound"
    with pytest.raises(ValueError, match="verdict"):
        roofline.validate_device_profile(bad)
    bad = dict(prof)
    bad["surprise"] = 1
    with pytest.raises(ValueError, match="unknown"):
        roofline.validate_device_profile(bad)
    bad = dict(prof)
    del bad["measured_mfu"]
    with pytest.raises(ValueError, match="measured_mfu"):
        roofline.validate_device_profile(bad)


def test_roofline_verdicts_from_synthetic_timings():
    """Fixed peaks (ridge = 100 FLOP/B): intensity and achieved rate
    pick the verdict."""
    flags.set_flags({"device_peak_flops": 1e12,
                     "device_peak_bytes_per_sec": 1e10})
    prog = fluid.Program()

    def verdict(flops, ba, secs):
        p = roofline.build_device_profile(
            prog, source="estimate", device_seconds=secs, steps=1,
            compile_report=_report(flops, ba), backend="cpu")
        roofline.validate_device_profile(p)
        return p

    # intensity 1000 >= ridge 100, achieved 0.8e12 of permitted 1e12
    p = verdict(8e11, 8e8, 1.0)
    assert p["verdict"] == "compute_bound"
    assert p["measured_mfu"] == pytest.approx(0.8)
    assert p["intensity"] == pytest.approx(1000.0)
    assert p["ridge_intensity"] == pytest.approx(100.0)
    # intensity 10 < ridge: memory roof (permitted 1e11; achieved 0.8e11)
    p = verdict(8e10, 8e9, 1.0)
    assert p["verdict"] == "memory_bound"
    # same intensity but 10x slower: under OVERHEAD_FRACTION of the roof
    p = verdict(8e10, 8e9, 10.0)
    assert p["verdict"] == "overhead"
    # no cost numbers at all -> unknown, null mfu
    p = roofline.build_device_profile(
        prog, source="estimate", device_seconds=1.0, steps=1,
        backend="cpu")
    assert p["verdict"] == "unknown" and p["measured_mfu"] is None


def test_profile_from_xplane_top_ops_and_measured_mfu(tmp_path):
    flags.set_flags({"device_peak_flops": 1e12,
                     "device_peak_bytes_per_sec": 1e10,
                     "device_profile_top_k": 2})
    path = _write_capture(tmp_path, [
        ("/device:TPU:0", [("dot.1", PS // 2, 1),      # 0.5 s
                           ("fusion.2", PS // 4, 2),   # 0.5 s
                           ("copy.3", PS // 10, 1)]),  # 0.1 s
    ])
    prog = fluid.Program()
    prof = roofline.profile_from_xplane(
        path, prog, steps=1,
        compile_report=_report(5.5e11, 1e9, hist={"mul": 1}))
    assert prof["source"] == "xplane"
    assert prof["device_seconds"] == pytest.approx(1.1)
    # measured MFU from the PARSED device seconds: 5.5e11/1.1/1e12 = 0.5
    assert prof["measured_mfu"] == pytest.approx(0.5)
    # top-K = 2 trims the copy; ordered by device seconds
    assert [o["name"] for o in prof["top_ops"]] == ["dot.1", "fusion.2"]
    assert prof["top_ops"][0]["share"] == pytest.approx(0.5 / 1.1)
    assert prof["top_ops"][0]["framework_ops"] == ["mul"]
    groups = prof["groups"]
    assert groups["matmul"]["seconds"] == pytest.approx(0.5)
    assert groups["data_movement"]["count"] == 1
    roofline.validate_device_profile(prof)
    # recorded: /profile summary + the top-op gauge
    assert roofline.profiles()[prof["program"]]["source"] == "xplane"
    monitor.enable()
    roofline.record_profile(prof)
    g = monitor.gauge("pt_device_op_seconds")
    assert g.value(labels={"op": "dot.1"}) == pytest.approx(0.5)
    # the gauge mirrors ONE profile: a later profile's cells REPLACE
    # the previous ops (per-compile HLO uids would accrete forever)
    path2 = _write_capture(tmp_path / "second", [
        ("/device:TPU:0", [("dot.9", PS // 5, 1)])])
    roofline.profile_from_xplane(path2, fluid.Program())
    assert g.value(labels={"op": "dot.9"}) == pytest.approx(0.2)
    assert g.value(labels={"op": "dot.1"}) == 0.0  # stale cell gone
    # an untimed (estimate) profile EMPTIES the gauge — a dead
    # capture's op mix must not keep serving next to fresh MFU values
    roofline.estimate_profile(fluid.Program(), device_seconds=0.1)
    assert not g._cells


# --------------------------------------------------------------------------
# executor integration
# --------------------------------------------------------------------------

def _small_program(width=32):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[width], dtype="float32")
        loss = layers.mean(layers.fc(x, width))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def test_executor_samples_estimate_profile_and_instruments(tmp_path):
    flags.set_flags({"telemetry": True, "step_phases_every_n": 1,
                     "device_profile_every_n_steps": 1,
                     "compile_report_dir": str(tmp_path)})
    main, startup, loss = _small_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={"x": np.ones((4, 32), np.float32)},
                    fetch_list=[loss])
    prof = roofline.latest(main)
    assert prof is not None and prof["source"] == "estimate"
    roofline.validate_device_profile(prof)
    # the estimate path joins the compile report's real XLA costs with
    # the executor's measured device phase
    assert prof["flops"] and prof["flops"] > 0
    assert prof["device_seconds"] and prof["device_seconds"] > 0
    assert prof["measured_mfu"] and prof["measured_mfu"] > 0
    assert prof["verdict"] in roofline.ROOFLINE_VERDICTS
    # estimate top_ops mirror the op histogram (no per-op seconds)
    assert prof["top_ops"] and all(o["seconds"] is None
                                   for o in prof["top_ops"])
    assert monitor.gauge("pt_program_mfu").value(
        labels={"program": prof["program"]}) == prof["measured_mfu"]
    assert monitor.counter("pt_device_profiles_total").value(
        labels={"source": "estimate"}) >= 1


def test_executor_window_profile_covers_window_steps(tmp_path):
    flags.set_flags({"telemetry": True, "step_phases_every_n": 1,
                     "device_profile_every_n_steps": 1,
                     "compile_report_dir": str(tmp_path)})
    main, startup, loss = _small_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feeds = [{"x": np.ones((4, 32), np.float32)}]
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_steps(main, feed_list=feeds, steps=5, fetch_list=[loss])
        exe.run_steps(main, feed_list=feeds, steps=5, fetch_list=[loss])
    prof = roofline.latest(main)
    assert prof is not None and prof["steps"] == 5
    rep = monitor.compile_reports()[prof["program"]]
    assert rep["window_steps"] == 5
    monitor.validate_compile_report(rep)
    # window report flops cover the whole window; the profile keeps the
    # whole-interval total (flops == report flops for a same-size call)
    if rep["flops"] is not None:
        assert prof["flops"] == pytest.approx(rep["flops"])


def test_executor_xplane_flag_degrades_on_cpu_once(tmp_path):
    """device_profile_xplane on the CPU container: the capture runs but
    has no device plane — every sampled step still profiles via the
    estimate path, and the degrade warns ONCE per process, not once
    per step."""
    flags.set_flags({"telemetry": True, "step_phases_every_n": 1,
                     "device_profile_every_n_steps": 1,
                     "device_profile_xplane": True,
                     "compile_report_dir": str(tmp_path)})
    main, startup, loss = _small_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(3):
                exe.run(main, feed={"x": np.ones((4, 32), np.float32)},
                        fetch_list=[loss])
    prof = roofline.latest(main)
    assert prof is not None and prof["source"] == "estimate"
    degrade = [w for w in caught
               if "source=\"estimate\"" in str(w.message)]
    assert len(degrade) == 1, [str(w.message) for w in degrade]


def test_roofline_sampling_counts_phase_sampled_steps_per_program():
    """take_sample fires on every Nth CALL for a given program (the
    executor calls it once per phase-sampled step), so the cadence
    never stretches to lcm(step_phases_every_n,
    device_profile_every_n_steps) the way an absolute-step modulo
    would — and interleaved programs never parity-starve each other
    out of profiles."""
    flags.set_flags({"telemetry": True,
                     "device_profile_every_n_steps": 4})
    assert roofline.active()
    a = fluid.Program()
    fires = [roofline.take_sample(a) for _ in range(9)]
    assert fires == [True, False, False, False,
                     True, False, False, False, True]
    # the starvation trap: two programs strictly alternating with
    # _every=2 — a process-global counter would give one of them every
    # even slot and the other NONE, forever
    flags.set_flags({"device_profile_every_n_steps": 2})
    b, c = fluid.Program(), fluid.Program()
    seen = {b._uid: [], c._uid: []}
    for _ in range(4):
        seen[b._uid].append(roofline.take_sample(b))
        seen[c._uid].append(roofline.take_sample(c))
    assert seen[b._uid] == [True, False, True, False]
    assert seen[c._uid] == [True, False, True, False]
    # disabled: False, and no counter advances
    flags.set_flags({"device_profile_every_n_steps": 0})
    assert not roofline.active() and not roofline.take_sample(a)


# --------------------------------------------------------------------------
# measured vs analytic MFU agreement
# --------------------------------------------------------------------------

def test_measured_mfu_agrees_with_analytic_on_matmul_program(tmp_path):
    """Matmul-dominated forward program: the XLA cost-analysis flops
    behind measured MFU must agree with the hand-derived analytic count
    within the 25% acceptance tolerance (same seconds, same peak, so
    the ratio IS the flops ratio)."""
    import jax

    flags.set_flags({"telemetry": True,
                     "compile_report_dir": str(tmp_path),
                     "device_peak_flops": 1e12,
                     "device_peak_bytes_per_sec": 1e10})
    B, D = 64, 256
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[D], dtype="float32")
        h = layers.fc(layers.fc(layers.fc(x, D), D), D)
        out = layers.mean(h)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((B, D), np.float32)}
    import time as _time

    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[out])  # compile + report
        t0 = _time.perf_counter()
        steps = 5
        for _ in range(steps):
            r = exe.run(main, feed=feed, fetch_list=[out],
                        return_numpy=False)
        jax.block_until_ready(r[0])
        secs = _time.perf_counter() - t0
        prof = roofline.estimate_profile(main, device_seconds=secs,
                                         steps=steps)
    analytic_per_step = 3 * 2.0 * B * D * D  # three D x D matmuls
    assert prof["measured_mfu"] is not None
    analytic_mfu = (analytic_per_step * steps / secs) / prof["peak_flops"]
    assert prof["measured_mfu"] == pytest.approx(analytic_mfu, rel=0.25)


# --------------------------------------------------------------------------
# debugger annotation
# --------------------------------------------------------------------------

def test_pprint_program_roofline_header_and_device_column(tmp_path):
    flags.set_flags({"device_peak_flops": 1e12,
                     "device_peak_bytes_per_sec": 1e10})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        layers.mean(layers.fc(x, 4))
    path = _write_capture(tmp_path, [
        ("/device:TPU:0", [("dot.1", PS // 2, 1)])])
    hist = {"mul": 1, "elementwise_add": 1, "mean": 1}
    roofline.profile_from_xplane(
        path, main, compile_report=_report(4e11, 1e9, hist=hist))
    listing = debugger.pprint_program(main)
    assert "device profile (v1, source=xplane" in listing
    assert "top device ops: dot.1=500.00ms" in listing
    # the mul op line carries the per-op device-time column
    mul_line = next(ln for ln in listing.splitlines() if "mul(" in ln)
    assert "[dev ~500.000ms]" in mul_line
    assert "device profile" not in debugger.pprint_program(
        main, with_roofline=False)


# --------------------------------------------------------------------------
# disabled-path allocation proofs
# --------------------------------------------------------------------------

def _alloc_growth(filenames, scope, n_runs, run):
    with fluid.scope_guard(scope):
        for _ in range(3):
            run()
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            run()
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    stats = snap.compare_to(base, "filename")
    return {
        fn: sum(s.size_diff for s in stats
                if s.traceback[0].filename.endswith(fn)
                and s.size_diff > 0)
        for fn in filenames
    }


def test_disabled_plane_zero_alloc_in_monitor_and_roofline():
    """Telemetry fully off: the roofline hooks add nothing to the
    executor hot path — no allocations in roofline.py OR monitor.py."""
    assert not monitor.enabled()
    main, startup, loss = _small_program(width=8)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 8), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
    n = 30
    grew = _alloc_growth(
        ("roofline.py", "monitor.py"), scope, n,
        lambda: exe.run(main, feed=feed, fetch_list=[loss]))
    assert grew["roofline.py"] < n * 16, grew
    assert grew["monitor.py"] < n * 16, grew


def test_roofline_off_zero_alloc_with_telemetry_on():
    """Telemetry + phases on but the roofline plane off (the default
    device_profile_every_n_steps=0): roofline.py allocates nothing."""
    flags.set_flags({"telemetry": True, "step_phases_every_n": 1,
                     "device_profile_every_n_steps": 0})
    main, startup, loss = _small_program(width=8)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 8), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
    n = 30
    grew = _alloc_growth(
        ("roofline.py",), scope, n,
        lambda: exe.run(main, feed=feed, fetch_list=[loss]))
    assert grew["roofline.py"] < n * 16, grew
