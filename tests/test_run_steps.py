"""Executor.run_steps: whole-window compiled loop parity with step-wise
run (reference analog: Executor::RunFromDataset hot loop,
framework/executor.cc:120-147)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _build(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 16], append_batch_size=False,
                        stop_gradient=True)
        label = layers.data("label", shape=[8, 1], dtype="int64",
                            append_batch_size=False)
        h = layers.fc(x, 32, act="relu")
        h = layers.dropout(h, 0.3)      # exercises the per-step RNG fold
        logits = layers.fc(h, 4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feeds(k=3):
    r = np.random.RandomState(0)
    out = []
    for i in range(k):
        x = r.randn(8, 16).astype(np.float32)
        out.append({"x": x,
                    "label": (np.argmax(x[:, :4], 1)[:, None]).astype(
                        np.int64)})
    return out


def test_run_steps_matches_stepwise():
    main, startup, loss = _build()
    feeds = _feeds(3)
    n = 7  # not a multiple of len(feeds): exercises the rotation

    scope_a, scope_b = fluid.executor.Scope(), fluid.executor.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.executor.scope_guard(scope_a):
        exe.run(startup)
        snapshot = {name: np.asarray(scope_a.find_var(name))
                    for name in scope_a.var_names()}
    for name, v in snapshot.items():
        scope_b.set(name, v)

    exe_a = fluid.Executor(fluid.CPUPlace())
    step_losses = []
    for i in range(n):
        out = exe_a.run(main, feed=feeds[i % len(feeds)], fetch_list=[loss],
                        scope=scope_a)
        step_losses.append(float(np.asarray(out[0])))

    exe_b = fluid.Executor(fluid.CPUPlace())
    out_multi = exe_b.run_steps(main, feed_list=feeds, steps=n,
                                fetch_list=[loss], scope=scope_b)
    # last-step fetch matches the step-wise stream bit-for-bit
    assert float(np.asarray(out_multi[0])) == step_losses[-1]
    # parameters after n steps match
    for name in scope_a.var_names():
        a = np.asarray(scope_a.find_var(name))
        b = np.asarray(scope_b.find_var(name))
        np.testing.assert_array_equal(a, b, err_msg=name)
    # training actually progressed
    assert step_losses[-1] < step_losses[0]


def test_run_steps_sees_in_place_feed_mutation():
    """A feed buffer refilled in place between run_steps calls (the
    preallocated-loader pattern) must be re-staged, not served from the
    identity cache. Only OWNING frozen arrays may be cached — a frozen
    view is still mutable through its writeable base."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 4], append_batch_size=False,
                        stop_gradient=True)
        s = layers.reduce_sum(x)
    exe = fluid.Executor(fluid.CPUPlace())
    buf = np.full((4, 4), 1.0, np.float32)
    out = exe.run_steps(main, feed_list=[{"x": buf}], steps=1,
                        fetch_list=[s])
    assert float(np.asarray(out[0])) == 16.0
    buf[...] = 2.0  # in-place refill, same identity
    out = exe.run_steps(main, feed_list=[{"x": buf}], steps=1,
                        fetch_list=[s])
    assert float(np.asarray(out[0])) == 32.0
    # a frozen VIEW must NOT be cached: its base is still writeable
    view = buf.view()
    view.flags.writeable = False
    exe.run_steps(main, feed_list=[{"x": view}], steps=1, fetch_list=[s])
    assert len(exe._staged) == 0
    buf[...] = 3.0  # mutation through the base reaches the frozen view
    out = exe.run_steps(main, feed_list=[{"x": view}], steps=1,
                        fetch_list=[s])
    assert float(np.asarray(out[0])) == 48.0
    # an OWNING frozen copy DOES hit the staging cache
    frozen = buf.copy()
    frozen.flags.writeable = False
    exe.run_steps(main, feed_list=[{"x": frozen}], steps=1, fetch_list=[s])
    cached = next(iter(exe._staged.values()))["stacked"]["x"]
    # an interleaved mutable-feed call must not wipe the frozen entry
    exe.run_steps(main, feed_list=[{"x": buf}], steps=1, fetch_list=[s])
    exe.run_steps(main, feed_list=[{"x": frozen}], steps=1, fetch_list=[s])
    assert next(iter(exe._staged.values()))["stacked"]["x"] is cached


def test_run_steps_continues_the_step_counter():
    main, startup, loss = _build(seed=11)
    feeds = _feeds(2)
    scope = fluid.executor.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
    # interleave: 2 single steps, a 3-step window, 1 single step
    l0 = exe.run(main, feed=feeds[0], fetch_list=[loss], scope=scope)
    exe.run(main, feed=feeds[1], fetch_list=[loss], scope=scope)
    exe.run_steps(main, feed_list=feeds, steps=3, fetch_list=[loss],
                  scope=scope)
    out = exe.run(main, feed=feeds[1], fetch_list=[loss], scope=scope)
    assert np.isfinite(np.asarray(out[0])).all()
    assert float(np.asarray(out[0])) < float(np.asarray(l0[0]))
