"""Scan-over-layers transformer build: parity with the unrolled build
and trainability (compile-time optimization; STATUS.md round-3 item
brought forward)."""

import pytest
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import transformer as T


def _cfg():
    return T.TransformerConfig(
        src_vocab_size=60, trg_vocab_size=60, max_length=32, d_model=16,
        d_inner=32, n_head=2, n_layer=3, dropout=0.0, label_smooth_eps=0.0)


@pytest.mark.full
def test_scan_build_matches_unrolled_build():
    cfg = _cfg()
    batch = T.make_batch(cfg, 4, 12, 10, seed=0)

    # unrolled reference
    scope_a = fluid.Scope()
    main_a, startup_a = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_a, startup_a):
        model_a = T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope_a):
        exe.run(startup_a)
        (ref,) = exe.run(main_a, feed=batch, fetch_list=[model_a["loss"]])

    # scan build with the SAME weights stacked
    scope_b = fluid.Scope()
    main_b, startup_b = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_b, startup_b):
        model_b = T.build_scan(cfg, is_test=True)
    exe_b = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope_b):
        exe_b.run(startup_b)
        # shared non-layer weights (embeddings, post-LN, proj) copy by name
        for name in scope_a.var_names():
            if scope_b.has(name):
                scope_b.set(name, np.asarray(scope_a.find_var(name)))
        T.stack_weights_from_layers(cfg, scope_a, scope_b)
        (got,) = exe_b.run(main_b, feed=batch, fetch_list=[model_b["loss"]])
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-5)


def test_scan_build_trains():
    cfg = _cfg()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = T.build_scan(cfg)
        fluid.optimizer.Adam(2e-3).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = np.array(scope.find_var("enc_stack_qkv.w_stacked"))
        for step in range(8):
            fd = T.make_batch(cfg, 8, 10, 10, seed=step % 2)
            losses.append(float(
                exe.run(main, feed=fd, fetch_list=[model["loss"]])[0]))
        w1 = np.array(scope.find_var("enc_stack_qkv.w_stacked"))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # every layer's slice of the stacked weight moved (grads through scan)
    per_layer_delta = np.abs(w1 - w0).reshape(cfg.n_layer, -1).max(axis=1)
    assert (per_layer_delta > 0).all(), per_layer_delta
