"""Serving plane (serving.py + transformer build_prefill/build_decode_step):
continuous batch assembly over an on-device KV cache.

The load-bearing drill: N requests of different lengths admitted at
staggered steps through a shared slot pool must produce token-for-token
identical output to each request decoded solo (greedy) — the continuous
batching correctness contract. Around it: decode-loop executor-cache
accounting (zero fresh compiles in steady state), queue backpressure,
deadlines, graceful drain, chaos sites, the /serve route, and the int8
PTQ artifact as a deployable weight source.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import faults, flags, monitor, serving
from paddle_tpu.models import transformer as T

BOS, EOS = 0, 1


def tiny_cfg(n_layer=1):
    return T.TransformerConfig(
        src_vocab_size=37, trg_vocab_size=41, max_length=64,
        d_model=16, d_inner=32, n_head=2, n_layer=n_layer,
        dropout=0.0, label_smooth_eps=0.0,
    )


@pytest.fixture(scope="module")
def weights():
    """Startup-initialized tiny transformer weights (shared scope)."""
    cfg = tiny_cfg()
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return cfg, scope


def _srcs(k, seed=0, lens=(5, 3, 7, 4, 6, 2, 8, 5)):
    r = np.random.RandomState(seed)
    return [r.randint(2, 37, (lens[i % len(lens)],)).astype(np.int64)
            for i in range(k)]


def _solo_decode(cfg, scope, src, max_len=10, end_id=EOS):
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                max_len=max_len, bos_id=BOS, end_id=end_id)
    req = eng.submit(src)
    eng.run_until_idle()
    eng.close()
    return list(req.tokens), req.outcome


# --------------------------------------------------------------------------
# the continuous-batching correctness drill
# --------------------------------------------------------------------------

def test_staggered_admissions_match_solo_greedy(weights):
    """5 requests, 2 slots: admissions happen at staggered decode steps
    as slots free up, yet every request's tokens must equal its solo
    greedy decode — the mixed in-flight batch never contaminates a
    neighbor's math (slot rows are independent in every kernel)."""
    cfg, scope = weights
    srcs = _srcs(5, seed=1)
    solo = [_solo_decode(cfg, scope, s)[0] for s in srcs]

    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8, max_len=10,
                                bos_id=BOS, end_id=EOS)
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_idle()
    batched = [list(q.tokens) for q in reqs]
    assert batched == solo
    assert all(q.done for q in reqs)
    assert eng.stats()["requests_completed"] == 5
    # staggering really happened: 5 requests cannot fit 2 slots at once
    assert eng.stats()["decode_steps"] < sum(len(t) + 1 for t in solo)
    eng.close()


def test_engine_matches_offline_beam1_decode(weights):
    """Anchor the KV-cache decode step to the INDEPENDENTLY-tested
    offline path: the engine's greedy stream must equal
    build_decode(beam_size=1) (which test_decode.py proves equal to the
    training program's step-by-step argmax) — so a systematic
    decode-step math bug cannot hide behind engine-vs-engine parity."""
    cfg, scope = weights
    max_len = 6
    srcs = _srcs(3, seed=20, lens=(8, 8, 8))  # src_len must match
    src = np.stack(srcs)
    src_pad = np.ones((3, 8), np.float32)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        dec = T.build_decode(cfg, beam_size=1, max_len=max_len,
                             src_len=8, bos_id=BOS, end_id=EOS)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        ids, _ = exe.run(prog, feed={"src_ids": src,
                                     "src_pad_mask": src_pad},
                         fetch_list=[dec["ids"], dec["scores"]])
    ids = np.asarray(ids)

    eng = serving.ServingEngine(cfg, scope, slots=3, src_len=8,
                                max_len=max_len, bos_id=BOS, end_id=EOS)
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_idle()
    for row, req in enumerate(reqs):
        seq = list(ids[row, 0, 1:])  # strip BOS
        if EOS in seq:
            seq = seq[:seq.index(EOS)]
        assert list(req.tokens) == seq, f"row {row}"
    eng.close()


def test_eos_completion_and_slot_reuse(weights):
    """Pick end_id = the model's actually-favored first token so the EOS
    path fires deterministically: the request completes without the
    token, the slot frees, and a queued request is admitted into it."""
    cfg, scope = weights
    srcs = _srcs(3, seed=2)
    probe, _ = _solo_decode(cfg, scope, srcs[0], max_len=6)
    eos = probe[0]  # this source's greedy first token
    toks, outcome = _solo_decode(cfg, scope, srcs[0], max_len=6,
                                 end_id=eos)
    assert toks == [] and outcome == "completed"

    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=6,
                                bos_id=BOS, end_id=eos)
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_idle()
    assert [q.outcome for q in reqs] == ["completed"] * 3
    assert list(reqs[0].tokens) == []  # EOS excluded from the output
    solo = [_solo_decode(cfg, scope, s, max_len=6, end_id=eos)[0]
            for s in srcs]
    assert [list(q.tokens) for q in reqs] == solo
    eng.close()


def test_max_new_tokens_and_length_outcome(weights):
    cfg, scope = weights
    # probe for a source whose natural greedy decode runs >= 4 tokens,
    # so a 3-token budget is a real truncation
    for seed in range(3, 16):
        (src,) = _srcs(1, seed=seed)
        full, _ = _solo_decode(cfg, scope, src)
        if len(full) >= 4:
            break
    else:
        pytest.skip("no probe source decoded >= 4 tokens")
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=10)
    req = eng.submit(src, max_new_tokens=3)
    eng.run_until_idle()
    assert len(req.tokens) == 3 and req.outcome == "length"
    assert list(req.tokens) == full[:3]
    eng.close()


# --------------------------------------------------------------------------
# decode loop x executor cache: zero fresh compiles in steady state
# --------------------------------------------------------------------------

def test_decode_loop_hits_executor_cache_after_warmup(weights):
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    try:
        eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8,
                                    max_len=12)
        reqs = [eng.submit(s) for s in _srcs(2, seed=4)]
        eng.step()  # warmup: prefill x2 + first decode step compile
        eng.step()
        misses0 = monitor.counter(
            "pt_executor_cache_misses_total").value()
        steps0 = eng.stats()["decode_steps"]
        eng.run_until_idle()
        assert eng.stats()["decode_steps"] > steps0
        assert monitor.counter(
            "pt_executor_cache_misses_total").value() == misses0
        outcomes = [r["cache"] for r in monitor.recent_steps()]
        assert outcomes[-3:] == ["hit", "hit", "hit"]
        assert all(q.done for q in reqs)
        eng.close()
    finally:
        flags.set_flags({"telemetry": False})


def test_close_releases_compiled_entries(weights):
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8, max_len=8)
    eng.submit(_srcs(1, seed=5)[0])
    eng.run_until_idle()
    assert len(eng._exe._cache) >= 2  # prefill + decode entries
    eng.close()
    assert len(eng._exe._cache) == 0
    with pytest.raises(serving.EngineClosed):
        eng.submit([2, 3])
    eng.close()  # idempotent


# --------------------------------------------------------------------------
# queue backpressure, deadlines, drain
# --------------------------------------------------------------------------

def test_queue_backpressure_rejects_beyond_capacity(weights):
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=8,
                                queue_depth=2)
    srcs = _srcs(3, seed=6)
    eng.submit(srcs[0])
    eng.submit(srcs[1])
    with pytest.raises(serving.QueueFull):
        eng.submit(srcs[2])
    eng.close()


def test_deadline_evicts_at_token_boundary(weights):
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                max_len=32)
    req = eng.submit(_srcs(1, seed=7)[0], deadline_ms=1.0)
    time.sleep(0.01)  # the deadline passes before/while decoding
    eng.run_until_idle()
    assert req.outcome == "expired"
    # the partial output (possibly empty) stays on the handle and the
    # slot was freed for the next admission
    assert eng.stats()["slots_active"] == 0
    eng.close()


def test_drain_finishes_inflight_and_marks_queued(weights):
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8, max_len=8)
    srcs = _srcs(4, seed=8)
    reqs = [eng.submit(s) for s in srcs]
    eng.step()  # admit two into slots
    assert eng.drain(timeout_s=60.0)
    outs = [q.outcome for q in reqs]
    assert outs.count("drained") == 2  # the two never admitted
    assert all(o in ("completed", "length") for o in outs[:2])
    with pytest.raises(serving.EngineClosed):
        eng.submit(srcs[0])
    eng.close()


def test_submit_validation_and_pad_shapes(weights):
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=8)
    # src_pad accepted at the request's own length AND the engine's
    # full src_len (the training graph's mask shape); others raise
    r_short = eng.submit([5, 6, 7], src_pad=[1, 1, 1])
    r_full = eng.submit([5, 6, 7], src_pad=[1, 1, 1, 0, 0, 0, 0, 0])
    np.testing.assert_array_equal(r_short.src_pad, r_full.src_pad)
    with pytest.raises(ValueError, match="matches neither"):
        eng.submit([5, 6, 7], src_pad=[1, 1, 1, 0])
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit([5, 6], max_new_tokens=0)
    eng.run_until_idle()
    # identical pads -> identical greedy streams
    assert list(r_short.tokens) == list(r_full.tokens)
    eng.close()


def test_close_after_failed_drain_never_strands_handles(weights):
    """A close whose drain times out (stalled decode loop) must still
    finish every in-flight handle — result() may never block forever on
    a closed engine."""
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                max_len=32)
    req = eng.submit(_srcs(1, seed=12)[0])
    eng.step()  # admitted + first decode step in flight
    eng.close(drain_timeout_s=0.0)  # drain gives up immediately
    assert req.done and req.outcome in ("drained", "completed", "length")
    assert req.result(timeout=1) == list(req.tokens)


def test_queue_and_slot_gauges_sum_across_engines(weights):
    """The process-wide gauges aggregate over live engines: an idle
    engine must not zero out a busy neighbor's queue reading."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    try:
        busy = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                     max_len=8)
        idle = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                     max_len=8)
        for s in _srcs(3, seed=13):
            busy.submit(s)
        # the idle engine republishing (via its own submit/finish flow)
        # must still report the busy engine's queue
        r = idle.submit([2, 3])
        idle.run_until_idle()
        assert r.done
        assert monitor.gauge("pt_serve_queue_depth").value() == 3
        busy.run_until_idle()
        assert monitor.gauge("pt_serve_queue_depth").value() == 0
        busy.close()
        idle.close()
    finally:
        flags.set_flags({"telemetry": False})


# --------------------------------------------------------------------------
# chaos sites + SLO metrics + /serve route
# --------------------------------------------------------------------------

def test_serve_fault_sites_registered_and_fire(weights):
    cfg, scope = weights
    assert {"serve.enqueue", "serve.prefill", "serve.decode",
            "serve.fetch"} <= set(faults.BUILTIN_SITES)
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=8)
    faults.arm("serve.enqueue:raise@1")
    try:
        with pytest.raises(faults.InjectedFault):
            eng.submit([2, 3, 4])
    finally:
        faults.disarm()
    # a prefill-site fault tears the admission seam: the popped request
    # surfaces 'error' on its handle, the engine keeps serving
    req = eng.submit([2, 3, 4])
    faults.arm("serve.prefill:raise@1")
    try:
        with pytest.raises(faults.InjectedFault):
            eng.run_until_idle()
    finally:
        faults.disarm()
    assert req.done and req.outcome == "error"
    req2 = eng.submit([2, 3, 4])
    eng.run_until_idle()
    assert req2.done and req2.outcome in ("completed", "length")
    eng.close()


def test_unhinted_decode_fault_fails_engine(weights):
    """A decode raise WITHOUT a slot hint is an unattributable device
    error: the engine fails (an EngineSupervisor would restart it),
    step() raises EngineFailed from then on, and close() finishes the
    pending handle with 'error' — result() never hangs."""
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=8)
    req = eng.submit([2, 3, 4])
    faults.arm("serve.decode:raise@1")
    try:
        with pytest.raises(faults.InjectedFault):
            eng.run_until_idle()
    finally:
        faults.disarm()
    assert eng.state == "failed"
    with pytest.raises(serving.EngineFailed):
        eng.step()
    with pytest.raises(serving.EngineFailed):
        eng.submit([5, 6])
    assert not req.done  # pending: a supervisor could still replay it
    eng.close()
    assert req.done and req.outcome == "error"
    assert req.result(timeout=1) == []


def test_serve_metrics_and_route(weights):
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    try:
        tokens0 = monitor.counter("pt_serve_tokens_total").value()
        eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8,
                                    max_len=8)
        reqs = [eng.submit(s) for s in _srcs(2, seed=9)]
        eng.run_until_idle()
        emitted = sum(len(q.tokens) for q in reqs)
        assert emitted > 0
        assert monitor.counter(
            "pt_serve_tokens_total").value() == tokens0 + emitted
        assert monitor.counter("pt_serve_prefill_total").value() >= 2
        assert serving._M_TOKEN_SECONDS.count() >= emitted
        assert serving._M_TTFT_SECONDS.count() >= 2

        port = monitor.serve(port=0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/serve", timeout=10) as r:
                doc = json.loads(r.read())
            assert doc["engine_count"] >= 1
            row = next(e for e in doc["engines"]
                       if e["tokens_emitted"] == emitted)
            assert row["requests_completed"] == 2
            assert doc["token_latency_s"]["p50"] is not None
            # the route is in the served index
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/", timeout=10) as r:
                assert "/serve" in json.loads(r.read())["routes"]
        finally:
            monitor.stop_server()
        eng.close()
    finally:
        flags.set_flags({"telemetry": False})


def test_engine_lifecycle_state_on_monitor_plane(weights):
    """ISSUE 14 serving tie-in: a replica being rotated out is
    observable BEFORE its queue is torn down — the engine lifecycle
    (serving -> draining -> closed) surfaces as the
    pt_serve_engine_state gauge, the /serve stats row, and per-engine
    rows on /healthz (a load balancer's probe must see 'draining' and
    stop routing while in-flight requests finish)."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    try:
        eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                    max_len=8)
        eid = str(eng.engine_id)

        def _gauge():
            return monitor.gauge("pt_serve_engine_state").value(
                labels={"engine": eid})

        def _healthz(port):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                return json.loads(r.read())

        assert eng.state == "serving" and _gauge() == 0
        assert eng.stats()["state"] == "serving"
        port = monitor.serve(port=0)
        try:
            assert _healthz(port)["engines"][eid] == "serving"
            req = eng.submit(_srcs(1, seed=4)[0])
            eng.drain()
            # drained with the request finished; the engine stays
            # draining (rotated out, not yet torn down) and says so
            assert req.done
            assert eng.state == "draining" and _gauge() == 1
            assert _healthz(port)["engines"][eid] == "draining"
            with pytest.raises(serving.EngineClosed):
                eng.submit(_srcs(1, seed=5)[0])
            eng.close()
            assert eng.state == "closed" and _gauge() == 2
            assert _healthz(port)["engines"][eid] == "closed"
            # idempotent shutdown: drain() on a closed engine must not
            # regress the published lifecycle closed -> draining
            assert eng.drain() is True
            assert eng.state == "closed" and _gauge() == 2
            assert _healthz(port)["engines"][eid] == "closed"
        finally:
            monitor.stop_server()
    finally:
        flags.set_flags({"telemetry": False})


# --------------------------------------------------------------------------
# int8 PTQ artifact as a deployable weight source
# --------------------------------------------------------------------------

def test_int8_artifact_deploys_into_engine(weights, tmp_path):
    """Calibrate + export the tiny transformer's int8 artifact (slim/),
    then deploy it: the engine loads the dequantized weights and serves
    greedy decode from them."""
    from paddle_tpu.slim.calibration import (Calibrator,
                                             save_int8_inference_model)

    cfg, scope = weights
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / "int8_model")
    with fluid.scope_guard(scope):
        calib = Calibrator(main, exe, scope=scope, algo="abs_max")
        for s in range(2):
            calib.sample(T.make_batch(cfg, 2, 5, 5, seed=s))
        calib.compute_scales()
        save_int8_inference_model(
            d, ["src_ids", "trg_ids", "lbl_ids", "src_pad_mask",
                "trg_pad_mask"], [model["logits"]], exe, main, calib,
            scope=scope)

    eng = serving.ServingEngine(cfg, d, slots=2, src_len=8, max_len=8)
    assert eng.int8 and eng.stats()["int8"]
    reqs = [eng.submit(s) for s in _srcs(2, seed=10)]
    eng.run_until_idle()
    assert all(q.done for q in reqs)
    assert all(len(q.tokens) > 0 for q in reqs)
    # int8 deployment is deterministic: a second engine over the same
    # artifact reproduces the tokens exactly
    eng2 = serving.ServingEngine(cfg, d, slots=2, src_len=8, max_len=8)
    reqs2 = [eng2.submit(s) for s in _srcs(2, seed=10)]
    eng2.run_until_idle()
    assert [list(q.tokens) for q in reqs2] == [list(q.tokens)
                                              for q in reqs]
    eng.close()
    eng2.close()


# --------------------------------------------------------------------------
# warm replica start through the persistent compile cache
# --------------------------------------------------------------------------

HERE = os.path.dirname(os.path.abspath(__file__))


def test_warm_replica_zero_fresh_compiles(tmp_path):
    """Two fresh 'serving replica' processes (tests/serving_worker.py:
    Predictor with enable_compile_cache + a tiny ServingEngine decode)
    against one cache dir: the warm replica resolves EVERY executable —
    predictor run, serving prefill, decode step — from disk, with
    byte-identical predictor output and decode tokens."""
    # the saved model the replica's Predictor serves
    from paddle_tpu import io, layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        probs = layers.softmax(layers.fc(x, 4))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    model_d = str(tmp_path / "model")
    with fluid.scope_guard(scope):
        exe.run(startup)
        io.save_inference_model(model_d, ["x"], [probs], exe, main)

    cache_d = str(tmp_path / "cc")
    env = {**os.environ, "PYTHONPATH": os.path.dirname(HERE)}

    def launch():
        out = subprocess.run(
            [sys.executable, os.path.join(HERE, "serving_worker.py"),
             cache_d, model_d],
            capture_output=True, text=True, timeout=600, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = launch()
    assert cold["stats"]["misses"] >= 4  # pred + startup + prefill + decode
    assert cold["stats"]["errors"] == {"spec": 0, "load": 0, "store": 0}
    assert cold["pred_entries"] == 1 and cold["closed_entries"] == 0

    warm = launch()
    assert warm["stats"]["misses"] == 0, warm
    assert warm["stats"]["hits"] == cold["stats"]["misses"]
    assert "miss" not in warm["outcomes"]
    assert set(warm["outcomes"]) <= {"disk", "hit"}, warm["outcomes"]
    # the disk-resolved executables compute the same functions
    assert warm["tokens"] == cold["tokens"]
    np.testing.assert_allclose(warm["probs_sum"], cold["probs_sum"],
                               rtol=1e-6)


# --------------------------------------------------------------------------
# the full-slot-count e2e (the verify SKILL.md smoke, tier-2)
# --------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.serving_e2e
def test_eight_concurrent_requests_match_solo(weights):
    """8 concurrent requests through a 4-slot engine: every stream must
    match its solo greedy decode, with zero fresh compiles after the
    warmup step and SLO histograms populated."""
    cfg, scope = weights
    flags.set_flags({"telemetry": True})
    try:
        srcs = _srcs(8, seed=11)
        solo = [_solo_decode(cfg, scope, s, max_len=12)[0] for s in srcs]
        eng = serving.ServingEngine(cfg, scope, slots=4, src_len=8,
                                    max_len=12)
        reqs = [eng.submit(s) for s in srcs]
        eng.step()
        eng.step()  # warmup: prefills + decode compile
        misses0 = monitor.counter(
            "pt_executor_cache_misses_total").value()
        eng.run_until_idle()
        assert monitor.counter(
            "pt_executor_cache_misses_total").value() == misses0
        assert [list(q.tokens) for q in reqs] == solo
        assert serving._M_TOKEN_SECONDS.count() > 0
        eng.close()
    finally:
        flags.set_flags({"telemetry": False})
