"""Serving-plane resilience (serving.py): decode fault containment,
supervised warm engine restart, and deadline-aware overload shedding.

The load-bearing drills:

- **containment**: a slot-hinted decode/fetch fault (or per-slot
  non-finite logits) evicts ONLY the poisoned slot — every other
  in-flight request's token stream is byte-identical to an undisturbed
  run — and the freed slot serves the next admission.
- **supervised restart**: an engine-killing fault (unhinted raise,
  wedged decode loop) triggers an EngineSupervisor warm restart with
  ZERO fresh compiles (persistent compile cache, misses unchanged),
  after which replayed requests return byte-identical tokens.
- **overload**: with submit rate over capacity, unmeetable-deadline
  requests are refused AT SUBMIT (outcome ``rejected_early``, never
  queued), admitted requests' per-token p99 stays within 2x the
  unloaded p99, and no handle ever hangs; sustained saturation engages
  brownout (admissions' max_new_tokens capped).
"""

import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import faults, flags, monitor, numerics, serving
from paddle_tpu.models import transformer as T

BOS, EOS = 0, 1


def tiny_cfg(n_layer=1):
    return T.TransformerConfig(
        src_vocab_size=37, trg_vocab_size=41, max_length=64,
        d_model=16, d_inner=32, n_head=2, n_layer=n_layer,
        dropout=0.0, label_smooth_eps=0.0,
    )


@pytest.fixture(scope="module")
def weights():
    cfg = tiny_cfg()
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return cfg, scope


def _srcs(k, seed=0, lens=(5, 3, 7, 4, 6, 2, 8, 5)):
    r = np.random.RandomState(seed)
    return [r.randint(2, 37, (lens[i % len(lens)],)).astype(np.int64)
            for i in range(k)]


def _undisturbed(cfg, scope, srcs, slots, max_len=10, **kw):
    """Token streams of an undisturbed engine run over ``srcs``."""
    eng = serving.ServingEngine(cfg, scope, slots=slots, src_len=8,
                                max_len=max_len, bos_id=BOS, end_id=EOS,
                                **kw)
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_idle()
    out = [list(q.tokens) for q in reqs]
    eng.close()
    return out


@pytest.fixture()
def telemetry():
    flags.set_flags({"telemetry": True})
    try:
        yield
    finally:
        flags.set_flags({"telemetry": False})


# --------------------------------------------------------------------------
# decode fault containment
# --------------------------------------------------------------------------

def test_slot_hinted_decode_fault_evicts_only_poisoned_slot(
        weights, telemetry):
    """The chaos drill: serve.decode:raise(slot=1) mid-stream evicts
    only slot 1 — its request finishes 'evicted' with the partial
    output, every other stream is byte-identical to an undisturbed run,
    and the freed slot serves a queued request."""
    cfg, scope = weights
    srcs = _srcs(4, seed=31)
    clean = _undisturbed(cfg, scope, srcs, slots=3)

    ev0 = monitor.counter("pt_serve_slot_evictions_total").value(
        labels={"cause": "fault"})
    eng = serving.ServingEngine(cfg, scope, slots=3, src_len=8, max_len=10,
                                bos_id=BOS, end_id=EOS)
    reqs = [eng.submit(s) for s in srcs]
    faults.arm("serve.decode:raise(poisoned slot=1)@3")
    try:
        eng.run_until_idle()  # the fault is CONTAINED: nothing raises
    finally:
        faults.disarm()
    assert eng.state == "serving"  # the engine never failed
    # slot 1's occupant (admission order = submit order): evicted with
    # the tokens emitted before the poisoned step — a byte-prefix of
    # its undisturbed stream
    assert reqs[1].outcome == "evicted"
    assert list(reqs[1].tokens) == clean[1][:len(reqs[1].tokens)]
    assert len(reqs[1].tokens) < len(clean[1])
    # every healthy stream byte-identical
    for i in (0, 2, 3):
        assert list(reqs[i].tokens) == clean[i], f"request {i}"
        assert reqs[i].outcome in ("completed", "length")
    # the queued 4th request was admitted into a freed slot
    assert reqs[3].done
    assert monitor.counter("pt_serve_slot_evictions_total").value(
        labels={"cause": "fault"}) == ev0 + 1
    eng.close()


def test_nonfinite_logits_evict_only_poisoned_slot(weights, telemetry):
    """Per-slot poison probe: NaN injected into one slot's device-
    resident cross-attention cache evicts that slot (outcome 'error',
    numerics-plane provenance) while the neighbor decodes
    byte-identically; the scrubbed slot serves the next admission."""
    cfg, scope = weights
    srcs = _srcs(3, seed=33)
    clean = _undisturbed(cfg, scope, srcs, slots=2)

    numerics.reset()
    nf0 = monitor.counter("pt_nonfinite_total").value(
        labels={"op": "decode_step", "var": "slot1:logits"})
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8, max_len=10,
                                bos_id=BOS, end_id=EOS)
    reqs = [eng.submit(s) for s in srcs]
    eng.step()  # admit both + dispatch step 1 (clean)
    eng.step()  # process step 1 + dispatch step 2 (clean)
    # poison slot 1's device state: the next decode step's logits for
    # slot 1 (and ONLY slot 1 — rows are independent) go non-finite
    arr = np.array(np.asarray(eng.scope.find_var("serve_ck0")))
    arr[1] = np.nan
    eng.scope.set("serve_ck0", arr)
    eng.run_until_idle()
    assert reqs[1].outcome == "error"
    assert list(reqs[1].tokens) == clean[1][:len(reqs[1].tokens)]
    assert list(reqs[0].tokens) == clean[0]
    assert reqs[0].outcome in ("completed", "length")
    # the scrubbed slot admitted the queued request, which decodes
    # byte-identically (a stale NaN K/V row would have re-poisoned it
    # through the softmax mask: 0 * NaN = NaN)
    assert list(reqs[2].tokens) == clean[2]
    # surfaced through the numerics plane
    assert monitor.counter("pt_nonfinite_total").value(
        labels={"op": "decode_step", "var": "slot1:logits"}) > nf0
    recs = [r for r in numerics.provenance_records()
            if r["op_type"] == "decode_step"]
    assert recs and recs[-1]["kind"] == "serve"
    eng.close()


def test_fetch_fault_contained_and_healthy_tokens_kept(weights, telemetry):
    """A slot-hinted serve.fetch fault (async materialization seam)
    evicts the hinted slot and RETRIES the step's fetches once — the
    healthy slot's already-computed token is not lost, its stream stays
    byte-identical."""
    cfg, scope = weights
    srcs = _srcs(2, seed=35)
    clean = _undisturbed(cfg, scope, srcs, slots=2)

    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8, max_len=10,
                                bos_id=BOS, end_id=EOS)
    reqs = [eng.submit(s) for s in srcs]
    faults.arm("serve.fetch:raise(slot=0)@2")
    try:
        eng.run_until_idle()
    finally:
        faults.disarm()
    assert eng.state == "serving"
    assert reqs[0].outcome == "evicted"
    assert list(reqs[0].tokens) == clean[0][:len(reqs[0].tokens)]
    assert list(reqs[1].tokens) == clean[1]
    eng.close()


def test_unhinted_fetch_fault_fails_engine(weights):
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=8)
    eng.submit(_srcs(1, seed=36)[0])
    faults.arm("serve.fetch:raise@1")
    try:
        with pytest.raises(faults.InjectedFault):
            eng.run_until_idle()
    finally:
        faults.disarm()
    assert eng.state == "failed"
    eng.close()


def test_decode_oom_runs_serve_forensics_and_fails_engine(
        weights, telemetry):
    """RESOURCE_EXHAUSTED on the decode path runs the existing OOM
    forensics with phase='serve' (donated-buffer hygiene already ran in
    the executor) and fails the engine — the supervisor-restart seam,
    not a containment case."""
    cfg, scope = weights
    oom0 = monitor.counter("pt_oom_events_total").value(
        labels={"phase": "serve"})
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=8)
    req = eng.submit(_srcs(1, seed=37)[0])
    faults.arm("serve.decode:raise(RESOURCE_EXHAUSTED: synthetic)@1")
    try:
        with pytest.raises(faults.InjectedFault):
            eng.run_until_idle()
    finally:
        faults.disarm()
    assert eng.state == "failed"
    assert monitor.counter("pt_oom_events_total").value(
        labels={"phase": "serve"}) == oom0 + 1
    assert any(r["phase"] == "serve" for r in monitor.oom_records())
    eng.close()
    assert req.outcome == "error"


# --------------------------------------------------------------------------
# supervised warm restart
# --------------------------------------------------------------------------

def test_supervised_restart_zero_fresh_compiles_byte_identical_replay(
        weights, telemetry, tmp_path):
    """The restart half of the chaos drill: an engine-killing decode
    fault triggers a supervised warm restart through the persistent
    compile cache (compile-cache misses UNCHANGED = zero fresh
    compiles), after which every replayed request returns tokens
    byte-identical to an undisturbed run."""
    cfg, scope = weights
    srcs = _srcs(3, seed=41)
    clean = _undisturbed(cfg, scope, srcs, slots=2)

    flags.set_flags({"compile_cache_dir": str(tmp_path / "cc")})
    sup = None
    try:
        sup = serving.EngineSupervisor(
            cfg, scope, slots=2, src_len=8, max_len=10, bos_id=BOS,
            end_id=EOS, poll_s=0.005, wedge_timeout_ms=60_000,
            max_restarts=2)
        # warm the disk tier (prefill + decode stored on first use)
        warm = sup.submit(_srcs(1, seed=42)[0], max_new_tokens=2)
        assert warm.result(timeout=60) is not None
        misses0 = monitor.counter(
            "pt_compile_cache_misses_total").value()
        restarts0 = monitor.counter(
            "pt_serve_engine_restarts_total").value()

        # hit counters reset at arm(): the 2nd decode step AFTER arming
        # fails with no slot hint -> engine-fatal -> supervised restart
        faults.arm("serve.decode:raise@2")
        try:
            reqs = [sup.submit(s) for s in srcs]
            streams = [r.result(timeout=120) for r in reqs]
        finally:
            faults.disarm()
        assert streams == clean
        assert [r.outcome for r in reqs] == ["completed"] * 3 or all(
            r.outcome in ("completed", "length") for r in reqs)
        assert sup.restarts == 1
        assert sup.replayed >= 1
        assert any(r.replays >= 1 for r in reqs)
        assert monitor.counter(
            "pt_serve_engine_restarts_total").value() == restarts0 + 1
        assert monitor.counter(
            "pt_serve_requests_replayed_total").value() >= 1
        # zero fresh compiles: the rebuilt engine resolved every
        # executable from the persistent cache
        assert monitor.counter(
            "pt_compile_cache_misses_total").value() == misses0
    finally:
        if sup is not None:
            sup.close(drain_timeout_s=5.0)
        flags.set_flags({"compile_cache_dir": ""})


def test_supervisor_restarts_wedged_engine(weights, telemetry):
    """Wedge detection rides engine heartbeats + monitor.stall_guard: a
    decode step stuck past serve_wedge_timeout_ms is declared dead by
    the watchdog, a stall record fires for site 'serve.decode', and the
    replayed requests complete byte-identically."""
    cfg, scope = weights
    srcs = _srcs(2, seed=44)
    clean = _undisturbed(cfg, scope, srcs, slots=2)

    stalls0 = monitor.counter("pt_stall_total").value(
        labels={"site": "serve.decode"})
    sup = serving.EngineSupervisor(
        cfg, scope, slots=2, src_len=8, max_len=10, bos_id=BOS,
        end_id=EOS, poll_s=0.01, wedge_timeout_ms=250, max_restarts=2)
    try:
        faults.arm("serve.decode:delay(1.5)@2")
        try:
            with pytest.warns(RuntimeWarning):
                reqs = [sup.submit(s) for s in srcs]
                streams = [r.result(timeout=60) for r in reqs]
        finally:
            faults.disarm()
        assert streams == clean
        assert sup.restarts == 1
        assert monitor.counter("pt_stall_total").value(
            labels={"site": "serve.decode"}) > stalls0
    finally:
        sup.close(drain_timeout_s=5.0)


def test_supervisor_restart_budget_exhaustion_fails_pending(weights):
    """Past serve_max_restarts the supervisor gives up: pending handles
    finish 'error' (no hang), the supervisor closes, submit raises."""
    cfg, scope = weights
    sup = serving.EngineSupervisor(
        cfg, scope, slots=1, src_len=8, max_len=8, poll_s=0.005,
        wedge_timeout_ms=60_000, max_restarts=0)
    try:
        faults.arm("serve.decode:raise@1")
        try:
            req = sup.submit(_srcs(1, seed=45)[0])
            assert req.result(timeout=30) == []
        finally:
            faults.disarm()
        assert req.outcome == "error"
        deadline = time.perf_counter() + 10
        while sup.state != "closed" and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert sup.state == "closed"
        with pytest.raises(serving.EngineClosed):
            sup.submit(_srcs(1, seed=46)[0])
    finally:
        sup.close(drain_timeout_s=1.0)


def test_supervised_front_end_and_predictor_seam(weights):
    """serve(..., supervised=True) returns a self-driving supervisor
    (no caller step loop needed); Predictor exposes the same seam."""
    from paddle_tpu import inference

    cfg, scope = weights
    sup = serving.serve(cfg, scope, supervised=True, slots=1, src_len=8,
                        max_len=8, poll_s=0.005)
    try:
        req = sup.submit(_srcs(1, seed=47)[0])
        assert req.result(timeout=60) == list(req.tokens)
        assert req.outcome in ("completed", "length")
        assert sup.stats()["supervised"] and sup.stats()["restarts"] == 0
    finally:
        sup.close(drain_timeout_s=5.0)
    assert callable(getattr(inference.Predictor, "serving_engine"))


# --------------------------------------------------------------------------
# deadline-aware admission control + overload drill
# --------------------------------------------------------------------------

def test_rejected_early_refused_at_submit(weights):
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                max_len=32, queue_depth=8)
    # measured per-token latency: 50 ms (white-box primed — the EWMA
    # normally comes from decode-step walls)
    eng._token_ewma_s = 0.05
    a = eng.submit(_srcs(1, seed=51)[0], max_new_tokens=10)
    # ~10 tokens ahead x 50 ms >> 20 ms deadline: refused AT submit
    with pytest.raises(serving.DeadlineUnmeetable) as ei:
        eng.submit(_srcs(1, seed=52)[0], deadline_ms=20)
    rej = ei.value.request
    assert rej.done and rej.outcome == "rejected_early"
    assert eng.stats()["queue_depth"] == 1  # never queued
    # a meetable deadline is admitted
    ok = eng.submit(_srcs(1, seed=53)[0], deadline_ms=60_000)
    assert ok.outcome is None
    # flag off: no admission control
    flags.set_flags({"serve_admission_control": False})
    try:
        off = eng.submit(_srcs(1, seed=54)[0], deadline_ms=20)
        assert off.outcome is None
    finally:
        flags.set_flags({"serve_admission_control": True})
    eng.run_until_idle()
    assert a.done and ok.done and off.done
    eng.close()


@pytest.fixture(scope="module")
def weights_mid():
    """A model whose decode step costs a few ms: the overload drill's
    2x p99 bound compares device-paced steps, not sub-ms host churn."""
    cfg = T.TransformerConfig(
        src_vocab_size=37, trg_vocab_size=41, max_length=64,
        d_model=96, d_inner=256, n_head=4, n_layer=3,
        dropout=0.0, label_smooth_eps=0.0,
    )
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return cfg, scope


@pytest.mark.multidevice_fragile
def test_overload_drill_p99_and_no_hangs(weights_mid, telemetry):
    """The overload acceptance drill: submit rate >= 2x capacity —
    unmeetable deadlines are refused at submit (rejected_early, never
    queued), admitted requests' per-token p99 stays within 2x the
    unloaded p99, and every handle reaches a terminal outcome."""
    cfg, scope = weights_mid

    def drive(eng, reqs_srcs, deadline_ms=None, submit_per_step=1):
        """Submit while stepping (sustained pressure); returns
        (handles, rejected_early_count, dispatch->host decode walls —
        the honest per-token latency, prefill work excluded)."""
        handles, rejected = [], 0
        pending = list(reqs_srcs)
        eng._step_walls.clear()
        while pending or eng.busy():
            for _ in range(submit_per_step):
                if not pending:
                    break
                try:
                    handles.append(eng.submit(
                        pending.pop(0), max_new_tokens=6,
                        deadline_ms=deadline_ms))
                except serving.DeadlineUnmeetable as e:
                    rejected += 1
                    assert e.request.outcome == "rejected_early"
                except serving.QueueFull:
                    pass
            eng.step()
        return handles, rejected, list(eng._step_walls)

    # unloaded baseline: trickled requests through the same engine
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8,
                                max_len=10, queue_depth=16)
    w = eng.submit(_srcs(1, seed=60)[0], max_new_tokens=2)
    eng.run_until_idle()  # warmup: compiles excluded from the window
    assert w.done
    _, _, unloaded = drive(eng, _srcs(4, seed=61))
    unloaded_p99 = float(np.percentile(unloaded, 99))

    # loaded: 16 requests pushed 2-per-step through 2 slots with a
    # deadline sized for roughly a third of them
    per_token_ms = eng._token_ewma_s * 1e3
    deadline_ms = per_token_ms * 6 * 3
    handles, rejected, loaded = drive(
        eng, _srcs(16, seed=62), deadline_ms=deadline_ms,
        submit_per_step=2)
    loaded_p99 = float(np.percentile(loaded, 99))

    assert rejected >= 1, "no request was refused at submit"
    assert handles, "every request was refused"
    for h in handles:
        h.result(timeout=30)  # no handle ever hangs
        assert h.outcome in ("completed", "length", "expired")
    assert loaded_p99 <= 2.0 * unloaded_p99, (
        f"loaded p99 {loaded_p99 * 1e3:.2f} ms vs unloaded "
        f"{unloaded_p99 * 1e3:.2f} ms")
    eng.close()


def test_brownout_caps_admissions_under_sustained_saturation(
        weights, telemetry):
    cfg, scope = weights
    flags.set_flags({"serve_brownout_queue_factor": 0.5,
                     "serve_brownout_window": 2,
                     "serve_brownout_max_new_tokens": 2})
    capped0 = monitor.counter("pt_serve_brownout_capped_total").value()
    try:
        eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                    max_len=32, queue_depth=8)
        srcs = _srcs(6, seed=65, lens=(7, 7, 7, 7, 7, 7))
        reqs = [eng.submit(s, max_new_tokens=8) for s in srcs]
        with pytest.warns(RuntimeWarning, match="brownout engaged"):
            eng.run_until_idle()
        assert monitor.counter(
            "pt_serve_brownout_capped_total").value() > capped0
        capped = [r for r in reqs if r.capped]
        assert capped, "brownout never capped an admission"
        for r in capped:
            assert len(r.tokens) <= 2
            assert r.outcome in ("completed", "length")
        # the first admission predates the engage window
        assert not reqs[0].capped
        # queue drained -> disengaged
        assert eng.stats()["brownout"] is False
        assert all(r.done for r in reqs)
        eng.close()
    finally:
        flags.set_flags({"serve_brownout_queue_factor": 0.0,
                         "serve_brownout_window": 16,
                         "serve_brownout_max_new_tokens": 16})


# --------------------------------------------------------------------------
# deadline eviction racing the async double-buffered fetch (satellite)
# --------------------------------------------------------------------------

def test_deadline_expiring_during_inflight_fetch_keeps_partial_output(
        weights):
    """A request whose deadline expires while step N's LazyFetches is
    still in flight keeps the partial output already materialized (plus
    step N's token, which was computed before the boundary) and never
    hangs result()."""
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                max_len=32, pipeline_depth=1)
    req = eng.submit(_srcs(1, seed=70)[0], deadline_ms=40)
    eng.step()  # admit + dispatch step 1; fetches in flight
    assert eng._pending is not None
    time.sleep(0.08)  # the deadline passes with the fetch in flight
    eng.run_until_idle()
    assert req.outcome == "expired"
    assert len(req.tokens) >= 1  # step N's token was kept
    assert req.result(timeout=1) == list(req.tokens)  # no hang
    assert eng.stats()["slots_active"] == 0  # the slot was freed
    eng.close()
    assert req.result(timeout=1) == list(req.tokens)


# --------------------------------------------------------------------------
# engine-state map hygiene (satellite)
# --------------------------------------------------------------------------

def test_closed_engine_state_rows_age_out(weights, telemetry):
    """A rotated replica's terminal 'closed' row (and its
    pt_serve_engine_state gauge cell) ages out of /healthz after
    ENGINE_STATE_TTL_S instead of being served forever."""
    cfg, scope = weights
    old_ttl = serving.ENGINE_STATE_TTL_S
    serving.ENGINE_STATE_TTL_S = 0.05
    try:
        eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                    max_len=8)
        eid = str(eng.engine_id)
        eng.close()
        assert serving.engine_states().get(eid) == "closed"
        cells = monitor.snapshot()["pt_serve_engine_state"]["values"]
        assert any(c["labels"].get("engine") == eid for c in cells)
        time.sleep(0.08)
        assert eid not in serving.engine_states()
        cells = monitor.snapshot()["pt_serve_engine_state"]["values"]
        assert not any(c["labels"].get("engine") == eid for c in cells)
    finally:
        serving.ENGINE_STATE_TTL_S = old_ttl


# --------------------------------------------------------------------------
# review-round regressions
# --------------------------------------------------------------------------

def test_fetch_materialization_does_not_hold_engine_lock(weights):
    """A slow/hung fetch must not wedge submit()/busy() behind it (the
    supervisor watchdog takes the same lock to declare a wedge): the
    blocking device wait runs outside the engine lock."""
    import threading

    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8,
                                max_len=12)
    eng.submit(_srcs(1, seed=80)[0])
    eng.step()  # dispatch; the next _process_ready materializes
    faults.arm("serve.fetch:delay(0.6)@1")
    stepper = threading.Thread(target=eng.step)
    try:
        stepper.start()
        time.sleep(0.1)  # the stepper is inside the delayed wait
        t0 = time.perf_counter()
        eng.submit(_srcs(1, seed=81)[0])
        eng.busy()
        blocked_s = time.perf_counter() - t0
        assert blocked_s < 0.3, (
            f"submit()/busy() blocked {blocked_s:.2f}s behind the fetch")
    finally:
        stepper.join(timeout=5)
        faults.disarm()
    eng.run_until_idle()
    eng.close()


def test_idle_gap_does_not_read_as_wedge(weights):
    """The heartbeat resets at work arrival: an idle gap longer than
    serve_wedge_timeout_ms followed by a submit must not be declared a
    wedge (it previously burned one restart per idle gap)."""
    cfg, scope = weights
    sup = serving.EngineSupervisor(
        cfg, scope, slots=1, src_len=8, max_len=8, poll_s=0.01,
        wedge_timeout_ms=200, max_restarts=1)
    try:
        warm = sup.submit(_srcs(1, seed=82)[0])
        warm.result(timeout=60)  # warmed: decode_steps > 0
        time.sleep(0.5)  # idle well past the wedge timeout
        req = sup.submit(_srcs(1, seed=83)[0])
        req.result(timeout=60)
        assert req.outcome in ("completed", "length")
        assert sup.restarts == 0
    finally:
        sup.close(drain_timeout_s=5.0)


def test_replay_that_never_reprefills_keeps_partial_output(weights):
    """The replay token wipe happens at the rebuilt engine's ADMISSION:
    a replay whose intake lands on a dead engine finishes 'error' with
    the already-streamed partial output intact (and is not counted as
    replayed)."""
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=8)
    req = eng.submit(_srcs(1, seed=84)[0])
    req.tokens.extend([7, 8, 9])  # the partial stream already handed out
    (harvested,) = eng._harvest_for_replay()
    assert harvested is req
    dead = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                 max_len=8)
    dead.close()
    replays0 = req.replays
    dead._enqueue_replay(req)
    assert req.done and req.outcome == "error"
    assert list(req.tokens) == [7, 8, 9]  # partial output survived
    assert req.replays == replays0  # never re-prefilled, never counted
    eng.close()


def test_submit_after_supervisor_drain_fails_fast(weights):
    """drain() is explicit rotation, not a restart race: a subsequent
    submit() raises EngineClosed immediately instead of spinning the
    supervisor's restart-retry window."""
    cfg, scope = weights
    sup = serving.EngineSupervisor(
        cfg, scope, slots=1, src_len=8, max_len=8, poll_s=0.005,
        wedge_timeout_ms=60_000)
    try:
        sup.submit(_srcs(1, seed=85)[0]).result(timeout=60)
        assert sup.drain(timeout_s=30)
        t0 = time.perf_counter()
        with pytest.raises(serving.EngineClosed):
            sup.submit(_srcs(1, seed=86)[0])
        assert time.perf_counter() - t0 < 5.0
    finally:
        sup.close(drain_timeout_s=5.0)


def test_hint_matching_no_active_slot_fails_engine(weights):
    """A slot hint that evicts nothing (out-of-range / already-finished
    slot) contains nothing: the error must fail the engine, not be
    swallowed into a zero-progress livelock."""
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8, max_len=8)
    eng.submit(_srcs(1, seed=90)[0])
    faults.arm("serve.decode:raise(slot=9)@1")
    try:
        with pytest.raises(faults.InjectedFault):
            eng.run_until_idle()
    finally:
        faults.disarm()
    assert eng.state == "failed"
    eng.close()


def test_brownout_never_caps_a_replay(weights):
    """Capping a replay would break the byte-identical invariant (and
    could return fewer tokens than its pre-restart partial output):
    replays are exempt from the brownout cap at admission."""
    cfg, scope = weights
    flags.set_flags({"serve_brownout_queue_factor": 0.5,
                     "serve_brownout_window": 1,
                     "serve_brownout_max_new_tokens": 1})
    try:
        eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                    max_len=10, queue_depth=4)
        req = eng.submit(_srcs(1, seed=91)[0], max_new_tokens=6)
        (harvested,) = eng._harvest_for_replay()
        assert harvested is req
        eng2 = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                     max_len=10, queue_depth=4)
        eng2.brownout = True  # engaged when the replay is admitted
        eng2._enqueue_replay(req)
        eng2.run_until_idle()
        assert req.done and not req.capped
        assert req.max_new_tokens == 6  # the budget survived brownout
        assert req.replays == 1
        eng.close()
        eng2.close()
    finally:
        flags.set_flags({"serve_brownout_queue_factor": 0.0,
                         "serve_brownout_window": 16,
                         "serve_brownout_max_new_tokens": 16})


def test_steady_submit_traffic_does_not_defer_wedge_detection(weights):
    """The work-arrival heartbeat reset applies only to an IDLE engine:
    submits landing on an engine with work in flight must not refresh
    the beat, or steady traffic would hide a wedged decode loop from
    the watchdog until the queue filled."""
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=1, src_len=8,
                                max_len=12, queue_depth=8)
    eng.submit(_srcs(1, seed=92)[0])
    eng.step()  # in flight: slot occupied
    eng._beat -= 100.0  # simulate a long-wedged decode loop
    eng.submit(_srcs(1, seed=93)[0])  # traffic keeps arriving
    assert eng.heartbeat_age_s() > 50.0  # the wedge age survived
    eng.run_until_idle()
    # and the idle case still resets (the false-positive guard)
    eng._beat -= 100.0
    eng.submit(_srcs(1, seed=94)[0])
    assert eng.heartbeat_age_s() < 50.0
    eng.run_until_idle()
    eng.close()


def test_slot_scrub_runs_on_device(weights):
    """The poisoned-slot scrub is a compiled device-state update: no
    host round-trip of the KV caches (the whole point of the serving
    state design), and the scrubbed rows really are zero."""
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8,
                                max_len=10)
    reqs = [eng.submit(s) for s in _srcs(2, seed=95)]
    eng.step()
    eng.step()  # both slots hold real K/V rows now
    before = np.array(np.asarray(eng.scope.find_var("serve_k0")))
    assert np.abs(before[0]).sum() > 0
    eng._scrub_slot_state(0)
    after = np.asarray(eng.scope.find_var("serve_k0"))
    assert np.abs(after[0]).sum() == 0  # slot 0 zeroed...
    np.testing.assert_array_equal(after[1], before[1])  # ...slot 1 kept
    assert not np.asarray(eng.scope.find_var("serve_live"))[0]
    eng.run_until_idle()
    assert all(r.done for r in reqs)
    eng.close()


def test_scrub_runs_outside_engine_lock(weights):
    """The scrub is a blocking device call: it must run with the engine
    lock RELEASED, or a hung scrub would wedge submit()/busy() and the
    watchdog itself (the exact hang the supervisor recovers from)."""
    import threading

    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8,
                                max_len=12)
    reqs = [eng.submit(s) for s in _srcs(2, seed=96)]
    eng.step()  # admit + dispatch; the next materialization can fault
    orig = eng._scrub_slot_state
    in_scrub = threading.Event()

    def slow_scrub(i):
        in_scrub.set()
        time.sleep(0.6)
        orig(i)

    eng._scrub_slot_state = slow_scrub
    faults.arm("serve.fetch:raise(slot=1)@1")
    stepper = threading.Thread(target=eng.step)
    stepper.start()
    try:
        assert in_scrub.wait(10)
        t0 = time.perf_counter()
        eng.submit(_srcs(1, seed=97)[0])
        eng.busy()
        blocked_s = time.perf_counter() - t0
        assert blocked_s < 0.3, (
            f"submit()/busy() blocked {blocked_s:.2f}s behind the scrub")
    finally:
        stepper.join(timeout=10)
        faults.disarm()
        eng._scrub_slot_state = orig
    assert reqs[1].outcome == "evicted"  # the eviction still landed
    eng.run_until_idle()
    eng.close()


def test_scrub_failure_fails_engine_without_dropping_tokens(weights):
    """A failing scrub leaves an unscrubbed slot that would re-poison
    its next occupant: the engine must FAIL (supervisor restarts), not
    half-contain — and the healthy slot's token from that step was
    already applied before the scrub ran."""
    cfg, scope = weights
    eng = serving.ServingEngine(cfg, scope, slots=2, src_len=8,
                                max_len=12)
    reqs = [eng.submit(s) for s in _srcs(2, seed=98)]
    eng.step()
    eng.step()
    tokens_before = len(reqs[0].tokens)
    arr = np.array(np.asarray(eng.scope.find_var("serve_ck0")))
    arr[1] = np.nan
    eng.scope.set("serve_ck0", arr)

    def broken_scrub(i):
        raise RuntimeError("scrub device error")

    eng._scrub_slot_state = broken_scrub
    with pytest.raises(RuntimeError, match="scrub device error"):
        eng.run_until_idle()
    assert eng.state == "failed"
    # the poisoned step's healthy-slot token landed before the scrub
    assert len(reqs[0].tokens) > tokens_before
    eng.close()
    assert all(r.done for r in reqs)
