"""Multi-slice (DCN) outer data axis: slice x dp composed batch sharding
with loss parity vs the single-device run (the reference's 2-level
hierarchical allreduce capability, platform/nccl_helper.h:179-210 /
parallel_executor.cc:180, expressed as a mesh axis)."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import layers, parallel
from paddle_tpu.parallel.strategy import transformer_rules


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, 32, act="relu")
        logits = layers.fc(h, 4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feeds(n, batch=8):
    r = np.random.RandomState(0)
    out = []
    for _ in range(n):
        x = r.randn(batch, 16).astype(np.float32)
        out.append({"x": x, "label": np.argmax(
            x[:, :4], axis=1)[:, None].astype(np.int64)})
    return out


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_slice2_dp2_parity_with_single_device():
    feeds = _feeds(3)
    losses = {}
    for mode in ("single", "slice_dp"):
        main, startup, loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if mode == "single":
                prog = main
            else:
                mesh = parallel.create_slice_mesh(
                    2, {"data": 2}, devices=jax.devices()[:4])
                assert mesh.axis_names == ("slice", "data")
                strategy = parallel.DistributedStrategy(
                    mesh, data_axis="data", slice_axis="slice")
                # batch shards over BOTH axes (outer slice, inner data)
                spec = strategy.batch_sharding().spec
                assert tuple(spec) == (("slice", "data"),)
                prog = fluid.CompiledProgram(main).with_strategy(strategy)
            cur = []
            for fd in feeds:
                out = exe.run(prog, feed=fd, fetch_list=[loss])
                cur.append(float(np.asarray(out[0])))
        losses[mode] = cur
    np.testing.assert_allclose(losses["single"], losses["slice_dp"],
                               rtol=2e-5, atol=2e-5)
    assert losses["single"][-1] < losses["single"][0]


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
@pytest.mark.full
def test_slice2_within_dp2_tp2_composes():
    """slice x (dp x tp) on 8 devices: the hierarchical-allreduce mesh
    composed with tensor parallelism in one program."""
    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        src_vocab_size=100, trg_vocab_size=100, d_model=32, d_inner=64,
        n_head=2, n_layer=1, max_length=20, dropout=0.0)
    losses = {}
    for mode in ("single", "slice_dp_tp"):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            model = T.build(cfg)
            fluid.optimizer.SGD(0.05).minimize(model["loss"])
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if mode == "single":
                prog = main
            else:
                mesh = parallel.create_slice_mesh(
                    2, {"data": 2, "model": 2}, devices=jax.devices()[:8])
                strategy = parallel.DistributedStrategy(
                    mesh, data_axis="data", slice_axis="slice",
                    rules=transformer_rules("model"), strict=True)
                prog = fluid.CompiledProgram(main).with_strategy(strategy)
            cur = []
            for s in range(2):
                fd = T.make_batch(cfg, batch=8, src_len=16, trg_len=16,
                                  seed=s)
                out = exe.run(prog, feed=fd, fetch_list=[model["loss"]])
                cur.append(float(np.asarray(out[0])))
        losses[mode] = cur
    np.testing.assert_allclose(losses["single"], losses["slice_dp_tp"],
                               rtol=2e-4, atol=2e-4)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
@pytest.mark.full
def test_slice2_dp2_sp2_ring_attention_parity():
    """slice x dp x sp-ring in one program: the shard_map ring-attention
    kernel receives the COMPOSED (slice, data) batch axis through
    SpmdCtx and stays parity-exact with the single-device run."""
    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        src_vocab_size=100, trg_vocab_size=100, d_model=32, d_inner=64,
        n_head=2, n_layer=1, max_length=40, dropout=0.0)
    losses = {}
    for mode in ("single", "slice_dp_sp"):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            model = T.build(cfg)
            fluid.optimizer.SGD(0.05).minimize(model["loss"])
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if mode == "single":
                prog = main
            else:
                mesh = parallel.create_slice_mesh(
                    2, {"data": 2, "sp": 2}, devices=jax.devices()[:8])
                strategy = parallel.DistributedStrategy(
                    mesh, data_axis="data", slice_axis="slice",
                    context_axis="sp")
                prog = fluid.CompiledProgram(main).with_strategy(strategy)
            cur = []
            for s in range(2):
                fd = T.make_batch(cfg, batch=8, src_len=32, trg_len=32,
                                  seed=s)
                # ring attention shards the sequence axis evenly
                fd["src_pad_mask"][:] = 1.0
                fd["trg_pad_mask"][:] = 1.0
                out = exe.run(prog, feed=fd, fetch_list=[model["loss"]])
                cur.append(float(np.asarray(out[0])))
        losses[mode] = cur
    np.testing.assert_allclose(losses["single"], losses["slice_dp_sp"],
                               rtol=2e-4, atol=2e-4)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_slice2_ep4_moe_parity():
    """slice x ep: expert-parallel all_to_all dispatch with the batch
    sharded over the outer slice axis; aux statistics pmean over the
    composed axes keep router gradients global."""
    losses = {}
    for mode in ("single", "slice_ep"):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            xv = layers.data("x", shape=[16], dtype="float32")
            out_v, aux_v = layers.switch_moe(
                xv, num_experts=4, d_ff=32, name="moe")
            loss = layers.elementwise_add(
                layers.mean(layers.square(out_v)),
                layers.scale(aux_v, scale=0.01))
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if mode == "single":
                prog = main
            else:
                from paddle_tpu.parallel.strategy import moe_rules

                mesh = parallel.create_slice_mesh(
                    2, {"expert": 4}, devices=jax.devices()[:8])
                strategy = parallel.DistributedStrategy(
                    mesh, data_axis=None, slice_axis="slice",
                    rules=moe_rules("expert"), expert_axis="expert")
                prog = fluid.CompiledProgram(main).with_strategy(strategy)
            cur = []
            for s in range(2):
                fd = {"x": np.random.RandomState(s).normal(
                    0, 1, (16, 16)).astype(np.float32)}
                out = exe.run(prog, feed=fd, fetch_list=[loss])
                cur.append(float(np.asarray(out[0])))
        losses[mode] = cur
    np.testing.assert_allclose(losses["single"], losses["slice_ep"],
                               rtol=2e-4, atol=2e-4)
