"""Slim compression tests: QAT pass, PTQ int8 export, distillation
(reference: contrib/slim/quantization/quantization_pass.py,
slim/distillation/distiller.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, slim
from tests.op_test import OpHarness

RS = np.random.RandomState


def test_fake_quant_op_values_and_ste_grad():
    x = RS(0).randn(4, 5) * 3
    h = OpHarness("fake_quantize_dequantize", {"X": x}, attrs={"bits": 8})
    scale = np.abs(x).max()
    q = np.clip(np.round(x / scale * 127), -127, 127) * scale / 127
    h.check_output({"Out": q}, atol=1e-6)
    # quantization error is bounded by half a step
    assert np.abs(q - x).max() <= scale / 127


def _mlp(quant=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, 32, act="relu",
                      param_attr=fluid.ParamAttr(name="q1.w"),
                      bias_attr=fluid.ParamAttr(name="q1.b"))
        logits = layers.fc(h, 4,
                           param_attr=fluid.ParamAttr(name="q2.w"),
                           bias_attr=fluid.ParamAttr(name="q2.b"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        n_q = 0
        if quant:
            n_q = slim.QuantizationTransformPass().apply(main)
        fluid.optimizer.Adam(5e-3).minimize(loss)
    return main, startup, loss, logits, n_q


def _batches(n=30):
    rng = RS(3)
    probe = RS(5).randn(16, 4)
    out = []
    for _ in range(n):
        x = rng.randn(32, 16).astype(np.float32)
        y = np.argmax(x @ probe, 1).astype(np.int64)[:, None]
        out.append({"x": x, "label": y})
    return out


def test_qat_pass_inserts_and_trains():
    main, startup, loss, logits, n_q = _mlp(quant=True)
    # 2 fc layers x (activation + weight) = 4 fake-quant sites
    assert n_q == 4
    assert sum(1 for op in main.global_block().ops
               if op.type == "fake_quantize_dequantize") == 4
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for fd in _batches():
            losses.append(float(
                exe.run(main, feed=fd, fetch_list=[loss])[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7  # trains THROUGH the fake quant


def test_ptq_int8_roundtrip_close():
    main, startup, loss, logits, _ = _mlp(quant=False)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fd = _batches(1)[0]
    with fluid.scope_guard(scope):
        exe.run(startup)
        for b in _batches(10):
            exe.run(main, feed=b, fetch_list=[loss])
        (ref,) = exe.run(main, feed=fd, fetch_list=[logits])
        packed = slim.quantize_weights_int8(main, scope)
    assert set(packed) == {"q1.w", "q1.b", "q2.w", "q2.b"}
    assert all(q.dtype == np.int8 for q, _ in packed.values())

    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        exe2.run(startup)  # fresh (wrong) init
        slim.dequantize_weights(packed, scope2)
        (got,) = exe2.run(main, feed=fd, fetch_list=[logits])
    # int8 round-trip keeps logits close and rankings identical
    assert np.abs(got - ref).max() < 0.25  # per-tensor int8 noise
    assert (np.argmax(got, -1) == np.argmax(ref, -1)).mean() > 0.95


def test_distillation_loss_trains_student_toward_teacher():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        t_logits = layers.data("t_logits", shape=[4], dtype="float32")
        s_logits = layers.fc(x, 4,
                             param_attr=fluid.ParamAttr(name="s.w"),
                             bias_attr=fluid.ParamAttr(name="s.b"))
        dloss = slim.soft_label_distill_loss(s_logits, t_logits,
                                             temperature=2.0)
        fluid.optimizer.Adam(1e-2).minimize(dloss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = RS(0)
    teacher_w = RS(1).randn(8, 4).astype(np.float32)
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(250):
            xv = rng.randn(32, 8).astype(np.float32)
            fd = {"x": xv, "t_logits": (xv @ teacher_w)}
            losses.append(float(
                exe.run(main, feed=fd, fetch_list=[dloss])[0]))
    assert losses[-1] < losses[0] * 0.35  # student matches teacher dist


def test_optimizers_adamax_adadelta():
    """New optimizer tails converge on a quadratic (reference:
    optimizer.py:41-47 Adamax/Adadelta)."""
    for opt_cls, kwargs in [
        (fluid.optimizer.Adamax, {"learning_rate": 0.05}),
        (fluid.optimizer.Adadelta, {"learning_rate": 1.0, "rho": 0.9}),
    ]:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            y = layers.fc(x, 1, bias_attr=False)
            loss = layers.mean(layers.square(y))
            opt_cls(**kwargs).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        xv = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = [float(exe.run(main, feed={"x": xv},
                                    fetch_list=[loss])[0])
                      for _ in range(150)]
        assert losses[-1] < losses[0] * 0.4, (opt_cls.__name__, losses[::30])


def test_structured_pruning_uniform():
    from paddle_tpu import slim

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[3, 8, 8], dtype="float32")
        x = layers.conv2d(img, 8, 3, padding=1,
                          param_attr=fluid.ParamAttr(name="conv1_weights"))
        x = layers.conv2d(x, 8, 3, padding=1,
                          param_attr=fluid.ParamAttr(name="conv2_weights"))
        loss = layers.mean(x)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"img": np.random.RandomState(1).randn(2, 3, 8, 8).astype(
        np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        strat = slim.UniformPruneStrategy(target_ratio=0.5,
                                          pruned_params="conv.*_weights")
        strat.on_compression_begin(scope)
        # half the output channels are zero
        w = np.asarray(scope.find_var("conv1_weights"))
        zero_ch = np.sum(np.abs(w.reshape(w.shape[0], -1)).sum(1) == 0)
        assert zero_ch == 4
        assert abs(slim.pruned_ratio(scope, strat.masks) - 0.5) < 1e-6
        # pruned channels survive an optimizer step via on_batch_end
        exe.run(main, feed=feed, fetch_list=[loss])
        strat.on_batch_end(scope)
        w2 = np.asarray(scope.find_var("conv1_weights"))
        assert np.sum(np.abs(w2.reshape(w2.shape[0], -1)).sum(1) == 0) == 4


def test_structured_pruning_sensitive():
    from paddle_tpu import slim

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        h = layers.fc(x, 8, param_attr=fluid.ParamAttr(name="fc_weights"),
                      act="relu")
        out = layers.fc(h, 1)
        loss = layers.mean(layers.square(out))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    xv = np.random.RandomState(2).randn(16, 6).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)

        def metric():
            # higher-is-better metric: negative loss
            return -float(exe.run(main, feed={"x": xv},
                                  fetch_list=[loss])[0])

        strat = slim.SensitivePruneStrategy(
            delta_rate=0.25, target_ratio=0.5,
            pruned_params="fc_weights", max_metric_loss=1e9)
        ratios = strat.prune(scope, metric)
        assert "fc_weights" in ratios and 0 < ratios["fc_weights"] <= 0.5
        assert strat.sensitivities["fc_weights"]
