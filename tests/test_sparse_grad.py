"""Row-sparse gradients (SelectedRows equivalent) for embeddings.

VERDICT round-1 row 15: "no sparse-gradient story at all". The sparse path
must be numerically identical to the dense path for SGD (linear update),
and match the lazy-Adam/Momentum semantics on touched rows. Reference:
lookup_table_op.cc SelectedRows grad + math/selected_rows_functor.cc
MergeAdd + optimizers' lazy modes.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers

VOCAB, DIM = 64, 8


def _program(optimizer, is_sparse, padding_idx=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[6], dtype="int64")
        y = layers.data("y", shape=[6, DIM], dtype="float32")
        emb = layers.embedding(
            ids, size=[VOCAB, DIM], is_sparse=is_sparse,
            padding_idx=padding_idx, name="emb",
            param_attr=fluid.ParamAttr(name="emb.w"),
        )
        loss = layers.reduce_mean(layers.square_error_cost(emb, y))
        optimizer().minimize(loss)
    return main, startup, loss


def _batches(n, seed=0, with_dups=True):
    r = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = r.randint(0, VOCAB, (4, 6)).astype(np.int64)
        if with_dups:
            ids[:, 1] = ids[:, 0]  # guaranteed duplicate ids per row
        out.append({"ids": ids,
                    "y": r.normal(0, 1, (4, 6, DIM)).astype(np.float32)})
    return out


def _train(optimizer, is_sparse, batches, padding_idx=None):
    main, startup, loss = _program(optimizer, is_sparse, padding_idx)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    losses = [
        float(exe.run(main, feed=fd, fetch_list=[loss], scope=scope)[0])
        for fd in batches
    ]
    w = np.array(scope.find_var("emb.w"))
    return losses, w


def test_sparse_sgd_matches_dense():
    batches = _batches(8)
    opt = lambda: fluid.optimizer.SGD(0.5)
    dense_l, dense_w = _train(opt, False, batches)
    sparse_l, sparse_w = _train(opt, True, batches)
    np.testing.assert_allclose(dense_l, sparse_l, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dense_w, sparse_w, rtol=1e-5, atol=1e-6)


def test_sparse_momentum_matches_dense_on_touched_rows():
    """Momentum with merged duplicate rows: touched rows must match the
    dense update exactly when every row is touched every step (ids cover
    the vocab is not needed — we compare only touched rows)."""
    batches = _batches(6, seed=3)
    opt = lambda: fluid.optimizer.Momentum(0.2, 0.9)
    dense_l, dense_w = _train(opt, False, batches)
    sparse_l, sparse_w = _train(opt, True, batches)
    touched = np.unique(np.concatenate([b["ids"].ravel() for b in batches]))
    untouched = np.setdiff1d(np.arange(VOCAB), touched)
    # untouched rows identical (no decay happened in either mode: dense
    # momentum's velocity for a zero-grad row stays zero)
    np.testing.assert_allclose(dense_w[untouched], sparse_w[untouched])
    # dense momentum decays velocity on zero-grad steps; sparse (lazy)
    # does not — but a row touched EVERY step matches exactly. Build such
    # a stream:
    batches2 = _batches(6, seed=4)
    for b in batches2:
        b["ids"][:, 0] = 7  # row 7 touched every step
        b["ids"][:, 1] = 7
    d_l, d_w = _train(opt, False, batches2)
    s_l, s_w = _train(opt, True, batches2)
    np.testing.assert_allclose(d_w[7], s_w[7], rtol=1e-5, atol=1e-6)


def test_sparse_adam_trains_and_skips_untouched_rows():
    base = _batches(4, seed=5)
    for b in base:
        b["ids"][:] = np.clip(b["ids"], 0, 31)  # rows 32+ never touched
    batches = [base[i % 4] for i in range(40)]  # fixed set, learnable
    opt = lambda: fluid.optimizer.Adam(5e-2)
    losses, w = _train(opt, True, batches)
    # conflicting random targets per row leave irreducible variance; the
    # learnable part (row means) must be absorbed
    assert np.mean(losses[-4:]) < 0.85 * np.mean(losses[:4]), losses[::4]
    # untouched rows: bit-identical to init (lazy adam touches nothing)
    main, startup, _ = _program(opt, True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    w0 = np.array(scope.find_var("emb.w"))
    np.testing.assert_array_equal(w[32:], w0[32:])


def test_sparse_padding_idx_rows_frozen():
    batches = _batches(5, seed=6)
    for b in batches:
        b["ids"][:, 2] = 3  # padding id appears in the stream
    opt = lambda: fluid.optimizer.SGD(0.5)
    losses, w = _train(opt, True, batches, padding_idx=3)
    main, startup, _ = _program(opt, True, padding_idx=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    w0 = np.array(scope.find_var("emb.w"))
    np.testing.assert_array_equal(w[3], w0[3])


def test_sparse_shared_table_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[4], dtype="int64")
        ids2 = layers.data("ids2", shape=[4], dtype="int64")
        attr = fluid.ParamAttr(name="shared.w")
        e1 = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                              param_attr=attr)
        e2 = layers.embedding(ids2, size=[VOCAB, DIM], is_sparse=True,
                              param_attr=attr)
        loss = layers.reduce_mean(
            layers.elementwise_add(e1, e2))
        with pytest.raises(ValueError, match="multiple lookups"):
            fluid.optimizer.SGD(0.1).minimize(loss)


def test_sparse_plus_dense_contribution_raises():
    """A dense grad contribution to a sparse table (e.g. a direct penalty
    on W) cannot be summed with the row-sparse pair — must raise whichever
    order backward visits the consumers."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[4], dtype="int64")
        attr = fluid.ParamAttr(name="pen.w")
        e = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                             param_attr=attr)
        w_var = main.global_block().var("pen.w")
        penalty = layers.reduce_mean(layers.square(w_var))
        loss = layers.elementwise_add(layers.reduce_mean(e), penalty)
        with pytest.raises(ValueError,
                           match="multiple lookups|cannot be combined"):
            fluid.optimizer.SGD(0.1).minimize(loss)
