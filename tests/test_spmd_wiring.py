"""SPMD wiring of ring attention + sharded embedding through the Program IR.

VERDICT round-1 item 4: these capabilities must run via
``exe.run(CompiledProgram)`` — not as standalone JAX calls. Both are
checked for loss/gradient parity against the plain single-device path on
the virtual 8-device mesh (reference parity harness analog:
tests/unittests/parallel_executor_test_base.py).
"""

import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.models import deepfm
from paddle_tpu.parallel.strategy import DistributedStrategy, ShardingRule


def _snapshot(prog):
    return {
        p.name: np.array(fluid.global_scope().find_var(p.name))
        for p in prog.all_parameters()
    }


def _restore(snap):
    for k, v in snap.items():
        fluid.global_scope().set(k, v)


def _mesh(shape, names):
    import jax

    devs = np.asarray(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


# --- sharded embedding through the IR (DeepFM) ---


def test_deepfm_trains_single_device():
    cfg = deepfm.DeepFMConfig(num_fields=8, vocab_size=128, embed_dim=4)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = deepfm.build(cfg)
        fluid.optimizer.Adam(5e-3).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for step in range(60):
        fd = deepfm.make_batch(cfg, 64, seed=step % 8)
        losses.append(float(exe.run(main, feed=fd,
                                    fetch_list=[model["loss"]])[0]))
    assert losses[-1] < 0.55, f"DeepFM did not learn: {losses[-1]}"
    assert losses[-1] < losses[0]


def test_deepfm_sharded_table_loss_parity():
    """Row-sharded embedding tables (table_axis) vs single device: same
    per-step losses while training through the Executor."""
    cfg = deepfm.DeepFMConfig(num_fields=8, vocab_size=128, embed_dim=4)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = deepfm.build(cfg)
        fluid.optimizer.SGD(0.1).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    snap = _snapshot(main)
    batches = [deepfm.make_batch(cfg, 32, seed=s) for s in range(6)]

    single = [
        float(exe.run(main, feed=fd, fetch_list=[model["loss"]])[0])
        for fd in batches
    ]

    _restore(snap)
    mesh = _mesh((2, 4), ("data", "model"))
    strategy = DistributedStrategy(
        mesh,
        data_axis="data",
        table_axis="model",
        rules=[
            ShardingRule(r"^deepfm_(first|factor)\.w(_|$)", P("model", None)),
        ],
    )
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    exe2 = fluid.Executor(fluid.CPUPlace())
    sharded = [
        float(exe2.run(compiled, feed=fd, fetch_list=[model["loss"]])[0])
        for fd in batches
    ]
    np.testing.assert_allclose(single, sharded, rtol=1e-4, atol=1e-4)
    assert sharded[-1] < sharded[0]


# --- ring attention through the IR (sequence parallelism) ---


def _attn_program(t=16, d=8, h=2, causal=True):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[t, d], dtype="float32")
        pad = layers.data("pad", shape=[t], dtype="float32")
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper("attn")
        bias = helper.create_variable_for_type_inference("float32", True)
        helper.append_op("attn_bias", inputs={"PadMask": pad},
                         outputs={"Out": bias}, attrs={"causal": causal})
        q = layers.fc(x, d, num_flatten_dims=2,
                      param_attr=fluid.ParamAttr(name="q.w"), bias_attr=False)
        k = layers.fc(x, d, num_flatten_dims=2,
                      param_attr=fluid.ParamAttr(name="k.w"), bias_attr=False)
        v = layers.fc(x, d, num_flatten_dims=2,
                      param_attr=fluid.ParamAttr(name="v.w"), bias_attr=False)

        def heads(z):
            z = layers.reshape(z, [0, 0, h, d // h])
            return layers.transpose(z, [0, 2, 1, 3])

        ctx = helper.create_variable_for_type_inference("float32")
        lse = helper.create_variable_for_type_inference("float32")
        lse.stop_gradient = True
        helper.append_op(
            "scaled_dot_product_attention",
            inputs={"Q": heads(q), "K": heads(k), "V": heads(v),
                    "Bias": bias},
            outputs={"Out": ctx, "Lse": lse},
            attrs={"is_test": True, "dropout_prob": 0.0},
        )
        loss = layers.mean(ctx)
        fluid.optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_through_executor_parity(causal):
    """sdpa routes to ring attention under a context-axis strategy; the
    full train step (fwd + grads + SGD) must match single-device."""
    t = 16
    main, startup, loss = _attn_program(t=t, causal=causal)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    snap = _snapshot(main)
    rng = np.random.RandomState(0)
    batches = []
    for s in range(4):
        x = rng.randn(4, t, 8).astype(np.float32)
        pad = (np.arange(t)[None, :] < rng.randint(t // 2, t + 1, 4)[:, None]
               ).astype(np.float32)
        batches.append({"x": x, "pad": pad})

    single = [float(exe.run(main, feed=fd, fetch_list=[loss])[0])
              for fd in batches]

    _restore(snap)
    mesh = _mesh((2, 4), ("data", "sp"))
    strategy = DistributedStrategy(mesh, data_axis="data", context_axis="sp")
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    exe2 = fluid.Executor(fluid.CPUPlace())
    ring = [float(exe2.run(compiled, feed=fd, fetch_list=[loss])[0])
            for fd in batches]

    np.testing.assert_allclose(single, ring, rtol=2e-4, atol=2e-5)


def test_ring_attention_transformer_model_parity():
    """Flagship transformer forward under dp x sp sequence parallelism."""
    from paddle_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        src_vocab_size=50, trg_vocab_size=50, max_length=32, d_model=16,
        d_inner=32, n_head=2, n_layer=1, dropout=0.0, label_smooth_eps=0.0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = transformer.build(cfg, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    batch = transformer.make_batch(cfg, 4, 16, 16, seed=0)
    # full-length rows: ring attention shards the sequence axis evenly
    batch["src_pad_mask"][:] = 1.0
    batch["trg_pad_mask"][:] = 1.0

    (ref,) = exe.run(main, feed=batch, fetch_list=[model["loss"]])

    mesh = _mesh((2, 4), ("data", "sp"))
    strategy = DistributedStrategy(mesh, data_axis="data", context_axis="sp")
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    exe2 = fluid.Executor(fluid.CPUPlace())
    (got,) = exe2.run(compiled, feed=batch, fetch_list=[model["loss"]])
    np.testing.assert_allclose(float(ref), float(got), rtol=2e-4)


def test_sharded_table_adam_scalar_accumulators():
    """Adam's scalar beta-pow accumulators must not inherit a rank-2 table
    rule via the name-suffix match (verify-drive finding, round 2)."""
    cfg = deepfm.DeepFMConfig(num_fields=4, vocab_size=64, embed_dim=4)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = deepfm.build(cfg)
        fluid.optimizer.Adam(5e-3).minimize(model["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mesh = _mesh((2, 4), ("data", "model"))
    strategy = DistributedStrategy(
        mesh, data_axis="data", table_axis="model",
        rules=[ShardingRule(r"^deepfm_(first|factor)\.w(_|$)",
                            P("model", None))])
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    losses = [
        float(exe.run(compiled, feed=deepfm.make_batch(cfg, 32, seed=s),
                      fetch_list=[model["loss"]])[0])
        for s in range(30)
    ]
    assert losses[-1] < losses[0]
