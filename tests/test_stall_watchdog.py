"""Collective stall watchdog (PR 2 tentpole, piece 3): a guarded section
that outlives its deadline must increment pt_stall_total, buffer a
structured stall record carrying the arming thread's span stack, and
(flag-gated) dump the flight recorder — while a fast section leaves no
trace and a disabled guard is the shared nullcontext."""

import json
import time
import warnings

import pytest

from paddle_tpu import flags, monitor


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    defaults = {"telemetry": False, "step_log_path": "",
                "stall_timeout_ms": 0, "stall_dump_dir": ""}
    flags.set_flags(defaults)
    yield
    monitor.reset()
    flags.set_flags(defaults)


def test_forced_stall_records_and_counts():
    monitor.enable()
    flags.set_flags({"stall_timeout_ms": 100})
    with pytest.warns(RuntimeWarning, match="stall watchdog"):
        with monitor.span("outer"), monitor.span("fleet.barrier"):
            with monitor.stall_guard("fleet.barrier"):
                time.sleep(0.35)  # deliberately blows the 100ms deadline
    assert monitor.counter("pt_stall_total").value(
        labels={"site": "fleet.barrier"}) == 1
    recs = monitor.stalls()
    assert len(recs) == 1
    rec = recs[0]
    assert rec["site"] == "fleet.barrier"
    assert rec["deadline_ms"] == 100
    # the span stack pinpoints WHERE the thread sat when the timer fired
    assert rec["span_stack"] == ["outer", "fleet.barrier"]
    assert rec["v"] == monitor.STALL_RECORD_SCHEMA_VERSION
    assert rec["last_step"] is None  # no executor steps ran


def test_stall_record_carries_last_step():
    monitor.enable()
    monitor.log_step({"kind": "step", "step": 7, "wall_ms": 1.0,
                      "compile_ms": None, "cache": "hit", "evictions": 0,
                      "feed_bytes": 0, "fetch_bytes": 0,
                      "nan_check": None, "strategy": None})
    with pytest.warns(RuntimeWarning, match="stall watchdog"):
        with monitor.stall_guard("trainer.step", deadline_ms=50):
            time.sleep(0.25)
    rec = monitor.stalls()[-1]
    assert rec["last_step"]["step"] == 7


def test_fast_section_leaves_no_trace():
    monitor.enable()
    flags.set_flags({"stall_timeout_ms": 10_000})
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        with monitor.stall_guard("fleet.barrier"):
            pass
    # give a mis-armed timer a moment to (wrongly) fire
    time.sleep(0.05)
    assert monitor.counter("pt_stall_total").value(
        labels={"site": "fleet.barrier"}) == 0
    assert monitor.stalls() == []


def test_disabled_guard_is_shared_nullcontext():
    # telemetry off: no allocation, one shared object
    assert monitor.stall_guard("x") is monitor.stall_guard("y")
    # telemetry on but no deadline anywhere: still the nullcontext
    monitor.enable()
    assert monitor.stall_guard("x") is monitor.stall_guard("y")
    with monitor.stall_guard("x"):
        pass
    assert monitor.stalls() == []


def test_flight_recorder_dump(tmp_path):
    monitor.enable()
    flags.set_flags({"stall_dump_dir": str(tmp_path)})
    monitor.log_step({"kind": "step", "step": 3, "wall_ms": 1.0,
                      "compile_ms": None, "cache": "hit", "evictions": 0,
                      "feed_bytes": 0, "fetch_bytes": 0,
                      "nan_check": None, "strategy": None})
    monitor.counter("t_wd_c", "doc").inc(5)
    with pytest.warns(RuntimeWarning, match="stall watchdog"):
        with monitor.stall_guard("pipeline.dispatch", deadline_ms=50):
            time.sleep(0.25)
    dumps = list(tmp_path.glob("stall-*.json"))
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    assert payload["stall"]["site"] == "pipeline.dispatch"
    assert [s["step"] for s in payload["steps"]] == [3]
    assert payload["metrics"]["t_wd_c"]["values"][0]["value"] == 5.0
    assert "compile_reports" in payload


def test_watchdog_fires_once_per_guard():
    """One guarded section -> at most one stall record, however long it
    overruns (threading.Timer is one-shot) — and cancel on exit means a
    section that finishes JUST after arming never double-reports."""
    monitor.enable()
    with pytest.warns(RuntimeWarning, match="stall watchdog"):
        with monitor.stall_guard("fleet.kv_get", deadline_ms=40):
            time.sleep(0.3)  # ~7x the deadline: still one firing
    assert monitor.counter("pt_stall_total").value(
        labels={"site": "fleet.kv_get"}) == 1
    assert len(monitor.stalls()) == 1
