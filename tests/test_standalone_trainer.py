"""C++ standalone trainer (reference: train/demo/demo_trainer.cc,
train/test_train_recognize_digits.cc): train a serialized program from a
native binary without writing Python."""

import os
import re
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib import standalone

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
BIN = os.path.join(REPO, "csrc", "standalone_trainer")


def test_standalone_trainer_trains(tmp_path):
    import shutil

    if not (shutil.which("make") and shutil.which("g++")
            and shutil.which("python3-config")):
        pytest.skip("native toolchain unavailable")
    r = subprocess.run(["make", "-C", os.path.join(REPO, "csrc"),
                        "standalone_trainer"], capture_output=True,
                       text=True)
    # with a toolchain present, a compile error is a real failure
    assert r.returncode == 0 and os.path.exists(BIN), r.stderr[-800:]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[13], dtype="float32")
        y = layers.data("y", shape=[1], dtype="int64")
        logits = layers.fc(layers.fc(x, 16, act="relu"), 3)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    d = str(tmp_path / "standalone")
    # labels drawn from {0, 1} of 3 classes: the logit bias learns to
    # exclude class 2, so the loss falls below the ln(3) chance level
    standalone.save_train_program(d, main, startup, [x, y],
                                  int_maxes={"y": 2})
    env = {**os.environ, "PT_REPO": REPO, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run([BIN, d, "12", "16"], capture_output=True,
                         text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr[-800:]
    losses = [float(m) for m in re.findall(r"loss ([0-9.]+)", out.stdout)]
    assert len(losses) == 12, out.stdout
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.log(3.0) - 0.05, losses
