"""Tensor-parallel (dp x tp) correctness on the virtual 8-device mesh.

Analog of the reference's multi-device loss-parity harness
(reference: tests/unittests/parallel_executor_test_base.py) applied to the
strategy the reference lacks: Megatron-style TP via GSPMD sharding rules
(paddle_tpu/parallel/strategy.py), validated against a single-device run of
the identical program.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.models import transformer as T


CFG = T.TransformerConfig(
    src_vocab_size=64,
    trg_vocab_size=64,
    d_model=32,
    d_inner=64,
    n_head=4,
    n_layer=2,
    max_length=32,
    dropout=0.0,  # determinism across runs
)


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = T.build(CFG, is_test=False)
        fluid.optimizer.Adam(1e-3).minimize(model["loss"])
    return main, startup, model


def _run_steps(compiled_or_prog, main, startup, model, n_steps=2):
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    losses = []
    for i in range(n_steps):
        feed = T.make_batch(CFG, batch=8, src_len=16, trg_len=16, seed=i)
        out = exe.run(
            compiled_or_prog,
            feed=feed,
            fetch_list=[model["loss"]],
            scope=scope,
        )
        losses.append(float(out[0]))
    return losses, scope


@pytest.mark.full
def test_dp_tp_loss_parity():
    """4x2 dp x tp full training steps match single-device to tight tol."""
    import jax

    assert len(jax.devices()) == 8
    main, startup, model = _build()
    single, _ = _run_steps(main, main, startup, model)

    mesh = parallel.create_mesh({"data": 4, "model": 2})
    strategy = parallel.DistributedStrategy(
        mesh, "data", parallel.transformer_rules("model"), strict=True
    )
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    sharded, scope = _run_steps(compiled, main, startup, model)

    np.testing.assert_allclose(single, sharded, rtol=0, atol=2e-4)


@pytest.mark.full
def test_tp_param_is_actually_sharded():
    """The column-parallel weight must be laid out sharded on the mesh, not
    replicated — guards against rules silently degrading to replication."""
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = parallel.create_mesh({"data": 4, "model": 2})
    strategy = parallel.DistributedStrategy(
        mesh, "data", parallel.transformer_rules("model"), strict=True
    )
    assert strategy.spec_for("enc1_attn_qkv_colp.w") == P(None, "model")
    assert strategy.spec_for("enc1_attn_out_rowp.w") == P("model", None)
    assert strategy.spec_for("enc1_attn_qkv_colp.w_moment1_0") == P(None, "model")
    assert strategy.spec_for("enc1_preattn_ln.scale") == P()

    main, startup, model = _build()
    compiled = fluid.CompiledProgram(main).with_strategy(strategy)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    feed = T.make_batch(CFG, batch=8, src_len=16, trg_len=16, seed=0)
    exe.run(compiled, feed=feed, fetch_list=[model["loss"]], scope=scope)

    w = scope.find_var("enc1_attn_qkv_colp.w")
    assert isinstance(w, jax.Array)
    # Each shard holds half the columns on the 2-way model axis.
    shard_shape = w.sharding.shard_shape(w.shape)
    assert shard_shape[-1] == w.shape[-1] // 2


def test_strict_strategy_rejects_unmatched_name():
    """A parameter name no rule matches must raise, not silently replicate
    (VERDICT round 1 weak #3)."""
    mesh = parallel.create_mesh({"data": 4, "model": 2})
    strategy = parallel.DistributedStrategy(
        mesh, "data", parallel.transformer_rules("model"), strict=True
    )
    with pytest.raises(ValueError, match="matches no rule"):
        strategy.spec_for("enc1_attn_q_colp_typo.weight")


def test_nonstrict_strategy_falls_back_to_replicated():
    from jax.sharding import PartitionSpec as P

    mesh = parallel.create_mesh({"data": 4, "model": 2})
    strategy = parallel.DistributedStrategy(
        mesh, "data", parallel.transformer_rules("model"), strict=False
    )
    assert strategy.spec_for("some_unmatched_name") == P()
