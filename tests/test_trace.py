"""Time-attribution plane (PR 4 tentpole): executor step-phase
breakdown + boundedness verdict, the Chrome-trace timeline ring,
trace_dir export, the /trace route, merge_traces, legacy-profiler
routing, and the disabled-path zero-allocation contract."""

import json
import os
import tracemalloc
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers, monitor, profiler

# step_phases_every_n forced to 1 here: per-step phases are the thing
# under test (the sampled-phases contract has its own suite in
# tests/test_async_pipeline.py)
_RESET_FLAGS = {"telemetry": False, "step_log_path": "",
                "metrics_dump_path": "", "trace_dir": "",
                "trace_every_n_steps": 1, "metrics_port": 0,
                "step_phases": True, "step_phases_every_n": 1,
                "check_nan_inf": False}


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.reset()
    flags.set_flags(dict(_RESET_FLAGS))
    yield
    monitor.stop_server()
    monitor.reset()
    flags.set_flags(dict(_RESET_FLAGS))


def _tiny_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        loss = layers.mean(layers.fc(x, 4))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _run_steps(n=3, trace_dir=None):
    """n training steps of the tiny program under telemetry."""
    new = {"telemetry": True}
    if trace_dir is not None:
        new["trace_dir"] = trace_dir
    flags.set_flags(new)
    main, startup, loss = _tiny_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(n):
            exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                    fetch_list=[loss])
    return exe


# --------------------------------------------------------------------------
# activation gate
# --------------------------------------------------------------------------

def test_trace_inactive_without_visibility():
    """Tracing needs telemetry AND a sink (trace_dir or the live
    endpoint) — same never-on-by-accident rule as compile reports."""
    assert not monitor.trace_active()
    flags.set_flags({"telemetry": True})
    assert not monitor.trace_active()
    monitor.trace_event("ghost", "span", 0.0, 1.0)
    assert monitor.trace_events() == []
    flags.set_flags({"trace_dir": "/tmp"})
    assert monitor.trace_active()
    flags.set_flags({"telemetry": False})
    assert not monitor.trace_active()


def test_server_alone_activates_tracing():
    flags.set_flags({"telemetry": True})
    assert not monitor.trace_active()
    monitor.serve(0)
    assert monitor.trace_active()
    monitor.stop_server()
    assert not monitor.trace_active()


# --------------------------------------------------------------------------
# event schema + ring semantics
# --------------------------------------------------------------------------

def _assert_chrome_schema(events):
    """Required keys per event; ts non-negative and monotone per
    (pid, tid) track; X events carry a non-negative dur."""
    last_ts = {}
    assert events, "no trace events"
    for ev in events:
        for k in ("name", "ph", "ts", "pid", "tid"):
            assert k in ev, f"event missing '{k}': {ev}"
        if ev["ph"] == "M":
            continue
        assert ev["ts"] >= 0
        track = (ev["pid"], ev["tid"])
        assert ev["ts"] >= last_ts.get(track, 0.0), (
            f"ts not monotone on track {track}")
        last_ts[track] = ev["ts"]
        if ev["ph"] == "X":
            assert ev["dur"] >= 0


def test_trace_events_conform_to_chrome_schema(tmp_path):
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    with monitor.span("trace.outer"):
        with monitor.span("trace.inner"):
            pass
    monitor.trace_event("mark", "stall", 1.0)  # instant event
    doc = monitor.trace_snapshot()
    _assert_chrome_schema(doc["traceEvents"])
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"trace.outer", "trace.inner", "mark"} <= names
    # category -> synthetic track: spans and stalls on distinct tids
    by_name = {e["name"]: e for e in doc["traceEvents"] if e["ph"] != "M"}
    assert by_name["trace.outer"]["tid"] != by_name["mark"]["tid"]
    # json round-trip (what a trace viewer loads)
    assert json.loads(json.dumps(doc, default=str))["traceEvents"]


def test_trace_ring_is_bounded_with_drop_counter(tmp_path):
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    n = monitor.TRACE_RING_CAPACITY
    for i in range(n + 7):
        monitor.trace_event(f"e{i}", "span", float(i), float(i) + 0.5)
    evs = monitor.trace_events()
    assert len(evs) == n
    assert evs[0]["name"] == "e7"  # oldest evicted first
    assert monitor.counter("pt_trace_events_total").value() == n + 7
    assert monitor.counter("pt_trace_events_dropped_total").value() == 7


# --------------------------------------------------------------------------
# legacy profiler routing (satellite): one clock, one stream
# --------------------------------------------------------------------------

def test_record_event_span_appears_in_exported_trace(tmp_path):
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    with profiler.record_event("legacy.record_event"):
        pass
    path = monitor.export_trace()
    doc = json.load(open(path))
    spans = [e for e in doc["traceEvents"]
             if e.get("cat") == "span" and e["ph"] == "X"]
    assert any(e["name"] == "legacy.record_event" for e in spans)
    # same clock: the legacy span's ts is comparable to a monitor.span's
    with monitor.span("new.span"):
        pass
    evs = monitor.trace_events()
    legacy = next(e for e in evs if e["name"] == "legacy.record_event")
    new = next(e for e in evs if e["name"] == "new.span")
    assert legacy["tid"] == new["tid"]
    assert legacy["ts"] <= new["ts"]


def test_start_stop_profiler_marks_the_timeline(tmp_path, monkeypatch):
    from paddle_tpu import native

    monkeypatch.setattr(native, "available", lambda: False)
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    profiler.start_profiler()
    profiler.stop_profiler(profile_path=str(tmp_path / "p"))
    names = [e["name"] for e in monitor.trace_events()]
    assert names.count("profiler.start") == 1
    assert names.count("profiler.stop") == 1


def test_record_event_untraced_is_a_bare_yield():
    """Both collectors off: record_event must not buffer anything."""
    with profiler.record_event("invisible"):
        pass
    assert monitor.trace_events() == []


# --------------------------------------------------------------------------
# executor step phases + boundedness verdict
# --------------------------------------------------------------------------

def test_run_records_phases_and_bound(tmp_path):
    _run_steps(3)
    recs = monitor.recent_steps()
    assert len(recs) == 4  # startup + 3 train steps
    for rec in recs:
        monitor.validate_step_record(rec)
        assert rec["sampled"] is True  # every_n=1: all sampled
        phases = rec["phases"]
        assert set(phases) == set(monitor.STEP_PHASES)
        for name, ms in phases.items():
            assert ms > 0, f"phase '{name}' not measured"
        # phases are measured sub-intervals of the wall interval
        assert sum(phases.values()) <= rec["wall_ms"]
    # only COMMITTED CACHE-HIT steps are verdict-scored: a fresh
    # compile's host time would pollute the dispatch share (the two
    # misses here are the startup program and the first train step)
    for rec in recs[:2]:
        assert rec["cache"] == "miss" and "bound" not in rec
    for rec in recs[2:]:
        assert rec["cache"] == "hit"
        assert rec["bound"] in monitor.BOUND_VERDICTS
    # histograms observed once per phase per SAMPLED step (miss or hit)
    h = monitor.histogram("pt_step_phase_seconds")
    for phase in monitor.STEP_PHASES:
        assert h.count(labels={"phase": phase}) == 4
    # every scored step counted into exactly one verdict
    c = monitor.counter("pt_step_bound_total")
    total = sum(c.value(labels={"verdict": v})
                for v in monitor.BOUND_VERDICTS)
    assert total == 2
    assert monitor.boundedness()["steps"] == 2


def test_run_steps_window_records_phases(tmp_path):
    flags.set_flags({"telemetry": True})
    main, startup, loss = _tiny_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"x": np.ones((2, 8), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_steps(main, feed_list=[feed], steps=4, fetch_list=[loss])
        rec = monitor.recent_steps()[-1]
        assert rec["kind"] == "window"
        monitor.validate_step_record(rec)
        assert all(v > 0 for v in rec["phases"].values())
        # the first window is a fresh compile: phases measured, verdict
        # withheld (compile time would pollute the dispatch share)
        assert rec["cache"] == "miss" and "bound" not in rec
        exe.run_steps(main, feed_list=[feed], steps=4, fetch_list=[loss])
    rec = monitor.recent_steps()[-1]
    assert rec["cache"] == "hit"
    assert all(v > 0 for v in rec["phases"].values())
    assert rec["bound"] in monitor.BOUND_VERDICTS


def test_input_wait_tips_verdict_to_input_bound():
    """Reader consumer waits drained into the verdict scores dominate a
    cheap device step: the window must call it input_bound."""
    flags.set_flags({"telemetry": True})
    monitor.note_input_wait(5.0)
    verdict = monitor.record_step_phases(0.001, 0.002, 0.003, 0.001)
    assert verdict == "input_bound"
    b = monitor.boundedness()
    assert b["verdict"] == "input_bound"
    assert b["shares"]["input"] > 0.99
    # the accumulator drained: an undisturbed next step is device_bound
    assert monitor.record_step_phases(0.0, 0.0, 60.0, 0.0) == "device_bound"


def test_step_phases_flag_opts_out_of_sync_and_phases():
    """step_phases=False keeps telemetry records but skips the phase
    marks (and their per-step block_until_ready): no phases/bound
    fields, no histogram cells, no verdict."""
    flags.set_flags({"step_phases": False})
    _run_steps(2)
    recs = monitor.recent_steps()
    assert len(recs) == 3
    for rec in recs:
        monitor.validate_step_record(rec)
        assert "phases" not in rec and "bound" not in rec
        # phase plane fully off: no sampled marker either (the marker
        # distinguishes sampled/unsampled WITHIN an active plane)
        assert "sampled" not in rec
    assert monitor.histogram("pt_step_phase_seconds")._cells == {}
    assert monitor.boundedness() is None
    # flipping it back mid-process takes effect immediately
    flags.set_flags({"step_phases": True})
    assert monitor.phases_active()


def test_failed_step_logs_record_without_phases():
    """A step that raises before commit (check_nan_inf) must log its
    postmortem record WITHOUT phases — truncated durations would skew
    the rolling verdict window."""
    flags.set_flags({"telemetry": True, "check_nan_inf": True})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.log(x)  # log(0) -> -inf
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(FloatingPointError):
            exe.run(main, feed={"x": np.zeros((1, 4), np.float32)},
                    fetch_list=[y])
    rec = monitor.recent_steps()[-1]
    assert rec["nan_check"] == "fail"
    assert "phases" not in rec and "bound" not in rec


def test_phase_trace_events_respect_sampling(tmp_path):
    flags.set_flags({"trace_every_n_steps": 2})
    _run_steps(4, trace_dir=str(tmp_path))
    phase_steps = {e["args"]["step"] for e in monitor.trace_events()
                   if e.get("cat") == "phase"}
    # steps 0 (startup), 1..4 (train); only even executor steps sampled
    assert phase_steps == {0, 2, 4}


def test_window_sampling_does_not_alias_against_stride(tmp_path):
    """A run_steps window is sampled whenever ANY of its steps hits the
    period — windows of 4 against trace_every_n_steps=7 must not only
    trace every lcm(4,7)=28th step."""
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path),
                     "trace_every_n_steps": 7})
    # window [4, 8) contains step 7: sampled despite 4 % 7 != 0
    assert monitor.trace_step_sampled(4, 4)
    assert monitor.trace_step_sampled(7, 1)
    assert not monitor.trace_step_sampled(4, 3)  # [4, 7) misses it
    assert not monitor.trace_step_sampled(8, 1)


def test_stale_input_wait_cleared_when_phases_flip_on():
    """Waits accumulated while nobody drains them (phases off) must not
    dump into the first attributed step and fake an input_bound
    verdict."""
    flags.set_flags({"telemetry": True, "step_phases": False})
    # with phases off the accumulator doesn't even grow...
    monitor.note_input_wait(3600.0)
    flags.set_flags({"step_phases": True})
    # ...and flipping phases on clears anything that did (transition
    # guard) — a device-heavy step stays device_bound
    assert monitor.record_step_phases(0.0, 0.0, 1.0, 0.0) == "device_bound"


def test_compile_events_on_their_own_track(tmp_path):
    _run_steps(2, trace_dir=str(tmp_path))
    evs = monitor.trace_events()
    tids = {cat: {e["tid"] for e in evs if e.get("cat") == cat}
            for cat in ("span", "phase", "compile")}
    assert all(len(v) == 1 for v in tids.values()), tids
    assert len({next(iter(v)) for v in tids.values()}) == 3, tids
    compiles = [e for e in evs if e.get("cat") == "compile"]
    assert len(compiles) == 2  # startup + train program
    assert all(e["dur"] > 0 for e in compiles)


# --------------------------------------------------------------------------
# export / serve / merge
# --------------------------------------------------------------------------

def test_export_trace_writes_per_process_file(tmp_path):
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    with monitor.span("export.me"):
        pass
    path = monitor.export_trace()
    assert os.path.basename(path).startswith("trace-")
    assert str(os.getpid()) in os.path.basename(path)
    doc = json.load(open(path))
    assert doc["metadata"]["os_pid"] == os.getpid()
    assert doc["metadata"]["v"] == monitor.TRACE_SCHEMA_VERSION
    _assert_chrome_schema(doc["traceEvents"])
    # no trace_dir, no implicit write target
    flags.set_flags({"trace_dir": ""})
    assert monitor.export_trace() is None


def test_trace_route_round_trips():
    flags.set_flags({"telemetry": True})
    port = monitor.serve(0)
    with monitor.span("served.span"):
        pass
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/trace", timeout=10) as r:
        assert r.status == 200
        doc = json.loads(r.read())
    assert any(e["name"] == "served.span" for e in doc["traceEvents"])
    _assert_chrome_schema(doc["traceEvents"])


def test_merge_traces_aligns_ranks_and_clocks(tmp_path):
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    with monitor.span("worker.span"):
        pass
    base = monitor.trace_snapshot()
    # fake a second worker: same events, clock 1s ahead, rank 1
    other = json.loads(json.dumps(base, default=str))
    other["metadata"]["rank"] = 1
    for ev in other["traceEvents"]:
        if ev["ph"] != "M":
            ev["ts"] += 1e6
    p0, p1 = tmp_path / "t0.json", tmp_path / "t1.json"
    p0.write_text(json.dumps(base, default=str))
    p1.write_text(json.dumps(other, default=str))

    out = tmp_path / "merged.json"
    merged = monitor.merge_traces([str(p0), str(p1)], out_path=str(out))
    assert json.load(open(out)) == json.loads(
        json.dumps(merged, default=str))
    data = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    assert {e["pid"] for e in data} == {0, 1}  # rank-tagged tracks
    assert merged["metadata"]["merged_ranks"] == [0, 1]
    assert min(e["ts"] for e in data) == 0  # rebased
    assert data == sorted(data, key=lambda e: e["ts"])
    # offsets_us corrects a measured skew: rank 1 pulled back into sync
    fixed = monitor.merge_traces([str(p0), str(p1)],
                                 offsets_us={1: -1e6})
    fdata = [e for e in fixed["traceEvents"] if e["ph"] != "M"]
    r0 = sorted(e["ts"] for e in fdata if e["pid"] == 0)
    r1 = sorted(e["ts"] for e in fdata if e["pid"] == 1)
    assert r0 == pytest.approx(r1)


def test_merge_traces_rank_collision_falls_back_to_unused_rank(tmp_path):
    """Two traces claiming the same rank (re-runs, misconfigured fleet)
    must still land on distinct pid tracks."""
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    with monitor.span("dup.span"):
        pass
    base = monitor.trace_snapshot()
    a = json.loads(json.dumps(base, default=str))
    b = json.loads(json.dumps(base, default=str))
    a["metadata"]["rank"] = b["metadata"]["rank"] = 1
    merged = monitor.merge_traces([a, b])
    data = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    assert {e["pid"] for e in data} == {0, 1}
    assert merged["metadata"]["merged_ranks"] == [0, 1]


def test_reset_clears_timeline_and_verdict(tmp_path):
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    with monitor.span("gone"):
        pass
    monitor.record_step_phases(0.1, 0.1, 0.1, 0.1)
    monitor.reset()
    assert monitor.trace_events() == []
    assert monitor.boundedness() is None


# --------------------------------------------------------------------------
# disabled path: the one-boolean-check zero-allocation contract
# --------------------------------------------------------------------------

def test_disabled_executor_run_allocates_nothing_in_new_code():
    """With telemetry off, the PR-4 instrumentation (phase marks, trace
    gates, record_event hook) must add zero allocations attributable to
    monitor.py or profiler.py to Executor.run — the contract that lets
    the hot path stay permanently instrumented."""
    assert not monitor.enabled() and not monitor.trace_active()
    main, startup, _ = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((2, 8), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # warm compile cache + lazy interp state
            exe.run(main, feed=feed)
        n_runs = 30
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(n_runs):
            exe.run(main, feed=feed)
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
    stats = snap.compare_to(base, "filename")
    grew = sum(s.size_diff for s in stats
               if s.traceback[0].filename.endswith(
                   ("monitor.py", "profiler.py"))
               and s.size_diff > 0)
    assert grew < n_runs * 16, (
        f"disabled Executor.run allocated {grew}B in telemetry code "
        f"over {n_runs} runs")


# --------------------------------------------------------------------------
# end-to-end: 3-step MNIST train with the full plane on
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_mnist_three_step_phase_breakdown_and_trace(tmp_path):
    from paddle_tpu.models import mnist as mnist_model

    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        model = mnist_model.get_model(use_conv=False)
        fluid.optimizer.SGD(0.1).minimize(model["loss"])
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            feed = {
                "pixel": rng.rand(16, 784).astype(np.float32),
                "label": rng.randint(0, 10, (16, 1)).astype(np.int64),
            }
            exe.run(main, feed=feed, fetch_list=[model["loss"]])

    # acceptance: each phase > 0 and the sum within 20% of wall_ms
    for rec in monitor.recent_steps():
        monitor.validate_step_record(rec)
        phases = rec["phases"]
        assert all(phases[p] > 0 for p in monitor.STEP_PHASES)
        assert sum(phases.values()) <= rec["wall_ms"]
        assert sum(phases.values()) >= 0.8 * rec["wall_ms"], (
            phases, rec["wall_ms"])
        # verdicts only on committed cache-hit steps (sampled contract)
        if rec["cache"] == "hit":
            assert rec["bound"] in monitor.BOUND_VERDICTS
        else:
            assert "bound" not in rec

    # acceptance: the exported trace loads, with span + phase + compile
    # events on three distinct tracks
    doc = json.load(open(monitor.export_trace()))
    _assert_chrome_schema(doc["traceEvents"])
    tids = {}
    for cat in ("span", "phase", "compile"):
        evs = [e for e in doc["traceEvents"] if e.get("cat") == cat]
        assert evs, f"no '{cat}' events in the exported trace"
        tids[cat] = {e["tid"] for e in evs}
    assert len({next(iter(v)) for v in tids.values()}) == 3
    phase_names = {e["name"] for e in doc["traceEvents"]
                   if e.get("cat") == "phase"}
    assert phase_names == set(monitor.STEP_PHASES)


# --------------------------------------------------------------------------
# dynamic request tracks (serving request plane)
# --------------------------------------------------------------------------

def test_dynamic_request_tracks_schema_and_metadata(tmp_path):
    """Per-request timeline tracks: trace_event's tid override lands
    events on a dynamic track (>= REQUEST_TRACK_BASE), the registered
    label is exported as thread_name metadata, and the snapshot still
    conforms to the Chrome schema."""
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    base = monitor.REQUEST_TRACK_BASE
    monitor.trace_register_track(base, "req r1")
    monitor.trace_register_track(base + 1, "req r2")
    monitor.trace_event("a", "request", 1.0, 2.0, tid=base)
    monitor.trace_event("b", "request", 1.5, tid=base + 1)
    monitor.trace_event("c", "request", 2.5, 3.0, tid=base)
    doc = monitor.trace_snapshot()
    _assert_chrome_schema(doc["traceEvents"])
    by_name = {e["name"]: e for e in doc["traceEvents"] if e["ph"] != "M"}
    assert by_name["a"]["tid"] == base
    assert by_name["b"]["tid"] == base + 1
    metas = {e["tid"]: e["args"]["name"]
             for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert metas[base] == "req r1" and metas[base + 1] == "req r2"
    # re-registering a recycled tid replaces its label
    monitor.trace_register_track(base, "req r9")
    metas = {e["tid"]: e["args"]["name"]
             for e in monitor.trace_snapshot()["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert metas[base] == "req r9"


def test_dynamic_track_label_set_is_bounded(tmp_path):
    """Track labels are a bounded set: past _DYN_TRACK_CAP the oldest
    registration ages out (its events keep their tid — only the
    thread_name row is dropped). Inactive tracing registers nothing."""
    flags.set_flags({"telemetry": True, "trace_dir": str(tmp_path)})
    base = monitor.REQUEST_TRACK_BASE
    n = monitor._DYN_TRACK_CAP + 7
    for i in range(n):
        monitor.trace_register_track(base + i, f"req r{i}")
    metas = [e for e in monitor.trace_snapshot()["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"
             and e["tid"] >= base]
    assert len(metas) == monitor._DYN_TRACK_CAP
    names = {e["args"]["name"] for e in metas}
    assert "req r0" not in names and f"req r{n - 1}" in names
    # inactive: registration is a no-op, reset clears the labels
    monitor.reset()
    flags.set_flags({"telemetry": False, "trace_dir": ""})
    monitor.trace_register_track(base, "ghost")
    with monitor._TRACE_LOCK:
        assert monitor._DYN_TRACKS == {}
